"""TSAN-lite interleave sanitizer for ``# cordum: guarded-by`` async state.

cordumlint's CL008 proves statically that a read-modify-write of shared
instance state never *spans an await* without its declared lock.  This
module is the dynamic half of that contract: with ``CORDUM_SYNC_SANITIZER=1``
every attribute carrying a ``# cordum: guarded-by(<lock>)`` annotation on an
:func:`instrument`-decorated class is replaced by a tracking descriptor, the
named lock is wrapped so ownership is attributable to an asyncio task, and
each access records ``(task, write-generation)``.  A *lost update* — task A
reads the attribute, task B commits a write at a later generation, then A
writes back without holding the lock — produces a :class:`Report` instead of
silently clobbering B's state.  The test harness asserts zero reports after
every test (``tests/conftest.py``), and CI runs the full tier-1 suite under
the sanitizer as a separate step.

Design constraints:

* **Zero cost when off.**  :func:`instrument` returns the class untouched
  unless the env var is set, so production import paths pay nothing.
* **No new dependencies.**  Annotations are recovered from the class source
  with :func:`inspect.getsource` + a regex — the same grammar cordumlint
  parses — so the two halves can never drift on syntax.
* **Attribution, not interception.**  Reports are collected, not raised, at
  the access site: raising inside a descriptor would turn a diagnosed race
  into a behavior change.  The harness decides when reports are fatal.
"""
from __future__ import annotations

import asyncio
import dataclasses
import inspect
import os
import re
from typing import Any, Optional

ENV_VAR = "CORDUM_SYNC_SANITIZER"

# same grammar cordumlint's program_rules._ANNOT_RE accepts for the
# attribute-level form: the annotation trails the `self.<attr> = ...` line
_GUARD_RE = re.compile(
    r"self\.(?P<attr>\w+)\s*[:=][^#\n]*#\s*cordum:\s*guarded-by\((?P<lock>\w+)\)"
)


def enabled() -> bool:
    return os.environ.get(ENV_VAR, "") == "1"


# ---------------------------------------------------------------------------
# reports
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Report:
    """One diagnosed interleave conflict on a guarded attribute."""

    kind: str  # "lost-update" | "write-under-foreign-lock"
    cls: str
    attr: str
    lock: str
    writer_task: str
    other_task: str
    detail: str

    def __str__(self) -> str:  # pragma: no cover - repr convenience
        return (f"[syncsan:{self.kind}] {self.cls}.{self.attr} "
                f"(guarded-by {self.lock}): {self.detail}")


_reports: list[Report] = []
_gen = 0  # global write generation; bumped on every tracked write


def reports() -> list[Report]:
    return list(_reports)


def reset() -> None:
    _reports.clear()


def _task_label() -> str:
    t = _current_task()
    if t is None:
        return "<no-task>"
    return t.get_name() if hasattr(t, "get_name") else repr(t)


def _current_task() -> Optional[asyncio.Task]:
    try:
        return asyncio.current_task()
    except RuntimeError:
        return None


def _task_key() -> int:
    t = _current_task()
    return id(t) if t is not None else 0


# ---------------------------------------------------------------------------
# lock ownership
# ---------------------------------------------------------------------------

class TrackedLock:
    """Wraps the guarding lock so the sanitizer can attribute ownership to a
    task — asyncio.Lock knows *whether* it is held, never *by whom*."""

    def __init__(self, inner: Any):
        self._inner = inner
        self._owner: int = 0  # task key; 0 = unowned

    async def acquire(self) -> bool:
        got = await self._inner.acquire()
        self._owner = _task_key()
        return got

    def release(self) -> None:
        self._owner = 0
        self._inner.release()

    async def __aenter__(self) -> "TrackedLock":
        await self.acquire()
        return self

    async def __aexit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def held_by_current(self) -> bool:
        return self.locked() and self._owner == _task_key()


class _LockAttr:
    """Data descriptor for the guarding lock attribute: wraps the assigned
    lock in a :class:`TrackedLock` at set time."""

    def __init__(self, name: str):
        self.name = name
        self.slot = "__ss_lock_" + name

    def __get__(self, obj: Any, objtype: Any = None) -> Any:
        if obj is None:
            return self
        try:
            return obj.__dict__[self.slot]
        except KeyError:
            raise AttributeError(self.name) from None

    def __set__(self, obj: Any, value: Any) -> None:
        if value is not None and not isinstance(value, TrackedLock) \
                and hasattr(value, "__aenter__"):
            value = TrackedLock(value)
        obj.__dict__[self.slot] = value


# ---------------------------------------------------------------------------
# guarded attribute tracking
# ---------------------------------------------------------------------------

class _GuardedAttr:
    """Data descriptor replacing a guarded-by-annotated attribute.

    Per (object, attribute) it keeps the last write ``(generation, task)``
    and, per task, the generation current at that task's last *unprotected*
    read.  An unprotected write whose task read the attribute before a
    foreign write landed is a lost update."""

    def __init__(self, cls_name: str, name: str, lock_name: str):
        self.cls_name = cls_name
        self.name = name
        self.lock_name = lock_name
        self.slot = "__ss_val_" + name
        self.meta = "__ss_meta_" + name

    def _meta(self, obj: Any) -> dict:
        m = obj.__dict__.get(self.meta)
        if m is None:
            m = obj.__dict__[self.meta] = {"last_write": None, "reads": {}}
        return m

    def _lock(self, obj: Any) -> Optional[TrackedLock]:
        lk = obj.__dict__.get("__ss_lock_" + self.lock_name)
        return lk if isinstance(lk, TrackedLock) else None

    def __get__(self, obj: Any, objtype: Any = None) -> Any:
        if obj is None:
            return self
        try:
            val = obj.__dict__[self.slot]
        except KeyError:
            raise AttributeError(self.name) from None
        lock = self._lock(obj)
        if lock is None or not lock.held_by_current():
            self._meta(obj)["reads"][_task_key()] = _gen
        return val

    def __set__(self, obj: Any, value: Any) -> None:
        global _gen
        meta = self._meta(obj)
        lock = self._lock(obj)
        held = lock is not None and lock.held_by_current()
        tid = _task_key()
        if not held:
            last = meta["last_write"]
            my_read = meta["reads"].get(tid)
            if (last is not None and my_read is not None
                    and last[1] != tid and last[0] > my_read):
                _reports.append(Report(
                    kind="lost-update", cls=self.cls_name, attr=self.name,
                    lock=self.lock_name, writer_task=_task_label(),
                    other_task=f"task#{last[1]}",
                    detail=(f"write at gen {_gen + 1} is based on a read from "
                            f"gen {my_read}, but a foreign write landed at "
                            f"gen {last[0]} in between — hold "
                            f"`async with self.{self.lock_name}` across the "
                            f"read and the write"),
                ))
            if lock is not None and lock.locked() and lock._owner not in (0, tid):
                _reports.append(Report(
                    kind="write-under-foreign-lock", cls=self.cls_name,
                    attr=self.name, lock=self.lock_name,
                    writer_task=_task_label(),
                    other_task=f"task#{lock._owner}",
                    detail=(f"unlocked write while another task holds "
                            f"{self.lock_name} — the guarded section it "
                            f"protects can no longer trust the attribute"),
                ))
        _gen += 1
        meta["last_write"] = (_gen, tid)
        # our own write supersedes our stale-read bookkeeping; other tasks'
        # read marks stay so *their* next unlocked write is attributable
        meta["reads"].pop(tid, None)
        obj.__dict__[self.slot] = value


# ---------------------------------------------------------------------------
# class instrumentation
# ---------------------------------------------------------------------------

def guarded_attrs(cls: type) -> dict[str, str]:
    """``attr -> lock`` pairs declared in ``cls``'s source via
    ``# cordum: guarded-by(<lock>)`` trailing an assignment."""
    try:
        src = inspect.getsource(cls)
    except (OSError, TypeError):  # built under exec / REPL: nothing to scan
        return {}
    return {m.group("attr"): m.group("lock") for m in _GUARD_RE.finditer(src)}


def instrument(cls: type) -> type:
    """Class decorator: installs tracking descriptors for every guarded-by
    declared attribute.  A no-op (returns ``cls`` unchanged) unless
    ``CORDUM_SYNC_SANITIZER=1`` — production pays nothing."""
    if not enabled():
        return cls
    pairs = guarded_attrs(cls)
    for attr, lock in pairs.items():
        setattr(cls, attr, _GuardedAttr(cls.__name__, attr, lock))
        if not isinstance(getattr(cls, lock, None), _LockAttr):
            setattr(cls, lock, _LockAttr(lock))
    return cls
