"""Batched text embedder: the context engine's TPU compute path.

The reference context engine is CPU string-ops only (``core/context/engine/
service.go``); the north star moves its embedding/window ops onto the TPU
worker pool (BASELINE.json: "context-engine embeds/sec" is a headline
metric).  This model is that path: a small transformer encoder with mean
pooling and L2 normalization, fed by a deterministic hashing tokenizer (no
external vocab files — embeddings are for similarity/recall inside the
control plane, not for generation).

TPU-first: bfloat16 params, batch-only sharding (``dp``; embedding batches
are wide and the model is small, so data parallel over the slice is the
right mapping — tensor parallel would waste ICI on tiny matmuls), static
``max_len`` so XLA compiles one program per batch bucket.
"""
from __future__ import annotations

import hashlib
import math
import re
from dataclasses import dataclass
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

Params = dict


@dataclass(frozen=True)
class EmbedderConfig:
    vocab_size: int = 32768  # hash buckets
    d_model: int = 256
    n_layers: int = 4
    n_heads: int = 4
    d_ff: int = 1024
    max_len: int = 128
    dtype: Any = jnp.bfloat16


_TOKEN_RE = re.compile(r"[a-z0-9]+|[^\sa-z0-9]")


def tokenize(text: str, cfg: EmbedderConfig) -> list[int]:
    """Deterministic hashing tokenizer: lowercase word/punct split, each
    token hashed into [2, vocab); 0 = pad, 1 = CLS."""
    toks = _TOKEN_RE.findall(text.lower())[: cfg.max_len - 1]
    ids = [1]
    for t in toks:
        h = int.from_bytes(hashlib.blake2b(t.encode(), digest_size=4).digest(), "big")
        ids.append(2 + h % (cfg.vocab_size - 2))
    return ids


def token_count(text: str, cfg: EmbedderConfig) -> int:
    """Exact tokenized length of ``text`` (CLS included, capped at max_len)
    without building the row — the micro-batcher's length-bucket key."""
    return min(1 + len(_TOKEN_RE.findall(text.lower())), cfg.max_len)


def batch_tokenize(
    texts: Sequence[str], cfg: EmbedderConfig, *, max_len: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """(ids [B, L] int32, mask [B, L] float32); ``L`` = ``max_len`` (bucket
    length, capped at the model max) or the model max when 0."""
    length = min(max_len, cfg.max_len) if max_len else cfg.max_len
    b = len(texts)
    ids = np.zeros((b, length), np.int32)
    mask = np.zeros((b, length), np.float32)
    for i, t in enumerate(texts):
        row = tokenize(t, cfg)[:length]
        ids[i, : len(row)] = row
        mask[i, : len(row)] = 1.0
    return ids, mask


def init_params(key: jax.Array, cfg: EmbedderConfig) -> Params:
    keys = jax.random.split(key, cfg.n_layers + 2)
    d, f = cfg.d_model, cfg.d_ff

    def dense(k, shape, scale_dim):
        return (jax.random.normal(k, shape, jnp.float32) / math.sqrt(scale_dim)).astype(cfg.dtype)

    layers = []
    for i in range(cfg.n_layers):
        lk = jax.random.split(keys[i], 6)
        layers.append(
            {
                "norm1": jnp.ones((d,), cfg.dtype),
                "wqkv": dense(lk[0], (d, 3 * d), d),
                "wo": dense(lk[1], (d, d), d),
                "norm2": jnp.ones((d,), cfg.dtype),
                "w1": dense(lk[2], (d, f), d),
                "w2": dense(lk[3], (f, d), f),
            }
        )
    return {
        "embed": dense(keys[-2], (cfg.vocab_size, d), d),
        "pos": dense(keys[-1], (cfg.max_len, d), d),
        "layers": layers,
        "final_norm": jnp.ones((d,), cfg.dtype),
    }


def _layer_norm(x, w, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def forward(params: Params, ids: jax.Array, mask: jax.Array, cfg: EmbedderConfig) -> jax.Array:
    """[B, max_len] ids + mask → [B, d_model] L2-normalized embeddings."""
    b, t = ids.shape
    h, hd = cfg.n_heads, cfg.d_model // cfg.n_heads
    x = params["embed"][ids] + params["pos"][None, :t]
    attn_bias = jnp.where(mask[:, None, None, :] > 0, 0.0, -1e30).astype(jnp.float32)
    for layer in params["layers"]:
        y = _layer_norm(x, layer["norm1"])
        qkv = (y @ layer["wqkv"]).reshape(b, t, 3, h, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / math.sqrt(hd)
        probs = jax.nn.softmax(scores + attn_bias, axis=-1).astype(x.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, t, cfg.d_model)
        x = x + attn @ layer["wo"]
        y = _layer_norm(x, layer["norm2"])
        x = x + jax.nn.gelu(y @ layer["w1"]) @ layer["w2"]
    x = _layer_norm(x, params["final_norm"]).astype(jnp.float32)
    pooled = jnp.sum(x * mask[..., None], axis=1) / jnp.maximum(
        jnp.sum(mask, axis=1, keepdims=True), 1.0
    )
    return pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-6)


class Embedder:
    """Convenience wrapper holding params + a jitted forward, with optional
    dp sharding over a mesh."""

    def __init__(self, cfg: EmbedderConfig | None = None, *, seed: int = 0, mesh=None):
        self.cfg = cfg or EmbedderConfig()
        self.params = init_params(jax.random.PRNGKey(seed), self.cfg)
        self.mesh = mesh
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(mesh, P())
            self.params = jax.tree.map(lambda x: jax.device_put(x, repl), self.params)
            self._data_sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
        else:
            self._data_sharding = None
        self._fwd = jax.jit(lambda p, i, m: forward(p, i, m, self.cfg))

    def embed(self, texts: Sequence[str]) -> np.ndarray:
        ids, mask = batch_tokenize(texts, self.cfg)
        return self.embed_tokens(ids, mask)

    def embed_tokens(self, ids: np.ndarray, mask: np.ndarray) -> np.ndarray:
        """Forward pre-tokenized (already padded/bucketed) rows — the
        micro-batcher's entry point; ``embed`` is tokenizer + this."""
        b = ids.shape[0]
        if self._data_sharding is not None:
            pad = -b % self.mesh.devices.size
            if pad:
                ids = np.pad(ids, ((0, pad), (0, 0)))
                mask = np.pad(mask, ((0, pad), (0, 0)))
            ids = jax.device_put(ids, self._data_sharding)
            mask = jax.device_put(mask, self._data_sharding)
        out = np.asarray(self._fwd(self.params, jnp.asarray(ids), jnp.asarray(mask)))
        return out[:b]
