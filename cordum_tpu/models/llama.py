"""Llama-family decoder, TPU-first.

This is the flagship model the TPU worker executes for inference jobs
(BASELINE.json config #5: "Llama-3-8B JAX inference step behind safety-kernel
REQUIRE_APPROVAL").  Design choices for the MXU/ICI:

  * functional pytree params + pure ``forward`` — everything jits, no
    framework indirection; params default to bfloat16 (MXU-native)
  * GQA attention with RoPE, RMSNorm, SwiGLU — Llama-3 architecture family
  * sharding by annotation: :func:`param_specs` gives the Megatron-style
    tensor-parallel layout (column-parallel qkv/gate, row-parallel
    out/down), activations are constrained to ``(dp, sp, ·)`` so long
    sequences shard over the ``sp`` axis; XLA GSPMD inserts the ICI
    collectives (all-gather for KV over ``sp``, psum for row-parallel
    matmuls) — no hand-written NCCL-style code, per the scaling-book recipe
  * static shapes, ``lax``-friendly: causal mask built with iota/compare,
    no data-dependent Python control flow
"""
from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import AXIS_DP, AXIS_SP, AXIS_TP

Params = dict


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 4
    d_ff: int = 1536
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    # long-context: ring attention over `sp` (K/V rotate via ppermute, no
    # device ever holds the full sequence) instead of the KV all-gather
    use_ring_attention: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls(
            vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, d_ff=14336, rope_theta=500000.0, max_seq_len=8192,
        )

    @classmethod
    def tiny(cls) -> "LlamaConfig":
        return cls(vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=128, max_seq_len=128)


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------


def init_params(key: jax.Array, cfg: LlamaConfig) -> Params:
    keys = jax.random.split(key, cfg.n_layers + 2)
    d, h, kvh, hd, f = cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.d_ff

    def dense(k, shape, scale_dim):
        return (jax.random.normal(k, shape, jnp.float32) / math.sqrt(scale_dim)).astype(cfg.dtype)

    layers = []
    for i in range(cfg.n_layers):
        lk = jax.random.split(keys[i], 7)
        layers.append(
            {
                "attn_norm": jnp.ones((d,), cfg.dtype),
                "wq": dense(lk[0], (d, h * hd), d),
                "wk": dense(lk[1], (d, kvh * hd), d),
                "wv": dense(lk[2], (d, kvh * hd), d),
                "wo": dense(lk[3], (h * hd, d), h * hd),
                "mlp_norm": jnp.ones((d,), cfg.dtype),
                "w_gate": dense(lk[4], (d, f), d),
                "w_up": dense(lk[5], (d, f), d),
                "w_down": dense(lk[6], (f, d), f),
            }
        )
    return {
        "embed": dense(keys[-2], (cfg.vocab_size, d), d),
        "layers": layers,
        "final_norm": jnp.ones((d,), cfg.dtype),
        "lm_head": dense(keys[-1], (d, cfg.vocab_size), d),
    }


def param_specs(cfg: LlamaConfig) -> Params:
    """Megatron-style TP layout as a PartitionSpec pytree."""
    layer = {
        "attn_norm": P(),
        "wq": P(None, AXIS_TP),
        "wk": P(None, AXIS_TP),
        "wv": P(None, AXIS_TP),
        "wo": P(AXIS_TP, None),
        "mlp_norm": P(),
        "w_gate": P(None, AXIS_TP),
        "w_up": P(None, AXIS_TP),
        "w_down": P(AXIS_TP, None),
    }
    return {
        "embed": P(AXIS_TP, None),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
        "final_norm": P(),
        "lm_head": P(None, AXIS_TP),
    }


def shard_params(params: Params, cfg: LlamaConfig, mesh: Mesh) -> Params:
    specs = param_specs(cfg)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs,
        is_leaf=lambda x: isinstance(x, jnp.ndarray) or dataclasses.is_dataclass(x),
    )


#: KV page arenas shard by attention head — axis 3 of
#: ``[L, num_pages, page_size, kvh, hd]`` — matching the column-parallel
#: wk/wv layout, so the ragged step's page writes and gathers stay local to
#: each TP rank (docs/SERVING.md §Sharded serving).
KV_ARENA_SPEC = P(None, None, None, AXIS_TP, None)


def shard_serving_state(
    params: Params, k_pages: jax.Array, v_pages: jax.Array,
    cfg: LlamaConfig, mesh: Mesh,
) -> tuple[Params, jax.Array, jax.Array]:
    """Place serving state onto a TP mesh: weights per :func:`param_specs`,
    both page arenas split over ``kvh`` (:data:`KV_ARENA_SPEC`).  On a
    size-1 mesh (the CPU-CI full-replica fallback) every spec degenerates
    to a trivial placement and this is a no-op device_put."""
    arena = NamedSharding(mesh, KV_ARENA_SPEC)
    return (
        shard_params(params, cfg, mesh),
        jax.device_put(k_pages, arena),
        jax.device_put(v_pages, arena),
    )


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * rms).astype(x.dtype) * w


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding; x: [B, T, H, Dh], positions: [B, T]."""
    hd = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, hd, 2, dtype=jnp.float32) / hd))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, T, Dh/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _attention(q, k, v, cfg: LlamaConfig, *, causal: bool = True, q_offset=None):
    """SDPA with GQA head expansion; fp32 softmax accumulation."""
    b, tq, h, hd = q.shape
    tk = k.shape[1]
    rep = cfg.n_heads // cfg.n_kv_heads
    k = jnp.repeat(k, rep, axis=2)
    v = jnp.repeat(v, rep, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / math.sqrt(hd)
    if causal:
        q_pos = jnp.arange(tq)[:, None] if q_offset is None else q_offset[:, :, None]
        k_pos = jnp.arange(tk)[None, :]
        mask = q_pos >= k_pos  # [Tq, Tk] or [B, Tq, Tk]
        if mask.ndim == 2:
            mask = mask[None, None, :, :]
        else:
            mask = mask[:, None, :, :]
        scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _block(x, layer, cfg: LlamaConfig, positions, constrain, mesh=None):
    b, t, d = x.shape
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    attn_in = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = (attn_in @ layer["wq"]).reshape(b, t, h, hd)
    k = (attn_in @ layer["wk"]).reshape(b, t, kvh, hd)
    v = (attn_in @ layer["wv"]).reshape(b, t, kvh, hd)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    if cfg.use_ring_attention and mesh is not None and mesh.shape.get(AXIS_SP, 1) > 1:
        # ring flavor: K/V never materialize the full sequence anywhere —
        # chunks rotate the sp ring with an online softmax (long contexts)
        from ..ops.ring_attention import ring_attention

        attn = ring_attention(q, k, v, mesh)
    else:
        # context parallelism (all-gather flavor): Q stays sequence-sharded
        # over `sp`; K/V are constrained to full sequence, so GSPMD inserts
        # the all-gather over the sp axis
        k = constrain(k, P(AXIS_DP, None, None, None))
        v = constrain(v, P(AXIS_DP, None, None, None))
        attn = _attention(q, k, v, cfg, q_offset=positions)
    x = x + (attn.reshape(b, t, h * hd) @ layer["wo"])
    x = constrain(x, P(AXIS_DP, AXIS_SP, None))

    mlp_in = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    gate = jax.nn.silu(mlp_in @ layer["w_gate"])
    up = mlp_in @ layer["w_up"]
    x = x + ((gate * up) @ layer["w_down"])
    x = constrain(x, P(AXIS_DP, AXIS_SP, None))
    return x


def forward(
    params: Params,
    tokens: jax.Array,
    cfg: LlamaConfig,
    *,
    mesh: Optional[Mesh] = None,
    positions: Optional[jax.Array] = None,
) -> jax.Array:
    """Logits for next-token prediction; tokens: [B, T] int32 → [B, T, V]."""
    if mesh is not None and AXIS_SP in mesh.axis_names:
        def constrain(x, spec):
            return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    else:
        def constrain(x, spec):  # single-device / no-mesh path
            return x

    b, t = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    x = params["embed"][tokens]  # gather; embed sharded over tp on vocab dim
    x = constrain(x, P(AXIS_DP, AXIS_SP, None))
    for layer in params["layers"]:
        x = _block(x, layer, cfg, positions, constrain, mesh=mesh)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return x @ params["lm_head"]


# ---------------------------------------------------------------------------
# paged KV cache: the ragged mixed prefill+decode entry (serving subsystem)
# ---------------------------------------------------------------------------
#
# The serving path (cordum_tpu/serving) holds the conversation KV cache as a
# block-granular page arena shaped [L, num_pages, page_size, kvh, hd]; a
# sequence's logical position ``p`` lives at page ``page_table[p // ps]``,
# slot ``p % ps`` (the Ragged Paged Attention layout, PAPERS.md — here a
# gather-based jnp formulation that runs anywhere; a Pallas kernel that walks
# the page table in VMEM is the TPU upgrade path).  Page 0 is the NULL page:
# padding rows and padded page-table tails point at it, so their writes land
# harmlessly in slots no live sequence ever attends to (the causal mask cuts
# every k_pos > position).
#
# Page aliasing invariants (docs/SERVING.md §Prefix cache and tiering): the
# attention gather walks ONLY the row of ``page_tables`` handed to it for
# each sequence, so two tables may point at the SAME physical page and the
# kernel cannot tell — physical-page aliasing is free here, which is what
# makes copy-on-write prefix sharing a pure control-plane feature.  The
# contract the serving layer must keep for an aliased page:
#   * read-only — a write lands in every table that maps the page, so the
#     engine CoW-copies (``copy_kv_page``) before any position inside a
#     shared page is written;
#   * identical logical prefix — a page's K/V depends on every position
#     before it (attention), so a page may only be shared between
#     sequences whose token ids agree on [0, end_of_page).
# The allocator's refcount table (serving/pager.py) enforces the lifetime
# half: an aliased page cannot return to the free list while any table
# still maps it.


def init_kv_pages(
    cfg: LlamaConfig, num_pages: int, page_size: int, dtype: Any = None
) -> tuple[jax.Array, jax.Array]:
    """Preallocated page arenas for K and V: [L, num_pages, page_size, kvh, hd]."""
    shape = (cfg.n_layers, num_pages, page_size, cfg.n_kv_heads, cfg.head_dim)
    dt = dtype or cfg.dtype
    return jnp.zeros(shape, dt), jnp.zeros(shape, dt)


# One jitted program each for reading/writing a single arena page with the
# page INDEX as a traced operand: every page of every migration reuses the
# same two executables (a python-int index baked into an eager slice would
# compile one executable per (page, length) pair — ~150ms per page hop).
@jax.jit
def _gather_page(pages: jax.Array, pid: jax.Array) -> jax.Array:
    return jax.lax.dynamic_index_in_dim(pages, pid, axis=1, keepdims=False)


@jax.jit
def _scatter_page(pages: jax.Array, pid: jax.Array, block: jax.Array) -> jax.Array:
    return jax.lax.dynamic_update_index_in_dim(pages, block, pid, axis=1)


@jax.jit
def _copy_page(pages: jax.Array, src: jax.Array, dst: jax.Array) -> jax.Array:
    return jax.lax.dynamic_update_index_in_dim(
        pages,
        jax.lax.dynamic_index_in_dim(pages, src, axis=1, keepdims=False),
        dst, axis=1,
    )


def copy_kv_page(
    k_pages: jax.Array, v_pages: jax.Array, src: int, dst: int
) -> tuple[jax.Array, jax.Array]:
    """Duplicate one arena page on device — the copy-on-write half of
    prefix sharing (docs/SERVING.md §Prefix cache and tiering).  Both
    indices are traced operands, so every CoW of every session reuses the
    same cached executable; the copy never leaves the device (no host
    round trip, unlike the migration gather/scatter pair)."""
    return _copy_page(k_pages, src, dst), _copy_page(v_pages, src, dst)


def gather_kv_pages(
    k_pages: jax.Array,
    v_pages: jax.Array,
    page_ids: list[int],
    used: list[int],
) -> list[tuple[Any, Any]]:
    """Read pages out of the arena at their TRUE lengths — the export half
    of live KV-page migration (docs/PROTOCOL.md §Page transfer).

    ``page_ids[i]`` is an arena page index and ``used[i]`` how many of its
    ``page_size`` token slots hold live positions (only the sequence's last
    page is partial).  The device read is always the full page (static
    shape → one cached program); the trim to ``used`` happens host-side so
    only live slots ride the wire.  Returns per-page ``(k, v)`` numpy
    arrays of shape ``[L, used, kvh, hd]`` upcast to float32 — an exact
    round trip for the bf16/fp32 arenas, and a wire format the receiver
    can cast back without knowing the sender's dtype."""
    import numpy as np

    out = []
    for pid, n in zip(page_ids, used):
        k = np.asarray(_gather_page(k_pages, pid))[:, :n].astype(np.float32)
        v = np.asarray(_gather_page(v_pages, pid))[:, :n].astype(np.float32)
        out.append((k, v))
    return out


def scatter_kv_pages(
    k_pages: jax.Array,
    v_pages: jax.Array,
    page_ids: list[int],
    blocks: list[tuple[Any, Any]],
) -> tuple[jax.Array, jax.Array]:
    """Write migrated pages into the arena at their true lengths — the
    import half of live KV-page migration.  ``blocks[i]`` is the
    ``(k, v)`` pair :func:`gather_kv_pages` produced for ``page_ids[i]``.
    Each write pads its block to the full page (static shape → one cached
    program); slots past the true length are zero-filled, which is inert —
    the causal mask makes unwritten positions unreachable, and the resumed
    session overwrites them as it decodes.  Returns the updated arenas."""
    import numpy as np

    dt = k_pages.dtype
    ps = k_pages.shape[2]
    for pid, (k, v) in zip(page_ids, blocks):
        n = k.shape[1]
        if n < ps:
            pad = [(0, 0), (0, ps - n), (0, 0), (0, 0)]
            k = np.pad(np.asarray(k), pad)
            v = np.pad(np.asarray(v), pad)
        k_pages = _scatter_page(k_pages, pid, jnp.asarray(k, dt))
        v_pages = _scatter_page(v_pages, pid, jnp.asarray(v, dt))
    return k_pages, v_pages


def ragged_step(
    params: Params,
    k_pages: jax.Array,
    v_pages: jax.Array,
    tokens: jax.Array,
    positions: jax.Array,
    page_tables: jax.Array,
    token_seq: jax.Array,
    out_idx: jax.Array,
    cfg: LlamaConfig,
    *,
    sample_logits: bool = True,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One ragged mixed prefill+decode step over the paged KV cache — the
    Ragged Paged Attention entry point (PAPERS.md): a single XLA program
    serves any mix of prefill chunks and decode steps over arbitrary
    per-sequence lengths.

    The batch dimension is **tokens, not sequences**: a decode step
    contributes one token, a prefill chunk contributes its whole slice, and
    they ride the same flat buffer.

    tokens: [T] int32 flat token buffer (decode last-tokens and prefill
    chunk tokens interleaved; tail padded with 0s mapped to the padding
    row); positions: [T] int32 global sequence position of each token (==
    the page slot it writes); page_tables: [S+1, P] int32 per-sequence page
    tables — row S is the all-null padding row; token_seq: [T] int32 row of
    ``page_tables`` each token belongs to (padding tokens → S); out_idx:
    [S] int32 index into the token buffer of each sequence's last fed token
    (the sampling position; unused rows point anywhere).  Returns
    (next_tokens [T] int32 — the next-token argmax after every fed buffer
    position; a sequence's sample is row ``out_idx[s]``, a draft row's
    per-position verification votes are its contiguous token slots —
    k_pages, v_pages).

    Shape discipline is the whole point: every operand has a static shape
    regardless of how many sequences are live or how long each one is, so
    the program compiles exactly ONCE — no prompt-length buckets, no batch
    buckets, no recompile cliff when sessions join or leave.  Raggedness is
    expressed through the metadata: each token writes its K/V at
    ``(page_tables[token_seq[t]][positions[t] // ps], positions[t] % ps)``
    *before* the gather, then attends to its own sequence's pages under the
    causal mask ``k_pos <= position`` — in-chunk tokens see each other
    exactly as a full-sequence forward would, padding rows park on the null
    page, and no token can reach another sequence's pages because the
    gather walks only its own page-table row.  (This is the gather-based
    jnp formulation that runs anywhere; a Pallas kernel walking the page
    table in VMEM is the TPU upgrade path.)

    ``sample_logits`` is a STATIC flag for serving-gang followers
    (docs/SERVING.md §Sharded serving): rank 0 alone owns sampling, so
    follower ranks compile with ``sample_logits=False`` and get a program
    whose lm_head projection + argmax are dead-code-eliminated — they still
    produce byte-identical K/V arena updates (the writes depend only on the
    transformer stack), but return an all-zeros token buffer nothing
    reads."""
    t_buf = tokens.shape[0]
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    ps = k_pages.shape[2]
    pos2 = positions[:, None]  # [T, 1]
    pt_tok = page_tables[token_seq]  # [T, P] — each token's own table row
    page_idx = jnp.take_along_axis(pt_tok, pos2 // ps, axis=1)[:, 0]  # [T]
    slot = positions % ps
    x = params["embed"][tokens][:, None, :]  # [T, 1, d]
    for li, layer in enumerate(params["layers"]):
        attn_in = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = (attn_in @ layer["wq"]).reshape(t_buf, 1, h, hd)
        k = (attn_in @ layer["wk"]).reshape(t_buf, 1, kvh, hd)
        v = (attn_in @ layer["wv"]).reshape(t_buf, 1, kvh, hd)
        q = rope(q, pos2, cfg.rope_theta)
        k = rope(k, pos2, cfg.rope_theta)
        # write EVERY token's K/V before the gather: a prefill chunk's later
        # tokens must attend to its earlier ones within the same call (the
        # causal mask cuts the other direction), and a decode token must
        # attend to itself
        k_pages = k_pages.at[li, page_idx, slot].set(k[:, 0])
        v_pages = v_pages.at[li, page_idx, slot].set(v[:, 0])
        kc = k_pages[li][pt_tok].reshape(t_buf, -1, kvh, hd)  # [T, P*ps, ...]
        vc = v_pages[li][pt_tok].reshape(t_buf, -1, kvh, hd)
        attn = _attention(q, kc, vc, cfg, q_offset=pos2)
        x = x + (attn.reshape(t_buf, 1, h * hd) @ layer["wo"])
        mlp_in = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(mlp_in @ layer["w_gate"])
        up = mlp_in @ layer["w_up"]
        x = x + ((gate * up) @ layer["w_down"])
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    # lm_head over EVERY buffer row: speculative verification needs the
    # next-token prediction at each fed draft position, not just the
    # sequence-final one — a draft row's k+1 per-position argmaxes are the
    # accept-prefix votes (docs/SERVING.md §Speculative decoding).  The
    # per-row argmax at ``out_idx`` positions is unchanged math, so
    # non-draft sampling reads ``preds[out_idx]`` and gets exactly the
    # tokens the sequence-final projection produced; padding rows project
    # too but nothing reads them.
    if not sample_logits:
        # follower ranks: K/V writes above are the whole job — skip the
        # [T, V] projection entirely (static flag → XLA never emits it)
        return jnp.zeros((t_buf,), jnp.int32), k_pages, v_pages
    logits = x[:, 0] @ params["lm_head"]  # [T, V]
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), k_pages, v_pages


def loss_fn(params: Params, tokens: jax.Array, cfg: LlamaConfig, *, mesh=None) -> jax.Array:
    """Next-token cross entropy over all positions but the last."""
    logits = forward(params, tokens, cfg, mesh=mesh).astype(jnp.float32)
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


# ---------------------------------------------------------------------------
# training step (used by the multi-chip dry run + training jobs)
# ---------------------------------------------------------------------------


def make_train_step(cfg: LlamaConfig, mesh: Mesh, optimizer=None):
    """Build a jitted SPMD train step: params sharded per :func:`param_specs`,
    batch over ``(dp, sp)``; gradients/optimizer states inherit param
    shardings via jit output shardings."""
    import optax

    opt = optimizer or optax.adamw(3e-4, weight_decay=0.01)
    pspecs = param_specs(cfg)
    param_shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs)
    batch_sharding = NamedSharding(mesh, P(AXIS_DP, AXIS_SP))

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(lambda p: loss_fn(p, tokens, cfg, mesh=mesh))(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    from ..parallel.compat import donated_train_step

    jstep = donated_train_step(
        step, mesh=mesh, param_shardings=param_shardings, batch_sharding=batch_sharding
    )

    def init(key):
        params = init_params(key, cfg)
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, pspecs
        )
        opt_state = opt.init(params)
        return params, opt_state

    return init, jstep
