"""Mixture-of-Experts decoder blocks with expert parallelism (``ep``).

Extends the Llama-family decoder (models/llama.py) with a switch-style MoE
FFN: top-k routing, capacity-bounded one-hot dispatch (static shapes — no
gather/scatter with data-dependent sizes, so XLA tiles everything onto the
MXU), experts sharded over the ``ep`` mesh axis so expert FFN weights live
``n_experts/ep`` per device and token dispatch rides ICI all-to-alls that
GSPMD inserts from the shardings.

Router/dispatch design (compiler-friendly):
  * router logits → top-k expert ids + weights
  * position-in-expert computed with a cumulative-sum over the one-hot
    dispatch mask; tokens beyond ``capacity`` drop to the residual path
  * dispatch/combine as einsums against the one-hot mask (dense, static)
"""
from __future__ import annotations

import math
from dataclasses import dataclass, replace
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import AXIS_DP, AXIS_EP, AXIS_SP, AXIS_TP
from . import llama as llama_mod
from .llama import LlamaConfig, rms_norm


@dataclass(frozen=True)
class MoEConfig:
    base: LlamaConfig = LlamaConfig.tiny()
    n_experts: int = 4
    top_k: int = 2
    capacity_factor: float = 1.25
    d_expert: int = 0  # 0 → base.d_ff

    @property
    def d_ff(self) -> int:
        return self.d_expert or self.base.d_ff

    @classmethod
    def tiny(cls) -> "MoEConfig":
        return cls(base=LlamaConfig.tiny(), n_experts=4, top_k=2)


def init_moe_layer(key: jax.Array, cfg: MoEConfig) -> dict:
    d, f, e = cfg.base.d_model, cfg.d_ff, cfg.n_experts
    ks = jax.random.split(key, 4)

    def dense(k, shape, scale):
        return (jax.random.normal(k, shape, jnp.float32) / math.sqrt(scale)).astype(cfg.base.dtype)

    return {
        "router": dense(ks[0], (d, e), d).astype(jnp.float32),  # fp32 routing
        "w_gate": dense(ks[1], (e, d, f), d),
        "w_up": dense(ks[2], (e, d, f), d),
        "w_down": dense(ks[3], (e, f, d), f),
    }


def moe_layer_specs() -> dict:
    """Experts sharded over ep; expert-internal FFN dim over tp."""
    return {
        "router": P(),
        "w_gate": P(AXIS_EP, None, AXIS_TP),
        "w_up": P(AXIS_EP, None, AXIS_TP),
        "w_down": P(AXIS_EP, AXIS_TP, None),
    }


def moe_ffn(x: jax.Array, layer: dict, cfg: MoEConfig, constrain=lambda v, s: v):
    """x: [B, T, D] → [B, T, D] plus aux losses dict."""
    b, t, d = x.shape
    e, k = cfg.n_experts, cfg.top_k
    n = b * t
    tokens = x.reshape(n, d)

    logits = tokens.astype(jnp.float32) @ layer["router"]  # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    topk_p, topk_idx = jax.lax.top_k(probs, k)  # [N, k]
    topk_p = topk_p / jnp.maximum(jnp.sum(topk_p, axis=-1, keepdims=True), 1e-9)

    capacity = max(1, int(cfg.capacity_factor * n * k / e))
    # one-hot dispatch with capacity: mask[N, k, E]
    onehot = jax.nn.one_hot(topk_idx, e, dtype=jnp.float32)  # [N, k, E]
    # position of each (token, slot) within its expert queue
    flat = onehot.reshape(n * k, e)
    pos = jnp.cumsum(flat, axis=0) - flat  # positions start at 0
    pos = pos.reshape(n, k, e)
    within_cap = (pos < capacity).astype(jnp.float32) * onehot
    pos_idx = jnp.einsum("nke,nke->nk", pos, within_cap).astype(jnp.int32)  # [N,k]
    cap_onehot = jax.nn.one_hot(pos_idx, capacity, dtype=jnp.float32)  # [N,k,C]
    # dispatch tensor [N, k, E, C] → combine weights folded in later
    dispatch = within_cap[..., None] * cap_onehot[:, :, None, :]
    # expert inputs [E, C, D]
    expert_in = jnp.einsum("nkec,nd->ecd", dispatch, tokens.astype(jnp.float32)).astype(x.dtype)
    expert_in = constrain(expert_in, P(AXIS_EP, None, None))
    # expert FFN (batched over E; E sharded over ep)
    gate = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, layer["w_gate"]))
    up = jnp.einsum("ecd,edf->ecf", expert_in, layer["w_up"])
    out = jnp.einsum("ecf,efd->ecd", gate * up, layer["w_down"])  # [E, C, D]
    out = constrain(out, P(AXIS_EP, None, None))
    # combine back to tokens with routing weights
    combine = dispatch * topk_p[..., None, None]  # [N, k, E, C]
    y = jnp.einsum("nkec,ecd->nd", combine.astype(jnp.float32), out.astype(jnp.float32))

    # aux load-balancing loss (Switch-style): E * sum_e f_e * p_e
    me = jnp.mean(probs, axis=0)  # mean router prob per expert
    ce = jnp.mean(onehot.sum(1), axis=0)  # fraction of tokens per expert
    aux_loss = e * jnp.sum(me * ce)
    return y.reshape(b, t, d).astype(x.dtype), {"moe_aux_loss": aux_loss}


# ---------------------------------------------------------------------------
# full MoE decoder: llama attention + MoE FFN every layer
# ---------------------------------------------------------------------------


def init_params(key: jax.Array, cfg: MoEConfig) -> dict:
    base_params = llama_mod.init_params(key, cfg.base)
    moe_keys = jax.random.split(jax.random.fold_in(key, 7), cfg.base.n_layers)
    for i, layer in enumerate(base_params["layers"]):
        layer.pop("w_gate", None)
        layer.pop("w_up", None)
        layer.pop("w_down", None)
        layer["moe"] = init_moe_layer(moe_keys[i], cfg)
    return base_params


def param_specs(cfg: MoEConfig) -> dict:
    specs = llama_mod.param_specs(cfg.base)
    for layer in specs["layers"]:
        layer.pop("w_gate", None)
        layer.pop("w_up", None)
        layer.pop("w_down", None)
        layer["moe"] = moe_layer_specs()
    return specs


def forward(params: dict, tokens: jax.Array, cfg: MoEConfig, *, mesh: Optional[Mesh] = None):
    """[B, T] → (logits [B, T, V], aux {moe_aux_loss})."""
    base = cfg.base
    if mesh is not None:
        def constrain(v, spec):
            return jax.lax.with_sharding_constraint(v, NamedSharding(mesh, spec))
    else:
        def constrain(v, spec):
            return v

    b, t = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    x = params["embed"][tokens]
    x = constrain(x, P(AXIS_DP, AXIS_SP, None))
    aux_total = jnp.zeros((), jnp.float32)
    for layer in params["layers"]:
        attn_in = rms_norm(x, layer["attn_norm"], base.norm_eps)
        h, kvh, hd = base.n_heads, base.n_kv_heads, base.head_dim
        q = (attn_in @ layer["wq"]).reshape(b, t, h, hd)
        k = (attn_in @ layer["wk"]).reshape(b, t, kvh, hd)
        v = (attn_in @ layer["wv"]).reshape(b, t, kvh, hd)
        q = llama_mod.rope(q, positions, base.rope_theta)
        k = llama_mod.rope(k, positions, base.rope_theta)
        k = constrain(k, P(AXIS_DP, None, None, None))
        v = constrain(v, P(AXIS_DP, None, None, None))
        attn = llama_mod._attention(q, k, v, base, q_offset=positions)
        x = x + attn.reshape(b, t, h * hd) @ layer["wo"]
        x = constrain(x, P(AXIS_DP, AXIS_SP, None))
        ffn_in = rms_norm(x, layer["mlp_norm"], base.norm_eps)
        y, aux = moe_ffn(ffn_in, layer["moe"], cfg, constrain)
        aux_total = aux_total + aux["moe_aux_loss"]
        x = x + y
        x = constrain(x, P(AXIS_DP, AXIS_SP, None))
    x = rms_norm(x, params["final_norm"], base.norm_eps)
    return x @ params["lm_head"], {"moe_aux_loss": aux_total / max(1, base.n_layers)}


def loss_fn(params: dict, tokens: jax.Array, cfg: MoEConfig, *, mesh=None, aux_weight: float = 0.01):
    logits, aux = forward(params, tokens, cfg, mesh=mesh)
    logits = logits.astype(jnp.float32)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll) + aux_weight * aux["moe_aux_loss"]


def make_train_step(cfg: MoEConfig, mesh: Mesh, optimizer=None):
    import optax

    opt = optimizer or optax.adamw(3e-4, weight_decay=0.01)
    pspecs = param_specs(cfg)
    param_shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs)
    batch_sharding = NamedSharding(mesh, P(AXIS_DP, AXIS_SP))

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(lambda p: loss_fn(p, tokens, cfg, mesh=mesh))(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    from ..parallel.compat import donated_train_step

    jstep = donated_train_step(
        step, mesh=mesh, param_shardings=param_shardings, batch_sharding=batch_sharding
    )

    def init(key):
        params = init_params(key, cfg)
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, pspecs
        )
        return params, opt.init(params)

    return init, jstep
