"""Pipeline parallelism (``pp``): GPipe-style microbatch pipeline as a
shard_map program.

Each ``pp`` rank owns a contiguous stage of decoder layers (the stacked
per-stage params are sharded ``P('pp', ...)`` on their leading stage axis).
Microbatches stream through the ring: at every schedule tick each stage
applies its layers to the activation it holds, the last stage accumulates
logits/loss, and activations ``ppermute`` one hop down the pipeline — the
classic ``M + S - 1``-tick GPipe schedule with bubble ticks masked out.
``jax.grad`` differentiates straight through the ``ppermute`` chain, so the
backward pipeline falls out of autodiff (reverse permutes), no hand-written
schedule needed.

Composes with ``dp``: microbatch rows are sharded over ``dp`` and the loss
is averaged with a ``psum`` over both axes.  (``tp`` within a stage composes
via the same param-spec mechanism as models/llama.py; kept off in round 1
to keep the stage program small.)
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.compat import axis_size, shard_map_compat
from ..parallel.mesh import AXIS_DP, AXIS_PP
from .llama import LlamaConfig, rms_norm, rope


@dataclass(frozen=True)
class PipelineConfig:
    base: LlamaConfig = LlamaConfig.tiny()
    n_stages: int = 2
    n_microbatches: int = 2

    @property
    def layers_per_stage(self) -> int:
        assert self.base.n_layers % self.n_stages == 0, "n_layers must divide n_stages"
        return self.base.n_layers // self.n_stages


def init_params(key: jax.Array, cfg: PipelineConfig) -> dict:
    """Per-stage layer params stacked on a leading [n_stages, L/S] axis."""
    base = cfg.base
    d, h, kvh, hd, f = base.d_model, base.n_heads, base.n_kv_heads, base.head_dim, base.d_ff
    s, lps = cfg.n_stages, cfg.layers_per_stage
    ks = jax.random.split(key, 9)

    def dense(k, shape, scale):
        return (jax.random.normal(k, shape, jnp.float32) / math.sqrt(scale)).astype(base.dtype)

    def stack(k, shape, scale):
        return dense(k, (s, lps, *shape), scale)

    return {
        "embed": dense(ks[0], (base.vocab_size, d), d),
        "stages": {
            "attn_norm": jnp.ones((s, lps, d), base.dtype),
            "wq": stack(ks[1], (d, h * hd), d),
            "wk": stack(ks[2], (d, kvh * hd), d),
            "wv": stack(ks[3], (d, kvh * hd), d),
            "wo": stack(ks[4], (h * hd, d), h * hd),
            "mlp_norm": jnp.ones((s, lps, d), base.dtype),
            "w_gate": stack(ks[5], (d, f), d),
            "w_up": stack(ks[6], (d, f), d),
            "w_down": stack(ks[7], (f, d), f),
        },
        "final_norm": jnp.ones((d,), base.dtype),
        "lm_head": dense(ks[8], (d, base.vocab_size), d),
    }


def param_specs(cfg: PipelineConfig) -> dict:
    stage_spec = {k: P(AXIS_PP, *([None] * (3 if k.endswith("norm") else 4))[1:])
                  for k in ("attn_norm", "wq", "wk", "wv", "wo", "mlp_norm",
                            "w_gate", "w_up", "w_down")}
    # leading axis is the stage axis; norms are [S, L, D], weights [S, L, D, F]
    stage_spec = {
        k: (P(AXIS_PP, None, None) if k.endswith("norm") else P(AXIS_PP, None, None, None))
        for k in stage_spec
    }
    return {
        "embed": P(),
        "stages": stage_spec,
        "final_norm": P(),
        "lm_head": P(),
    }


def _stage_apply(stage_params: dict, x: jax.Array, positions: jax.Array, base: LlamaConfig) -> jax.Array:
    """Apply this stage's [L/S] layers to x: [mb, T, D] (scan over layers)."""

    def layer_step(h, layer):
        b, t, d = h.shape
        nh, kvh, hd = base.n_heads, base.n_kv_heads, base.head_dim
        attn_in = rms_norm(h, layer["attn_norm"], base.norm_eps)
        q = (attn_in @ layer["wq"]).reshape(b, t, nh, hd)
        k = (attn_in @ layer["wk"]).reshape(b, t, kvh, hd)
        v = (attn_in @ layer["wv"]).reshape(b, t, kvh, hd)
        q = rope(q, positions, base.rope_theta)
        k = rope(k, positions, base.rope_theta)
        rep = nh // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / math.sqrt(hd)
        mask = jnp.arange(t)[:, None] >= jnp.arange(t)[None, :]
        scores = jnp.where(mask[None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(h.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, t, nh * hd)
        h = h + attn @ layer["wo"]
        mlp_in = rms_norm(h, layer["mlp_norm"], base.norm_eps)
        h = h + (jax.nn.silu(mlp_in @ layer["w_gate"]) * (mlp_in @ layer["w_up"])) @ layer["w_down"]
        return h, None

    x, _ = jax.lax.scan(layer_step, x, stage_params)
    return x


def _pipeline_local(params: dict, tokens_mb: jax.Array, cfg: PipelineConfig,
                    *, pp_axis: str, dp_axis: str) -> tuple[jax.Array, jax.Array]:
    """Per-device body: tokens_mb [M, mb_local, T] → ([1,1] loss sum, [1,1]
    token count).  The cross-device reduction happens OUTSIDE the shard_map:
    claiming a replicated scalar output (out_specs=P()) requires replication
    tracking that older jax cannot prove through the fori_loop, so each
    device returns its mapped partial sums instead."""
    base = cfg.base
    s = axis_size(pp_axis)
    stage = jax.lax.axis_index(pp_axis)
    m, mb, t = tokens_mb.shape
    d = base.d_model
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (mb, t))
    # this device's stage params: stacked leading axis is already sharded to
    # size 1 under shard_map → squeeze it
    stage_params = jax.tree.map(lambda p: p[0], params["stages"])

    n_ticks = m + s - 1
    perm = [(i, (i + 1) % s) for i in range(s)]

    def tick(i, carry):
        recv, loss_sum, tok_count = carry
        # stage 0 injects microbatch i (when in range); others use recv
        mb_idx = jnp.clip(i, 0, m - 1)
        injected = params["embed"][jax.lax.dynamic_index_in_dim(tokens_mb, mb_idx, 0, keepdims=False)]
        x = jnp.where(stage == 0, injected.astype(base.dtype), recv)
        y = _stage_apply(stage_params, x, positions, base)
        # last stage: compute loss for the microbatch that just completed
        out_idx = i - (s - 1)
        valid_out = jnp.logical_and(stage == s - 1, out_idx >= 0)
        tgt_mb = jax.lax.dynamic_index_in_dim(
            tokens_mb, jnp.clip(out_idx, 0, m - 1), 0, keepdims=False
        )
        h = rms_norm(y, params["final_norm"], base.norm_eps)
        logits = (h @ params["lm_head"]).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        nll = -jnp.take_along_axis(logp, tgt_mb[:, 1:][..., None], axis=-1)[..., 0]
        # accumulate as [1,1] (never rank 0): scalar residuals of the grad
        # partial-eval are mishandled by older jax's shard_map
        valid = valid_out.astype(jnp.float32).reshape(1, 1)
        loss_sum = loss_sum + valid * jnp.sum(nll, keepdims=True)
        tok_count = tok_count + valid * float(nll.size)
        recv = jax.lax.ppermute(y, pp_axis, perm)
        return recv, loss_sum, tok_count

    recv0 = jnp.zeros((mb, t, d), base.dtype)
    zero11 = jnp.zeros((1, 1), jnp.float32)
    _, loss_sum, tok_count = jax.lax.fori_loop(
        0, n_ticks, tick, (recv0, zero11, zero11)
    )
    return loss_sum, tok_count


def make_loss_fn(cfg: PipelineConfig, mesh: Mesh, *, pp_axis: str = AXIS_PP, dp_axis: str = AXIS_DP):
    pspecs = param_specs(cfg)
    tok_spec = P(None, dp_axis, None)  # [M, mb, T], mb sharded over dp
    part_spec = P(dp_axis, pp_axis)  # per-device [1,1] partial sums

    def loss(params, tokens_mb):
        fn = shard_map_compat(
            partial(_pipeline_local, cfg=cfg, pp_axis=pp_axis, dp_axis=dp_axis),
            mesh=mesh,
            in_specs=(pspecs, tok_spec),
            out_specs=(part_spec, part_spec),
            check_vma=False,
        )
        loss_sums, tok_counts = fn(params, tokens_mb)
        return jnp.sum(loss_sums) / jnp.maximum(jnp.sum(tok_counts), 1.0)

    return loss


def make_train_step(cfg: PipelineConfig, mesh: Mesh, optimizer=None):
    import optax

    opt = optimizer or optax.adamw(3e-4)
    pspecs = param_specs(cfg)
    param_shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs)
    tok_sharding = NamedSharding(mesh, P(None, AXIS_DP, None))
    loss_fn = make_loss_fn(cfg, mesh)

    def step(params, opt_state, tokens_mb):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens_mb)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    from ..parallel.compat import donated_train_step

    jstep = donated_train_step(
        step, mesh=mesh, param_shardings=param_shardings, batch_sharding=tok_sharding
    )

    def init(key):
        params = init_params(key, cfg)
        params = jax.tree.map(
            lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)), params, pspecs
        )
        return params, opt.init(params)

    return init, jstep


def microbatch(tokens: jax.Array, n_micro: int) -> jax.Array:
    """[B, T] → [M, B/M, T]."""
    b, t = tokens.shape
    assert b % n_micro == 0, f"batch {b} not divisible by {n_micro} microbatches"
    return tokens.reshape(n_micro, b // n_micro, t)
