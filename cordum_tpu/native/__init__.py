"""Native (C) acceleration for control-plane hot loops.

Builds lazily with the system compiler on first use and loads via ctypes
(no pybind11 in the image); every consumer has a pure-Python fallback, so
the framework works without a toolchain.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

from ..infra import logging as logx

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "strategy_scan.c")
_LIB = os.path.join(_DIR, "libstrategy_scan.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    for cc in ("cc", "gcc", "clang"):
        try:
            subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", "-o", _LIB, _SRC],
                check=True, capture_output=True, timeout=60,
            )
            return True
        except (FileNotFoundError, subprocess.CalledProcessError, subprocess.TimeoutExpired):
            continue
    return False


def load_strategy_scan() -> Optional[ctypes.CDLL]:
    """The compiled scan library, or None (callers fall back to Python)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    try:
        if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
            if not _build():
                logx.warn("native strategy scan unavailable (no C compiler)")
                return None
        lib = ctypes.CDLL(_LIB)
        lib.pick_worker.restype = ctypes.c_int32
        lib.pick_worker.argtypes = [
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint64),   # cap_bits
            ctypes.POINTER(ctypes.c_int32),    # pool_id
            ctypes.POINTER(ctypes.c_int32),    # topology_id
            ctypes.POINTER(ctypes.c_int32),    # chip_count
            ctypes.POINTER(ctypes.c_float),    # active_jobs
            ctypes.POINTER(ctypes.c_float),    # max_parallel
            ctypes.POINTER(ctypes.c_float),    # cpu_load
            ctypes.POINTER(ctypes.c_float),    # duty_cycle
            ctypes.POINTER(ctypes.c_uint8),    # healthy
            ctypes.c_uint64,                   # req_caps
            ctypes.POINTER(ctypes.c_int32),    # allowed_pools
            ctypes.c_int32,                    # n_pools
            ctypes.c_int32,                    # min_chips
            ctypes.c_int32,                    # req_topology_id
        ]
        _lib = lib
        logx.info("native strategy scan loaded", lib=_LIB)
    except OSError as e:
        logx.warn("native strategy scan failed to load", err=str(e))
        _lib = None
    return _lib
