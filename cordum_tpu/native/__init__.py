"""Native (C) acceleration for control-plane hot loops.

Builds lazily with the system compiler on first use and loads via ctypes
(no pybind11 in the image); every consumer has a pure-Python fallback, so
the framework works without a toolchain.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import Optional

from ..infra import logging as logx

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "strategy_scan.c")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _lib_path() -> str:
    """Output path stamped with the source hash.

    Binaries are never committed; the library is only loaded if its name
    matches the current source's hash, so a stale artifact (from a previous
    source revision) can never be silently loaded into the scheduler hot path.
    """
    with open(_SRC, "rb") as f:
        h = hashlib.sha256(f.read()).hexdigest()[:12]
    return os.path.join(_DIR, f"libstrategy_scan-{h}.so")


def _build(out: str) -> bool:
    for cc in ("cc", "gcc", "clang"):
        try:
            subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", "-o", out, _SRC],
                check=True, capture_output=True, timeout=60,
            )
            return True
        except (FileNotFoundError, subprocess.CalledProcessError, subprocess.TimeoutExpired):
            continue
    return False


def load_strategy_scan() -> Optional[ctypes.CDLL]:
    """The compiled scan library, or None (callers fall back to Python)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    try:
        lib_file = _lib_path()
        if not os.path.exists(lib_file):
            import glob

            for stale in glob.glob(os.path.join(_DIR, "libstrategy_scan-*.so")):
                try:
                    os.unlink(stale)  # drop artifacts of older source revisions
                except OSError:
                    pass
            if not _build(lib_file):
                logx.warn("native strategy scan unavailable (no C compiler)")
                return None
        lib = ctypes.CDLL(lib_file)
        lib.pick_worker.restype = ctypes.c_int32
        lib.pick_worker.argtypes = [
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint64),   # cap_bits
            ctypes.POINTER(ctypes.c_int32),    # pool_id
            ctypes.POINTER(ctypes.c_int32),    # topology_id
            ctypes.POINTER(ctypes.c_int32),    # chip_count
            ctypes.POINTER(ctypes.c_float),    # active_jobs
            ctypes.POINTER(ctypes.c_float),    # max_parallel
            ctypes.POINTER(ctypes.c_float),    # cpu_load
            ctypes.POINTER(ctypes.c_float),    # duty_cycle
            ctypes.POINTER(ctypes.c_uint8),    # healthy
            ctypes.c_uint64,                   # req_caps
            ctypes.POINTER(ctypes.c_int32),    # allowed_pools
            ctypes.c_int32,                    # n_pools
            ctypes.c_int32,                    # min_chips
            ctypes.c_int32,                    # req_topology_id
        ]
        _lib = lib
        logx.info("native strategy scan loaded", lib=lib_file)
    except OSError as e:
        logx.warn("native strategy scan failed to load", err=str(e))
        _lib = None
    return _lib
