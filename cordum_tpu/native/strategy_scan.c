/* Native worker-selection scan: the scheduler's hottest loop.
 *
 * The least-loaded strategy scans every live worker per dispatch
 * (reference strategy_least_loaded.go:40-140; its published number is
 * 18,234 selections/s at 1000 workers).  The Python scan is O(workers) of
 * interpreted attribute access; this C kernel runs the same selection over
 * packed parallel arrays the registry maintains incrementally.
 *
 * Selection semantics (must match cordum_tpu/controlplane/scheduler/
 * strategy.py — tested against it):
 *   eligible = pool_mask & capability_mask & chips & topology & healthy
 *              & !overloaded(active>=0.9*max || cpu>=90 || duty>=90)
 *   score    = active_jobs + cpu/100 + duty/100 ; least wins,
 *              ties broken by lowest worker index (caller sorts ids).
 *
 * Capability/pool/topology matching is precomputed by the caller into
 * bitmasks: each job presents a required-capability bitmask (bit i set ->
 * worker must have capability i) plus pool-membership and topology-id
 * columns.  Returns the winning worker index or -1.
 *
 * Build: cc -O2 -shared -fPIC -o libstrategy_scan.so strategy_scan.c
 */
#include <stdint.h>

#define OVERLOAD_FRACTION 0.9
#define OVERLOAD_UTIL 90.0

/* returns index of best worker, or -1 if none eligible */
int32_t pick_worker(
    int32_t n,
    const uint64_t *cap_bits,      /* per-worker capability bitmask        */
    const int32_t *pool_id,        /* per-worker pool id                   */
    const int32_t *topology_id,    /* per-worker topology id (0 = none)    */
    const int32_t *chip_count,     /* per-worker chips                     */
    const float *active_jobs,      /* per-worker active jobs               */
    const float *max_parallel,     /* per-worker max parallel (0 = unset)  */
    const float *cpu_load,         /* per-worker cpu %                     */
    const float *duty_cycle,       /* per-worker TPU duty %                */
    const uint8_t *healthy,        /* per-worker device health             */
    uint64_t req_caps,             /* required capability bits             */
    const int32_t *allowed_pools,  /* eligible pool ids for the topic      */
    int32_t n_pools,
    int32_t min_chips,
    int32_t req_topology_id        /* 0 = any */
) {
    int32_t best = -1;
    double best_score = 1e30;
    for (int32_t i = 0; i < n; i++) {
        if (!healthy[i]) continue;
        if ((cap_bits[i] & req_caps) != req_caps) continue;
        if (min_chips > 0 && chip_count[i] < min_chips) continue;
        if (req_topology_id != 0 && topology_id[i] != req_topology_id) continue;
        if (n_pools > 0) {
            int ok = 0;
            for (int32_t p = 0; p < n_pools; p++) {
                if (pool_id[i] == allowed_pools[p]) { ok = 1; break; }
            }
            if (!ok) continue;
        }
        if (max_parallel[i] > 0.0f &&
            active_jobs[i] >= OVERLOAD_FRACTION * max_parallel[i]) continue;
        if (cpu_load[i] >= OVERLOAD_UTIL || duty_cycle[i] >= OVERLOAD_UTIL) continue;
        double score = (double)active_jobs[i]
                     + (double)cpu_load[i] / 100.0
                     + (double)duty_cycle[i] / 100.0;
        if (score < best_score) {
            best_score = score;
            best = i;
        }
    }
    return best;
}
