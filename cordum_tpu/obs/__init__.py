"""Observability: the flight recorder + the fleet telemetry plane.

Flight recorder (per-request):

* :mod:`tracer` — create/finish :class:`~cordum_tpu.protocol.types.Span`
  objects, propagate span context through ``contextvars`` inside a process
  and through ``BusPacket.span_id`` across processes, publish finished
  spans on the durable ``sys.trace.span`` subject.
* :mod:`collector` — bus consumer persisting spans to KV as per-trace ring
  buffers with retention caps, feeding the ``cordum_stage_seconds``
  histograms.
* :mod:`assembler` — rebuild the span tree, compute per-stage durations and
  the critical path, render ASCII waterfalls for the CLI.

Fleet telemetry plane (per-fleet, ISSUE 9):

* :mod:`telemetry` — per-process exporter publishing delta-encoded metric
  snapshots + health beacons on ``sys.telemetry.<service>``.
* :mod:`fleet` — gateway-hosted aggregator merging counters/histograms
  fleet-wide (gauges keep their instance) with short time-series rings;
  serves ``/metrics?scope=fleet``, ``GET /api/v1/fleet``, ``cordumctl top``.
* :mod:`slo` — multi-window (5 m / 1 h) error-budget burn rates per job
  class from the aggregated series (pools.yaml ``slo:`` stanza).
* :mod:`profiler` — event-loop lag sampler, slow-tick stack dumps with the
  active trace id, GC-pause counters.

Capacity observatory (ISSUE 10):

* :mod:`capacity` — per-worker online device profiles (device-time EWMA +
  histogram, compile-vs-steady split, items/s, decode tokens/s, occupancy,
  KV-page headroom) published as a delta-encoded ``capacity`` beacon block;
  the aggregator folds them into the op × worker throughput matrix
  (``GET /api/v1/capacity``, ``cordumctl capacity``).
* tail-latency attribution — tail-based trace retention
  (:class:`collector.TailSampler`), cross-trace critical-path blame
  (:func:`assembler.aggregate_critical_paths`), and exemplars on
  ``Histogram.observe`` (``GET /api/v1/traces/analysis``,
  ``cordum traces blame``).

See docs/OBSERVABILITY.md for the end-to-end story.
"""
from __future__ import annotations

from ..infra import metrics as _metrics
from .assembler import (
    aggregate_critical_paths,
    assemble,
    critical_path_blame,
    render_blame,
    render_waterfall,
)
from .capacity import CapacityProfiler, render_capacity_table
from .collector import SpanCollector, TailSampler
from .fleet import FleetAggregator, render_fleet_table
from .profiler import RuntimeProfiler
from .slo import SLOObjective, SLOTracker
from .telemetry import TelemetryExporter
from .tracer import Tracer, current_trace_context, last_active_context

# ambient exemplar source: any Histogram.observe without an explicit
# exemplar picks up the active span's trace id (docs/OBSERVABILITY.md
# §Capacity observatory)
_metrics.set_exemplar_provider(current_trace_context)

__all__ = [
    "CapacityProfiler",
    "FleetAggregator",
    "RuntimeProfiler",
    "SLOObjective",
    "SLOTracker",
    "SpanCollector",
    "TailSampler",
    "TelemetryExporter",
    "Tracer",
    "aggregate_critical_paths",
    "assemble",
    "critical_path_blame",
    "current_trace_context",
    "last_active_context",
    "render_blame",
    "render_capacity_table",
    "render_fleet_table",
    "render_waterfall",
]
