"""Observability: the flight recorder + the fleet telemetry plane.

Flight recorder (per-request):

* :mod:`tracer` — create/finish :class:`~cordum_tpu.protocol.types.Span`
  objects, propagate span context through ``contextvars`` inside a process
  and through ``BusPacket.span_id`` across processes, publish finished
  spans on the durable ``sys.trace.span`` subject.
* :mod:`collector` — bus consumer persisting spans to KV as per-trace ring
  buffers with retention caps, feeding the ``cordum_stage_seconds``
  histograms.
* :mod:`assembler` — rebuild the span tree, compute per-stage durations and
  the critical path, render ASCII waterfalls for the CLI.

Fleet telemetry plane (per-fleet, ISSUE 9):

* :mod:`telemetry` — per-process exporter publishing delta-encoded metric
  snapshots + health beacons on ``sys.telemetry.<service>``.
* :mod:`fleet` — gateway-hosted aggregator merging counters/histograms
  fleet-wide (gauges keep their instance) with short time-series rings;
  serves ``/metrics?scope=fleet``, ``GET /api/v1/fleet``, ``cordumctl top``.
* :mod:`slo` — multi-window (5 m / 1 h) error-budget burn rates per job
  class from the aggregated series (pools.yaml ``slo:`` stanza).
* :mod:`profiler` — event-loop lag sampler, slow-tick stack dumps with the
  active trace id, GC-pause counters.

See docs/OBSERVABILITY.md for the end-to-end story.
"""
from __future__ import annotations

from .assembler import assemble, render_waterfall
from .collector import SpanCollector
from .fleet import FleetAggregator, render_fleet_table
from .profiler import RuntimeProfiler
from .slo import SLOObjective, SLOTracker
from .telemetry import TelemetryExporter
from .tracer import Tracer, current_trace_context, last_active_context

__all__ = [
    "FleetAggregator",
    "RuntimeProfiler",
    "SLOObjective",
    "SLOTracker",
    "SpanCollector",
    "TelemetryExporter",
    "Tracer",
    "assemble",
    "current_trace_context",
    "last_active_context",
    "render_fleet_table",
    "render_waterfall",
]
