"""Flight recorder: span-based distributed tracing for the control plane.

Three pieces:

* :mod:`tracer` — create/finish :class:`~cordum_tpu.protocol.types.Span`
  objects, propagate span context through ``contextvars`` inside a process
  and through ``BusPacket.span_id`` across processes, publish finished
  spans on the durable ``sys.trace.span`` subject.
* :mod:`collector` — bus consumer persisting spans to KV as per-trace ring
  buffers with retention caps, feeding the ``cordum_stage_seconds``
  histograms.
* :mod:`assembler` — rebuild the span tree, compute per-stage durations and
  the critical path, render ASCII waterfalls for the CLI.

See docs/OBSERVABILITY.md for the end-to-end story.
"""
from __future__ import annotations

from .assembler import assemble, render_waterfall
from .collector import SpanCollector
from .tracer import Tracer, current_trace_context

__all__ = [
    "SpanCollector",
    "Tracer",
    "assemble",
    "current_trace_context",
    "render_waterfall",
]
