"""Trace assembler: span list → tree, per-stage durations, critical path.

Pure functions over :class:`~cordum_tpu.protocol.types.Span` lists (and the
JSON-safe dicts :func:`assemble` produces), so the gateway API, the CLI
renderer, and bench.py all share one implementation.

Stage semantics: a span's ``name`` IS its pipeline stage.  The canonical
dispatch path is ``submit → policy-check (evaluate) → schedule → dispatch →
execute → result``; ``device`` spans nest under ``execute`` and carry the
TPU wall time around ``block_until_ready``.
"""
from __future__ import annotations

from typing import Any, Optional

from ..protocol.types import Span

# canonical ordering for stage tables (unknown names sort after, by name)
STAGE_ORDER = (
    "submit",
    "step-dispatch",
    "schedule",
    "policy-check",
    "evaluate",
    "strategy",
    "dispatch",
    "execute",
    "device",
    "result",
)


def _stage_rank(name: str) -> tuple[int, str]:
    try:
        return (STAGE_ORDER.index(name), name)
    except ValueError:
        return (len(STAGE_ORDER), name)


def assemble(trace_id: str, spans: list[Span]) -> dict[str, Any]:
    """Rebuild the span tree and derive the trace's shape.

    Returns a JSON-safe dict::

        {trace_id, span_count, services, total_us,
         spans: [{span_id, parent_span_id, name, service, start_us, end_us,
                  duration_us, status, depth, attrs}, ...]   # start order
         stages: {name: {"total_us": int, "count": int}},
         critical_path: [span_id, ...], critical_path_us: int}

    Orphan spans (parent not collected — ring-buffer eviction or a lost
    publish) are treated as roots so a holed trace still renders.
    """
    spans = sorted(spans, key=lambda s: (s.start_us, s.end_us))
    by_id = {s.span_id: s for s in spans}
    children: dict[str, list[Span]] = {}
    roots: list[Span] = []
    for s in spans:
        if s.parent_span_id and s.parent_span_id in by_id:
            children.setdefault(s.parent_span_id, []).append(s)
        else:
            roots.append(s)

    depth: dict[str, int] = {}
    stack = [(r, 0) for r in reversed(roots)]
    while stack:
        node, d = stack.pop()
        depth[node.span_id] = d
        for c in reversed(children.get(node.span_id, [])):
            stack.append((c, d + 1))

    stages: dict[str, dict[str, int]] = {}
    for s in spans:
        st = stages.setdefault(s.name, {"total_us": 0, "count": 0})
        st["total_us"] += s.duration_us
        st["count"] += 1

    path, path_us = _critical_path(roots, children)
    total_us = 0
    if spans:
        total_us = max(s.end_us for s in spans) - min(s.start_us for s in spans)
    return {
        "trace_id": trace_id,
        "span_count": len(spans),
        "services": sorted({s.service for s in spans if s.service}),
        "total_us": max(0, total_us),
        "spans": [
            {
                "span_id": s.span_id,
                "parent_span_id": s.parent_span_id,
                "name": s.name,
                "service": s.service,
                "start_us": s.start_us,
                "end_us": s.end_us,
                "duration_us": s.duration_us,
                "status": s.status,
                "depth": depth.get(s.span_id, 0),
                "attrs": dict(s.attrs),
            }
            for s in spans
        ],
        "stages": dict(sorted(stages.items(), key=lambda kv: _stage_rank(kv[0]))),
        "critical_path": path,
        "critical_path_us": path_us,
    }


def _critical_path(
    roots: list[Span], children: dict[str, list[Span]]
) -> tuple[list[str], int]:
    """Chain from the earliest root to the latest-finishing descendant: at
    each node follow the child whose ``end_us`` is greatest (the one the
    trace actually waited on).  Returns (span ids, wall µs covered)."""
    if not roots:
        return [], 0
    first = min(roots, key=lambda s: s.start_us)
    start = first.start_us
    end = first.end_us
    path: list[str] = []
    cur: Optional[Span] = first
    while cur is not None:
        path.append(cur.span_id)
        end = max(end, cur.end_us)
        kids = children.get(cur.span_id, [])
        cur = max(kids, key=lambda s: (s.end_us, s.duration_us)) if kids else None
    return path, max(0, end - start)


# ---------------------------------------------------------------------------
# ASCII waterfall (CLI `cordum trace <id>`)
# ---------------------------------------------------------------------------


def _fmt_ms(us: int) -> str:
    return f"{us / 1000.0:.2f}ms"


def render_waterfall(doc: dict[str, Any], width: int = 48) -> str:
    """Render an :func:`assemble` document (or its JSON round-trip) as an
    ASCII waterfall, one row per span in start order."""
    rows = doc.get("spans") or []
    if not rows:
        return f"trace {doc.get('trace_id', '?')}: no spans collected"
    t0 = min(r["start_us"] for r in rows)
    total = max(1, int(doc.get("total_us") or 1))
    crit = set(doc.get("critical_path") or [])
    lines = [
        f"trace {doc.get('trace_id', '?')}  "
        f"{doc.get('span_count', len(rows))} spans  "
        f"services: {', '.join(doc.get('services') or [])}  "
        f"total {_fmt_ms(total)}  critical path {_fmt_ms(int(doc.get('critical_path_us') or 0))}"
    ]
    label_w = max(len(f"{r['depth'] * '  '}{r['name']}") for r in rows) + 2
    svc_w = max((len(r["service"]) for r in rows), default=0) + 2
    for r in rows:
        label = f"{r['depth'] * '  '}{r['name']}".ljust(label_w)
        svc = str(r["service"]).ljust(svc_w)
        off = int((r["start_us"] - t0) * width / total)
        bar_len = max(1, int(r["duration_us"] * width / total))
        bar_len = min(bar_len, width - min(off, width - 1))
        fill = "#" if r["span_id"] in crit else "="
        bar = (" " * min(off, width - 1) + fill * bar_len).ljust(width)
        mark = " !" if r.get("status") == "ERROR" else ""
        lines.append(
            f"{label}{svc}|{bar}| +{_fmt_ms(r['start_us'] - t0)} "
            f"{_fmt_ms(r['duration_us'])}{mark}"
        )
    stages = doc.get("stages") or {}
    if stages:
        lines.append("stages: " + "  ".join(
            f"{name}={_fmt_ms(st['total_us'])}" + (f" x{st['count']}" if st["count"] > 1 else "")
            for name, st in stages.items()
        ))
    return "\n".join(lines)
