"""Trace assembler: span list → tree, per-stage durations, critical path.

Pure functions over :class:`~cordum_tpu.protocol.types.Span` lists (and the
JSON-safe dicts :func:`assemble` produces), so the gateway API, the CLI
renderer, and bench.py all share one implementation.

Stage semantics: a span's ``name`` IS its pipeline stage.  The canonical
dispatch path is ``submit → policy-check (evaluate) → schedule → dispatch →
execute → result``; ``device`` spans nest under ``execute`` and carry the
TPU wall time around ``block_until_ready``.
"""
from __future__ import annotations

from typing import Any, Optional

from ..protocol.types import Span

# canonical ordering for stage tables (unknown names sort after, by name)
STAGE_ORDER = (
    "submit",
    "step-dispatch",
    "schedule",
    "policy-check",
    "evaluate",
    "strategy",
    "dispatch",
    "execute",
    "device",
    "result",
)


def _stage_rank(name: str) -> tuple[int, str]:
    try:
        return (STAGE_ORDER.index(name), name)
    except ValueError:
        return (len(STAGE_ORDER), name)


def assemble(trace_id: str, spans: list[Span]) -> dict[str, Any]:
    """Rebuild the span tree and derive the trace's shape.

    Returns a JSON-safe dict::

        {trace_id, span_count, services, total_us,
         spans: [{span_id, parent_span_id, name, service, start_us, end_us,
                  duration_us, status, depth, attrs}, ...]   # start order
         stages: {name: {"total_us": int, "count": int}},
         critical_path: [span_id, ...], critical_path_us: int}

    Orphan spans (parent not collected — ring-buffer eviction or a lost
    publish) are treated as roots so a holed trace still renders.
    """
    spans = sorted(spans, key=lambda s: (s.start_us, s.end_us))
    by_id = {s.span_id: s for s in spans}
    children: dict[str, list[Span]] = {}
    roots: list[Span] = []
    for s in spans:
        if s.parent_span_id and s.parent_span_id in by_id:
            children.setdefault(s.parent_span_id, []).append(s)
        else:
            roots.append(s)

    depth: dict[str, int] = {}
    stack = [(r, 0) for r in reversed(roots)]
    while stack:
        node, d = stack.pop()
        depth[node.span_id] = d
        for c in reversed(children.get(node.span_id, [])):
            stack.append((c, d + 1))

    stages: dict[str, dict[str, int]] = {}
    for s in spans:
        st = stages.setdefault(s.name, {"total_us": 0, "count": 0})
        st["total_us"] += s.duration_us
        st["count"] += 1

    path, path_us = _critical_path(roots, children)
    total_us = 0
    if spans:
        total_us = max(s.end_us for s in spans) - min(s.start_us for s in spans)
    return {
        "trace_id": trace_id,
        "span_count": len(spans),
        "services": sorted({s.service for s in spans if s.service}),
        "total_us": max(0, total_us),
        "spans": [
            {
                "span_id": s.span_id,
                "parent_span_id": s.parent_span_id,
                "name": s.name,
                "service": s.service,
                "start_us": s.start_us,
                "end_us": s.end_us,
                "duration_us": s.duration_us,
                "status": s.status,
                "depth": depth.get(s.span_id, 0),
                "attrs": dict(s.attrs),
            }
            for s in spans
        ],
        "stages": dict(sorted(stages.items(), key=lambda kv: _stage_rank(kv[0]))),
        "critical_path": path,
        "critical_path_us": path_us,
    }


def _critical_path(
    roots: list[Span], children: dict[str, list[Span]]
) -> tuple[list[str], int]:
    """Chain from the earliest root to the latest-finishing descendant: at
    each node follow the child whose ``end_us`` is greatest (the one the
    trace actually waited on).  Returns (span ids, wall µs covered)."""
    if not roots:
        return [], 0
    first = min(roots, key=lambda s: s.start_us)
    start = first.start_us
    end = first.end_us
    path: list[str] = []
    cur: Optional[Span] = first
    while cur is not None:
        path.append(cur.span_id)
        end = max(end, cur.end_us)
        kids = children.get(cur.span_id, [])
        cur = max(kids, key=lambda s: (s.end_us, s.duration_us)) if kids else None
    return path, max(0, end - start)


# ---------------------------------------------------------------------------
# cross-trace critical-path aggregation (ISSUE 10: "where does p99 go")
# ---------------------------------------------------------------------------

UNTRACKED_STAGE = "(untracked)"


def critical_path_blame(doc: dict[str, Any]) -> dict[str, int]:
    """Per-stage **exclusive** µs along one :func:`assemble` doc's critical
    path.

    Each path span's self time is its duration minus its overlap with the
    **union** of the deeper path spans (the time the trace actually spent
    inside a descendant belongs to the descendant's stage — deeper wins, so
    no microsecond is attributed twice even when an async child outlives its
    parent); wall time the path covers but no span accounts for (queueing
    between publishes, clock-skew holes) lands in ``"(untracked)"``.  The
    returned µs sum to the trace's critical-path wall time (or the span-sum
    when clock skew pushes the union past the wall window), so blame shares
    over many traces sum to ~1.0."""
    spans = {s["span_id"]: s for s in doc.get("spans") or []}
    path = [spans[sid] for sid in doc.get("critical_path") or [] if sid in spans]
    out: dict[str, int] = {}
    covered = 0
    for i, sp in enumerate(path):
        self_us = _exclusive_us(sp, path[i + 1:])
        out[sp["name"]] = out.get(sp["name"], 0) + self_us
        covered += self_us
    total = int(doc.get("critical_path_us") or 0)
    if total > covered:
        out[UNTRACKED_STAGE] = out.get(UNTRACKED_STAGE, 0) + (total - covered)
    return out


def _exclusive_us(sp: dict[str, Any], deeper: list[dict[str, Any]]) -> int:
    """``sp``'s duration minus its overlap with the union of the ``deeper``
    path spans' intervals (merged sweep; path lengths are small)."""
    start, end = int(sp["start_us"]), int(sp["end_us"])
    if end <= start:
        return 0
    windows = sorted(
        (max(start, int(d["start_us"])), min(end, int(d["end_us"])))
        for d in deeper
    )
    overlap = 0
    cursor = start
    for w0, w1 in windows:
        w0 = max(w0, cursor)
        if w1 > w0:
            overlap += w1 - w0
            cursor = w1
    return max(0, (end - start) - overlap)


def _quantile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def aggregate_critical_paths(
    docs: list[dict[str, Any]], *, slowest: int = 5
) -> dict[str, Any]:
    """Merge many traces' ``critical_path`` results into per-stage blame.

    Returns a JSON-safe doc::

        {traces, critical_path_us_total,
         stages: {name: {blame_share, total_us, count, p50_ms, p99_ms}},
         slowest: [{trace_id, critical_path_us, total_us}, ...]}

    ``blame_share`` is each stage's fraction of the summed critical-path
    wall time — shares (including ``"(untracked)"``) sum to ~1.0, so the
    table answers "where does the tail go" directly.  ``p50_ms``/``p99_ms``
    are over the stage's per-trace exclusive times, so a stage that is
    cheap usually but catastrophic at p99 stands out against its share.
    """
    stages: dict[str, dict[str, Any]] = {}
    per_stage_ms: dict[str, list[float]] = {}
    grand = 0
    worst: list[tuple[int, str, int]] = []
    n = 0
    for doc in docs:
        if not doc.get("critical_path"):
            continue
        blame = critical_path_blame(doc)
        if not blame:
            continue
        n += 1
        trace_total = max(int(doc.get("critical_path_us") or 0),
                          sum(blame.values()))
        grand += trace_total
        for name, us in blame.items():
            st = stages.setdefault(name, {"total_us": 0, "count": 0})
            st["total_us"] += us
            st["count"] += 1
            per_stage_ms.setdefault(name, []).append(us / 1000.0)
        worst.append((trace_total, str(doc.get("trace_id", "")),
                      int(doc.get("total_us") or 0)))
    for name, st in stages.items():
        vals = sorted(per_stage_ms[name])
        st["blame_share"] = round(st["total_us"] / grand, 4) if grand else 0.0
        st["p50_ms"] = round(_quantile(vals, 0.50), 3)
        st["p99_ms"] = round(_quantile(vals, 0.99), 3)
    worst.sort(reverse=True)
    return {
        "traces": n,
        "critical_path_us_total": grand,
        "stages": dict(sorted(
            stages.items(),
            key=lambda kv: kv[1]["total_us"], reverse=True,
        )),
        # the slowest traces ARE the blame table's exemplars: each id
        # resolves via GET /api/v1/traces/{id} to a full waterfall
        "slowest": [
            {"trace_id": tid, "critical_path_us": cp, "total_us": tot}
            for cp, tid, tot in worst[:max(0, slowest)]
        ],
    }


def render_blame(doc: dict[str, Any], width: int = 32) -> str:
    """ASCII blame table for ``cordum traces blame`` from an
    :func:`aggregate_critical_paths` document."""
    n = doc.get("traces", 0)
    total_ms = (doc.get("critical_path_us_total") or 0) / 1000.0
    lines = [
        f"critical-path blame over {n} trace(s)  "
        f"(total critical-path time {total_ms:.2f}ms)"
    ]
    stages = doc.get("stages") or {}
    if not stages:
        return lines[0] + "\n(no traces with a critical path collected)"
    name_w = max(len(s) for s in stages) + 2
    lines.append(
        f"{'stage'.ljust(name_w)}{'share':>7}  {'p50ms':>9}  {'p99ms':>9}  "
        f"{'total_ms':>10}  {'n':>5}"
    )
    for name, st in stages.items():
        share = float(st.get("blame_share", 0.0))
        bar = "#" * max(0, int(share * width))
        lines.append(
            f"{name.ljust(name_w)}{share * 100:6.1f}%  "
            f"{st.get('p50_ms', 0.0):9.3f}  {st.get('p99_ms', 0.0):9.3f}  "
            f"{st.get('total_us', 0) / 1000.0:10.2f}  {st.get('count', 0):5d}  |{bar}"
        )
    slowest = doc.get("slowest") or []
    if slowest:
        lines.append("slowest traces: " + "  ".join(
            f"{t['trace_id']}={t['critical_path_us'] / 1000.0:.2f}ms"
            for t in slowest
        ))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# ASCII waterfall (CLI `cordum trace <id>`)
# ---------------------------------------------------------------------------


def _fmt_ms(us: int) -> str:
    return f"{us / 1000.0:.2f}ms"


def render_waterfall(doc: dict[str, Any], width: int = 48) -> str:
    """Render an :func:`assemble` document (or its JSON round-trip) as an
    ASCII waterfall, one row per span in start order."""
    rows = doc.get("spans") or []
    if not rows:
        return f"trace {doc.get('trace_id', '?')}: no spans collected"
    t0 = min(r["start_us"] for r in rows)
    total = max(1, int(doc.get("total_us") or 1))
    crit = set(doc.get("critical_path") or [])
    lines = [
        f"trace {doc.get('trace_id', '?')}  "
        f"{doc.get('span_count', len(rows))} spans  "
        f"services: {', '.join(doc.get('services') or [])}  "
        f"total {_fmt_ms(total)}  critical path {_fmt_ms(int(doc.get('critical_path_us') or 0))}"
    ]
    label_w = max(len(f"{r['depth'] * '  '}{r['name']}") for r in rows) + 2
    svc_w = max((len(r["service"]) for r in rows), default=0) + 2
    for r in rows:
        label = f"{r['depth'] * '  '}{r['name']}".ljust(label_w)
        svc = str(r["service"]).ljust(svc_w)
        off = int((r["start_us"] - t0) * width / total)
        bar_len = max(1, int(r["duration_us"] * width / total))
        bar_len = min(bar_len, width - min(off, width - 1))
        fill = "#" if r["span_id"] in crit else "="
        bar = (" " * min(off, width - 1) + fill * bar_len).ljust(width)
        mark = " !" if r.get("status") == "ERROR" else ""
        lines.append(
            f"{label}{svc}|{bar}| +{_fmt_ms(r['start_us'] - t0)} "
            f"{_fmt_ms(r['duration_us'])}{mark}"
        )
    stages = doc.get("stages") or {}
    if stages:
        lines.append("stages: " + "  ".join(
            f"{name}={_fmt_ms(st['total_us'])}" + (f" x{st['count']}" if st["count"] > 1 else "")
            for name, st in stages.items()
        ))
    return "\n".join(lines)
