"""Capacity observatory — the worker-side device profiler (ISSUE 10).

A :class:`CapacityProfiler` turns the worker's existing ``device_timer``
records, micro-batch flushes and serving decode steps into online
per-(op, bucket) performance profiles:

* device-time EWMA + a log-spaced millisecond histogram (p50/p99),
* a compile-vs-steady split from the ``compile_cached`` device attr (the
  first call of a new XLA shape is compilation, not capacity — steady-state
  rates exclude it),
* delivered **items/s** and decode **tokens/s** over steady device time,
* decode-batch occupancy and KV-page/arena headroom via callbacks read at
  snapshot time.

The profiler publishes a compact, **delta-encoded** ``capacity`` block in
the worker's telemetry beacon (``Worker.telemetry_health`` →
``TelemetryExporter`` health): rows carry *cumulative* values, and the
delta only decides which rows ride each beacon (rows whose observation
count moved, plus a periodic full block), so a lost beacon self-heals on
the next change and a worker restart is just a fresh epoch the aggregator
detects via ``TelemetrySnapshot.started_at_us``.

The read side lives in :mod:`cordum_tpu.obs.fleet`: the gateway-hosted
aggregator folds the blocks into the op × worker throughput matrix served
at ``GET /api/v1/capacity``, the ``cordum_capacity_items_per_sec`` gauges
under ``/metrics?scope=fleet``, and the ``cordumctl capacity`` table
rendered by :func:`render_capacity_table` below.  This matrix is the
read-only measurement substrate the heterogeneity-aware scheduling
strategies (ROADMAP item 2, Gavel-style policies) consume.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

from ..utils.ids import now_us

# log-spaced device-time buckets in MILLISECONDS (device work spans ~0.1 ms
# cached dispatches to multi-second compiles)
DEVICE_MS_BUCKETS = (
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)
DEFAULT_EWMA_ALPHA = 0.2
DEFAULT_FULL_EVERY = 15  # full block every N beacons (~30 s at 2 s cadence)
MAX_ROWS = 256  # (op, bucket) rows per worker; overflow folds into one row

GaugeFn = Callable[[], dict]


def _quantile_ms(buckets: tuple, counts: list, total: int, q: float) -> float:
    """Bucket-boundary quantile over cumulative counts (the same
    approximation infra.metrics.Histogram.quantile uses)."""
    if not total:
        return 0.0
    target = q * total
    for i, c in enumerate(counts):
        if c >= target:
            return float(buckets[i])
    return float(buckets[-1])


class CapacityProfiler:
    """Online per-(op, bucket) device-throughput profiles for one worker.

    ``observe()`` is called from the worker's event loop (job completion,
    micro-batch flush, serving decode step); ``snapshot()`` from the
    telemetry exporter's beacon timer.  A lock keeps the two honest if a
    handler ever observes from an executor thread.
    """

    def __init__(
        self,
        device_kind: str = "",
        *,
        ewma_alpha: float = DEFAULT_EWMA_ALPHA,
        full_every: int = DEFAULT_FULL_EVERY,
        buckets: tuple = DEVICE_MS_BUCKETS,
        max_rows: int = MAX_ROWS,
    ) -> None:
        self.device_kind = device_kind or "cpu"
        self.ewma_alpha = ewma_alpha
        self.full_every = max(1, full_every)
        self.buckets = buckets
        self.max_rows = max(1, max_rows)
        self._rows: dict[str, dict] = {}
        self._last_n: dict[str, int] = {}  # published n per row (delta state)
        self._seq = 0
        self._lock = threading.Lock()
        self._kv_headroom_fn: Optional[GaugeFn] = None
        self._occupancy_fn: Optional[GaugeFn] = None

    # ------------------------------------------------------------------
    def set_kv_headroom(self, fn: GaugeFn) -> None:
        """Callback returning ``{"pages_total": N, "pages_free": M}`` —
        read at snapshot time (the serving engine's page arena)."""
        self._kv_headroom_fn = fn

    def set_occupancy(self, fn: GaugeFn) -> None:
        """Callback returning occupancy gauges (e.g. the serving engine's
        mean/max decode-batch occupancy) — read at snapshot time."""
        self._occupancy_fn = fn

    # ------------------------------------------------------------------
    def observe(
        self,
        op: str,
        *,
        device_s: float,
        bucket: str = "-",
        items: int = 1,
        tokens: int = 0,
        compiled: bool = False,
    ) -> None:
        """Record one unit of device work for ``(op, bucket)``.

        ``compiled=True`` marks a call that paid XLA compilation (the
        ``compile_cached="false"`` device attr): it counts toward the
        compile split and is excluded from steady-state items/s."""
        if not op or device_s < 0:
            return
        ms = device_s * 1000.0
        key = f"{op}|{bucket}"
        with self._lock:
            r = self._rows.get(key)
            if r is None:
                if len(self._rows) >= self.max_rows:
                    key = "overflow|-"
                    op, bucket = "overflow", "-"
                    r = self._rows.get(key)
                if r is None:
                    r = self._rows[key] = {
                        "op": op, "bucket": str(bucket),
                        "n": 0, "items": 0, "tokens": 0,
                        "device_s": 0.0, "ewma_ms": 0.0,
                        "compile_n": 0, "compile_s": 0.0,
                        "steady_s": 0.0, "steady_items": 0, "steady_tokens": 0,
                        "hist": [0] * len(self.buckets),
                        "last_us": 0,
                    }
            r["n"] += 1
            r["items"] += max(0, items)
            r["tokens"] += max(0, tokens)
            r["device_s"] += device_s
            a = self.ewma_alpha
            r["ewma_ms"] = ms if r["n"] == 1 else a * ms + (1 - a) * r["ewma_ms"]
            for i, b in enumerate(self.buckets):  # cumulative, Histogram-style
                if ms <= b:
                    r["hist"][i] += 1
            if compiled:
                r["compile_n"] += 1
                r["compile_s"] += device_s
            else:
                r["steady_s"] += device_s
                r["steady_items"] += max(0, items)
                r["steady_tokens"] += max(0, tokens)
            r["last_us"] = now_us()

    # ------------------------------------------------------------------
    def _export_row(self, r: dict) -> dict:
        steady_s = r["steady_s"]
        if steady_s > 0:
            items_per_s = r["steady_items"] / steady_s
            tokens_per_s = r["steady_tokens"] / steady_s
        elif r["device_s"] > 0:  # everything compiled so far: best effort
            items_per_s = r["items"] / r["device_s"]
            tokens_per_s = r["tokens"] / r["device_s"]
        else:
            items_per_s = tokens_per_s = 0.0
        return {
            "op": r["op"], "bucket": r["bucket"],
            "n": r["n"], "items": r["items"], "tokens": r["tokens"],
            "device_s": round(r["device_s"], 6),
            "ewma_ms": round(r["ewma_ms"], 4),
            "compile_n": r["compile_n"],
            "compile_s": round(r["compile_s"], 6),
            "items_per_s": round(items_per_s, 3),
            "tokens_per_s": round(tokens_per_s, 3),
            "p50_ms": _quantile_ms(self.buckets, r["hist"], r["n"], 0.50),
            "p99_ms": _quantile_ms(self.buckets, r["hist"], r["n"], 0.99),
            "last_us": r["last_us"],
        }

    def snapshot(self, full: Optional[bool] = None) -> dict:
        """The beacon ``capacity`` block: delta-encoded (rows whose count
        moved since the last snapshot), with a periodic full block so a
        late-joining aggregator converges.  Rows carry cumulative values,
        so a lost beacon self-heals on the row's next change."""
        with self._lock:
            if full is None:
                full = self._seq % self.full_every == 0
            rows = {}
            for key, r in self._rows.items():
                if full or self._last_n.get(key) != r["n"]:
                    self._last_n[key] = r["n"]
                    rows[key] = self._export_row(r)
            block: dict[str, Any] = {
                "v": 1,
                "seq": self._seq,
                "full": bool(full),
                "device_kind": self.device_kind,
                "ts_us": now_us(),
                "rows": rows,
            }
            self._seq += 1
        for name, fn in (("kv_pages", self._kv_headroom_fn),
                         ("occupancy", self._occupancy_fn)):
            if fn is not None:
                try:
                    block[name] = fn()
                except Exception:  # noqa: BLE001 - gauges are best-effort
                    from ..infra import logging as logx

                    logx.warn("capacity gauge probe failed", gauge=name)
        return block

    def rows(self) -> list[dict]:
        """Every profile row (exported form) — local introspection/tests."""
        with self._lock:
            return [self._export_row(r) for r in self._rows.values()]

    def steady_tokens_per_s(self, op: str) -> float:
        """This worker's own steady-state tokens/s for ``op`` (summed over
        buckets, compile time excluded) — the heartbeat's
        ``cordum.decode_tokens_per_s`` self-measurement peers rank hand-off
        targets by (docs/SERVING.md §Disaggregation)."""
        with self._lock:
            s = tokens = 0.0
            for r in self._rows.values():
                if r["op"] == op and r["steady_s"] > 0:
                    s += r["steady_s"]
                    tokens += r["steady_tokens"]
            return tokens / s if s > 0 else 0.0


# ---------------------------------------------------------------------------
# CapacityView — the scheduler-side fold of worker capacity beacons
# ---------------------------------------------------------------------------


class CapacityView:
    """Per-worker per-op steady-state throughput, folded from the workers'
    telemetry beacons — the :class:`ThroughputAwareStrategy`'s read-side
    (ROADMAP item 1; docs/ADMISSION.md §Routing).

    The gateway's :class:`~cordum_tpu.obs.fleet.FleetAggregator` already
    folds these blocks into ``/api/v1/capacity``; the scheduler folds its
    own much smaller view (worker beacons only, rates only) from the same
    ``sys.telemetry.worker`` subject so routing needs no gateway RPC.
    Worker telemetry ``instance`` ids equal heartbeat ``worker_id``s
    (cmd/worker wires the exporter that way), so rows join the registry
    directly.  A restart (``started_at_us`` change) clears the dead
    epoch's rows; a worker silent past ``stale_after_s`` reads as
    unmeasured, which drops it back to LeastLoaded routing.
    """

    def __init__(self, *, stale_after_s: float = 15.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.stale_after_s = stale_after_s
        self.clock = clock
        # worker_id → {"rows": {op: {bucket: (items/s, tokens/s)}},
        #              "kv_pages": dict, "occupancy": dict,
        #              "serving_role": str, "draining": bool,
        #              "started_at_us": int, "last": monotonic}
        self._workers: dict[str, dict] = {}
        self._sub = None

    async def start(self, bus: Any) -> None:
        from ..protocol import subjects as subj

        self._sub = await bus.subscribe(subj.TELEMETRY_WILDCARD, self._on_snapshot)

    async def stop(self) -> None:
        if self._sub is not None:
            self._sub.unsubscribe()
            self._sub = None

    async def _on_snapshot(self, subject: str, pkt: Any) -> None:
        snap = pkt.telemetry
        if snap is not None:
            self.ingest(snap)

    def ingest(self, snap: Any) -> None:
        """Fold one telemetry snapshot (also the test entry point)."""
        if snap.service != "worker" or not snap.instance:
            return
        block = (snap.health or {}).get("capacity")
        if not isinstance(block, dict):
            return
        w = self._workers.get(snap.instance)
        if w is None or (
            snap.started_at_us and w["started_at_us"] != snap.started_at_us
        ):
            # new worker or restart: the dead epoch's cumulative rates are
            # a different machine-state — start a fresh fold
            w = self._workers[snap.instance] = {
                "rows": {}, "started_at_us": snap.started_at_us, "last": 0.0,
                "kv_pages": {}, "occupancy": {},
                "serving_role": "", "draining": False,
                "serving_gang": {},
            }
        w["last"] = self.clock()
        for key, row in (block.get("rows") or {}).items():
            if not isinstance(row, dict):
                continue
            op = str(row.get("op", "")) or str(key).split("|", 1)[0]
            # rows are per-(op, bucket); routing wants per-op, so keep the
            # per-bucket rates and recompute the op aggregate on read
            w["rows"].setdefault(op, {})[str(row.get("bucket", "-"))] = (
                float(row.get("items_per_s", 0.0)),
                float(row.get("tokens_per_s", 0.0)),
            )
        # decode-side serving state (docs/SERVING.md §Disaggregation): page
        # headroom, decode occupancy, the worker's serving role and its
        # drain flag ride every capacity block — the ServingPlacer and the
        # DecodeRebalancer read them with the same staleness bound as rates
        for extra in ("kv_pages", "occupancy"):
            v = block.get(extra)
            if isinstance(v, dict):
                w[extra] = dict(v)
        role = block.get("serving_role")
        if isinstance(role, str):
            w["serving_role"] = role
        w["draining"] = bool(block.get("draining", False))
        # serving-gang membership (docs/SERVING.md §Sharded serving): the
        # block rides every beacon while the worker is a gang member and
        # DISAPPEARS when the gang ends, so absence clears the fold
        sg = block.get("serving_gang")
        w["serving_gang"] = dict(sg) if isinstance(sg, dict) else {}

    def _fresh(self, worker_id: str) -> Optional[dict]:
        w = self._workers.get(worker_id)
        if w is None or self.clock() - w["last"] > self.stale_after_s:
            return None
        return w

    def rate(self, worker_id: str, op: str) -> float:
        """Fresh measured steady-state items/s this worker delivers for
        ``op`` (summed over buckets); 0.0 = unmeasured or stale."""
        w = self._fresh(worker_id)
        if w is None:
            return 0.0
        buckets = w["rows"].get(op)
        if not buckets:
            return 0.0
        return sum(items for items, _ in buckets.values())

    def token_rate(self, worker_id: str, op: str) -> float:
        """Fresh measured steady-state tokens/s for ``op`` (summed over
        buckets); 0.0 = unmeasured or stale.  The serving placement signal:
        ``llm.prefill`` rows measure prompt ingestion, ``llm.generate``
        rows measure steady decode (docs/SERVING.md §Disaggregation)."""
        w = self._fresh(worker_id)
        if w is None:
            return 0.0
        buckets = w["rows"].get(op)
        if not buckets:
            return 0.0
        return sum(tokens for _, tokens in buckets.values())

    def kv_pages(self, worker_id: str) -> dict:
        """Fresh KV-page arena gauges (``pages_total`` / ``pages_free`` /
        ``pages_in_use``); {} = unmeasured or stale."""
        w = self._fresh(worker_id)
        return dict(w["kv_pages"]) if w is not None else {}

    def decode_occupancy(self, worker_id: str) -> dict:
        """Fresh decode-occupancy gauges (``active_sessions`` /
        ``decode_mean`` / ``decode_max``); {} = unmeasured or stale."""
        w = self._fresh(worker_id)
        return dict(w["occupancy"]) if w is not None else {}

    def serving_role(self, worker_id: str) -> str:
        """The worker's beaconed serving role; "" = unknown/stale (readers
        treat it as ``mixed``)."""
        w = self._fresh(worker_id)
        return str(w["serving_role"]) if w is not None else ""

    def spec_accept(self, worker_id: str) -> Optional[float]:
        """The worker's speculative-decoding acceptance EWMA (rides the
        occupancy block, docs/SERVING.md §Speculative decoding); ``None``
        = speculation disabled there, or unmeasured/stale.  Presence is
        the ServingPlacer's draft-enabled signal for speculable traffic."""
        w = self._fresh(worker_id)
        if w is None:
            return None
        rate = w["occupancy"].get("spec_accept_rate")
        return float(rate) if rate is not None else None

    def draining(self, worker_id: str) -> bool:
        w = self._fresh(worker_id)
        return bool(w["draining"]) if w is not None else False

    def serving_workers(self) -> list[str]:
        """Every fresh worker currently reporting serving state (a KV-page
        arena in its capacity block) — the rebalancer's candidate set."""
        return [wid for wid in self._workers
                if (self._fresh(wid) or {}).get("kv_pages")]

    def measured_workers(self, op: str) -> dict[str, float]:
        """worker_id → fresh items/s for every worker measured on ``op``."""
        out = {}
        for wid in self._workers:
            r = self.rate(wid, op)
            if r > 0:
                out[wid] = r
        return out

    def serving_gang(self, worker_id: str) -> dict:
        """The worker's fresh serving-gang membership block; {} = not a
        gang member (or stale)."""
        w = self._fresh(worker_id)
        return dict(w.get("serving_gang") or {}) if w is not None else {}

    def serving_gangs(self) -> dict[str, dict]:
        """gang_id → ONE fused capacity row per live serving gang, folded
        from every fresh member's beacon (docs/SERVING.md §Sharded
        serving): the leader (rank 0) contributes the measured aggregate
        decode tokens/s — the fused step throughput IS rank 0's, every
        rank advances in lock-step — and page headroom fuses min-of-ranks
        (a gang admits only what its tightest arena can hold)."""
        out: dict[str, dict] = {}
        for wid in list(self._workers):
            w = self._fresh(wid)
            if w is None:
                continue
            sg = w.get("serving_gang") or {}
            gid = str(sg.get("gang_id", "") or "")
            if not gid:
                continue
            g = out.setdefault(gid, {
                "gang_id": gid, "size": int(sg.get("size", 0) or 0),
                "leader": "", "members": {}, "tokens_per_s": 0.0,
                "pages_free_min": None, "pages_total_min": None,
            })
            try:
                rank = int(sg.get("rank", -1))
            except (TypeError, ValueError):
                rank = -1
            g["members"][wid] = rank
            if rank == 0:
                g["leader"] = wid
                g["tokens_per_s"] = float(sg.get("tokens_per_s", 0.0) or 0.0)
            for src, dst in (("pages_free", "pages_free_min"),
                             ("pages_total", "pages_total_min")):
                v = sg.get(src)
                if isinstance(v, (int, float)):
                    g[dst] = v if g[dst] is None else min(g[dst], v)
        return out


# ---------------------------------------------------------------------------
# `cordumctl capacity` rendering (pure function so tests cover it offline)
# ---------------------------------------------------------------------------

_CAP_COLS = (
    ("op", "op"), ("bucket", "bucket"), ("worker", "worker"),
    ("device", "device_kind"), ("items/s", "items_per_s"),
    ("tok/s", "tokens_per_s"), ("p50ms", "p50_ms"), ("p99ms", "p99_ms"),
    ("ewma", "ewma_ms"), ("n", "n"), ("compile", "compile_n"),
    ("fresh", "fresh"),
)

# per-worker serving-state columns (docs/SERVING.md §Disaggregation and
# §Prefix cache and tiering): the beacons already carry the KV arena,
# decode occupancy, prefix-cache residency, session tiers, role and drain
# flag — this table surfaces them next to the throughput matrix
_WORKER_COLS = (
    ("worker", "worker"), ("role", "role"), ("kv_free", "kv_free"),
    ("kv_used", "kv_used"), ("sessions", "sessions"), ("occ", "occ"),
    ("pfx_pages", "pfx_pages"), ("pfx_hit", "pfx_hit"),
    ("resident", "resident"), ("hib", "hib"), ("accept", "accept"),
    ("draining", "draining"), ("fresh", "fresh"),
)


def _render_rows(cols: tuple, rows: list[dict]) -> list[str]:
    widths = {
        key: max(len(title), *(len(row[key]) for row in rows))
        for title, key in cols
    }
    out = ["  ".join(t.ljust(widths[k]) for t, k in cols)]
    for row in rows:
        out.append("  ".join(row[k].ljust(widths[k]) for _, k in cols))
    return out


def render_worker_table(workers: dict) -> list[str]:
    """Per-worker serving-state lines (KV-page headroom, decode occupancy,
    role, draining) from a capacity doc's ``workers`` map; [] when no
    worker reports serving state."""
    rows = []
    for wid in sorted(workers):
        w = workers[wid] or {}
        kv = w.get("kv_pages") or {}
        occ = w.get("occupancy") or {}
        if not kv and not occ and not w.get("serving_role"):
            continue
        # prefix-cache + tiering fields ride the same beacons; workers
        # without the cache (or older beacons) render "-"
        resident = "-"
        if "resident_warm" in occ or "resident_cold" in occ:
            resident = (f"{occ.get('resident_warm', 0)}w/"
                        f"{occ.get('resident_cold', 0)}c")
        rows.append({
            "worker": str(wid),
            "role": str(w.get("serving_role") or "mixed"),
            "kv_free": str(kv.get("pages_free", "-")),
            "kv_used": str(kv.get("pages_in_use", "-")),
            "sessions": str(occ.get("active_sessions", "-")),
            "occ": f"{occ.get('decode_mean', 0.0):g}",
            "pfx_pages": str(kv.get("prefix_pages", "-")),
            "pfx_hit": (f"{occ['prefix_hit_rate']:.0%}"
                        if "prefix_hit_rate" in occ else "-"),
            "resident": resident,
            "hib": str(occ.get("hibernated_sessions", "-")),
            # speculative acceptance EWMA; "-" = speculation disabled on
            # that worker (the key never rides its occupancy beacon)
            "accept": (f"{occ['spec_accept_rate']:.0%}"
                       if "spec_accept_rate" in occ else "-"),
            "draining": "yes" if w.get("draining") else "no",
            "fresh": "yes" if w.get("fresh", True) else "no",
        })
    return _render_rows(_WORKER_COLS, rows) if rows else []


_GANG_COLS = (
    ("gang", "gang"), ("size", "size"), ("tok/s", "tokens_per_s"),
    ("kv_free_min", "kv_free_min"), ("members", "members"),
)


def render_serving_gang_table(gangs: list) -> list[str]:
    """ONE fused line per serving gang (docs/SERVING.md §Sharded serving):
    aggregate decode tokens/s, min-of-ranks page headroom, and the member
    ranks — instead of N unrelated worker rows.  [] when no gang is live."""
    rows = []
    for g in sorted(gangs or [], key=lambda g: str(g.get("gang_id", ""))):
        members = g.get("members") or {}
        rows.append({
            "gang": str(g.get("gang_id", "")),
            "size": str(g.get("size", len(members))),
            "tokens_per_s": f"{g.get('tokens_per_s', 0.0):.1f}",
            "kv_free_min": str(g.get("pages_free_min", "-")),
            "members": " ".join(
                f"{wid}:{rank}" for wid, rank in
                sorted(members.items(), key=lambda kv: kv[1])),
        })
    return _render_rows(_GANG_COLS, rows) if rows else []


def render_capacity_table(doc: dict) -> str:
    """ASCII op × worker throughput table for ``cordumctl capacity`` from a
    ``GET /api/v1/capacity`` document, with a per-worker serving-state
    section (KV-page headroom, decode occupancy, role, draining) and one
    fused row per live serving gang."""
    matrix = doc.get("matrix") or []
    ops = doc.get("ops") or {}
    head = "cordum capacity — {w} worker(s), {r} profile row(s)".format(
        w=len(doc.get("workers") or {}), r=len(matrix))
    if ops:
        head += "  |  " + "  ".join(
            f"{op}={v}/s" for op, v in sorted(ops.items()))
    worker_lines = render_worker_table(doc.get("workers") or {})
    gang_lines = render_serving_gang_table(doc.get("serving_gangs") or [])
    if gang_lines:
        worker_lines = [*worker_lines, "", "serving gangs:", *gang_lines]
    if not matrix:
        return "\n".join(
            [head, *worker_lines, "(no capacity profiles reported yet)"])
    rows = []
    for r in sorted(matrix, key=lambda r: (r.get("op", ""), r.get("bucket", ""),
                                           r.get("worker", ""))):
        rows.append({
            "op": str(r.get("op", "")),
            "bucket": str(r.get("bucket", "")),
            "worker": str(r.get("worker", "")),
            "device_kind": str(r.get("device_kind", "")),
            "items_per_s": f"{r.get('items_per_s', 0.0):.1f}",
            "tokens_per_s": f"{r.get('tokens_per_s', 0.0):.1f}",
            "p50_ms": f"{r.get('p50_ms', 0.0):g}",
            "p99_ms": f"{r.get('p99_ms', 0.0):g}",
            "ewma_ms": f"{r.get('ewma_ms', 0.0):.2f}",
            "n": str(r.get("n", 0)),
            "compile_n": str(r.get("compile_n", 0)),
            "fresh": "no" if r.get("stale") else "yes",
        })
    out = [head]
    if worker_lines:
        out.extend(worker_lines)
        out.append("")
    out.extend(_render_rows(_CAP_COLS, rows))
    return "\n".join(out)
