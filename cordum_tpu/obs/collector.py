"""Span collector: the flight recorder's persistence side.

Consumes finished spans from the durable ``sys.trace.span`` subject (queue
group ``cordum-span-collector`` — one collector instance per deployment
persists each span) and stores them in KV as per-trace ring buffers:

* ``trace:spans:<trace_id>`` — list of span JSON blobs, capped at
  ``max_spans_per_trace`` (oldest spans fall off first) with a TTL so
  abandoned traces expire;
* ``trace:spans:index`` — z-set of trace ids scored by last-write µs; when
  it exceeds ``max_traces`` the oldest traces are evicted wholesale.

On persist the collector also feeds the ``cordum_stage_seconds{stage,
service}`` histograms, which is how per-stage latency reaches ``/metrics``
without every service double-observing locally.
"""
from __future__ import annotations

import json
from typing import Optional

from ..infra import logging as logx
from ..infra.bus import Bus, Subscription
from ..infra.kv import KV
from ..infra.metrics import Metrics
from ..protocol import subjects as subj
from ..protocol.types import BusPacket, Span
from ..utils.ids import now_us

DEFAULT_MAX_SPANS_PER_TRACE = 512
DEFAULT_MAX_TRACES = 2048
DEFAULT_TRACE_TTL_S = 3600.0

INDEX_KEY = "trace:spans:index"


def spans_key(trace_id: str) -> str:
    return f"trace:spans:{trace_id}"


class SpanCollector:
    def __init__(
        self,
        kv: KV,
        bus: Bus,
        *,
        metrics: Optional[Metrics] = None,
        max_spans_per_trace: int = DEFAULT_MAX_SPANS_PER_TRACE,
        max_traces: int = DEFAULT_MAX_TRACES,
        trace_ttl_s: float = DEFAULT_TRACE_TTL_S,
    ) -> None:
        self.kv = kv
        self.bus = bus
        self.metrics = metrics
        self.max_spans_per_trace = max_spans_per_trace
        self.max_traces = max_traces
        self.trace_ttl_s = trace_ttl_s
        self._sub: Optional[Subscription] = None

    # ------------------------------------------------------------------
    async def start(self) -> None:
        self._sub = await self.bus.subscribe(
            subj.TRACE_SPAN, self._on_span, queue=subj.QUEUE_SPAN_COLLECTOR
        )

    async def stop(self) -> None:
        if self._sub is not None:
            self._sub.unsubscribe()
            self._sub = None

    # ------------------------------------------------------------------
    async def _on_span(self, subject: str, pkt: BusPacket) -> None:
        sp = pkt.span
        if sp is None or not sp.trace_id or not sp.span_id:
            return
        await self.add(sp)

    async def add(self, sp: Span) -> None:
        key = spans_key(sp.trace_id)
        length = await self.kv.rpush(
            key, json.dumps(sp.to_dict(), sort_keys=True).encode()
        )
        # ring-buffer retention: keep the newest max_spans_per_trace; the
        # drop is counted so silent truncation is observable
        # (cordum_spans_dropped_total — platform_smoke asserts it stays 0)
        if length > self.max_spans_per_trace:
            await self.kv.ltrim(key, -self.max_spans_per_trace, -1)
            if self.metrics is not None:
                self.metrics.spans_dropped.inc(
                    amount=float(length - self.max_spans_per_trace),
                    reason="per_trace_cap",
                )
        await self.kv.expire(key, self.trace_ttl_s)
        await self.kv.zadd(INDEX_KEY, sp.trace_id, float(now_us()))
        await self._evict_over_cap()
        if self.metrics is not None:
            self.metrics.spans_collected.inc(service=sp.service)
            self.metrics.stage_seconds.observe(
                sp.duration_us / 1e6, stage=sp.name, service=sp.service
            )

    async def _evict_over_cap(self) -> None:
        over = await self.kv.zcard(INDEX_KEY) - self.max_traces
        if over <= 0:
            return
        oldest = await self.kv.zrange(INDEX_KEY, 0, over - 1)
        for tid in oldest:
            await self._drop_trace(tid, reason="trace_evicted")
        logx.debug("span collector evicted traces", count=len(oldest))

    async def _drop_trace(self, trace_id: str, *, reason: str) -> None:
        key = spans_key(trace_id)
        if self.metrics is not None:
            n = await self.kv.llen(key)
            if n:
                self.metrics.spans_dropped.inc(amount=float(n), reason=reason)
        await self.kv.delete(key)
        await self.kv.zrem(INDEX_KEY, trace_id)

    # ------------------------------------------------------------------
    # read side (gateway trace API / bench)
    # ------------------------------------------------------------------
    async def spans(self, trace_id: str) -> list[Span]:
        out: list[Span] = []
        for b in await self.kv.lrange(spans_key(trace_id)):
            try:
                sp = Span.from_dict(json.loads(b))
            except (ValueError, TypeError) as e:
                logx.warn("undecodable span in trace", trace_id=trace_id, err=str(e))
                continue
            if sp is not None:
                out.append(sp)
        return out

    async def purge_older_than(self, cutoff_us: int) -> int:
        """Drop traces whose last span landed at or before ``cutoff_us``."""
        stale = await self.kv.zrangebyscore(INDEX_KEY, 0, float(cutoff_us))
        for tid in stale:
            await self._drop_trace(tid, reason="trace_purged")
        return len(stale)

    async def recent(self, n: int = 20) -> list[dict]:
        """The newest ``n`` traces as summaries (`cordum traces --last N`):
        trace id, root span name/service, span count, service count, wall
        duration, last-write age."""
        ids = await self.kv.zrange(INDEX_KEY, 0, max(0, n - 1), desc=True)
        out = []
        for tid in ids:
            spans = await self.spans(tid)
            if not spans:
                continue
            root = next(
                (s for s in spans if not s.parent_span_id),
                min(spans, key=lambda s: s.start_us),
            )
            start = min(s.start_us for s in spans)
            end = max(s.end_us or s.start_us for s in spans)
            out.append({
                "trace_id": tid,
                "root": root.name,
                "root_service": root.service,
                "span_count": len(spans),
                "services": sorted({s.service for s in spans if s.service}),
                "duration_ms": round((end - start) / 1000.0, 3),
                "age_s": round(max(0, now_us() - end) / 1e6, 1),
            })
        return out
