"""Span collector: the flight recorder's persistence side.

Consumes finished spans from the durable ``sys.trace.span`` subject (queue
group ``cordum-span-collector`` — one collector instance per deployment
persists each span) and stores them in KV as per-trace ring buffers:

* ``trace:spans:<trace_id>`` — list of span JSON blobs, capped at
  ``max_spans_per_trace`` (oldest spans fall off first) with a TTL so
  abandoned traces expire;
* ``trace:spans:index`` — z-set of trace ids scored by last-write µs; when
  it exceeds ``max_traces`` the oldest traces are evicted wholesale.

On persist the collector also feeds the ``cordum_stage_seconds{stage,
service}`` histograms, which is how per-stage latency reaches ``/metrics``
without every service double-observing locally.  Each stage observation
carries the span's trace id as an exemplar, so a bucket spike links
straight to an offending trace (ISSUE 10).

Tail-based retention (ISSUE 10): with ``tail_keep_fraction < 1.0`` the
collector keeps **every** trace whose end-to-end duration (the root span's)
reaches the rolling p95 of recent traces, and only a deterministic
``keep_fraction`` sample of the fast rest — so at scale the store holds the
traces worth debugging without storing the flood.  The default (1.0) keeps
everything, matching the pre-ISSUE-10 behavior.  Tail-dropped spans are
counted under ``cordum_spans_dropped_total{reason="tail_sampled"}`` and the
stage histograms still see every span (sampling bounds storage, not
measurement).
"""
from __future__ import annotations

import json
import zlib
from collections import OrderedDict, deque
from typing import Optional

from ..infra import logging as logx
from ..infra.bus import Bus, Subscription
from ..infra.kv import KV
from ..infra.metrics import Metrics
from ..protocol import subjects as subj
from ..protocol.types import BusPacket, Span
from ..utils.ids import now_us

DEFAULT_MAX_SPANS_PER_TRACE = 512
DEFAULT_MAX_TRACES = 2048
DEFAULT_TRACE_TTL_S = 3600.0
DEFAULT_TAIL_WINDOW = 256
DEFAULT_TAIL_PERCENTILE = 0.95
DEFAULT_TAIL_MIN_SAMPLES = 30

INDEX_KEY = "trace:spans:index"


def spans_key(trace_id: str) -> str:
    return f"trace:spans:{trace_id}"


class TailSampler:
    """Keep-all-slow / sample-the-fast trace retention decision.

    ``admit(trace_id, e2e_us)`` is called once per trace when its root span
    finishes.  A trace at or above the rolling p95 of the recent window is
    ALWAYS kept; a faster trace is kept iff a deterministic hash of its id
    lands under ``keep_fraction`` (deterministic so retries/tests agree and
    a multi-gateway deployment makes the same call).  Until the window has
    ``min_samples`` durations everything is kept — there is no meaningful
    p95 to protect yet.
    """

    def __init__(
        self,
        keep_fraction: float = 1.0,
        *,
        window: int = DEFAULT_TAIL_WINDOW,
        percentile: float = DEFAULT_TAIL_PERCENTILE,
        min_samples: int = DEFAULT_TAIL_MIN_SAMPLES,
    ) -> None:
        self.keep_fraction = min(1.0, max(0.0, keep_fraction))
        self.percentile = percentile
        self.min_samples = max(1, min_samples)
        self._window: deque[int] = deque(maxlen=max(self.min_samples, window))

    @property
    def active(self) -> bool:
        return self.keep_fraction < 1.0

    def threshold_us(self) -> Optional[int]:
        """Rolling p95 (None until the window is warm)."""
        if len(self._window) < self.min_samples:
            return None
        ordered = sorted(self._window)
        idx = min(len(ordered) - 1, int(self.percentile * len(ordered)))
        return ordered[idx]

    @staticmethod
    def _hash01(trace_id: str) -> float:
        return (zlib.crc32(trace_id.encode()) & 0xFFFFFFFF) / 2**32

    def admit(self, trace_id: str, e2e_us: int) -> bool:
        thr = self.threshold_us()
        self._window.append(max(0, e2e_us))
        if not self.active or thr is None or e2e_us >= thr:
            return True
        return self._hash01(trace_id) < self.keep_fraction


class SpanCollector:
    def __init__(
        self,
        kv: KV,
        bus: Bus,
        *,
        metrics: Optional[Metrics] = None,
        max_spans_per_trace: int = DEFAULT_MAX_SPANS_PER_TRACE,
        max_traces: int = DEFAULT_MAX_TRACES,
        trace_ttl_s: float = DEFAULT_TRACE_TTL_S,
        tail_keep_fraction: float = 1.0,
        tail_window: int = DEFAULT_TAIL_WINDOW,
        tail_min_samples: int = DEFAULT_TAIL_MIN_SAMPLES,
    ) -> None:
        self.kv = kv
        self.bus = bus
        self.metrics = metrics
        self.max_spans_per_trace = max_spans_per_trace
        self.max_traces = max_traces
        self.trace_ttl_s = trace_ttl_s
        self.tail_sampler = TailSampler(
            tail_keep_fraction, window=tail_window, min_samples=tail_min_samples
        )
        # traces the sampler dropped: late spans of a dropped trace are
        # skipped instead of resurrecting a half-empty ring (LRU-capped)
        self._tail_dropped: OrderedDict[str, None] = OrderedDict()
        self._tail_dropped_cap = 4096
        self._sub: Optional[Subscription] = None

    # ------------------------------------------------------------------
    async def start(self) -> None:
        self._sub = await self.bus.subscribe(
            subj.TRACE_SPAN, self._on_span, queue=subj.QUEUE_SPAN_COLLECTOR
        )

    async def stop(self) -> None:
        if self._sub is not None:
            self._sub.unsubscribe()
            self._sub = None

    # ------------------------------------------------------------------
    async def _on_span(self, subject: str, pkt: BusPacket) -> None:
        sp = pkt.span
        if sp is None or not sp.trace_id or not sp.span_id:
            return
        await self.add(sp)

    async def add(self, sp: Span) -> None:
        # stage measurement sees EVERY span — tail sampling bounds trace
        # storage, not the latency histograms (the span's trace id rides as
        # an exemplar so bucket spikes resolve to a stored trace)
        if self.metrics is not None:
            self.metrics.stage_seconds.observe(
                sp.duration_us / 1e6, exemplar=sp.trace_id,
                stage=sp.name, service=sp.service,
            )
        if sp.trace_id in self._tail_dropped:
            # late span of a tail-dropped trace: don't resurrect the ring
            self._tail_dropped.move_to_end(sp.trace_id)
            if self.metrics is not None:
                self.metrics.spans_dropped.inc(reason="tail_sampled")
            return
        # tail retention decision at the trace's root-span finish (the root
        # lands last: children finished before their parent published)
        if (
            self.tail_sampler.active
            and not sp.parent_span_id
            and sp.end_us
            and not self.tail_sampler.admit(sp.trace_id, sp.duration_us)
        ):
            n = await self.kv.llen(spans_key(sp.trace_id))
            await self.kv.delete(spans_key(sp.trace_id))
            await self.kv.zrem(INDEX_KEY, sp.trace_id)
            self._tail_dropped[sp.trace_id] = None
            while len(self._tail_dropped) > self._tail_dropped_cap:
                self._tail_dropped.popitem(last=False)
            if self.metrics is not None:
                self.metrics.spans_dropped.inc(
                    amount=float(n + 1), reason="tail_sampled"
                )
            return
        key = spans_key(sp.trace_id)
        length = await self.kv.rpush(
            key, json.dumps(sp.to_dict(), sort_keys=True).encode()
        )
        # ring-buffer retention: keep the newest max_spans_per_trace; the
        # drop is counted so silent truncation is observable
        # (cordum_spans_dropped_total — platform_smoke asserts it stays 0)
        if length > self.max_spans_per_trace:
            await self.kv.ltrim(key, -self.max_spans_per_trace, -1)
            if self.metrics is not None:
                self.metrics.spans_dropped.inc(
                    amount=float(length - self.max_spans_per_trace),
                    reason="per_trace_cap",
                )
        await self.kv.expire(key, self.trace_ttl_s)
        await self.kv.zadd(INDEX_KEY, sp.trace_id, float(now_us()))
        await self._evict_over_cap()
        if self.metrics is not None:
            self.metrics.spans_collected.inc(service=sp.service)

    async def _evict_over_cap(self) -> None:
        over = await self.kv.zcard(INDEX_KEY) - self.max_traces
        if over <= 0:
            return
        oldest = await self.kv.zrange(INDEX_KEY, 0, over - 1)
        for tid in oldest:
            await self._drop_trace(tid, reason="trace_evicted")
        logx.debug("span collector evicted traces", count=len(oldest))

    async def _drop_trace(self, trace_id: str, *, reason: str) -> None:
        key = spans_key(trace_id)
        if self.metrics is not None:
            n = await self.kv.llen(key)
            if n:
                self.metrics.spans_dropped.inc(amount=float(n), reason=reason)
        await self.kv.delete(key)
        await self.kv.zrem(INDEX_KEY, trace_id)

    # ------------------------------------------------------------------
    # read side (gateway trace API / bench)
    # ------------------------------------------------------------------
    async def spans(self, trace_id: str) -> list[Span]:
        out: list[Span] = []
        for b in await self.kv.lrange(spans_key(trace_id)):
            try:
                sp = Span.from_dict(json.loads(b))
            except (ValueError, TypeError) as e:
                logx.warn("undecodable span in trace", trace_id=trace_id, err=str(e))
                continue
            if sp is not None:
                out.append(sp)
        return out

    async def purge_older_than(self, cutoff_us: int) -> int:
        """Drop traces whose last span landed at or before ``cutoff_us``."""
        stale = await self.kv.zrangebyscore(INDEX_KEY, 0, float(cutoff_us))
        for tid in stale:
            await self._drop_trace(tid, reason="trace_purged")
        return len(stale)

    async def recent_trace_ids(self, n: int = 50) -> list[str]:
        """Newest ``n`` trace ids (the analysis endpoint's working set)."""
        return await self.kv.zrange(INDEX_KEY, 0, max(0, n - 1), desc=True)

    async def recent(self, n: int = 20) -> list[dict]:
        """The newest ``n`` traces as summaries (`cordum traces --last N`):
        trace id, root span name/service, span count, service count, wall
        duration, last-write age."""
        ids = await self.kv.zrange(INDEX_KEY, 0, max(0, n - 1), desc=True)
        out = []
        for tid in ids:
            spans = await self.spans(tid)
            if not spans:
                continue
            root = next(
                (s for s in spans if not s.parent_span_id),
                min(spans, key=lambda s: s.start_us),
            )
            start = min(s.start_us for s in spans)
            end = max(s.end_us or s.start_us for s in spans)
            out.append({
                "trace_id": tid,
                "root": root.name,
                "root_service": root.service,
                "span_count": len(spans),
                "services": sorted({s.service for s in spans if s.service}),
                "duration_ms": round((end - start) / 1000.0, 3),
                "age_s": round(max(0, now_us() - end) / 1e6, 1),
            })
        return out
