"""Span collector: the flight recorder's persistence side.

Consumes finished spans from the durable ``sys.trace.span`` subject (queue
group ``cordum-span-collector`` — one collector instance per deployment
persists each span) and stores them in KV as per-trace ring buffers:

* ``trace:spans:<trace_id>`` — list of span JSON blobs, capped at
  ``max_spans_per_trace`` (oldest spans fall off first) with a TTL so
  abandoned traces expire;
* ``trace:spans:index`` — z-set of trace ids scored by last-write µs; when
  it exceeds ``max_traces`` the oldest traces are evicted wholesale.

On persist the collector also feeds the ``cordum_stage_seconds{stage,
service}`` histograms, which is how per-stage latency reaches ``/metrics``
without every service double-observing locally.
"""
from __future__ import annotations

import json
from typing import Optional

from ..infra import logging as logx
from ..infra.bus import Bus, Subscription
from ..infra.kv import KV
from ..infra.metrics import Metrics
from ..protocol import subjects as subj
from ..protocol.types import BusPacket, Span
from ..utils.ids import now_us

DEFAULT_MAX_SPANS_PER_TRACE = 512
DEFAULT_MAX_TRACES = 2048
DEFAULT_TRACE_TTL_S = 3600.0

INDEX_KEY = "trace:spans:index"


def spans_key(trace_id: str) -> str:
    return f"trace:spans:{trace_id}"


class SpanCollector:
    def __init__(
        self,
        kv: KV,
        bus: Bus,
        *,
        metrics: Optional[Metrics] = None,
        max_spans_per_trace: int = DEFAULT_MAX_SPANS_PER_TRACE,
        max_traces: int = DEFAULT_MAX_TRACES,
        trace_ttl_s: float = DEFAULT_TRACE_TTL_S,
    ) -> None:
        self.kv = kv
        self.bus = bus
        self.metrics = metrics
        self.max_spans_per_trace = max_spans_per_trace
        self.max_traces = max_traces
        self.trace_ttl_s = trace_ttl_s
        self._sub: Optional[Subscription] = None

    # ------------------------------------------------------------------
    async def start(self) -> None:
        self._sub = await self.bus.subscribe(
            subj.TRACE_SPAN, self._on_span, queue=subj.QUEUE_SPAN_COLLECTOR
        )

    async def stop(self) -> None:
        if self._sub is not None:
            self._sub.unsubscribe()
            self._sub = None

    # ------------------------------------------------------------------
    async def _on_span(self, subject: str, pkt: BusPacket) -> None:
        sp = pkt.span
        if sp is None or not sp.trace_id or not sp.span_id:
            return
        await self.add(sp)

    async def add(self, sp: Span) -> None:
        key = spans_key(sp.trace_id)
        await self.kv.rpush(key, json.dumps(sp.to_dict(), sort_keys=True).encode())
        # ring-buffer retention: keep the newest max_spans_per_trace
        await self.kv.ltrim(key, -self.max_spans_per_trace, -1)
        await self.kv.expire(key, self.trace_ttl_s)
        await self.kv.zadd(INDEX_KEY, sp.trace_id, float(now_us()))
        await self._evict_over_cap()
        if self.metrics is not None:
            self.metrics.spans_collected.inc(service=sp.service)
            self.metrics.stage_seconds.observe(
                sp.duration_us / 1e6, stage=sp.name, service=sp.service
            )

    async def _evict_over_cap(self) -> None:
        over = await self.kv.zcard(INDEX_KEY) - self.max_traces
        if over <= 0:
            return
        oldest = await self.kv.zrange(INDEX_KEY, 0, over - 1)
        for tid in oldest:
            await self.kv.delete(spans_key(tid))
            await self.kv.zrem(INDEX_KEY, tid)
        logx.debug("span collector evicted traces", count=len(oldest))

    # ------------------------------------------------------------------
    # read side (gateway trace API / bench)
    # ------------------------------------------------------------------
    async def spans(self, trace_id: str) -> list[Span]:
        out: list[Span] = []
        for b in await self.kv.lrange(spans_key(trace_id)):
            try:
                sp = Span.from_dict(json.loads(b))
            except (ValueError, TypeError) as e:
                logx.warn("undecodable span in trace", trace_id=trace_id, err=str(e))
                continue
            if sp is not None:
                out.append(sp)
        return out

    async def purge_older_than(self, cutoff_us: int) -> int:
        """Drop traces whose last span landed at or before ``cutoff_us``."""
        stale = await self.kv.zrangebyscore(INDEX_KEY, 0, float(cutoff_us))
        for tid in stale:
            await self.kv.delete(spans_key(tid))
            await self.kv.zrem(INDEX_KEY, tid)
        return len(stale)
