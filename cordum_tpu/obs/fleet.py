"""Fleet aggregator: the telemetry plane's read side (gateway-hosted).

Consumes ``sys.telemetry.>`` snapshots from every process and merges them
into fleet-wide series:

* **counters** sum across instances, with Prometheus-style reset handling —
  a process restart (new ``started_at_us`` at the same (service, instance))
  folds the last-seen values into a base so the fleet total keeps the dead
  epoch's contribution and keeps climbing;
* **histograms** bucket-merge (bucket counts, sums and totals add — the
  merged quantile is the quantile of the union stream, at the same bucket
  resolution every process already uses);
* **gauges** keep their instance: summing ``cordum_workers_live`` across
  two scheduler shards that both watch the same heartbeats would double
  count, so gauges are re-labeled ``instance=...`` instead of merged.

Short time-series rings (fine: ~5 min at 2 s; coarse: ~1 h at 30 s) back
the fleet rate and the SLO tracker's multi-window burn rates.  Surfaced as
``/metrics?scope=fleet`` (text exposition), ``GET /api/v1/fleet`` (JSON:
per-service health beacons + fleet rates + stage latencies + SLO states)
and the ``cordumctl top`` table (docs/OBSERVABILITY.md §Fleet telemetry).
"""
from __future__ import annotations

import asyncio
import time
from typing import Any, Optional

from ..infra import logging as logx
from ..infra.bus import Bus, Subscription
from ..infra.metrics import Metrics, _fmt_labels, _fmt_le, format_exemplar
from ..protocol import subjects as subj
from ..protocol.types import BusPacket, TelemetrySnapshot
from ..utils.ids import now_us

FINE_STEP_S = 2.0
FINE_RETENTION_S = 300.0
COARSE_STEP_S = 30.0
COARSE_RETENTION_S = 3600.0
INSTANCE_EVICT_S = 600.0  # forget an instance silent this long

# metric families the rings/fleet doc read by name
_DISPATCHED = "cordum_jobs_dispatched_total"
_COMPLETED = "cordum_jobs_completed_total"
_BY_CLASS = "cordum_jobs_completed_by_class_total"
_E2E = "cordum_job_e2e_seconds"
_STAGE = "cordum_stage_seconds"
_REPL_LAG = "cordum_statebus_replication_lag_ops"
_SESSIONS = "cordum_serving_active_sessions"
_BATCH_DEPTH = "cordum_batch_queue_depth"
_SPANS_DROPPED = "cordum_spans_dropped_total"

LabelKey = tuple[tuple[str, str], ...]


def quantile_from_buckets(
    buckets: list[float], counts: list[int], total: int, q: float
) -> Optional[float]:
    """Bucket-boundary quantile, the same approximation
    :meth:`Histogram.quantile` uses (counts are cumulative per bucket)."""
    if not total:
        return None
    target = q * total
    for i, c in enumerate(counts):
        if c >= target:
            return buckets[i]
    return buckets[-1] if buckets else None


class _InstanceState:
    """Per-(service, instance) accumulation: last beacon + cumulative metric
    values with a restart-fold base."""

    __slots__ = (
        "service", "instance", "started_at_us", "seq", "interval_s",
        "uptime_s", "health", "last_seen", "counters", "gauges", "hists",
        "hist_buckets", "hist_exemplars", "capacity_rows", "capacity_meta",
    )

    def __init__(self, service: str, instance: str) -> None:
        self.service = service
        self.instance = instance
        self.started_at_us = 0
        self.seq = -1
        self.interval_s = 0.0
        self.uptime_s = 0.0
        self.health: dict[str, Any] = {}
        self.last_seen = 0.0  # monotonic
        # (family, labelkey) → [base, last]; fleet value = base + last
        self.counters: dict[tuple[str, LabelKey], list[float]] = {}
        self.gauges: dict[tuple[str, LabelKey], float] = {}
        # (family, labelkey) → {"base_*": folded, "counts"/"sum"/"total": last}
        self.hists: dict[tuple[str, LabelKey], dict[str, Any]] = {}
        self.hist_buckets: dict[str, list[float]] = {}
        # (family, labelkey) → {bucket_idx(str): [trace_id, value, ts_us]}
        self.hist_exemplars: dict[tuple[str, LabelKey], dict[str, list]] = {}
        # capacity observatory (ISSUE 10): "op|bucket" → exported profile row,
        # folded from the beacon's delta-encoded `capacity` block.  Rows are
        # cumulative-per-epoch, so a restart clears them (fold_restart) and
        # the fresh epoch's full block repopulates.
        self.capacity_rows: dict[str, dict] = {}
        self.capacity_meta: dict[str, Any] = {}

    def fold_restart(self) -> None:
        """The process restarted: its cumulative series reset to zero.
        Keep the dead epoch's contribution as a base so fleet totals only
        ever climb (counter-reset detection)."""
        for entry in self.counters.values():
            entry[0] += entry[1]
            entry[1] = 0.0
        for h in self.hists.values():
            h["base_counts"] = [
                b + c for b, c in zip(h["base_counts"], h["counts"])
            ]
            h["base_sum"] += h["sum"]
            h["base_total"] += h["total"]
            h["counts"] = [0] * len(h["counts"])
            h["sum"] = 0.0
            h["total"] = 0
        # capacity profiles are rate views of the dead epoch's cumulative
        # device time — a restarted worker starts a fresh profile, so stale
        # rows must not linger in the matrix
        self.capacity_rows.clear()

    def apply(self, snap: TelemetrySnapshot) -> None:
        if self.started_at_us and snap.started_at_us != self.started_at_us:
            self.fold_restart()
        self.started_at_us = snap.started_at_us
        self.seq = snap.seq
        self.interval_s = snap.interval_s
        self.uptime_s = snap.uptime_s
        self.health = dict(snap.health or {})
        self.last_seen = time.monotonic()
        cap = self.health.pop("capacity", None)
        if isinstance(cap, dict):
            self._fold_capacity(cap)
        doc = snap.metrics or {}
        for name, series in (doc.get("counters") or {}).items():
            for labels, value in series:
                k = (name, tuple(sorted(labels.items())))
                entry = self.counters.setdefault(k, [0.0, 0.0])
                entry[1] = float(value)
        for name, series in (doc.get("gauges") or {}).items():
            for labels, value in series:
                self.gauges[(name, tuple(sorted(labels.items())))] = float(value)
        for name, fam in (doc.get("histograms") or {}).items():
            buckets = list(fam.get("buckets") or [])
            self.hist_buckets[name] = buckets
            for labels, counts, sum_, total in fam.get("series") or []:
                k = (name, tuple(sorted(labels.items())))
                h = self.hists.get(k)
                if h is None:
                    h = self.hists[k] = {
                        "base_counts": [0] * len(counts),
                        "base_sum": 0.0, "base_total": 0,
                        "counts": [0] * len(counts), "sum": 0.0, "total": 0,
                    }
                h["counts"] = list(counts)
                h["sum"] = float(sum_)
                h["total"] = int(total)
            for labels, exmap in fam.get("exemplars") or []:
                k = (name, tuple(sorted(labels.items())))
                cur = self.hist_exemplars.setdefault(k, {})
                for idx, ex in (exmap or {}).items():
                    cur[str(idx)] = list(ex)

    def _fold_capacity(self, block: dict) -> None:
        """Fold one beacon `capacity` block: rows carry cumulative values,
        the delta only decides which rows rode this beacon, so folding is a
        plain overwrite (a lost beacon self-heals on the next change)."""
        self.capacity_meta = {
            k: block.get(k)
            for k in ("device_kind", "ts_us", "seq", "kv_pages", "occupancy",
                      "serving_role", "draining", "serving_gang")
            if block.get(k) is not None
        }
        for key, row in (block.get("rows") or {}).items():
            if isinstance(row, dict):
                self.capacity_rows[str(key)] = dict(row)

    def counter_total(self, name: str) -> float:
        return sum(b + l for (n, _), (b, l) in self.counters.items() if n == name)


class FleetAggregator:
    """Merge per-process telemetry snapshots into the fleet view."""

    def __init__(
        self,
        bus: Optional[Bus],
        *,
        metrics: Optional[Metrics] = None,
        fine_step_s: float = FINE_STEP_S,
        coarse_step_s: float = COARSE_STEP_S,
        instance_evict_s: float = INSTANCE_EVICT_S,
    ) -> None:
        self.bus = bus
        self.metrics = metrics
        self.fine_step_s = max(0.05, fine_step_s)
        self.coarse_step_s = max(self.fine_step_s, coarse_step_s)
        self.instance_evict_s = instance_evict_s
        self._instances: dict[tuple[str, str], _InstanceState] = {}
        self._fine: list[dict] = []  # ring of _sample() entries
        self._coarse: list[dict] = []
        self._fine_cap = max(2, int(FINE_RETENTION_S / self.fine_step_s))
        self._coarse_cap = max(2, int(COARSE_RETENTION_S / self.coarse_step_s))
        self._last_coarse = 0.0
        self._sub: Optional[Subscription] = None
        self._task: Optional[asyncio.Task] = None

    # ------------------------------------------------------------------
    async def start(self) -> None:
        if self.bus is not None:
            self._sub = await self.bus.subscribe(
                subj.TELEMETRY_WILDCARD, self._on_snapshot
            )
        # zero baseline: windows cover everything since aggregator start
        # (after an aggregator restart the first window over-counts the
        # instances' pre-start history, the same artifact a fresh
        # Prometheus rate() has — totals stay exact either way)
        self.sample()
        self._task = asyncio.ensure_future(self._sample_loop())

    async def stop(self) -> None:
        if self._sub is not None:
            self._sub.unsubscribe()
            self._sub = None
        if self._task is not None:
            task, self._task = self._task, None
            task.cancel()
            await logx.join_task(task, name="fleet-aggregator")

    async def _on_snapshot(self, subject: str, pkt: BusPacket) -> None:
        snap = pkt.telemetry
        if snap is None or not snap.service:
            if self.metrics is not None:
                self.metrics.telemetry_dropped.inc(reason="decode_error")
            return
        self.ingest(snap)

    def ingest(self, snap: TelemetrySnapshot) -> None:
        """Apply one snapshot (also the test/bench entry point)."""
        key = (snap.service, snap.instance)
        inst = self._instances.get(key)
        if inst is None:
            inst = self._instances[key] = _InstanceState(snap.service, snap.instance)
        inst.apply(snap)

    # ------------------------------------------------------------------
    # merged views
    # ------------------------------------------------------------------
    def merged_counters(self) -> dict[str, dict[LabelKey, float]]:
        out: dict[str, dict[LabelKey, float]] = {}
        for inst in self._instances.values():
            for (name, lk), (base, last) in inst.counters.items():
                fam = out.setdefault(name, {})
                fam[lk] = fam.get(lk, 0.0) + base + last
        return out

    def merged_histograms(self) -> dict[str, tuple[list[float], dict[LabelKey, dict]]]:
        out: dict[str, tuple[list[float], dict[LabelKey, dict]]] = {}
        for inst in self._instances.values():
            for (name, lk), h in inst.hists.items():
                buckets = inst.hist_buckets.get(name, [])
                fam = out.setdefault(name, (buckets, {}))[1]
                m = fam.get(lk)
                counts = [b + c for b, c in zip(h["base_counts"], h["counts"])]
                if m is None:
                    fam[lk] = {
                        "counts": counts,
                        "sum": h["base_sum"] + h["sum"],
                        "total": h["base_total"] + h["total"],
                    }
                else:
                    m["counts"] = [a + b for a, b in zip(m["counts"], counts)]
                    m["sum"] += h["base_sum"] + h["sum"]
                    m["total"] += h["base_total"] + h["total"]
        return out

    def counter_total(self, name: str) -> float:
        return sum(
            inst.counter_total(name) for inst in self._instances.values()
        )

    def _merged_class_series(self) -> dict[LabelKey, float]:
        return self.merged_counters().get(_BY_CLASS, {})

    # ------------------------------------------------------------------
    # ring sampling (rates + SLO windows)
    # ------------------------------------------------------------------
    async def _sample_loop(self) -> None:
        while True:
            await asyncio.sleep(self.fine_step_s)
            try:
                self.sample()
            except Exception as e:  # noqa: BLE001 - sampler must never die silently
                logx.warn("fleet sampler failed", err=str(e))

    def sample(self) -> None:
        """Append one ring entry (also the test/bench entry point)."""
        now = time.monotonic()
        self._evict_stale(now)
        hists = self.merged_histograms()
        e2e = {
            lk: {"counts": list(m["counts"]), "total": m["total"]}
            for lk, m in hists.get(_E2E, (None, {}))[1].items()
        }
        entry = {
            "t": now,
            "dispatched": self.counter_total(_DISPATCHED),
            "completed": self.counter_total(_COMPLETED),
            "by_class": dict(self._merged_class_series()),
            "e2e": e2e,
            "e2e_buckets": hists.get(_E2E, ([], {}))[0],
        }
        self._fine.append(entry)
        if len(self._fine) > self._fine_cap:
            del self._fine[: len(self._fine) - self._fine_cap]
        if now - self._last_coarse >= self.coarse_step_s:
            self._last_coarse = now
            self._coarse.append(entry)
            if len(self._coarse) > self._coarse_cap:
                del self._coarse[: len(self._coarse) - self._coarse_cap]

    def _evict_stale(self, now: float) -> None:
        dead = [
            k for k, inst in self._instances.items()
            if now - inst.last_seen > self.instance_evict_s
        ]
        for k in dead:
            del self._instances[k]
            if self.metrics is not None:
                self.metrics.telemetry_dropped.inc(reason="instance_evicted")

    def _entry_at(self, age_s: float) -> Optional[dict]:
        """Oldest ring entry within ``age_s`` (fine ring first, coarse for
        longer windows); None when the ring is empty."""
        cutoff = time.monotonic() - age_s
        # fine ring first: when it reaches back far enough it wins on
        # resolution; the coarse ring serves the 1 h-class windows
        for ring in (self._fine, self._coarse):
            if ring and ring[0]["t"] <= cutoff:
                # oldest entry NEWER than the cutoff = exactly the window edge
                for entry in ring:
                    if entry["t"] >= cutoff:
                        return entry
        # window exceeds recorded history: use the oldest sample we have
        if self._coarse:
            return self._coarse[0]
        return self._fine[0] if self._fine else None

    def window_delta(self, window_s: float) -> dict:
        """Windowed deltas for rates and SLO burn math: per-class terminal
        counts and per-class e2e histogram deltas over (up to) ``window_s``
        seconds.  ``span_s`` reports the actual history covered."""
        base = self._entry_at(window_s)
        now_entry = {
            "t": time.monotonic(),
            "dispatched": self.counter_total(_DISPATCHED),
            "completed": self.counter_total(_COMPLETED),
            "by_class": dict(self._merged_class_series()),
            "e2e": {
                lk: {"counts": list(m["counts"]), "total": m["total"]}
                for lk, m in self.merged_histograms().get(_E2E, (None, {}))[1].items()
            },
        }
        if base is None:
            base = {"t": now_entry["t"], "dispatched": 0.0, "completed": 0.0,
                    "by_class": {}, "e2e": {}}
            span = 0.0
        else:
            span = max(0.0, now_entry["t"] - base["t"])
        by_class = {
            lk: max(0.0, v - base["by_class"].get(lk, 0.0))
            for lk, v in now_entry["by_class"].items()
        }
        e2e = {}
        for lk, cur in now_entry["e2e"].items():
            prev = base["e2e"].get(lk, {"counts": [0] * len(cur["counts"]), "total": 0})
            e2e[lk] = {
                "counts": [
                    max(0, a - b) for a, b in zip(cur["counts"], prev["counts"])
                ],
                "total": max(0, cur["total"] - prev["total"]),
            }
        return {
            "span_s": span,
            "dispatched": max(0.0, now_entry["dispatched"] - base["dispatched"]),
            "completed": max(0.0, now_entry["completed"] - base["completed"]),
            "by_class": by_class,
            "e2e": e2e,
            "e2e_buckets": self.merged_histograms().get(_E2E, ([], {}))[0],
        }

    # ------------------------------------------------------------------
    # surfaces
    # ------------------------------------------------------------------
    def _healthy(self, inst: _InstanceState, now: float) -> bool:
        ttl = max(6.0, 3.0 * (inst.interval_s or FINE_STEP_S))
        return now - inst.last_seen <= ttl

    def services(self) -> list[dict]:
        now = time.monotonic()
        out = []
        for inst in sorted(
            self._instances.values(), key=lambda i: (i.service, i.instance)
        ):
            doc = {
                "service": inst.service,
                "instance": inst.instance,
                "healthy": self._healthy(inst, now),
                "age_s": round(now - inst.last_seen, 2),
                "uptime_s": round(inst.uptime_s, 1),
                "seq": inst.seq,
                "interval_s": inst.interval_s,
            }
            doc.update(inst.health)
            out.append(doc)
        return out

    def fleet_doc(self, slo_tracker: Any = None) -> dict:
        """The ``GET /api/v1/fleet`` document."""
        services = self.services()
        counts: dict[str, int] = {}
        for s in services:
            if s["healthy"]:
                counts[s["service"]] = counts.get(s["service"], 0) + 1
        hists = self.merged_histograms()
        stage_p50: dict[str, float] = {}
        stage_p99: dict[str, float] = {}
        stage = hists.get(_STAGE)
        if stage is not None:
            buckets, fams = stage
            merged_by_stage: dict[str, dict] = {}
            for lk, m in fams.items():
                name = dict(lk).get("stage", "")
                agg = merged_by_stage.get(name)
                if agg is None:
                    merged_by_stage[name] = {
                        "counts": list(m["counts"]), "total": m["total"]
                    }
                else:
                    agg["counts"] = [
                        a + b for a, b in zip(agg["counts"], m["counts"])
                    ]
                    agg["total"] += m["total"]
            for name, m in merged_by_stage.items():
                p50 = quantile_from_buckets(buckets, m["counts"], m["total"], 0.50)
                p99 = quantile_from_buckets(buckets, m["counts"], m["total"], 0.99)
                if p50 is not None:
                    stage_p50[name] = round(p50 * 1000, 3)
                if p99 is not None:
                    stage_p99[name] = round(p99 * 1000, 3)
        gauges = self._gauge_rollup()
        rate = self.window_delta(60.0)
        rate_5m = self.window_delta(300.0)
        doc = {
            "ts_us": now_us(),
            "services": services,
            "counts": counts,
            "healthy_services": sum(counts.values()),
            "fleet": {
                "jobs_dispatched_total": self.counter_total(_DISPATCHED),
                "jobs_completed_total": self.counter_total(_COMPLETED),
                "scheduled_per_s": round(
                    rate["dispatched"] / rate["span_s"], 2
                ) if rate["span_s"] else 0.0,
                "completed_per_s": round(
                    rate["completed"] / rate["span_s"], 2
                ) if rate["span_s"] else 0.0,
                "completed_5m": rate_5m["completed"],
                "rate_window_s": round(rate["span_s"], 1),
                "stage_p50_ms": stage_p50,
                "stage_p99_ms": stage_p99,
                "replication_lag_ops": gauges["repl_lag"],
                "serving_active_sessions": gauges["sessions"],
                "batch_queue_depth": gauges["batch_depth"],
                "spans_dropped_total": self.counter_total(_SPANS_DROPPED),
            },
        }
        if slo_tracker is not None:
            doc["slo"] = slo_tracker.evaluate(self)
        return doc

    def capacity_doc(self) -> dict:
        """``GET /api/v1/capacity`` — the op × worker throughput matrix
        folded from the workers' beacon ``capacity`` blocks (ISSUE 10).

        Staleness handling: a row from an instance whose beacon is overdue
        (the same ``healthy`` bound the fleet doc uses) is marked
        ``stale: true`` and excluded from the per-op totals; an instance
        silent past ``instance_evict_s`` is dropped entirely by the sampler.
        This is the read-only input the heterogeneity-aware scheduling
        strategy (ROADMAP item 2) consumes."""
        now = time.monotonic()
        workers: dict[str, dict] = {}
        matrix: list[dict] = []
        ops: dict[str, float] = {}
        for inst in sorted(self._instances.values(),
                           key=lambda i: (i.service, i.instance)):
            if not inst.capacity_rows:
                continue
            fresh = self._healthy(inst, now)
            age = round(now - inst.last_seen, 2)
            meta = inst.capacity_meta
            wdoc: dict[str, Any] = {
                "service": inst.service,
                "device_kind": meta.get("device_kind", ""),
                "fresh": fresh,
                "age_s": age,
                "rows": len(inst.capacity_rows),
            }
            for extra in ("kv_pages", "occupancy", "serving_role", "draining",
                          "serving_gang"):
                if meta.get(extra) is not None:
                    wdoc[extra] = meta[extra]
            workers[inst.instance] = wdoc
            for key in sorted(inst.capacity_rows):
                row = dict(inst.capacity_rows[key])
                row["worker"] = inst.instance
                row["device_kind"] = meta.get("device_kind", "")
                row["stale"] = not fresh
                row["age_s"] = age
                matrix.append(row)
                if fresh:
                    op = str(row.get("op", ""))
                    ops[op] = ops.get(op, 0.0) + float(row.get("items_per_s", 0.0))
        # serving gangs fuse to ONE row per gang (docs/SERVING.md §Sharded
        # serving): rank 0's measured step throughput is the gang's — every
        # rank advances in lock-step — and page headroom is min-of-ranks.
        # Folded over ALL worker instances (a member with no profile rows
        # yet still beacons its membership).
        gangs: dict[str, dict] = {}
        for inst in self._instances.values():
            sg = inst.capacity_meta.get("serving_gang")
            if not isinstance(sg, dict) or not self._healthy(inst, now):
                continue
            gid = str(sg.get("gang_id", "") or "")
            if not gid:
                continue
            g = gangs.setdefault(gid, {
                "gang_id": gid, "size": int(sg.get("size", 0) or 0),
                "leader": "", "members": {}, "tokens_per_s": 0.0,
                "pages_free_min": None, "pages_total_min": None,
            })
            try:
                rank = int(sg.get("rank", -1))
            except (TypeError, ValueError):
                rank = -1
            g["members"][inst.instance] = rank
            if rank == 0:
                g["leader"] = inst.instance
                g["tokens_per_s"] = float(sg.get("tokens_per_s", 0.0) or 0.0)
            for src, dst in (("pages_free", "pages_free_min"),
                             ("pages_total", "pages_total_min")):
                v = sg.get(src)
                if isinstance(v, (int, float)):
                    g[dst] = v if g[dst] is None else min(g[dst], v)
        return {
            "ts_us": now_us(),
            "workers": workers,
            "matrix": matrix,
            "ops": {op: round(v, 2) for op, v in sorted(ops.items())},
            "serving_gangs": [gangs[k] for k in sorted(gangs)],
        }

    def gangs_doc(self) -> dict:
        """``GET /api/v1/gangs`` — the live gang table (docs/GANG.md),
        merged from every scheduler shard's health beacon (each shard
        beacons the gangs it owns, so the union is the fleet view)."""
        now = time.monotonic()
        gangs: list[dict] = []
        queue_depth = 0
        shards = 0
        for inst in sorted(self._instances.values(),
                           key=lambda i: (i.service, i.instance)):
            if inst.service != "scheduler":
                continue
            rows = inst.health.get("gangs")
            if rows is None:
                continue
            shards += 1
            fresh = self._healthy(inst, now)
            for g in rows:
                doc = dict(g)
                doc["shard"] = inst.instance
                doc["stale"] = not fresh
                gangs.append(doc)
            try:
                queue_depth += int(inst.health.get("gang_queue_depth", 0) or 0)
            except (TypeError, ValueError):
                pass
        return {
            "ts_us": now_us(),
            "gangs": gangs,
            "queue_depth": queue_depth,
            "scheduler_shards": shards,
        }

    def _merged_exemplars(
        self, name: str, lk: LabelKey
    ) -> dict[int, tuple[str, float, int]]:
        """Freshest exemplar per bucket across instances for one merged
        histogram series (exemplars don't merge — the newest wins)."""
        best: dict[int, tuple[str, float, int]] = {}
        for inst in self._instances.values():
            for idx, ex in (inst.hist_exemplars.get((name, lk)) or {}).items():
                try:
                    i = int(idx)
                    tid, value, ts = str(ex[0]), float(ex[1]), int(ex[2])
                except (TypeError, ValueError, IndexError):
                    continue
                if i not in best or ts > best[i][2]:
                    best[i] = (tid, value, ts)
        return best

    def _gauge_rollup(self) -> dict:
        repl_lag = 0.0
        sessions = 0.0
        batch_depth = 0.0
        for inst in self._instances.values():
            for (name, _), v in inst.gauges.items():
                if name == _REPL_LAG:
                    repl_lag = max(repl_lag, v)
                elif name == _SESSIONS:
                    sessions += v
                elif name == _BATCH_DEPTH:
                    batch_depth += v
        return {"repl_lag": repl_lag, "sessions": sessions,
                "batch_depth": batch_depth}

    def render(self) -> str:
        """Fleet-scope Prometheus exposition (``/metrics?scope=fleet``):
        counters and histograms merged across instances, gauges re-labeled
        per instance, plus a ``cordum_fleet_instances`` health gauge."""
        lines: list[str] = []
        for name, fam in sorted(self.merged_counters().items()):
            lines.append(f"# TYPE {name} counter")
            for lk, v in sorted(fam.items()):
                lines.append(f"{name}{_fmt_labels(dict(lk))} {v}")
        # gauges: one series per instance (summing would double count)
        gauge_lines: dict[str, list[str]] = {}
        for inst in self._instances.values():
            for (name, lk), v in inst.gauges.items():
                labels = dict(lk)
                labels["instance"] = inst.instance
                gauge_lines.setdefault(name, []).append(
                    f"{name}{_fmt_labels(labels)} {v}"
                )
        for name in sorted(gauge_lines):
            lines.append(f"# TYPE {name} gauge")
            lines.extend(sorted(gauge_lines[name]))
        for name, (buckets, fams) in sorted(self.merged_histograms().items()):
            lines.append(f"# TYPE {name} histogram")
            for lk, m in sorted(fams.items()):
                labels = dict(lk)
                exs = self._merged_exemplars(name, lk)
                for i, b in enumerate(buckets):
                    bl = dict(labels)
                    bl["le"] = _fmt_le(b)
                    lines.append(
                        f"{name}_bucket{_fmt_labels(bl)} {m['counts'][i]}"
                        + format_exemplar(exs.get(i))
                    )
                bl = dict(labels)
                bl["le"] = "+Inf"
                lines.append(
                    f"{name}_bucket{_fmt_labels(bl)} {m['total']}"
                    + format_exemplar(exs.get(len(buckets)))
                )
                lines.append(f"{name}_sum{_fmt_labels(labels)} {m['sum']}")
                lines.append(f"{name}_count{_fmt_labels(labels)} {m['total']}")
        # capacity observatory: the throughput matrix as fleet gauges, fresh
        # rows only (GET /api/v1/capacity carries the stale-flagged view)
        cap = self.capacity_doc()
        cap_rows = [r for r in cap["matrix"] if not r.get("stale")]
        if cap_rows:
            lines.append("# TYPE cordum_capacity_items_per_sec gauge")
            for r in cap_rows:
                lines.append(
                    "cordum_capacity_items_per_sec"
                    f"{_fmt_labels({'op': str(r.get('op', '')), 'bucket': str(r.get('bucket', '')), 'worker': str(r.get('worker', ''))})}"
                    f" {r.get('items_per_s', 0.0)}"
                )
            tok_rows = [r for r in cap_rows if float(r.get("tokens_per_s", 0.0)) > 0]
            if tok_rows:
                lines.append("# TYPE cordum_capacity_tokens_per_sec gauge")
                for r in tok_rows:
                    lines.append(
                        "cordum_capacity_tokens_per_sec"
                        f"{_fmt_labels({'op': str(r.get('op', '')), 'bucket': str(r.get('bucket', '')), 'worker': str(r.get('worker', ''))})}"
                        f" {r.get('tokens_per_s', 0.0)}"
                    )
        now = time.monotonic()
        lines.append("# TYPE cordum_fleet_instances gauge")
        per_service: dict[str, int] = {}
        for inst in self._instances.values():
            if self._healthy(inst, now):
                per_service[inst.service] = per_service.get(inst.service, 0) + 1
        for service, n in sorted(per_service.items()):
            lines.append(
                f"cordum_fleet_instances{_fmt_labels({'service': service})} {n}"
            )
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# `cordumctl top` rendering (pure function so tests cover it offline)
# ---------------------------------------------------------------------------

_TOP_COLS = (
    ("service", "service"), ("instance", "instance"), ("role", "role"),
    ("shard", "shard"), ("part", "partition"), ("epoch", "epoch"),
    ("lag", "lag_ops"), ("queue", "queue_depth"), ("jobs", "jobs_scheduled"),
    ("up(s)", "uptime_s"), ("ok", "healthy"),
)


def render_fleet_table(doc: dict) -> str:
    """ASCII fleet table for ``cordumctl top`` from a /api/v1/fleet doc."""
    fleet = doc.get("fleet") or {}
    rows = []
    for s in doc.get("services") or []:
        shard = s.get("shard_index")
        if shard is not None and s.get("shard_count"):
            shard = f"{shard}/{s['shard_count']}"
        rows.append({
            "service": s.get("service", ""),
            "instance": s.get("instance", ""),
            "role": s.get("role", ""),
            "shard": "" if shard is None else str(shard),
            "partition": _cell(s.get("partition")),
            "epoch": _cell(s.get("epoch")),
            "lag_ops": _cell(s.get("lag_ops")),
            "queue_depth": _cell(s.get("queue_depth")),
            "jobs_scheduled": _cell(s.get("jobs_scheduled")),
            "uptime_s": f"{s.get('uptime_s', 0):.0f}",
            "healthy": "yes" if s.get("healthy") else "NO",
        })
    widths = {
        key: max(len(title), *(len(r[key]) for r in rows)) if rows else len(title)
        for title, key in _TOP_COLS
    }
    out = [
        "cordum fleet — {n} healthy instance(s), {r} scheduled/s, "
        "{c} completed/s (window {w}s)".format(
            n=doc.get("healthy_services", 0),
            r=fleet.get("scheduled_per_s", 0.0),
            c=fleet.get("completed_per_s", 0.0),
            w=fleet.get("rate_window_s", 0.0),
        ),
    ]
    stage = fleet.get("stage_p50_ms") or {}
    if stage:
        p99 = fleet.get("stage_p99_ms") or {}
        out.append("stages p50/p99 ms: " + "  ".join(
            f"{k}={v}/{p99.get(k, '-')}" for k, v in sorted(stage.items())
        ))
    for state in doc.get("slo") or []:
        w = state.get("windows") or {}
        out.append(
            "slo {name} [{klass}] state={st} burn 5m={b5} 1h={b1}".format(
                name=state.get("name"), klass=state.get("job_class"),
                st=state.get("state"),
                b5=(w.get("5m") or {}).get("burn_rate", 0.0),
                b1=(w.get("1h") or {}).get("burn_rate", 0.0),
            )
        )
    out.append("  ".join(t.ljust(widths[k]) for t, k in _TOP_COLS))
    for r in rows:
        out.append("  ".join(r[k].ljust(widths[k]) for _, k in _TOP_COLS))
    return "\n".join(out)


def _cell(v: Any) -> str:
    return "" if v is None else str(v)
