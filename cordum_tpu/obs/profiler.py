"""Runtime profiler: per-process event-loop lag, slow-tick stack dumps, and
GC-pause accounting — the "why is this process slow" leg of the fleet
telemetry plane (docs/OBSERVABILITY.md §Fleet telemetry).

Three probes, all off the hot path:

* **event-loop lag sampler** — an ``asyncio.sleep(tick)`` loop measures how
  late the loop woke it; the excess is scheduling lag (a blocking call, a
  long callback, CPU starvation) and feeds the
  ``cordum_eventloop_lag_seconds`` histogram;
* **slow-tick detector** — when one tick's lag exceeds ``slow_tick_s`` the
  profiler dumps every live task's stack (newest frames) with the last
  active trace/span id to the log, increments ``cordum_slow_ticks_total``
  and keeps the dump on ``last_slow_tick`` so the telemetry beacon can ship
  a summary.  The trace id names the request the process was most recently
  working for when it stalled;
* **GC-pause counters** — ``gc.callbacks`` timing each collection into
  ``cordum_gc_pauses_total{generation}`` and ``cordum_gc_pause_seconds``
  (a generation-2 pause IS event-loop lag; correlating the two histograms
  separates GC stalls from blocking code).

Everything flows through the process's ``Metrics`` registry, so the
exporter ships it fleet-wide for free.
"""
from __future__ import annotations

import asyncio
import gc
import time
import traceback
from typing import Any, Optional

from ..infra import logging as logx
from ..infra.metrics import Metrics
from .tracer import last_active_context

DEFAULT_TICK_S = 0.25
DEFAULT_SLOW_TICK_S = 0.5
MAX_DUMP_TASKS = 12
MAX_DUMP_FRAMES = 6


class RuntimeProfiler:
    def __init__(
        self,
        metrics: Metrics,
        *,
        service: str = "",
        tick_s: float = DEFAULT_TICK_S,
        slow_tick_s: float = DEFAULT_SLOW_TICK_S,
    ) -> None:
        self.metrics = metrics
        self.service = service
        self.tick_s = max(0.01, tick_s)
        self.slow_tick_s = slow_tick_s
        self.last_slow_tick: Optional[dict[str, Any]] = None
        self._task: Optional[asyncio.Task] = None
        self._gc_start: dict[int, float] = {}
        self._gc_cb_installed = False

    # ------------------------------------------------------------------
    async def start(self) -> None:
        self._task = asyncio.ensure_future(self._loop())
        if not self._gc_cb_installed:
            gc.callbacks.append(self._on_gc)
            self._gc_cb_installed = True

    async def stop(self) -> None:
        if self._gc_cb_installed:
            try:
                gc.callbacks.remove(self._on_gc)
            except ValueError:
                logx.warn("gc callback already removed", service=self.service)
            self._gc_cb_installed = False
        if self._task is not None:
            task, self._task = self._task, None
            task.cancel()
            await logx.join_task(task, name="runtime-profiler")

    # ------------------------------------------------------------------
    async def _loop(self) -> None:
        while True:
            t0 = time.monotonic()
            await asyncio.sleep(self.tick_s)
            lag = max(0.0, time.monotonic() - t0 - self.tick_s)
            self.metrics.eventloop_lag.observe(lag)
            if lag >= self.slow_tick_s:
                try:
                    self._dump_slow_tick(lag)
                except Exception as e:  # noqa: BLE001 - diagnostics must not crash the host
                    logx.warn("slow-tick dump failed", err=str(e))

    def _dump_slow_tick(self, lag_s: float) -> None:
        """The loop just stalled for ``lag_s``: record who was running."""
        self.metrics.slow_ticks.inc()
        trace_id, span_id = last_active_context()
        tasks = []
        current = asyncio.current_task()
        for task in asyncio.all_tasks():
            if task is current or task.done():
                continue
            frames = task.get_stack(limit=MAX_DUMP_FRAMES)
            if not frames:
                continue
            stack = "".join(
                traceback.format_stack(f, limit=1)[0] for f in frames
            ).rstrip()
            tasks.append({"task": task.get_name(), "stack": stack})
            if len(tasks) >= MAX_DUMP_TASKS:
                break
        self.last_slow_tick = {
            "at_monotonic": time.monotonic(),
            "lag_s": round(lag_s, 4),
            "trace_id": trace_id,
            "span_id": span_id,
            "tasks": [t["task"] for t in tasks],
        }
        logx.warn(
            "slow event-loop tick",
            service=self.service,
            lag_s=round(lag_s, 4),
            trace_id=trace_id or "-",
            span_id=span_id or "-",
            tasks=len(tasks),
        )
        for t in tasks:
            logx.warn("slow-tick task stack", task=t["task"], stack=t["stack"])

    # ------------------------------------------------------------------
    def _on_gc(self, phase: str, info: dict) -> None:
        gen = int(info.get("generation", 0))
        if phase == "start":
            self._gc_start[gen] = time.monotonic()
        elif phase == "stop":
            t0 = self._gc_start.pop(gen, None)
            if t0 is not None:
                dur = time.monotonic() - t0
                self.metrics.gc_pauses.inc(generation=str(gen))
                self.metrics.gc_pause_seconds.observe(dur)

    # ------------------------------------------------------------------
    def health(self) -> dict[str, Any]:
        """Beacon fields the telemetry exporter ships (slow-tick summary)."""
        out: dict[str, Any] = {}
        if self.last_slow_tick is not None:
            out["last_slow_tick_lag_s"] = self.last_slow_tick["lag_s"]
            out["last_slow_tick_trace"] = self.last_slow_tick["trace_id"]
        return out
