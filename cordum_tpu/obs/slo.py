"""SLO tracker: multi-window burn rates over the fleet-aggregated series.

Objectives come from the ``slo:`` stanza in pools.yaml (docs/OBSERVABILITY.md
§Fleet telemetry)::

    slo:
      interactive:
        job_class: INTERACTIVE     # JobRequest.priority this objective covers
        latency_ms: 500            # latency objective threshold
        latency_target: 0.99       # fraction of jobs that must finish under it
        availability_target: 0.999 # fraction that must not FAIL/TIMEOUT (0=off)

The tracker evaluates each objective over two windows (5 m from the fine
ring, 1 h from the coarse ring) of the aggregator's merged
``cordum_job_e2e_seconds{job_class}`` histogram and
``cordum_jobs_completed_by_class_total{job_class,status}`` counter:

    error_fraction = bad / total            (per window)
    burn_rate      = error_fraction / (1 - target)

``burn_rate == 1.0`` means the error budget is being spent exactly at the
rate that exhausts it by the end of the SLO period; the classic
multi-window alert fires when BOTH the fast and slow windows burn hot
(fast-only = a blip, slow-only = stale damage already done).  States:
``page`` (5 m ≥ 14.4 AND 1 h ≥ 6 — the Google SRE workbook's 1h/5m page
pair), ``warn`` (either window ≥ 1.0), ``ok`` otherwise.  Latency is
bucket-quantized: the threshold snaps UP to the enclosing histogram bucket,
so a 250 ms objective is measured at the 250 ms bucket boundary.

Burn rates surface as ``cordum_slo_burn_rate{slo,window}`` gauges and in
``GET /api/v1/fleet``'s ``slo`` section — the measurement substrate the
ROADMAP item-2 admission controller will act on.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..infra.metrics import Metrics

WINDOWS = (("5m", 300.0), ("1h", 3600.0))
PAGE_FAST_BURN = 14.4  # 5 m window
PAGE_SLOW_BURN = 6.0  # 1 h window
_BAD_STATUSES = ("FAILED", "TIMEOUT")


@dataclass
class SLOObjective:
    name: str
    job_class: str = "BATCH"
    latency_ms: float = 1000.0
    latency_target: float = 0.99
    availability_target: float = 0.0  # 0 disables the availability objective

    @classmethod
    def from_doc(cls, name: str, doc: dict) -> "SLOObjective":
        return cls(
            name=name,
            job_class=str(doc.get("job_class", "BATCH")),
            latency_ms=float(doc.get("latency_ms", 1000.0)),
            latency_target=float(doc.get("latency_target", 0.99)),
            availability_target=float(doc.get("availability_target", 0.0)),
        )


class SLOTracker:
    def __init__(
        self, objectives: list[SLOObjective], *, metrics: Optional[Metrics] = None
    ) -> None:
        self.objectives = objectives
        self.metrics = metrics

    @classmethod
    def from_config(
        cls, slo_doc: dict, *, metrics: Optional[Metrics] = None
    ) -> "SLOTracker":
        """From the parsed pools.yaml ``slo:`` stanza (name → objective doc)."""
        return cls(
            [SLOObjective.from_doc(name, doc or {})
             for name, doc in sorted((slo_doc or {}).items())],
            metrics=metrics,
        )

    # ------------------------------------------------------------------
    def evaluate(self, aggregator) -> list[dict]:
        """Burn rates per objective per window from the aggregator's rings;
        sets the ``cordum_slo_burn_rate`` gauges as a side effect."""
        out = []
        deltas = {label: aggregator.window_delta(w_s) for label, w_s in WINDOWS}
        for obj in self.objectives:
            windows = {}
            for label, _ in WINDOWS:
                windows[label] = self._window_state(obj, deltas[label])
                if self.metrics is not None:
                    self.metrics.slo_burn_rate.set(
                        windows[label]["burn_rate"], slo=obj.name, window=label
                    )
            burn_fast = windows["5m"]["burn_rate"]
            burn_slow = windows["1h"]["burn_rate"]
            if burn_fast >= PAGE_FAST_BURN and burn_slow >= PAGE_SLOW_BURN:
                state = "page"
            elif burn_fast >= 1.0 or burn_slow >= 1.0:
                state = "warn"
            else:
                state = "ok"
            out.append({
                "name": obj.name,
                "job_class": obj.job_class,
                "latency_ms": obj.latency_ms,
                "latency_target": obj.latency_target,
                "availability_target": obj.availability_target,
                "windows": windows,
                "state": state,
            })
        return out

    # ------------------------------------------------------------------
    def _window_state(self, obj: SLOObjective, delta: dict) -> dict:
        lat_frac, lat_total = self._latency_error_fraction(obj, delta)
        avail_frac, avail_total = self._availability_error_fraction(obj, delta)
        lat_burn = _burn(lat_frac, obj.latency_target)
        avail_burn = (
            _burn(avail_frac, obj.availability_target)
            if obj.availability_target else 0.0
        )
        return {
            "span_s": round(delta["span_s"], 1),
            "total": lat_total,
            "latency_error_fraction": round(lat_frac, 6),
            "latency_burn_rate": round(lat_burn, 3),
            "availability_error_fraction": round(avail_frac, 6),
            "availability_burn_rate": round(avail_burn, 3),
            "availability_total": avail_total,
            "burn_rate": round(max(lat_burn, avail_burn), 3),
        }

    def _latency_error_fraction(
        self, obj: SLOObjective, delta: dict
    ) -> tuple[float, int]:
        buckets = delta.get("e2e_buckets") or []
        threshold_s = obj.latency_ms / 1000.0
        idx = None
        for i, b in enumerate(buckets):
            if b >= threshold_s - 1e-12:
                idx = i
                break
        total = 0
        good = 0
        for lk, series in (delta.get("e2e") or {}).items():
            if dict(lk).get("job_class", "") != obj.job_class:
                continue
            total += series["total"]
            if idx is not None:
                good += series["counts"][idx]
            # threshold above the last bucket: every bucketed observation is
            # good only up to +Inf resolution — count the whole total as good
            else:
                good += series["total"]
        if not total:
            return 0.0, 0
        return max(0.0, (total - good) / total), total

    def _availability_error_fraction(
        self, obj: SLOObjective, delta: dict
    ) -> tuple[float, int]:
        total = 0.0
        bad = 0.0
        for lk, v in (delta.get("by_class") or {}).items():
            labels = dict(lk)
            if labels.get("job_class", "") != obj.job_class:
                continue
            total += v
            if labels.get("status", "") in _BAD_STATUSES:
                bad += v
        if not total:
            return 0.0, 0
        return bad / total, int(total)


def _burn(error_fraction: float, target: float) -> float:
    budget = max(1e-9, 1.0 - target)
    return error_fraction / budget
