"""Telemetry exporter: the fleet telemetry plane's per-process write side.

Every control-plane process embeds a :class:`TelemetryExporter` that
publishes a :class:`~cordum_tpu.protocol.types.TelemetrySnapshot` on
``sys.telemetry.<service>`` every ``interval_s`` seconds: a health beacon
(role, shard/partition index, queue depths, uptime — whatever the hosting
service's ``health_fn`` reports) plus a **delta-encoded** snapshot of the
process's ``Metrics`` registry.  Deltas keep the wire small: only series
whose value changed since the last publish ride each snapshot, with a
periodic ``full=True`` snapshot (every ``full_every`` publishes) so a
late-joining aggregator converges on gauges and quiet series.

Cost discipline: the exporter is a timer, not a hot-path hook — the job
pipeline never calls into it.  Publishes are listener-gated like span
emission (``Bus.has_listener``), so a process with no aggregator attached
skips even the snapshot build.  Publish failures are logged, counted
(``cordum_telemetry_snapshots_dropped_total``) and never raised: telemetry
must not take down the telemetered process.
"""
from __future__ import annotations

import asyncio
import time
from typing import Any, Awaitable, Callable, Optional

from ..infra import logging as logx
from ..infra.bus import Bus
from ..infra.metrics import Metrics
from ..protocol import subjects as subj
from ..protocol.types import BusPacket, TelemetrySnapshot
from ..utils.ids import now_us

DEFAULT_INTERVAL_S = 2.0
DEFAULT_FULL_EVERY = 15  # one full snapshot per ~30 s at the default cadence

HealthFn = Callable[[], dict[str, Any]]
PublishFn = Callable[[str, BusPacket], Awaitable[None]]

_SeriesKey = tuple[str, tuple[tuple[str, str], ...]]


class TelemetryExporter:
    """Periodic metric-snapshot + health-beacon publisher for one process.

    ``publish`` overrides the bus publish (the statebus server routes its
    beacon to its own subscribers without being a bus client); ``health_fn``
    supplies the role-specific beacon fields.
    """

    def __init__(
        self,
        service: str,
        bus: Optional[Bus],
        metrics: Metrics,
        *,
        instance_id: str = "",
        interval_s: float = DEFAULT_INTERVAL_S,
        health_fn: Optional[HealthFn] = None,
        publish: Optional[PublishFn] = None,
        full_every: int = DEFAULT_FULL_EVERY,
    ) -> None:
        self.service = service
        self.bus = bus
        self.metrics = metrics
        self.instance_id = instance_id or service
        self.interval_s = max(0.05, interval_s)
        self.health_fn = health_fn
        self._publish = publish
        self.full_every = max(1, full_every)
        self.subject = subj.telemetry_subject(service)
        self.started_at_us = now_us()
        self._t0 = time.monotonic()
        self._seq = 0
        # last published value per series: counters/gauges → float,
        # histograms → (tuple(counts), sum, total)
        self._last_counters: dict[_SeriesKey, float] = {}
        self._last_gauges: dict[_SeriesKey, float] = {}
        self._last_hists: dict[_SeriesKey, tuple] = {}
        self._task: Optional[asyncio.Task] = None

    # ------------------------------------------------------------------
    async def start(self) -> None:
        self._task = asyncio.ensure_future(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            task, self._task = self._task, None
            task.cancel()
            await logx.join_task(task, name="telemetry-exporter")

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                await self.publish_once()
            except Exception as e:  # noqa: BLE001 - telemetry must not crash the host
                self.metrics.telemetry_dropped.inc(reason="publish_error")
                logx.warn("telemetry publish failed", service=self.service, err=str(e))

    # ------------------------------------------------------------------
    async def publish_once(self) -> bool:
        """Build and publish one snapshot; returns False when skipped
        (nobody listening).  Public so benches/tests can drive the cadence
        themselves."""
        if self._publish is None and (
            self.bus is None or not self.bus.has_listener(self.subject)
        ):
            return False
        snap = self.build_snapshot()
        pkt = BusPacket.wrap(snap, sender_id=self.instance_id)
        if self._publish is not None:
            await self._publish(self.subject, pkt)
        else:
            await self.bus.publish(self.subject, pkt)
        self.metrics.telemetry_snapshots.inc()
        return True

    def build_snapshot(self) -> TelemetrySnapshot:
        """One snapshot of the registry: full every ``full_every`` publishes
        (and on the first), changed-series delta otherwise."""
        full = self._seq % self.full_every == 0
        doc = self.metrics.snapshot()
        counters = self._delta_scalars(doc["counters"], self._last_counters, full)
        gauges = self._delta_scalars(doc["gauges"], self._last_gauges, full)
        hists = self._delta_hists(doc["histograms"], full)
        health = {"uptime_s": round(time.monotonic() - self._t0, 3)}
        if self.health_fn is not None:
            try:
                health.update(self.health_fn())
            except Exception as e:  # noqa: BLE001 - beacon best-effort, never fatal
                logx.warn("telemetry health probe failed",
                          service=self.service, err=str(e))
        snap = TelemetrySnapshot(
            service=self.service,
            instance=self.instance_id,
            seq=self._seq,
            started_at_us=self.started_at_us,
            uptime_s=health["uptime_s"],
            interval_s=self.interval_s,
            full=full,
            health=health,
            metrics={"counters": counters, "gauges": gauges, "histograms": hists},
        )
        self._seq += 1
        return snap

    # ------------------------------------------------------------------
    @staticmethod
    def _key(name: str, labels: dict) -> _SeriesKey:
        return (name, tuple(sorted(labels.items())))

    def _delta_scalars(
        self, fams: dict[str, list], last: dict[_SeriesKey, float], full: bool
    ) -> dict[str, list]:
        out: dict[str, list] = {}
        for name, series in fams.items():
            changed = []
            for labels, value in series:
                k = self._key(name, labels)
                if full or last.get(k) != value:
                    last[k] = value
                    changed.append([labels, value])
            if changed:
                out[name] = changed
        return out

    def _delta_hists(self, fams: dict[str, dict], full: bool) -> dict[str, dict]:
        out: dict[str, dict] = {}
        for name, fam in fams.items():
            ex_by_key = {
                self._key(name, labels): exmap
                for labels, exmap in fam.get("exemplars") or []
            }
            changed = []
            changed_ex = []
            for labels, counts, sum_, total in fam["series"]:
                k = self._key(name, labels)
                cur = (tuple(counts), sum_, total)
                if full or self._last_hists.get(k) != cur:
                    self._last_hists[k] = cur
                    changed.append([labels, counts, sum_, total])
                    exmap = ex_by_key.get(k)
                    if exmap:
                        # exemplars ride with their series (same delta
                        # cadence: a bucket only gains an exemplar when an
                        # observation moved the series)
                        changed_ex.append([labels, exmap])
            if changed:
                out[name] = {"buckets": fam["buckets"], "series": changed}
                if changed_ex:
                    out[name]["exemplars"] = changed_ex
        return out
