"""Span creation + context propagation (the flight recorder's write side).

A :class:`Tracer` is cheap and service-local: each control-plane service
owns one (``Tracer("scheduler", bus)``) and wraps its hot-path segments in
``async with tracer.span("policy-check"): ...``.  Span context flows two
ways:

* **in-process** — a ``contextvars.ContextVar`` holds the active
  ``(trace_id, span_id)`` pair, so nested spans parent themselves
  automatically (asyncio tasks inherit the context at creation time);
* **cross-process** — publishers stamp ``BusPacket.span_id`` /
  ``parent_span_id`` (see ``protocol/types.py``) and receivers pass
  ``pkt.span_id`` as ``parent_span_id`` when they open their own span.

Finished spans are published on the durable ``sys.trace.span`` subject,
fire-and-forget: tracing must never fail the traced work, so publish errors
are logged and swallowed.  Spans without a trace id are timed but not
published (nothing to attach them to).
"""
from __future__ import annotations

import contextlib
import contextvars
from typing import AsyncIterator, Optional

from ..infra import logging as logx
from ..infra.bus import Bus
from ..protocol import subjects as subj
from ..protocol.types import SPAN_ERROR, SPAN_OK, BusPacket, Span
from ..utils.ids import fast_id, now_us

# active (trace_id, span_id) for the current asyncio task tree
_CTX: contextvars.ContextVar[tuple[str, str]] = contextvars.ContextVar(
    "cordum_span_ctx", default=("", "")
)

# last span ANY task entered, readable across tasks/threads: contextvars are
# task-local, so the runtime profiler's slow-tick dump (which runs in its own
# task while the stalled work is suspended) could never see the stalled
# task's _CTX — this module-level echo is the cross-task best-effort view
_LAST_ACTIVE: list[str] = ["", ""]


def current_trace_context() -> tuple[str, str]:
    """→ ``(trace_id, span_id)`` of the active span ("" when untraced).
    Used to propagate context into side channels the bus doesn't carry,
    e.g. the remote safety-kernel HTTP headers."""
    return _CTX.get()


def last_active_context() -> tuple[str, str]:
    """→ the last ``(trace_id, span_id)`` any span in this process entered
    (cross-task; the profiler's slow-tick attribution)."""
    return (_LAST_ACTIVE[0], _LAST_ACTIVE[1])


TRACE_HEADER = "X-Cordum-Trace-Id"
SPAN_HEADER = "X-Cordum-Span-Id"


def trace_headers() -> dict[str, str]:
    """HTTP header pair carrying the current span context (empty dict when
    untraced) — the RPC-side analogue of ``BusPacket.span_id``."""
    trace_id, span_id = _CTX.get()
    if not trace_id:
        return {}
    return {TRACE_HEADER: trace_id, SPAN_HEADER: span_id}


class Tracer:
    """Service-local span factory + publisher."""

    def __init__(self, service: str, bus: Optional[Bus] = None) -> None:
        self.service = service
        self.bus = bus

    # ------------------------------------------------------------------
    # primitives (for code whose control flow doesn't fit a CM, e.g. the
    # worker's run-job state machine)
    # ------------------------------------------------------------------
    def begin(
        self,
        name: str,
        *,
        trace_id: str = "",
        parent_span_id: str = "",
        attrs: Optional[dict[str, str]] = None,
    ) -> Span:
        ctx_trace, ctx_span = _CTX.get()
        tid = trace_id or ctx_trace
        parent = parent_span_id
        if not parent and ctx_span and tid == ctx_trace:
            parent = ctx_span
        return Span(
            span_id=fast_id(),
            parent_span_id=parent,
            trace_id=tid,
            name=name,
            service=self.service,
            start_us=now_us(),
            attrs=dict(attrs or {}),
        )

    async def finish(self, span: Span, *, status: str = SPAN_OK) -> None:
        if not span.end_us:
            span.end_us = now_us()
        span.status = status
        await self.emit(span)

    async def emit(self, span: Span) -> None:
        """Publish a finished span; never raises into the traced work."""
        if self.bus is None or not span.trace_id:
            return
        if not self.bus.has_listener(subj.TRACE_SPAN):
            # no collector attached (1×1 bench / span-less deployments):
            # skip the wrap+publish entirely — an unheard loopback publish
            # is dropped at publish time anyway, and wire-backed buses
            # always answer True
            return
        try:
            await self.bus.publish(
                subj.TRACE_SPAN,
                BusPacket.wrap(span, trace_id=span.trace_id, sender_id=self.service),
            )
        except Exception as e:  # noqa: BLE001 - tracing must not fail the work
            logx.warn("span publish failed", span=span.name, err=str(e))

    # ------------------------------------------------------------------
    @contextlib.asynccontextmanager
    async def span(
        self,
        name: str,
        *,
        trace_id: str = "",
        parent_span_id: str = "",
        attrs: Optional[dict[str, str]] = None,
    ) -> AsyncIterator[Span]:
        """Time the enclosed block as a span and publish it on exit.

        The span becomes the ambient context for the block, so nested
        ``tracer.span(...)`` calls (even in other services' code running in
        this task) parent themselves under it.  Exceptions mark the span
        ``ERROR`` with the exception type in ``attrs["error"]`` and are
        re-raised untouched.
        """
        sp = self.begin(
            name, trace_id=trace_id, parent_span_id=parent_span_id, attrs=attrs
        )
        # value-restore rather than ContextVar tokens: a token must be reset
        # in the exact Context that created it, but eagerly-driven coroutines
        # (utils/eager.py) may enter a span in the caller's context and exit
        # in the continuation task's — restoring the saved value is identical
        # in the single-context case and benign in the split case
        prev = _CTX.get() if sp.trace_id else None
        if sp.trace_id:
            _CTX.set((sp.trace_id, sp.span_id))
            _LAST_ACTIVE[0] = sp.trace_id
            _LAST_ACTIVE[1] = sp.span_id
        status = SPAN_OK
        try:
            yield sp
        except BaseException as e:
            status = SPAN_ERROR
            sp.attrs.setdefault("error", type(e).__name__)
            raise
        finally:
            if prev is not None:
                _CTX.set(prev)
            await self.finish(sp, status=status)
