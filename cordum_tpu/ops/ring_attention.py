"""Ring attention: sequence-parallel exact attention over the ``sp`` axis.

Long-context jobs shard the sequence across devices; each device holds a
Q/K/V chunk.  K/V chunks rotate around the ring via ``ppermute`` (ICI
neighbor exchanges — bandwidth-optimal, no all-gather memory spike) while
each device accumulates its Q chunk's attention with an online (flash-style)
softmax: running max ``m``, normalizer ``l``, and unnormalized accumulator.
After ``sp`` steps every Q has attended to every K/V without any device ever
holding the full sequence.

This is the "ring attention or all-to-all sequence parallelism" requirement
(task brief / SURVEY §5 long-context): the all-to-all (KV-gather) flavor
lives in ``models/llama.py``; this op is the ring flavor for sequences too
long to gather.  Compute overlaps transfer naturally: XLA schedules the
next ppermute concurrently with the current chunk's matmuls.
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.compat import axis_size, shard_map_compat
from ..parallel.mesh import AXIS_DP, AXIS_SP

_NEG = -1e30


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool, scale: float):
    """Per-device body under shard_map; q: [B, Tq, H, D], k/v: [B, Tk, Hkv, D]."""
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    rep = h // k.shape[2]
    if rep > 1:  # GQA: expand KV heads once locally
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    q32 = q.astype(jnp.float32)
    q_pos = idx * tq + jnp.arange(tq)

    m0 = jnp.full((b, h, tq), _NEG, jnp.float32)
    l0 = jnp.zeros((b, h, tq), jnp.float32)
    acc0 = jnp.zeros((b, h, tq, d), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(step, carry):
        m, l, acc, k_cur, v_cur = carry
        owner = (idx - step) % n  # which shard's K/V we currently hold
        k_pos = owner * tk + jnp.arange(tk)
        scores = (
            jnp.einsum("bqhd,bkhd->bhqk", q32, k_cur.astype(jnp.float32)) * scale
        )
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None, :, :], scores, _NEG)
        chunk_max = jnp.max(scores, axis=-1)
        new_m = jnp.maximum(m, chunk_max)
        correction = jnp.exp(m - new_m)
        p = jnp.exp(scores - new_m[..., None])
        l = l * correction + jnp.sum(p, axis=-1)
        acc = acc * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32)
        )
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return new_m, l, acc, k_nxt, v_nxt

    m, l, acc, _, _ = jax.lax.fori_loop(0, n, body, (m0, l0, acc0, k, v))
    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B, H, Tq, D]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    sp_axis: str = AXIS_SP,
    dp_axis: str = AXIS_DP,
) -> jax.Array:
    """Sequence-parallel attention.  q: [B, T, H, D]; k/v: [B, T, Hkv, D]
    with T sharded over ``sp_axis`` and B over ``dp_axis``.  Returns [B, T, H, D]
    with the same sharding as q."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    spec = P(dp_axis, sp_axis, None, None)
    fn = shard_map_compat(
        partial(_ring_attention_local, axis_name=sp_axis, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def reference_attention(q, k, v, *, causal: bool = True) -> jax.Array:
    """Single-device exact attention for correctness checks."""
    b, t, h, d = q.shape
    rep = h // k.shape[2]
    if rep > 1:
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    scores = scores / math.sqrt(d)
    if causal:
        mask = jnp.arange(t)[:, None] >= jnp.arange(t)[None, :]
        scores = jnp.where(mask[None, None], scores, _NEG)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bhqd", probs, v.astype(jnp.float32))
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)
