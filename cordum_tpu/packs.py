"""Pack system: installable behavior bundles ("products").

Recreates the reference pack pipeline (``core/controlplane/gateway/packs.go``
+ ``cmd/cordumctl/pack.go``; manifest example
``examples/demo-guardrails/pack.yaml``): a pack directory holds a
``pack.yaml`` manifest declaring topics (with capability/risk tags),
resource workflows + JSON schemas, config overlays (JSON-merge-patch onto
config-service docs), policy overlays (rule fragments installed under the
``cfg:system:policy/`` namespace with an ``enabled`` toggle), and policy
simulations that must pass before the install commits.

Install is plan → apply → verify → rollback-on-failure; installed packs are
recorded in the registry doc ``cfg:system:packs``.

Manifest shape::

    apiVersion: cordum-tpu/v1
    kind: Pack
    id: demo-guardrails
    name: Demo guardrails
    version: 0.1.0
    topics:
      - topic: job.tpu.infer
        capability: tpu
        risk_tags: [model-inference]
    resources:
      workflows: [workflows/*.yaml]      # or inline: [{...}]
      schemas:   [schemas/*.json]        # or inline: {id: {...}}
    overlays:
      config:
        - scope: system
          id: default
          patch: {rate_limits: {concurrent_jobs: 8}}
      policy:
        - id: guardrails
          fragment:
            enabled: true
            rules: [...]
    simulations:
      - name: deny-destructive
        request: {topic: job.x, metadata: {risk_tags: [destructive]}}
        expect: DENY
"""
from __future__ import annotations

import glob as globmod
import json
import os
from dataclasses import dataclass, field
from typing import Any, Optional

import yaml

from .infra import logging as logx
from .infra.configsvc import ConfigService
from .infra.schemareg import SchemaRegistry
from .protocol.types import JobMetadata, PolicyCheckRequest
from .utils.ids import now_us
from .workflow.models import Workflow
from .workflow.store import WorkflowStore

PACKS_REGISTRY_ID = "packs"  # cfg:system:packs
POLICY_PREFIX = "policy/"
API_VERSION = "cordum-tpu/v1"


class PackError(Exception):
    pass


@dataclass
class PackManifest:
    id: str = ""
    name: str = ""
    version: str = "0.0.0"
    description: str = ""
    topics: list[dict] = field(default_factory=list)
    workflows: list[dict] = field(default_factory=list)       # resolved docs
    schemas: dict[str, dict] = field(default_factory=dict)    # id → schema
    config_overlays: list[dict] = field(default_factory=list)
    policy_overlays: list[dict] = field(default_factory=list)
    simulations: list[dict] = field(default_factory=list)


def load_pack_dir(path: str) -> PackManifest:
    manifest_path = os.path.join(path, "pack.yaml")
    if not os.path.exists(manifest_path):
        raise PackError(f"no pack.yaml in {path}")
    with open(manifest_path) as f:
        doc = yaml.safe_load(f) or {}
    if doc.get("apiVersion") != API_VERSION or doc.get("kind") != "Pack":
        raise PackError(f"not a {API_VERSION} Pack manifest")
    m = PackManifest(
        id=str(doc.get("id", "")),
        name=str(doc.get("name", doc.get("id", ""))),
        version=str(doc.get("version", "0.0.0")),
        description=str(doc.get("description", "")),
        topics=list(doc.get("topics") or []),
        simulations=list(doc.get("simulations") or []),
    )
    if not m.id:
        raise PackError("pack id is required")
    res = doc.get("resources") or {}
    for entry in res.get("workflows") or []:
        if isinstance(entry, dict):
            m.workflows.append(entry)
        else:
            for fp in sorted(globmod.glob(os.path.join(path, entry))):
                with open(fp) as f:
                    m.workflows.append(yaml.safe_load(f) or {})
    schemas = res.get("schemas")
    if isinstance(schemas, dict):
        m.schemas.update(schemas)
    else:
        for entry in schemas or []:
            for fp in sorted(globmod.glob(os.path.join(path, entry))):
                with open(fp) as f:
                    sid = os.path.splitext(os.path.basename(fp))[0]
                    m.schemas[sid] = json.load(f)
    overlays = doc.get("overlays") or {}
    m.config_overlays = list(overlays.get("config") or [])
    m.policy_overlays = list(overlays.get("policy") or [])
    return m


def manifest_from_doc(doc: dict) -> PackManifest:
    """Inline manifest (HTTP install path): resources must be inline."""
    m = PackManifest(
        id=str(doc.get("id", "")),
        name=str(doc.get("name", doc.get("id", ""))),
        version=str(doc.get("version", "0.0.0")),
        description=str(doc.get("description", "")),
        topics=list(doc.get("topics") or []),
        simulations=list(doc.get("simulations") or []),
    )
    if not m.id:
        raise PackError("pack id is required")
    res = doc.get("resources") or {}
    m.workflows = [w for w in (res.get("workflows") or []) if isinstance(w, dict)]
    schemas = res.get("schemas") or {}
    if isinstance(schemas, dict):
        m.schemas = dict(schemas)
    overlays = doc.get("overlays") or {}
    m.config_overlays = list(overlays.get("config") or [])
    m.policy_overlays = list(overlays.get("policy") or [])
    return m


class PackInstaller:
    """plan → apply (with undo journal) → verify → rollback-on-failure."""

    def __init__(
        self,
        *,
        configsvc: ConfigService,
        schemas: SchemaRegistry,
        wf_store: WorkflowStore,
        kernel: Any = None,  # SafetyKernel; needed for simulations + reload
    ):
        self.configsvc = configsvc
        self.schemas = schemas
        self.wf_store = wf_store
        self.kernel = kernel

    # -- registry -------------------------------------------------------
    async def list_installed(self) -> dict[str, dict]:
        doc = await self.configsvc.get("system", PACKS_REGISTRY_ID)
        return dict(doc.data) if doc else {}

    async def _record(self, m: PackManifest, record: dict) -> None:
        installed = await self.list_installed()
        installed[m.id] = record
        await self.configsvc.set("system", PACKS_REGISTRY_ID, installed)

    # -- plan -----------------------------------------------------------
    def plan(self, m: PackManifest) -> list[str]:
        actions = []
        for wf in m.workflows:
            actions.append(f"install workflow {wf.get('id', '?')}")
        for sid in m.schemas:
            actions.append(f"register schema {sid}")
        for ov in m.config_overlays:
            actions.append(f"patch config {ov.get('scope')}:{ov.get('id')}")
        for ov in m.policy_overlays:
            actions.append(f"install policy fragment {POLICY_PREFIX}{m.id}/{ov.get('id')}")
        for sim in m.simulations:
            actions.append(f"verify simulation {sim.get('name', '?')}")
        return actions

    # -- install --------------------------------------------------------
    async def install(self, m: PackManifest) -> dict:
        undo: list = []
        record: dict = {
            "id": m.id, "name": m.name, "version": m.version,
            "installed_at_us": now_us(),
            "workflows": [], "schemas": [], "policy_fragments": [],
            "config_overlays": [],
        }
        try:
            for wdoc in m.workflows:
                wf = Workflow.from_dict(wdoc)
                errs = wf.validate()
                if errs:
                    raise PackError(f"workflow {wf.id}: {'; '.join(errs)}")
                prev = await self.wf_store.get_workflow(wf.id)
                await self.wf_store.put_workflow(wf)
                undo.append(("workflow", wf.id, prev))
                record["workflows"].append(wf.id)
            for sid, schema in m.schemas.items():
                prev = await self.schemas.get(sid)
                await self.schemas.put(sid, schema)
                undo.append(("schema", sid, prev))
                record["schemas"].append(sid)
            for ov in m.config_overlays:
                scope, doc_id = str(ov.get("scope", "system")), str(ov.get("id", "default"))
                prev_doc = await self.configsvc.get(scope, doc_id)
                await self.configsvc.patch(scope, doc_id, ov.get("patch") or {})
                undo.append(("config", (scope, doc_id), prev_doc.data if prev_doc else None))
                record["config_overlays"].append({"scope": scope, "id": doc_id})
            for ov in m.policy_overlays:
                frag_id = f"{POLICY_PREFIX}{m.id}/{ov.get('id', 'fragment')}"
                prev_doc = await self.configsvc.get("system", frag_id)
                await self.configsvc.set("system", frag_id, ov.get("fragment") or {})
                undo.append(("policy", frag_id, prev_doc.data if prev_doc else None))
                record["policy_fragments"].append(frag_id)
            if self.kernel is not None and (m.policy_overlays or m.config_overlays):
                await self.kernel.reload()
            await self._verify(m)
            await self._record(m, record)
            logx.info("pack installed", pack=m.id, version=m.version)
            return record
        except Exception:
            await self._rollback(undo)
            raise

    async def _verify(self, m: PackManifest) -> None:
        """Run the pack's policy simulations against the live kernel
        (reference runPolicySimulation, packs.go:1725)."""
        if not m.simulations:
            return
        if self.kernel is None:
            raise PackError("pack declares simulations but no kernel is wired")
        for sim in m.simulations:
            reqdoc = sim.get("request") or {}
            meta = reqdoc.get("metadata")
            req = PolicyCheckRequest(
                tenant_id=str(reqdoc.get("tenant_id", "")),
                topic=str(reqdoc.get("topic", "")),
                labels={str(k): str(v) for k, v in (reqdoc.get("labels") or {}).items()},
                metadata=JobMetadata.from_dict(meta) if meta else None,
            )
            resp = await self.kernel.evaluate_raw(req)
            expect = str(sim.get("expect", "")).upper()
            if expect and resp.decision != expect:
                raise PackError(
                    f"simulation {sim.get('name', '?')}: expected {expect}, got "
                    f"{resp.decision} ({resp.reason})"
                )

    async def _rollback(self, undo: list) -> None:
        for kind, key, prev in reversed(undo):
            try:
                if kind == "workflow":
                    if prev is None:
                        await self.wf_store.delete_workflow(key)
                    else:
                        await self.wf_store.put_workflow(prev)
                elif kind == "schema":
                    if prev is None:
                        await self.schemas.delete(key)
                    else:
                        await self.schemas.put(key, prev)
                elif kind == "config":
                    scope, doc_id = key
                    if prev is None:
                        await self.configsvc.delete(scope, doc_id)
                    else:
                        await self.configsvc.set(scope, doc_id, prev)
                elif kind == "policy":
                    if prev is None:
                        await self.configsvc.delete("system", key)
                    else:
                        await self.configsvc.set("system", key, prev)
            except Exception:
                logx.error("pack rollback step failed", kind=kind, key=str(key))
        if self.kernel is not None:
            try:
                await self.kernel.reload()
            except Exception as e:  # noqa: BLE001 - rollback must not mask install error
                logx.error("kernel reload failed after pack rollback", err=str(e))

    # -- uninstall -------------------------------------------------------
    async def uninstall(self, pack_id: str) -> bool:
        installed = await self.list_installed()
        record = installed.pop(pack_id, None)
        if record is None:
            return False
        for wf_id in record.get("workflows", []):
            await self.wf_store.delete_workflow(wf_id)
        for sid in record.get("schemas", []):
            await self.schemas.delete(sid)
        for frag_id in record.get("policy_fragments", []):
            await self.configsvc.delete("system", frag_id)
        # config overlays are merge-patches; uninstall does not attempt to
        # un-merge them (matches reference semantics: overlays persist)
        await self.configsvc.set("system", PACKS_REGISTRY_ID, installed)
        if self.kernel is not None:
            await self.kernel.reload()
        logx.info("pack uninstalled", pack=pack_id)
        return True


# ---------------------------------------------------------------- catalogs


CATALOGS_DOC_ID = "pack_catalogs"  # cfg:system:pack_catalogs


class PackCatalog:
    """Pack catalogs: named collections of installable packs.

    The reference's marketplace catalogs fetch from allowed HTTP hosts
    (packs.go:933-1368); this deployment is network-isolated, so catalogs
    are *local directories* gated by an allowed-roots list stored alongside
    them — the same trust boundary (admins control which sources installs
    may come from), without the egress.
    """

    def __init__(self, configsvc: ConfigService, installer: PackInstaller):
        self.configsvc = configsvc
        self.installer = installer

    async def _doc(self) -> dict:
        doc = await self.configsvc.get("system", CATALOGS_DOC_ID)
        return dict(doc.data) if doc else {"catalogs": {}, "allowed_roots": []}

    async def add_catalog(self, name: str, path: str) -> dict:
        data = await self._doc()
        # Resolve symlinks before the containment check: a plain prefix test
        # would let /opt/packs-evil pass for allowed root /opt/packs, and a
        # symlink inside an allowed root could escape it.
        root = os.path.realpath(path)
        allowed = data.get("allowed_roots") or []
        if allowed and not any(self._contains(os.path.realpath(a), root) for a in allowed):
            raise PackError(f"catalog path {root} outside allowed roots {allowed}")
        if not os.path.isdir(root):
            raise PackError(f"catalog path {root} is not a directory")
        data.setdefault("catalogs", {})[name] = {"path": root}
        await self.configsvc.set("system", CATALOGS_DOC_ID, data)
        return data["catalogs"][name]

    @staticmethod
    def _contains(ancestor: str, path: str) -> bool:
        """True iff ``path`` is ``ancestor`` or lies inside it (both resolved)."""
        try:
            return os.path.commonpath([ancestor, path]) == ancestor
        except ValueError:  # different drives / mixed abs-rel
            return False

    async def set_allowed_roots(self, roots: list[str]) -> None:
        data = await self._doc()
        data["allowed_roots"] = [os.path.abspath(r) for r in roots]
        await self.configsvc.set("system", CATALOGS_DOC_ID, data)

    async def list_catalogs(self) -> dict:
        return (await self._doc()).get("catalogs", {})

    async def list_packs(self, catalog: str) -> list[dict]:
        catalogs = await self.list_catalogs()
        entry = catalogs.get(catalog)
        if entry is None:
            raise PackError(f"unknown catalog {catalog!r}")
        out = []
        for child in sorted(os.listdir(entry["path"])):
            pdir = os.path.join(entry["path"], child)
            if os.path.exists(os.path.join(pdir, "pack.yaml")):
                try:
                    m = load_pack_dir(pdir)
                    out.append({"id": m.id, "version": m.version, "name": m.name,
                                "description": m.description})
                except PackError:
                    continue
        return out

    async def install_from_catalog(self, catalog: str, pack_id: str) -> dict:
        catalogs = await self.list_catalogs()
        entry = catalogs.get(catalog)
        if entry is None:
            raise PackError(f"unknown catalog {catalog!r}")
        for child in sorted(os.listdir(entry["path"])):
            pdir = os.path.join(entry["path"], child)
            if not os.path.exists(os.path.join(pdir, "pack.yaml")):
                continue
            try:
                m = load_pack_dir(pdir)
            except PackError:
                continue
            if m.id == pack_id:
                return await self.installer.install(m)
        raise PackError(f"pack {pack_id!r} not found in catalog {catalog!r}")


# ---------------------------------------------------------------- CLI glue


PACK_SCAFFOLD = """apiVersion: cordum-tpu/v1
kind: Pack
id: {pack_id}
name: {pack_id}
version: 0.1.0
description: Example pack
topics:
  - topic: job.{pack_id}.echo
    capability: echo
    risk_tags: []
resources:
  workflows:
    - id: {pack_id}-hello
      name: hello
      steps:
        greet:
          topic: job.{pack_id}.echo
          input:
            message: "hello from {pack_id}: ${{input.name}}"
overlays:
  config: []
  policy: []
simulations: []
"""


def cli_pack(args) -> None:
    """`cordumctl pack ...` — create scaffolds locally; install/list/show
    go through the gateway HTTP API."""
    import httpx

    from .cli import DEFAULT_API, _check, _client, _die, _print

    if args.action == "create":
        pack_id = args.target or "my-pack"
        path = os.path.join(args.dir, pack_id)
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "pack.yaml"), "w") as f:
            f.write(PACK_SCAFFOLD.format(pack_id=pack_id))
        print(f"created {path}/pack.yaml")
        return
    if args.action == "verify":
        m = load_pack_dir(args.target or args.dir)
        print(f"pack {m.id} v{m.version}: {len(m.workflows)} workflow(s), "
              f"{len(m.schemas)} schema(s), {len(m.policy_overlays)} policy overlay(s)")
        return
    with _client() as c:
        if args.action == "install":
            m = load_pack_dir(args.target or args.dir)
            doc = {
                "id": m.id, "name": m.name, "version": m.version,
                "topics": m.topics,
                "resources": {"workflows": m.workflows, "schemas": m.schemas},
                "overlays": {"config": m.config_overlays, "policy": m.policy_overlays},
                "simulations": m.simulations,
            }
            _print(_check(c.post("/api/v1/packs", json=doc)))
        elif args.action == "uninstall":
            _print(_check(c.delete(f"/api/v1/packs/{args.target}")))
        elif args.action == "list":
            _print(_check(c.get("/api/v1/packs")))
        elif args.action == "show":
            _print(_check(c.get(f"/api/v1/packs/{args.target}")))
