"""JAX version-compat shims for the model/ops layer.

JAX renames and removes keyword arguments across minor releases faster than
TPU pod fleets upgrade (the multi-pod version-skew problem, cf. MPMD pipeline
parallelism deployments).  Passing a version-gated kwarg straight into
``shard_map``/``jit`` therefore breaks whole test tiers when the installed
jax predates (or postdates) the kwarg — e.g. ``check_vma`` landed as the
rename of ``check_rep``, so jax 0.4.x raises ``TypeError`` on it.

All ``shard_map`` call sites in this repo go through :func:`shard_map_compat`
so exactly one module knows about the skew.  cordumlint rule CL006 enforces
this: version-gated kwargs passed to ``shard_map``/``jit`` outside this
module are flagged.
"""
from __future__ import annotations

import inspect
from typing import Any, Callable

import jax

try:  # jax >= 0.7 exposes shard_map at top level
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map


def axis_size(axis_name: str) -> int:
    """Static size of a mapped mesh axis, inside a ``shard_map`` body.

    ``jax.lax.axis_size`` only exists in newer jax; on older releases
    ``psum(1, axis)`` constant-folds to the same static int.
    """
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return int(fn(axis_name))
    return int(jax.lax.psum(1, axis_name))

# kwarg renames, newest name first: {new_name: old_name}
_SHARD_MAP_RENAMES = {"check_vma": "check_rep"}

_accepted_cache: frozenset[str] | None = None


def _shard_map_accepted_kwargs() -> frozenset[str]:
    """Keyword names the installed ``shard_map`` accepts (cached)."""
    global _accepted_cache
    if _accepted_cache is None:
        try:
            params = inspect.signature(_shard_map).parameters
            if any(p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()):
                _accepted_cache = frozenset(params) | frozenset(_SHARD_MAP_RENAMES)
            else:
                _accepted_cache = frozenset(params)
        except (TypeError, ValueError):  # signature unavailable: pass-through
            _accepted_cache = frozenset(_SHARD_MAP_RENAMES) | frozenset(
                _SHARD_MAP_RENAMES.values()
            )
    return _accepted_cache


def donated_train_step(
    step: Callable[..., Any],
    *,
    mesh: Any,
    param_shardings: Any,
    batch_sharding: Any,
) -> Callable[..., Any]:
    """``jit(step, donate_argnums=(0, 1))`` with optimizer-state shardings
    pinned to the concrete first-call value.

    With ``out_shardings=None`` the compiler may pick a different layout for
    a donated opt-state buffer than its input had; newer jax silently skips
    the alias, but older jaxlibs (0.4.x) crash at dispatch with an INTERNAL
    aliased-buffer size mismatch.  Deriving the opt-state shardings from the
    real value and pinning them on both sides makes every donated alias
    exact on every jax version.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    jitted: Callable[..., Any] | None = None
    replicated = NamedSharding(mesh, PartitionSpec())

    def _pin(x: Any) -> Any:
        # keep mesh-native shardings (mu/nu mirror the param shardings);
        # anything else (uncommitted scalars like the adam step count) is
        # pinned replicated so in==out and the donated alias is exact
        s = getattr(x, "sharding", None)
        if isinstance(s, NamedSharding) and s.mesh == mesh:
            return s
        return replicated

    def wrapper(params: Any, opt_state: Any, batch: Any) -> Any:
        nonlocal jitted
        if jitted is None:
            opt_shardings = jax.tree.map(_pin, opt_state)
            jitted = jax.jit(
                step,
                in_shardings=(param_shardings, opt_shardings, batch_sharding),
                out_shardings=(param_shardings, opt_shardings, None),
                donate_argnums=(0, 1),
            )
        return jitted(params, opt_state, batch)

    return wrapper


def shard_map_compat(f: Callable[..., Any], **kwargs: Any) -> Callable[..., Any]:
    """``shard_map`` that tolerates kwarg skew across jax versions.

    Version-gated kwargs (currently ``check_vma``/``check_rep``) are
    translated to whatever the installed jax accepts, or dropped when the
    concept does not exist there at all.  Core kwargs (``mesh``,
    ``in_specs``, ``out_specs``) pass through untouched.
    """
    accepted = _shard_map_accepted_kwargs()
    call_kwargs: dict[str, Any] = {}
    for name, value in kwargs.items():
        if name in accepted:
            call_kwargs[name] = value
            continue
        old = _SHARD_MAP_RENAMES.get(name)
        if old is not None and old in accepted:
            call_kwargs[old] = value
        elif name in _SHARD_MAP_RENAMES or name in _SHARD_MAP_RENAMES.values():
            continue  # concept absent in this jax: drop rather than crash
        else:
            call_kwargs[name] = value  # unknown kwarg: surface the TypeError
    return _shard_map(f, **call_kwargs)
