"""Device mesh + sharding helpers: the SPMD substrate for TPU workers.

The reference has no in-process parallelism (NATS/Redis control plane only;
SURVEY.md §2.4) — in the TPU-native design, every worker owns a slice and
runs jobs as SPMD computations over a ``jax.sharding.Mesh``.  These helpers
build meshes that match the physical slice, derive the topology string the
worker reports in heartbeats, and provide the standard axis vocabulary:

  * ``dp``   — data parallel (batch)
  * ``tp``   — tensor/model parallel (MXU-heavy dims, rides ICI)
  * ``sp``   — sequence/context parallel (long-context activations)
  * ``ep``   — expert parallel (MoE routing)
  * ``pp``   — pipeline parallel (layer stages)

Meshes are created over whatever devices JAX exposes (TPU slice in prod,
``xla_force_host_platform_device_count`` CPU devices in tests).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DP = "dp"
AXIS_TP = "tp"
AXIS_SP = "sp"
AXIS_EP = "ep"
AXIS_PP = "pp"


@dataclass
class MeshSpec:
    """Logical mesh shape; -1 on one axis means "absorb remaining devices"."""

    dp: int = -1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        if n_devices < 1:
            raise ValueError("need at least one device")
        sizes = {"dp": self.dp, "tp": self.tp, "sp": self.sp, "ep": self.ep, "pp": self.pp}
        bad = {k: v for k, v in sizes.items() if v != -1 and v < 1}
        if bad:
            # 0 / negative axes must fail loudly: a zero axis used to slip
            # through `prod(v for v in ... if v > 0)` and build a 0-sized
            # mesh dimension downstream
            raise ValueError(f"mesh axes must be -1 or >= 1, got {bad}")
        fixed = math.prod(v for v in sizes.values() if v > 0)
        free = [k for k, v in sizes.items() if v == -1]
        if len(free) > 1:
            raise ValueError("at most one mesh axis may be -1")
        if fixed > n_devices:
            raise ValueError(f"mesh {sizes} needs {fixed} devices, have {n_devices}")
        if free:
            if n_devices % fixed:
                raise ValueError(f"{n_devices} devices not divisible by fixed axes {fixed}")
            sizes[free[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(f"mesh {sizes} needs {fixed} devices, have {n_devices}")
        return sizes


def build_mesh(
    spec: MeshSpec | None = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_names: Optional[Sequence[str]] = None,
) -> Mesh:
    """Build a named mesh over the devices.  Axes of size 1 are kept so the
    same PartitionSpecs work at every scale (XLA drops trivial collectives)."""
    devs = list(devices) if devices is not None else list(jax.devices())
    spec = spec or MeshSpec()
    sizes = spec.resolve(len(devs))
    names = list(axis_names) if axis_names else [AXIS_DP, AXIS_TP, AXIS_SP, AXIS_EP, AXIS_PP]
    shape = [sizes[n] for n in names]
    arr = np.array(devs).reshape(shape)
    return Mesh(arr, axis_names=tuple(names))


def simple_mesh(n_tp: int = 1, *, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """The common dp×tp mesh: tp fixed, dp absorbs the rest."""
    devs = list(devices) if devices is not None else list(jax.devices())
    n = len(devs)
    if n % n_tp:
        raise ValueError(f"{n} devices not divisible by tp={n_tp}")
    arr = np.array(devs).reshape(n // n_tp, n_tp)
    return Mesh(arr, axis_names=(AXIS_DP, AXIS_TP))


def slice_topology(devices: Optional[Sequence[jax.Device]] = None) -> str:
    """Physical topology string for heartbeats (e.g. ``2x2x1``); falls back
    to a flat ``N`` chip count when coords are unavailable (CPU backend)."""
    devs = list(devices) if devices is not None else list(jax.devices())
    coords = [getattr(d, "coords", None) for d in devs]
    if any(c is None for c in coords):
        return str(len(devs))
    dims = len(coords[0])
    extents = [len({c[i] for c in coords}) for i in range(dims)]
    return "x".join(str(e) for e in extents)


def device_kind(devices: Optional[Sequence[jax.Device]] = None) -> str:
    devs = list(devices) if devices is not None else list(jax.devices())
    return devs[0].device_kind if devs else ""


def hbm_stats(devices: Optional[Sequence[jax.Device]] = None) -> tuple[float, float]:
    """(used_gb, total_gb) summed over devices; (0,0) when unsupported."""
    devs = list(devices) if devices is not None else list(jax.devices())
    used = total = 0.0
    for d in devs:
        try:
            st = d.memory_stats()
        except Exception:
            return 0.0, 0.0
        if not st:
            return 0.0, 0.0
        used += st.get("bytes_in_use", 0) / 1e9
        total += st.get("bytes_limit", st.get("bytes_reservable_limit", 0)) / 1e9
    return used, total


def shard_batch(mesh: Mesh, batch, axes: Sequence[str] = (AXIS_DP,)):
    """Place a pytree of [B, ...] arrays with batch sharded over the given
    mesh axes and everything else replicated."""
    sharding = NamedSharding(mesh, P(tuple(axes) if len(axes) > 1 else axes[0]))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())
