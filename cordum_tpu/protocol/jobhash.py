"""Deterministic job-content hashing for approval binding.

An approval must be bound to the *exact* job content it was granted for;
otherwise a mutated job could ride an old approval.  The hash covers the
canonical JSON of the JobRequest minus mutable approval bookkeeping labels
and the injected effective-config env (reference semantics:
``core/controlplane/scheduler/job_hash.go:16-47``).
"""
from __future__ import annotations

import hashlib
import json

from .types import ENV_EFFECTIVE_CONFIG, JobRequest

# cordum.partition is shard-routing metadata stamped at dispatch time; it
# must not shift the hash an approval was bound to before sharding existed
_EXCLUDED_LABEL_PREFIXES = ("approval_", "cordum.bus_msg_id", "cordum.partition")
_EXCLUDED_ENV_KEYS = (ENV_EFFECTIVE_CONFIG,)


def job_hash(req: JobRequest) -> str:
    d = req.to_dict()
    labels = {
        k: v
        for k, v in (d.get("labels") or {}).items()
        if not any(k.startswith(p) for p in _EXCLUDED_LABEL_PREFIXES)
    }
    env = {k: v for k, v in (d.get("env") or {}).items() if k not in _EXCLUDED_ENV_KEYS}
    d["labels"] = labels
    d["env"] = env
    canonical = json.dumps(d, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canonical.encode()).hexdigest()
