"""Keyspace partitioning for the sharded control plane.

Scheduler shards and statebus partitions both carve the id space with the
same function: :func:`partition_of` maps any string id (job id, KV routing
key, subject token) onto ``[0, count)`` deterministically and **stably
across processes and Python versions** — it is the contract that lets the
gateway stamp a partition at submit time and a scheduler shard started
days later in another process agree on who owns the job (the thin
consistency layer of Gavel-style partitioned deciders, PAPERS.md).

CRC-32 rather than ``hash()``: the builtin is salted per process
(PYTHONHASHSEED), which would scatter ownership on every restart.
"""
from __future__ import annotations

import zlib


def partition_of(key: str, count: int) -> int:
    """Stable partition for ``key`` in ``[0, count)``; 0 when unsharded."""
    if count <= 1:
        return 0
    return zlib.crc32(key.encode()) % count


def owns(key: str, index: int, count: int) -> bool:
    """True iff shard/partition ``index`` of ``count`` owns ``key``."""
    return partition_of(key, count) == index
