"""Bus subject constants (reference ``core/protocol/capsdk/constants.go:3-12``)."""
from __future__ import annotations

SUBMIT = "sys.job.submit"
RESULT = "sys.job.result"
HEARTBEAT = "sys.heartbeat"
PROGRESS = "sys.job.progress"
CANCEL = "sys.job.cancel"
DLQ = "sys.job.dlq"
WORKFLOW_EVENT = "sys.workflow.event"
# workflow-internal step results (``context.*`` steps executed in-engine,
# docs/WORKFLOWS.md §Context engine): the same JobResult payloads as RESULT,
# but on a subject the scheduler does NOT consume — the jobstore never saw
# these jobs, so riding ``sys.job.result`` would log an illegal-transition
# error per context step.  The workflow-engine queue group consumes it, so
# any replica may apply the result under the run lock.
STEP_RESULT = "sys.workflow.step.result"
# graceful worker drain (docs/SERVING.md §Migration, drain, and failover):
# fan-out — every worker hears it and the addressed one drains.  Not
# durable: a drain request is an operator action, re-issued if lost.
DRAIN = "sys.worker.drain"
# batch-job preemption (docs/ADMISSION.md §Preemption): fan-out — every
# worker hears the JobPreempt and the one holding the job hands it back
# (SESSION_REQUEUE) where safe.  Not durable: the preemption governor
# re-issues while interactive pressure persists, so a lost request only
# delays one preemption by an evaluation interval.
PREEMPT = "sys.job.preempt"
# overload-pressure beacons from the gateway admission controller
# (docs/ADMISSION.md): the scheduler's preemption governor and the serving
# engines consume them.  Not durable: pressure is a live signal.
ADMISSION_PRESSURE = "sys.admission.pressure"
# serving disaggregation (docs/SERVING.md §Disaggregation): ownership
# announcements after a session migration commits (the adopting worker
# fans out SessionMoved so scheduler shards retarget session affinity to
# the new owner), and the decode rebalancer's move requests (the scheduler
# governor fans out SessionRebalance; the addressed worker migrates its
# cheapest sessions toward the named headroom target).  Neither is
# durable: affinity self-heals via eviction + re-election on loss, and the
# governor re-evaluates skew every interval so a lost rebalance request
# only delays one move.
SERVING_MOVED = "sys.serving.moved"
SERVING_REBALANCE = "sys.serving.rebalance"
# gang scheduling (docs/GANG.md): every multi-chip gang owns one subject,
# ``sys.job.gang.<gang_id>``, carrying its whole coordination traffic —
# member rendezvous beacons, the abort fan-out, per-member completion
# reports, and MPMD stage activations/cotangents.  Fan-out (members and the
# owning scheduler shard all subscribe) and deliberately NOT durable: gang
# coordination is live state — a lost beacon is re-published by the member's
# rendezvous loop, and a wedged gang is recovered by the scheduler-side
# watchdog (rendezvous timeout / dead-member abort), never by redelivery.
GANG_PREFIX = "sys.job.gang."
GANG_WILDCARD = "sys.job.gang.>"
JOB_EVENTS_WILDCARD = "sys.job.>"  # every job lifecycle event (gateway tap)
TRACE_SPAN = "sys.trace.span"  # finished flight-recorder spans → collector

# Fleet telemetry plane (docs/OBSERVABILITY.md §Fleet telemetry): every
# process publishes periodic metric snapshots + a health beacon on
# ``sys.telemetry.<service>``; the gateway-hosted FleetAggregator consumes
# the wildcard.  Deliberately NOT durable: a snapshot is stale the moment
# the next one lands, so redelivery would only add load.
TELEMETRY_PREFIX = "sys.telemetry."
TELEMETRY_WILDCARD = "sys.telemetry.>"


def telemetry_subject(service: str) -> str:
    """Telemetry snapshot subject for a service (``sys.telemetry.<service>``)."""
    return f"{TELEMETRY_PREFIX}{service}"


def gang_subject(gang_id: str) -> str:
    """Coordination subject for one gang (``sys.job.gang.<gang_id>``)."""
    return f"{GANG_PREFIX}{gang_id}"

JOB_PREFIX = "job."
WORKER_PREFIX = "worker."

# Queue (consumer-group) names
QUEUE_SCHEDULER = "cordum-scheduler"
QUEUE_WORKFLOW_ENGINE = "cordum-workflow-engine"
QUEUE_SPAN_COLLECTOR = "cordum-span-collector"


def direct_subject(worker_id: str) -> str:
    """Direct worker-targeted delivery subject (reference bus/nats.go:94-99)."""
    return f"worker.{worker_id}.jobs"


# -- keyspace-partitioned lifecycle subjects (sharded scheduler) -----------
# Shard ``i`` of ``n`` owns every job with partition_of(job_id, n) == i and
# consumes its slice via ``sys.job.submit.<i>`` / ``sys.job.result.<i>`` /
# ``sys.job.cancel.<i>``.  The plain subjects stay live as the unstamped
# fallback: whichever shard draws an unstamped message from the queue group
# forwards it to the owner's partition subject (docs/PROTOCOL.md).

def submit_subject(partition: int, partition_count: int) -> str:
    """Submit subject for a partition; plain SUBMIT when unsharded."""
    if partition_count <= 1:
        return SUBMIT
    return f"{SUBMIT}.{partition}"


def result_subject(partition: int, partition_count: int) -> str:
    if partition_count <= 1:
        return RESULT
    return f"{RESULT}.{partition}"


def cancel_subject(partition: int, partition_count: int) -> str:
    if partition_count <= 1:
        return CANCEL
    return f"{CANCEL}.{partition}"


def submit_subject_for(job_id: str, partition_count: int) -> str:
    """Partition-stamped submit subject for a job (gateway/SDK submit leg)."""
    from .partition import partition_of

    return submit_subject(partition_of(job_id, partition_count), partition_count)


def stamped_result_subject(partition_label: str) -> str:
    """Result subject for a request that carries ``LABEL_PARTITION``
    (workers echo the owning shard's partition); plain RESULT otherwise."""
    if partition_label.isdigit():
        return f"{RESULT}.{partition_label}"
    return RESULT


def is_durable_subject(subject: str) -> bool:
    """Subjects that get at-least-once semantics under the durable bus
    (reference nats.go:369-381: submit/result/dlq/job.*/worker.*.jobs;
    TRACE_SPAN added so a bus blip cannot silently hole a trace; the
    partitioned ``sys.job.submit.<p>``/``result.<p>``/``cancel.<p>``
    variants inherit their parents' durability)."""
    if subject in (SUBMIT, RESULT, DLQ, TRACE_SPAN, STEP_RESULT):
        # STEP_RESULT is durable: a dropped context-step result would strand
        # its run in RUNNING (these jobs have no jobstore state to replay)
        return True
    for parent in (SUBMIT, RESULT, CANCEL):
        if subject.startswith(parent + "."):
            return True
    if subject.startswith(JOB_PREFIX):
        return True
    if subject.startswith(WORKER_PREFIX) and subject.endswith(".jobs"):
        return True
    return False
