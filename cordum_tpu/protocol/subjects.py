"""Bus subject constants (reference ``core/protocol/capsdk/constants.go:3-12``)."""
from __future__ import annotations

SUBMIT = "sys.job.submit"
RESULT = "sys.job.result"
HEARTBEAT = "sys.heartbeat"
PROGRESS = "sys.job.progress"
CANCEL = "sys.job.cancel"
DLQ = "sys.job.dlq"
WORKFLOW_EVENT = "sys.workflow.event"
JOB_EVENTS_WILDCARD = "sys.job.>"  # every job lifecycle event (gateway tap)
TRACE_SPAN = "sys.trace.span"  # finished flight-recorder spans → collector

JOB_PREFIX = "job."
WORKER_PREFIX = "worker."

# Queue (consumer-group) names
QUEUE_SCHEDULER = "cordum-scheduler"
QUEUE_WORKFLOW_ENGINE = "cordum-workflow-engine"
QUEUE_SPAN_COLLECTOR = "cordum-span-collector"


def direct_subject(worker_id: str) -> str:
    """Direct worker-targeted delivery subject (reference bus/nats.go:94-99)."""
    return f"worker.{worker_id}.jobs"


def is_durable_subject(subject: str) -> bool:
    """Subjects that get at-least-once semantics under the durable bus
    (reference nats.go:369-381: submit/result/dlq/job.*/worker.*.jobs;
    TRACE_SPAN added so a bus blip cannot silently hole a trace)."""
    if subject in (SUBMIT, RESULT, DLQ, TRACE_SPAN):
        return True
    if subject.startswith(JOB_PREFIX):
        return True
    if subject.startswith(WORKER_PREFIX) and subject.endswith(".jobs"):
        return True
    return False
