"""Wire contract for the TPU control plane (CAP-v2-equivalent).

The reference control plane speaks protobuf ``BusPacket`` envelopes from the
external CAP module (see reference ``core/protocol/pb/v1/pb.go:1-78`` and
``docs/AGENT_PROTOCOL.md`` "Wire Contracts").  We re-design the same contract
as msgpack-serialized dataclasses: a ``BusPacket`` envelope with a tagged
payload union of ``JobRequest / JobResult / Heartbeat / JobProgress /
JobCancel / SystemAlert``, plus the safety-kernel ``PolicyCheck*`` pair.

TPU-first deltas from the reference contract:
  * ``Heartbeat`` reports TPU slice telemetry (``device_kind``, ``chip_count``,
    ``slice_topology``, ``tpu_duty_cycle``, ``hbm_used_gb/hbm_total_gb``)
    instead of ``gpu_utilization`` (reference Heartbeat fields documented in
    ``docs/AGENT_PROTOCOL.md``).
  * ``JobMetadata.requires`` can carry TPU constraints (``tpu``, ``chips:8``,
    ``topology:2x2x2``) consumed by the slice-aware scheduler strategy.
"""
from __future__ import annotations

import dataclasses
import enum
from dataclasses import dataclass, field
from typing import Any, Optional, TypeVar

_W = TypeVar("_W", bound="WireModel")

import msgpack

from ..utils.ids import new_id, now_us

PROTOCOL_VERSION = 1


class JobState(str, enum.Enum):
    """Job lifecycle states (reference ``core/controlplane/scheduler`` states,
    transition table at ``core/infra/memory/job_store.go:71-92``)."""

    PENDING = "PENDING"
    APPROVAL_REQUIRED = "APPROVAL_REQUIRED"
    SCHEDULED = "SCHEDULED"
    DISPATCHED = "DISPATCHED"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    CANCELLED = "CANCELLED"
    TIMEOUT = "TIMEOUT"
    DENIED = "DENIED"


TERMINAL_STATES = frozenset(
    {
        JobState.SUCCEEDED,
        JobState.FAILED,
        JobState.CANCELLED,
        JobState.TIMEOUT,
        JobState.DENIED,
    }
)

# Legal state transitions; "" is the no-state-yet origin.
# Mirrors reference job_store.go:71-92 semantics (not code).
ALLOWED_TRANSITIONS: dict[str, frozenset[JobState]] = {
    "": frozenset(
        {
            JobState.PENDING,
            JobState.APPROVAL_REQUIRED,
            JobState.SCHEDULED,
            JobState.DISPATCHED,
            JobState.RUNNING,
            JobState.FAILED,
        }
    ),
    JobState.PENDING: frozenset(
        {
            JobState.APPROVAL_REQUIRED,
            JobState.SCHEDULED,
            JobState.DISPATCHED,
            JobState.RUNNING,
            JobState.DENIED,
            JobState.FAILED,
            JobState.TIMEOUT,
            JobState.CANCELLED,
        }
    ),
    JobState.APPROVAL_REQUIRED: frozenset(
        {
            JobState.PENDING,
            JobState.SCHEDULED,
            JobState.DISPATCHED,
            JobState.RUNNING,
            JobState.DENIED,
            JobState.FAILED,
            JobState.TIMEOUT,
            JobState.CANCELLED,
        }
    ),
    JobState.SCHEDULED: frozenset(
        {
            JobState.DISPATCHED,
            JobState.RUNNING,
            JobState.DENIED,
            JobState.FAILED,
            JobState.TIMEOUT,
            JobState.SUCCEEDED,
            JobState.CANCELLED,
        }
    ),
    JobState.DISPATCHED: frozenset(
        {
            JobState.RUNNING,
            JobState.SUCCEEDED,
            JobState.FAILED,
            JobState.CANCELLED,
            JobState.TIMEOUT,
        }
    ),
    JobState.RUNNING: frozenset(
        {
            JobState.SUCCEEDED,
            JobState.FAILED,
            JobState.CANCELLED,
            JobState.TIMEOUT,
        }
    ),
    JobState.SUCCEEDED: frozenset(),
    JobState.FAILED: frozenset(),
    JobState.CANCELLED: frozenset(),
    JobState.TIMEOUT: frozenset(),
    JobState.DENIED: frozenset(),
}


def is_allowed_transition(prev: str | JobState, nxt: JobState) -> bool:
    key = prev if prev in ALLOWED_TRANSITIONS else ""
    if prev and prev not in ALLOWED_TRANSITIONS:
        return False
    return nxt in ALLOWED_TRANSITIONS[key]


class Priority(str, enum.Enum):
    INTERACTIVE = "INTERACTIVE"
    BATCH = "BATCH"
    CRITICAL = "CRITICAL"


class Decision(str, enum.Enum):
    """Safety-kernel decisions (reference safety_policy.go decision kinds)."""

    ALLOW = "ALLOW"
    DENY = "DENY"
    REQUIRE_APPROVAL = "REQUIRE_APPROVAL"
    ALLOW_WITH_CONSTRAINTS = "ALLOW_WITH_CONSTRAINTS"
    THROTTLE = "THROTTLE"


# ---------------------------------------------------------------------------
# serde helpers
# ---------------------------------------------------------------------------


_FIELD_CACHE: dict[type, tuple[str, ...]] = {}


def _field_names(cls: type) -> tuple[str, ...]:
    names = _FIELD_CACHE.get(cls)
    if names is None:
        names = tuple(f.name for f in dataclasses.fields(cls))
        _FIELD_CACHE[cls] = names
    return names


def _to_plain(v: Any) -> Any:
    # fast paths first: the wire hot loop is dominated by str/int/dict
    t = type(v)
    if t is str or t is int or t is float or t is bool or t is bytes or v is None:
        return v
    if t is dict:
        return {k: _to_plain(x) for k, x in v.items()}
    if t is list or t is tuple:
        return [_to_plain(x) for x in v]
    if dataclasses.is_dataclass(v):
        out = {}
        for name in _field_names(t):
            val = getattr(v, name)
            if val is not None:
                out[name] = _to_plain(val)
        return out
    if isinstance(v, enum.Enum):
        return v.value
    if isinstance(v, dict):
        return {k: _to_plain(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_to_plain(x) for x in v]
    return v


class WireModel:
    """Mixin: dict/msgpack serialization with unknown-field tolerance."""

    def to_dict(self) -> dict[str, Any]:
        return _to_plain(self)

    @classmethod
    def from_dict(cls: type[_W], d: dict[str, Any] | None) -> Optional[_W]:
        if d is None:
            return None
        kwargs: dict[str, Any] = {}
        for f in dataclasses.fields(cls):  # type: ignore[arg-type]
            if f.name not in d or d[f.name] is None:
                continue
            v = d[f.name]
            conv = _NESTED.get((cls, f.name))
            if conv is not None:
                v = conv(v)
            kwargs[f.name] = v
        return cls(**kwargs)  # type: ignore[call-arg]

    def to_wire(self) -> bytes:
        return msgpack.packb(self.to_dict(), use_bin_type=True)

    @classmethod
    def from_wire(cls: type[_W], b: bytes) -> Optional[_W]:
        return cls.from_dict(msgpack.unpackb(b, raw=False))


# ---------------------------------------------------------------------------
# payload types
# ---------------------------------------------------------------------------


@dataclass
class ContextHints(WireModel):
    # max_output_tokens was pruned (CL010): encoded on every packet, never
    # read anywhere — ``from_dict`` ignores unknown keys so old peers that
    # still send it decode fine
    max_input_tokens: int = 0
    mode: str = ""  # RAW | CHAT | RAG


@dataclass
class Budget(WireModel):
    max_tokens: int = 0
    max_cost_usd: float = 0.0
    # set by external submitters (gateway JSON → from_dict); nothing
    # in-tree constructs it, but the deadline sweeper reads it
    deadline_unix_ms: int = 0  # cordum: wire-compat -- populated by submitter SDKs


@dataclass
class JobMetadata(WireModel):
    """Policy/routing metadata (reference JobMetadata: capability, risk_tags,
    requires, pack_id — docs/AGENT_PROTOCOL.md "Safety & Tenancy")."""

    capability: str = ""
    risk_tags: list[str] = field(default_factory=list)
    requires: list[str] = field(default_factory=list)
    pack_id: str = ""


@dataclass
class JobRequest(WireModel):
    job_id: str = ""
    topic: str = ""
    priority: str = Priority.BATCH.value
    context_ptr: str = ""
    memory_id: str = ""
    tenant_id: str = ""
    principal_id: str = ""
    adapter_id: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    env: dict[str, str] = field(default_factory=dict)
    # parent_job_id was pruned (CL010): workflow lineage rides
    # workflow_id/run_id; nothing ever read the field.  Old peers that
    # still send it decode fine (from_dict ignores unknown keys).
    workflow_id: str = ""
    run_id: str = ""
    metadata: Optional[JobMetadata] = None
    context_hints: Optional[ContextHints] = None
    budget: Optional[Budget] = None


@dataclass
class JobResult(WireModel):
    job_id: str = ""
    status: str = JobState.SUCCEEDED.value
    result_ptr: str = ""
    worker_id: str = ""
    execution_ms: int = 0
    error_code: str = ""
    error_message: str = ""
    labels: dict[str, str] = field(default_factory=dict)


@dataclass
class Heartbeat(WireModel):
    """Worker heartbeat with TPU slice telemetry.

    Reference Heartbeat carries worker_id/region/type/cpu_load/gpu_utilization/
    active_jobs/capabilities/pool/max_parallel_jobs; the TPU-native shape keeps
    the scheduler-visible fields and replaces GPU telemetry with TPU slice
    health (SURVEY.md §5 "failure detection": add TPU-slice health).
    """

    worker_id: str = ""
    region: str = ""
    type: str = "tpu"
    cpu_load: float = 0.0
    tpu_duty_cycle: float = 0.0  # 0-100, MXU busy fraction
    hbm_used_gb: float = 0.0
    hbm_total_gb: float = 0.0
    active_jobs: int = 0
    max_parallel_jobs: int = 1
    capabilities: list[str] = field(default_factory=list)
    pool: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    device_kind: str = ""  # e.g. "TPU v5p"
    chip_count: int = 0
    slice_topology: str = ""  # e.g. "2x2x1"
    devices_healthy: bool = True
    # graceful drain (docs/SERVING.md): a draining worker is finishing or
    # migrating its work and must receive NO new placements — the scheduler
    # deregisters it and evicts its session/batch affinity entries on sight
    draining: bool = False


@dataclass
class JobProgress(WireModel):
    job_id: str = ""
    percent: float = 0.0
    message: str = ""
    result_ptr: str = ""
    # artifact_ptrs was pruned (CL010): artifacts ride JobResult, the
    # progress-side list was encoded but never read
    status_hint: str = ""
    worker_id: str = ""
    # llm.generate token stream: the tokens emitted since the last progress
    # packet, with status_hint=STATUS_HINT_STREAM (docs/SERVING.md).  Stream
    # packets are transport, not state: the scheduler does not persist them
    # (the terminal JobResult carries the full list).
    tokens: list[int] = field(default_factory=list)
    # token offset of ``tokens[0]`` within the session's full generation
    # (-1 = unknown, legacy packets).  A failed-over session replays its
    # already-streamed prefix, so stream consumers MUST dedupe by offset to
    # assemble an exactly-once token sequence (docs/PROTOCOL.md).
    offset: int = -1


@dataclass
class JobCancel(WireModel):
    job_id: str = ""
    reason: str = ""
    requested_by: str = ""


@dataclass
class WorkerDrain(WireModel):
    """Graceful-drain request for one worker (``sys.worker.drain`` fan-out;
    docs/SERVING.md §Migration, drain, and failover).  The addressed worker
    stops admitting, live-migrates its serving sessions to peers, finishes
    its per-job work, publishes a final ``draining`` heartbeat (which evicts
    its scheduler affinity), then exits — zero CANCELLED sessions."""

    worker_id: str = ""
    reason: str = ""
    requested_by: str = ""


@dataclass
class JobPreempt(WireModel):
    """Preemption request for one in-flight BATCH job (``sys.job.preempt``
    fan-out; docs/ADMISSION.md §Preemption).  The worker holding the job
    hands it back where that is cheap and safe — a serving session requeues
    mid-decode (its streamed tokens ride the failover resume prefix), a job
    still waiting for an intake slot gives the slot up — and ignores the
    request where it is not (a handler already executing on the device).
    The scheduler re-dispatches preempted jobs attempts-exempt after a
    short jittered hold-off, so preemption can never FAIL or CANCEL work."""

    job_id: str = ""
    reason: str = ""
    requested_by: str = ""


@dataclass
class SessionMoved(WireModel):
    """Ownership announcement after a serving-session migration commits
    (``sys.serving.moved`` fan-out; docs/SERVING.md §Disaggregation).
    Published by the ADOPTING worker — the only process that knows the
    commit landed — so scheduler shards retarget the session's affinity
    entry to the new owner and follow-up turns/cancels route correctly.
    Not durable: a lost announcement degrades to the pre-disaggregation
    behavior (the stale entry is lazily evicted and the next turn
    re-elects a worker)."""

    job_id: str = ""
    session_key: str = ""
    from_worker: str = ""
    to_worker: str = ""
    reason: str = ""  # handoff | rebalance | drain | hibernated | restored


@dataclass
class SessionRebalance(WireModel):
    """Decode-rebalance request for one worker (``sys.serving.rebalance``
    fan-out; docs/SERVING.md §Disaggregation).  The scheduler's governor
    detects decode-occupancy/page-pressure skew in the capacity view and
    asks the hot worker to live-migrate up to ``max_sessions`` of its
    cheapest sessions (fewest live pages, oldest decode position) to the
    named headroom target.  Rate-limited and hysteresis-guarded on the
    governor side; migrated-in sessions are cooldown-immune on the worker
    side, so sessions never ping-pong."""

    worker_id: str = ""  # the overloaded worker being asked to shed
    target_worker: str = ""
    target_addr: str = ""  # the target's migration listener host:port
    max_sessions: int = 1
    reason: str = ""
    requested_by: str = ""


@dataclass
class AdmissionPressure(WireModel):
    """Overload-pressure beacon from the gateway admission controller
    (``sys.admission.pressure`` fan-out; docs/ADMISSION.md).  Published when
    the brownout tier changes and periodically while shedding is active;
    the scheduler's preemption governor acts on ``preempt_batch`` and the
    serving engines read it as the batch-deprioritization hint.  Not
    durable: pressure is a live signal, stale the moment the next
    evaluation lands."""

    tier: int = 0  # brownout tier (0 = normal .. 3 = bounded interactive)
    interactive_burn_5m: float = 0.0  # worst interactive 5m burn rate
    preempt_batch: bool = False  # interactive burn >= warn: requeue batch
    reason: str = ""
    # sender was pruned (CL010): receivers key on the BusPacket envelope's
    # sender_id; the duplicate payload field was never read


@dataclass
class GangMsg(WireModel):
    """One gang-coordination message on ``sys.job.gang.<gang_id>``
    (docs/GANG.md).  A single wire shape serves the whole gang protocol:

    * ``kind="ready"`` — a member's rendezvous beacon, re-published every
      few hundred ms until the barrier passes (fan-out subjects are not
      durable, so a beacon that raced a peer's subscribe is simply
      repeated).
    * ``kind="abort"`` — any member (or the scheduler watchdog) aborting
      the WHOLE gang: peers stop between steps, the scheduler releases
      every reserved device and requeues the job attempts-bounded.
    * ``kind="done"`` — a member's completion report; ``stats`` carries its
      result doc (loss, steps, mesh).  The owning scheduler shard
      aggregates all ranks into the job's single terminal result.
    * ``kind="stage"`` — MPMD pipeline traffic: the activation (forward)
      or cotangent (backward) tensor for ``to_rank``, addressed by the
      unique ``tag`` (``fwd:<step>:<microbatch>`` / ``bwd:...``); ``data``
      is the raw float32 buffer, ``shape`` its dims.
    * ``kind="step"`` — serving-gang replay traffic (docs/SERVING.md
      §Sharded serving): rank 0 broadcasts the ragged-step entry batch it
      just ran so every follower replays the identical program against its
      head shard; ``stats`` carries the serialized ``StepEntry`` rows and a
      monotonic ``seq`` (followers replay in order), plus ``final=True`` on
      the shutdown marker.
    """

    gang_id: str = ""
    job_id: str = ""
    kind: str = ""  # ready | abort | done | stage | step
    rank: int = -1
    to_rank: int = -1  # stage messages: the addressed member
    worker_id: str = ""
    reason: str = ""  # abort cause
    tag: str = ""  # stage routing key (unique per step/microbatch/direction)
    data: bytes = b""  # stage tensor payload (raw little-endian float32)
    shape: list[int] = field(default_factory=list)
    stats: dict[str, Any] = field(default_factory=dict)  # done: member result


@dataclass
class SystemAlert(WireModel):
    # set from workflow notify steps; gateway event taps forward alerts
    # verbatim to external sinks, which key on it — no in-tree reader
    severity: str = "info"  # cordum: wire-compat -- consumed by alert sinks behind the gateway tap
    source: str = ""
    message: str = ""
    labels: dict[str, str] = field(default_factory=dict)


@dataclass
class TelemetrySnapshot(WireModel):
    """Periodic per-process metric snapshot + health beacon (the fleet
    telemetry plane's wire unit, docs/OBSERVABILITY.md §Fleet telemetry).

    Published on ``sys.telemetry.<service>`` every ``interval_s`` seconds by
    the :class:`~cordum_tpu.obs.telemetry.TelemetryExporter` embedded in each
    process.  ``metrics`` carries the process's ``Metrics`` registry in the
    compact snapshot format (``Metrics.snapshot()``), delta-encoded: only
    series whose value changed since the previous publish ride the wire,
    with a periodic ``full=True`` snapshot so a late-joining aggregator
    converges on gauges and quiet series.  ``started_at_us`` is the process
    epoch — a change at constant (service, instance) is a restart, which is
    how the aggregator detects counter resets."""

    service: str = ""  # gateway / scheduler / statebus / worker / ...
    instance: str = ""  # unique per process (instance_id, endpoint, ...)
    seq: int = 0  # snapshot sequence within this process epoch
    started_at_us: int = 0  # process start (restart/reset detection)
    uptime_s: float = 0.0
    interval_s: float = 0.0  # configured publish cadence (staleness bound)
    full: bool = False  # full registry snapshot vs changed-series delta
    health: dict[str, Any] = field(default_factory=dict)  # role beacon
    metrics: dict[str, Any] = field(default_factory=dict)  # Metrics.snapshot()


SPAN_OK = "OK"
SPAN_ERROR = "ERROR"


@dataclass
class Span(WireModel):
    """One timed segment of a trace (the flight-recorder unit).

    Spans form a tree per ``trace_id`` via ``parent_span_id``; services
    publish finished spans on the durable ``sys.trace.span`` subject and the
    collector (``cordum_tpu/obs/collector.py``) persists them per trace.
    Timestamps are wall-clock microseconds (``utils.ids.now_us`` — the job
    store's clock) so spans from different processes line up."""

    span_id: str = ""
    parent_span_id: str = ""
    trace_id: str = ""
    name: str = ""  # stage name: submit/policy-check/schedule/dispatch/...
    service: str = ""  # gateway/scheduler/safety-kernel/workflow-engine/worker
    start_us: int = 0
    end_us: int = 0
    status: str = SPAN_OK
    attrs: dict[str, str] = field(default_factory=dict)

    @property
    def duration_us(self) -> int:
        return max(0, self.end_us - self.start_us)


# ---------------------------------------------------------------------------
# safety kernel contract
# ---------------------------------------------------------------------------


@dataclass
class Constraints(WireModel):
    """Execution constraints attached to ALLOW_WITH_CONSTRAINTS decisions.

    TPU-native additions: max_chips / allowed_topologies bound what slice a
    job may be placed on (reference constraints are budgets/sandbox/toolchain/
    diff/redaction_level — config/safety_policy.go:13-146)."""

    max_tokens: int = 0
    max_cost_usd: float = 0.0
    # the scheduler forwards the whole Constraints dict verbatim to workers
    # via env[ENV_POLICY_CONSTRAINTS] (engine._apply_constraints); the
    # sandbox/toolchain/diff/redaction knobs are enforced by the worker-side
    # executor, not by any in-tree reader
    sandbox: str = ""  # cordum: wire-compat -- enforced worker-side via ENV_POLICY_CONSTRAINTS
    toolchain: str = ""  # cordum: wire-compat -- enforced worker-side via ENV_POLICY_CONSTRAINTS
    diff_limit: str = ""  # cordum: wire-compat -- enforced worker-side via ENV_POLICY_CONSTRAINTS
    redaction_level: str = ""  # cordum: wire-compat -- enforced worker-side via ENV_POLICY_CONSTRAINTS
    max_chips: int = 0
    allowed_topologies: list[str] = field(default_factory=list)  # cordum: wire-compat -- enforced worker-side via ENV_POLICY_CONSTRAINTS
    env: dict[str, str] = field(default_factory=dict)


@dataclass
class Remediation(WireModel):
    id: str = ""
    description: str = ""
    replacement_topic: str = ""
    replacement_capability: str = ""
    add_labels: dict[str, str] = field(default_factory=dict)
    remove_labels: list[str] = field(default_factory=list)


@dataclass
class PolicyCheckRequest(WireModel):
    job_id: str = ""
    tenant_id: str = ""
    principal_id: str = ""
    topic: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    metadata: Optional[JobMetadata] = None
    actor_id: str = ""
    actor_type: str = ""
    effective_config: dict[str, Any] = field(default_factory=dict)


@dataclass
class PolicyCheckResponse(WireModel):
    decision: str = Decision.ALLOW.value
    reason: str = ""
    rule_id: str = ""
    policy_snapshot: str = ""
    # mirrors decision==REQUIRE_APPROVAL as a plain bool so non-Python
    # admin tooling doesn't need the Decision enum; approval_ref was
    # pruned (CL010) — never set, never read
    approval_required: bool = False  # cordum: wire-compat -- read by external admin tooling
    throttle_delay_s: float = 0.0
    constraints: Optional[Constraints] = None
    remediations: list[Remediation] = field(default_factory=list)


# ---------------------------------------------------------------------------
# envelope
# ---------------------------------------------------------------------------

_PAYLOAD_TYPES: dict[str, type] = {
    "job_request": JobRequest,
    "job_result": JobResult,
    "heartbeat": Heartbeat,
    "job_progress": JobProgress,
    "job_cancel": JobCancel,
    "job_preempt": JobPreempt,
    "worker_drain": WorkerDrain,
    "admission_pressure": AdmissionPressure,
    "session_moved": SessionMoved,
    "session_rebalance": SessionRebalance,
    "gang_msg": GangMsg,
    "system_alert": SystemAlert,
    "span": Span,
    "telemetry": TelemetrySnapshot,
}
# O(1) reverse lookup for wrap() (exact types only; payloads are always the
# concrete dataclasses, and wrap() keeps an isinstance fallback for subclasses)
_KIND_BY_TYPE: dict[type, str] = {t: k for k, t in _PAYLOAD_TYPES.items()}


class BusPacket(WireModel):
    """Envelope for every bus message (reference BusPacket oneof payload).

    ``span_id``/``parent_span_id`` carry flight-recorder span context across
    process boundaries: a receiver that starts a span for the work this
    packet triggers uses ``span_id`` as its parent (see docs/PROTOCOL.md
    "Span context").

    Codec fast paths (docs/PROTOCOL.md "Fast-path specialization"):

    * **lazy decode** — ``from_wire``/``from_dict`` materialize only the
      envelope; the typed payload dataclass is built on first access, so
      routing-only consumers (dedupe, forward-to-owner, the statebus
      server's subject router) never pay the dataclass conversion.
    * **encode cache** — a packet decoded from the wire remembers its exact
      bytes; re-publishing it (shard forwarding, redelivery) reuses them
      instead of re-running ``to_dict``/``packb``.  Mutating ``payload``
      drops the cache; mutating the payload object *in place* after the
      first encode is a contract violation (stamp labels before wrapping).
    """

    __slots__ = (
        "trace_id", "sender_id", "created_at_us", "protocol_version",
        "kind", "span_id", "parent_span_id", "_payload", "_raw_payload",
        "_wire", "redelivery_count",
    )

    def __init__(
        self,
        *,
        trace_id: str = "",
        sender_id: str = "",
        created_at_us: int = 0,
        protocol_version: int = PROTOCOL_VERSION,
        kind: str = "",
        payload: Any = None,
        span_id: str = "",
        parent_span_id: str = "",
    ) -> None:
        self.trace_id = trace_id
        self.sender_id = sender_id
        self.created_at_us = created_at_us
        self.protocol_version = protocol_version
        self.kind = kind
        self.span_id = span_id  # span under which this packet was published
        self.parent_span_id = parent_span_id  # that span's parent
        self._payload = payload
        self._raw_payload: Any = None
        self._wire: Optional[bytes] = None
        # delivery-local, never serialized: how many times the bus has
        # redelivered THIS delivery after RetryAfter NAKs (0 on the first
        # attempt).  Handlers use it to back off exponentially instead of
        # NAKing at a fixed cadence (a tenant burst would otherwise
        # resonate as a synchronized retry storm).
        self.redelivery_count = 0

    def __repr__(self) -> str:  # debugging/log parity with the old dataclass
        return (
            f"BusPacket(kind={self.kind!r}, trace_id={self.trace_id!r}, "
            f"sender_id={self.sender_id!r}, payload={self._payload!r})"
        )

    @property
    def payload(self) -> Any:
        p = self._payload
        if p is None and self._raw_payload is not None:
            t = _PAYLOAD_TYPES.get(self.kind)
            raw = self._raw_payload
            p = t.from_dict(raw) if (t is not None and isinstance(raw, dict)) else raw
            self._payload = p
        return p

    @payload.setter
    def payload(self, value: Any) -> None:
        self._payload = value
        self._raw_payload = None
        self._wire = None

    @property
    def raw_payload(self) -> Any:
        """The payload as a plain wire dict when decoded lazily (None for
        locally constructed packets) — lets routing code peek at envelope-
        adjacent fields without forcing the dataclass conversion."""
        return self._raw_payload

    @classmethod
    def wrap(
        cls,
        payload: Any,
        *,
        trace_id: str = "",
        sender_id: str = "",
        span_id: str = "",
        parent_span_id: str = "",
    ) -> "BusPacket":
        kind = _KIND_BY_TYPE.get(type(payload), "")
        if not kind:
            for k, t in _PAYLOAD_TYPES.items():
                if isinstance(payload, t):
                    kind = k
                    break
        if not kind:
            raise TypeError(f"unsupported payload type {type(payload)!r}")
        return cls(
            trace_id=trace_id or new_id(),
            sender_id=sender_id,
            created_at_us=now_us(),
            kind=kind,
            payload=payload,
            span_id=span_id,
            parent_span_id=parent_span_id,
        )

    def to_dict(self) -> dict[str, Any]:
        d = {
            "trace_id": self.trace_id,
            "sender_id": self.sender_id,
            "created_at_us": self.created_at_us,
            "protocol_version": self.protocol_version,
            "kind": self.kind,
        }
        # span context rides only when set (wire stays lean for untraced
        # packets; old peers tolerate the extra keys either way)
        if self.span_id:
            d["span_id"] = self.span_id
        if self.parent_span_id:
            d["parent_span_id"] = self.parent_span_id
        if self._payload is not None:
            d["payload"] = _to_plain(self._payload)
        elif self._raw_payload is not None:
            d["payload"] = self._raw_payload
        return d

    def to_wire(self) -> bytes:
        w = self._wire
        if w is None:
            w = msgpack.packb(self.to_dict(), use_bin_type=True)
            self._wire = w
        return w

    @classmethod
    def from_wire(cls, b: bytes) -> Optional["BusPacket"]:
        pkt = cls.from_dict(msgpack.unpackb(b, raw=False))
        if pkt is not None:
            pkt._wire = bytes(b)
        return pkt

    @classmethod
    def from_dict(cls, d: dict[str, Any] | None) -> Optional["BusPacket"]:
        if d is None:
            return None
        pkt = cls(
            trace_id=d.get("trace_id", ""),
            sender_id=d.get("sender_id", ""),
            created_at_us=d.get("created_at_us", 0),
            protocol_version=d.get("protocol_version", PROTOCOL_VERSION),
            kind=d.get("kind", ""),
            span_id=d.get("span_id", ""),
            parent_span_id=d.get("parent_span_id", ""),
        )
        pkt._raw_payload = d.get("payload")
        return pkt

    # typed accessors ------------------------------------------------------
    @property
    def job_request(self) -> Optional[JobRequest]:
        return self.payload if self.kind == "job_request" else None

    @property
    def job_result(self) -> Optional[JobResult]:
        return self.payload if self.kind == "job_result" else None

    @property
    def heartbeat(self) -> Optional[Heartbeat]:
        return self.payload if self.kind == "heartbeat" else None

    @property
    def job_progress(self) -> Optional[JobProgress]:
        return self.payload if self.kind == "job_progress" else None

    @property
    def job_cancel(self) -> Optional[JobCancel]:
        return self.payload if self.kind == "job_cancel" else None

    @property
    def job_preempt(self) -> Optional[JobPreempt]:
        return self.payload if self.kind == "job_preempt" else None

    @property
    def worker_drain(self) -> Optional[WorkerDrain]:
        return self.payload if self.kind == "worker_drain" else None

    @property
    def admission_pressure(self) -> Optional[AdmissionPressure]:
        return self.payload if self.kind == "admission_pressure" else None

    @property
    def session_moved(self) -> Optional[SessionMoved]:
        return self.payload if self.kind == "session_moved" else None

    @property
    def session_rebalance(self) -> Optional[SessionRebalance]:
        return self.payload if self.kind == "session_rebalance" else None

    @property
    def gang_msg(self) -> Optional[GangMsg]:
        return self.payload if self.kind == "gang_msg" else None

    @property
    def system_alert(self) -> Optional[SystemAlert]:
        return self.payload if self.kind == "system_alert" else None

    @property
    def span(self) -> Optional[Span]:
        return self.payload if self.kind == "span" else None

    @property
    def telemetry(self) -> Optional[TelemetrySnapshot]:
        return self.payload if self.kind == "telemetry" else None


# nested-field converters for WireModel.from_dict
_NESTED: dict[tuple[type, str], Any] = {
    (JobRequest, "metadata"): JobMetadata.from_dict,
    (JobRequest, "context_hints"): ContextHints.from_dict,
    (JobRequest, "budget"): Budget.from_dict,
    (PolicyCheckRequest, "metadata"): JobMetadata.from_dict,
    (PolicyCheckResponse, "constraints"): Constraints.from_dict,
    (PolicyCheckResponse, "remediations"): lambda v: [
        Remediation.from_dict(x) for x in v
    ],
}

# Label key used by approvals / bus msg-id override
LABEL_APPROVAL_GRANTED = "approval_granted"
LABEL_APPROVAL_REF = "approval_ref"
LABEL_BUS_MSG_ID = "cordum.bus_msg_id"
LABEL_DRY_RUN = "cordum.dry_run"
LABEL_SECRETS_PRESENT = "secrets_present"
# Workflow SLO class (docs/WORKFLOWS.md): stamped on the run at start (from
# Workflow.slo_class or a per-run label override) and propagated by the
# engine into every dispatched JobRequest.priority, so agent-loop steps ride
# the admission ladder and the class-split e2e histogram like API submits.
LABEL_SLO_CLASS = "cordum.slo_class"
ENV_EFFECTIVE_CONFIG = "CORDUM_EFFECTIVE_CONFIG"

# ---------------------------------------------------------------------------
# micro-batching declaration (cordum_tpu/batching)
# ---------------------------------------------------------------------------

# Ops whose jobs the worker-side micro-batcher may coalesce into one padded
# XLA call.  Batchable = the op is a pure per-row computation (row i of the
# batched program equals the row run alone), so results scatter back as
# ordinary per-job JobResults.
BATCHABLE_OPS = frozenset({"embed", "infer"})

# Batch-routing label: the gateway stamps it at submit so the scheduler can
# route same-key jobs to the same worker (batch affinity) without reading
# the payload behind the context pointer.
LABEL_BATCH_KEY = "cordum.batch_key"

# Op-routing label: the gateway stamps the payload's ``op`` at submit so
# capacity-aware consumers (the ThroughputAwareStrategy's matrix lookup,
# the admission controller's per-op headroom) can key into the fleet
# throughput matrix without reading the payload behind the context pointer.
LABEL_OP = "cordum.op"

# Shard-routing label: the scheduler shard stamps its partition index on the
# dispatched request so the worker can publish the result straight to the
# owning shard's ``sys.job.result.<p>`` subject (no forwarding hop).  Pure
# routing metadata — excluded from the approval job hash (protocol/jobhash).
LABEL_PARTITION = "cordum.partition"


def payload_batch_key(payload: Any) -> str:
    """The batch key for a job payload: the batchable op name, or ``""``
    when the payload is not a batchable shape.  Key equality is the
    contract: two jobs with the same key may share one XLA program."""
    if isinstance(payload, dict):
        op = payload.get("op")
        if isinstance(op, str) and op in BATCHABLE_OPS:
            return op
    return ""


# ---------------------------------------------------------------------------
# serving declaration (cordum_tpu/serving)
# ---------------------------------------------------------------------------

# Ops the worker's serving engine handles: stateful autoregressive decode
# with a per-session paged KV cache (docs/SERVING.md).  Serving ops are NOT
# batchable ops — they join the continuous-batching decode loop instead of
# the stateless micro-batch queues.
SERVING_OPS = frozenset({"llm.generate"})

# Session-routing label: the gateway stamps it from the payload's
# ``session_id`` at submit, so the scheduler can route every turn of a
# conversation to the worker holding its KV pages (session affinity,
# generalizing LABEL_BATCH_KEY) without reading the payload behind the
# context pointer.
LABEL_SESSION_KEY = "cordum.session_key"

# JobProgress.status_hint marking a token-stream packet: relayed to WS
# stream consumers but never persisted as a job event (per-token events
# would swamp the job store's event log).
STATUS_HINT_STREAM = "stream"

# Forced-decode resume prefix (docs/SERVING.md §Migration, drain, and
# failover): when the scheduler fails a serving session over to a new
# worker it stamps the tokens the dead worker already streamed as a
# comma-joined label; the new worker prefills prompt + prefix (forced
# decode), re-emits the prefix at offset 0 (consumers dedupe by offset),
# and continues generating from there — no duplicated or missing tokens.
LABEL_RESUME_TOKENS = "cordum.resume_tokens"

# JobResult.error_code of a NON-terminal (status=RUNNING) result a worker
# publishes to hand a job back to the scheduler for failover instead of
# failing it: a draining worker with no migration target, or a crashed
# decode loop's live sessions.  The scheduler re-dispatches (bounded by the
# attempts counter) rather than recording a terminal state.
ERROR_SESSION_REQUEUE = "SESSION_REQUEUE"

# Heartbeat labels a serving worker advertises so peers can live-migrate KV
# pages to it: the migration listener's host:port, and its free-page count
# (the capacity-matrix KV headroom signal drain uses to pick a target).
LABEL_MIGRATE_ADDR = "cordum.migrate_addr"
LABEL_KV_PAGES_FREE = "cordum.kv_pages_free"

# Prefill/decode disaggregation (docs/SERVING.md §Disaggregation): the
# worker's serving role — ``prefill`` workers ingest prompts fast and hand
# sessions off post-prefill, ``decode`` workers adopt them for steady
# token generation, ``mixed`` (the default) does both and never hands off.
# Rides heartbeats (peer hand-off ranking) AND the beacon capacity block
# (scheduler-side placement + the capacity doc).
SERVING_ROLE_PREFILL = "prefill"
SERVING_ROLE_DECODE = "decode"
SERVING_ROLE_MIXED = "mixed"
SERVING_ROLES = frozenset(
    {SERVING_ROLE_PREFILL, SERVING_ROLE_DECODE, SERVING_ROLE_MIXED}
)
LABEL_SERVING_ROLE = "cordum.serving_role"
# Submitter hint that this session's prompts are templated/repetitive and
# will benefit from the serving engine's self-speculative decoder.  The
# ServingPlacer PREFERS draft-enabled workers (those exporting
# ``spec_accept_rate`` in their occupancy block) when this label is set,
# but never hard-filters on it — a fleet with speculation disabled
# everywhere still places normally.
LABEL_SPECULABLE = "cordum.speculable"
# Steady-state decode tokens/s this worker measured for itself (the
# capacity profiler's llm.generate row) — peers rank hand-off targets by
# KV-page headroom × this rate without a capacity-matrix RPC.
LABEL_DECODE_TOKENS_PER_S = "cordum.decode_tokens_per_s"

# The synthetic capacity-matrix op name for the prefill side of a mixed
# ragged step: the serving engine apportions each step's device time
# between prompt ingestion (this row) and token generation (the
# ``llm.generate`` row) by delivered tokens, so prefill tokens/s and
# decode tokens/s are separately measurable — the ServingPlacer routes new
# sessions on the prefill rate, the rebalancer and hand-off rank targets
# on the decode rate.
OP_SERVING_PREFILL = "llm.prefill"


def payload_session_key(payload: Any) -> str:
    """The session key for a serving payload (its ``session_id``), or ``""``
    for non-serving payloads and sessionless one-shot generations."""
    if isinstance(payload, dict) and payload.get("op") in SERVING_OPS:
        sid = payload.get("session_id")
        if isinstance(sid, str):
            return sid
    return ""


# ---------------------------------------------------------------------------
# gang scheduling declaration (docs/GANG.md)
# ---------------------------------------------------------------------------

# A gang job's payload carries a ``gang`` stanza next to its ``mesh``:
#
#   {"op": "train", "model": "llama-tiny", "steps": 2,
#    "mesh": {"dp": -1, "tp": 2, "sp": 2},
#    "gang": {"workers": 2, "chips_per_worker": 8}}
#
# The gateway stamps the stanza as routing labels at submit (mirroring
# LABEL_OP/LABEL_SESSION_KEY) so the scheduler's gang path never reads the
# payload behind the context pointer.  The scheduler-stamped dispatch
# labels (gang id / rank / size / members) tell each worker its place in
# the gang; they are routing metadata, excluded from the approval job hash.

# submit-time labels (gateway ← payload["gang"])
LABEL_GANG_WORKERS = "cordum.gang_workers"  # members requested (all-or-nothing)
LABEL_GANG_CHIPS = "cordum.gang_chips"  # min chips each member must own
LABEL_GANG_KIND = "cordum.gang_kind"  # "" (train) | "serving" (TP serving gang)

# dispatch-time labels (gang scheduler → members)
LABEL_GANG_ID = "cordum.gang_id"
LABEL_GANG_RANK = "cordum.gang_rank"
LABEL_GANG_SIZE = "cordum.gang_size"
LABEL_GANG_MEMBERS = "cordum.gang_members"  # comma-joined worker ids, rank order


def payload_gang(payload: Any) -> Optional[dict]:
    """The payload's ``gang`` stanza when it requests gang placement
    (``workers >= 1``), else None."""
    if not isinstance(payload, dict):
        return None
    g = payload.get("gang")
    if not isinstance(g, dict):
        return None
    try:
        if int(g.get("workers", 0)) < 1:
            return None
    except (TypeError, ValueError):
        return None
    return g


def gang_workers(labels: Optional[dict]) -> int:
    """Members a gang-labeled request asks for (0 = not a gang job)."""
    try:
        return max(0, int((labels or {}).get(LABEL_GANG_WORKERS, "0") or 0))
    except (TypeError, ValueError):
        return 0


def gang_chips(labels: Optional[dict]) -> int:
    try:
        return max(0, int((labels or {}).get(LABEL_GANG_CHIPS, "0") or 0))
    except (TypeError, ValueError):
        return 0


def gang_kind(labels: Optional[dict]) -> str:
    """The gang's workload kind ("" = training/generic, "serving" = a
    tensor-parallel serving gang; docs/SERVING.md §Sharded serving)."""
    v = (labels or {}).get(LABEL_GANG_KIND, "")
    return v if isinstance(v, str) else ""
