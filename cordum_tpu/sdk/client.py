"""Typed gateway client (reference ``sdk/client/client.go:23-393``): the
programmatic integration surface for external tools and tests.

Async (httpx) with a small sync facade; covers jobs, workflows/runs,
approvals, DLQ, artifacts, context, policy, packs.
"""
from __future__ import annotations

import asyncio
import time
from typing import Any, Optional

import httpx

from ..protocol.partition import partition_of  # noqa: F401 - re-export: lets
# partition-aware clients (load generators, shard-pinned tooling) pre-compute
# which scheduler shard will own a job id they submit

TERMINAL_JOB_STATES = {"SUCCEEDED", "FAILED", "CANCELLED", "TIMEOUT", "DENIED"}
TERMINAL_RUN_STATES = {"SUCCEEDED", "FAILED", "CANCELLED"}


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message


class Client:
    def __init__(
        self,
        base_url: str = "http://127.0.0.1:8081",
        *,
        api_key: str = "",
        principal_id: str = "",
        role: str = "",
        tenant_id: str = "",
        timeout_s: float = 30.0,
    ):
        headers = {}
        if api_key:
            headers["X-Api-Key"] = api_key
        if principal_id:
            headers["X-Principal-Id"] = principal_id
        if role:
            headers["X-Principal-Role"] = role
        if tenant_id:
            headers["X-Tenant-Id"] = tenant_id
        self._c = httpx.AsyncClient(base_url=base_url, headers=headers, timeout=timeout_s)

    async def close(self) -> None:
        await self._c.aclose()

    async def __aenter__(self) -> "Client":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    async def _req(self, method: str, path: str, **kw) -> Any:
        r = await self._c.request(method, path, **kw)
        try:
            body = r.json()
        except ValueError:
            body = {"raw": r.text}
        if r.status_code >= 400:
            raise ApiError(r.status_code, str(body.get("error", body)))
        return body

    # -- jobs -----------------------------------------------------------
    async def submit_job(
        self,
        topic: str,
        payload: Any = None,
        *,
        metadata: Optional[dict] = None,
        labels: Optional[dict] = None,
        env: Optional[dict] = None,
        budget: Optional[dict] = None,
        priority: str = "BATCH",
        idempotency_key: str = "",
        memory_id: str = "",
        job_id: str = "",
    ) -> dict:
        """Submit one job.  ``job_id`` pins the id client-side (the sharded
        gateway stamps the owning scheduler partition from it — see
        :func:`partition_of`); empty lets the gateway mint one."""
        body: dict[str, Any] = {"topic": topic, "payload": payload, "priority": priority}
        if job_id:
            body["job_id"] = job_id
        if metadata:
            body["metadata"] = metadata
        if labels:
            body["labels"] = labels
        if env:
            body["env"] = env
        if budget:
            body["budget"] = budget
        if idempotency_key:
            body["idempotency_key"] = idempotency_key
        if memory_id:
            body["memory_id"] = memory_id
        return await self._req("POST", "/api/v1/jobs", json=body)

    async def submit_jobs(self, jobs: list[dict]) -> dict:
        """Bulk submit via ``POST /api/v1/jobs:batch``: each entry is a
        single-submit body; per-job verdicts come back positionally in
        ``jobs`` (accepted entries carry ``job_id``/``trace_id``)."""
        return await self._req("POST", "/api/v1/jobs:batch", json={"jobs": jobs})

    async def job_status(self, job_id: str, *, events: bool = False, result: bool = False) -> dict:
        q = []
        if events:
            q.append("events=true")
        if result:
            q.append("result=true")
        qs = ("?" + "&".join(q)) if q else ""
        return await self._req("GET", f"/api/v1/jobs/{job_id}{qs}")

    async def wait_job(self, job_id: str, *, timeout_s: float = 120.0, poll_s: float = 0.25) -> dict:
        t0 = time.monotonic()
        while True:
            doc = await self.job_status(job_id, result=True)
            if doc.get("state") in TERMINAL_JOB_STATES:
                return doc
            if time.monotonic() - t0 > timeout_s:
                raise TimeoutError(f"job {job_id} not terminal after {timeout_s}s")
            await asyncio.sleep(poll_s)

    async def cancel_job(self, job_id: str) -> dict:
        return await self._req("POST", f"/api/v1/jobs/{job_id}/cancel")

    async def remediate_job(self, job_id: str, remediation_id: str = "") -> dict:
        return await self._req("POST", f"/api/v1/jobs/{job_id}/remediate",
                               json={"remediation_id": remediation_id})

    # -- approvals ------------------------------------------------------
    async def list_approvals(self) -> list[dict]:
        return (await self._req("GET", "/api/v1/approvals"))["approvals"]

    async def approve_job(self, job_id: str) -> dict:
        return await self._req("POST", f"/api/v1/approvals/{job_id}/approve")

    async def reject_job(self, job_id: str, reason: str = "") -> dict:
        return await self._req("POST", f"/api/v1/approvals/{job_id}/reject",
                               json={"reason": reason})

    # -- workflows / runs -----------------------------------------------
    async def put_workflow(self, doc: dict) -> dict:
        return await self._req("POST", "/api/v1/workflows", json=doc)

    async def start_run(self, workflow_id: str, input_value: Any = None, *,
                        idempotency_key: str = "", dry_run: bool = False) -> dict:
        headers = {"Idempotency-Key": idempotency_key} if idempotency_key else {}
        return await self._req("POST", f"/api/v1/workflows/{workflow_id}/runs",
                               json={"input": input_value, "dry_run": dry_run}, headers=headers)

    async def run_status(self, run_id: str) -> dict:
        return await self._req("GET", f"/api/v1/runs/{run_id}")

    async def wait_run(self, run_id: str, *, timeout_s: float = 300.0, poll_s: float = 0.25) -> dict:
        t0 = time.monotonic()
        while True:
            doc = await self.run_status(run_id)
            if doc.get("status") in TERMINAL_RUN_STATES:
                return doc
            if time.monotonic() - t0 > timeout_s:
                raise TimeoutError(f"run {run_id} not terminal after {timeout_s}s")
            await asyncio.sleep(poll_s)

    async def approve_step(self, run_id: str, step_id: str, *, approve: bool = True) -> dict:
        return await self._req("POST", f"/api/v1/runs/{run_id}/steps/{step_id}/approve",
                               json={"approve": approve})

    async def run_timeline(self, run_id: str) -> list[dict]:
        return (await self._req("GET", f"/api/v1/runs/{run_id}/timeline"))["timeline"]

    async def cancel_run(self, run_id: str) -> dict:
        return await self._req("POST", f"/api/v1/runs/{run_id}/cancel")

    async def rerun(self, run_id: str, from_step: str, *, dry_run: bool = False) -> dict:
        return await self._req("POST", f"/api/v1/runs/{run_id}/rerun",
                               json={"from_step": from_step, "dry_run": dry_run})

    # -- dlq / artifacts / context / misc --------------------------------
    async def list_dlq(self, offset: int = 0, limit: int = 50) -> dict:
        return await self._req("GET", f"/api/v1/dlq?offset={offset}&limit={limit}")

    async def retry_dlq(self, job_id: str) -> dict:
        return await self._req("POST", f"/api/v1/dlq/{job_id}/retry")

    async def put_artifact(self, data: bytes, *, retention: str = "standard") -> dict:
        return await self._req("POST", f"/api/v1/artifacts?retention={retention}", content=data)

    async def get_artifact(self, artifact_id: str) -> bytes:
        r = await self._c.get(f"/api/v1/artifacts/{artifact_id}")
        if r.status_code >= 400:
            raise ApiError(r.status_code, r.text)
        return r.content

    async def build_window(self, memory_id: str, *, mode: str = "RAW", payload: Any = None,
                           max_input_tokens: int = 4000) -> list[dict]:
        doc = await self._req("POST", "/api/v1/context/window", json={
            "memory_id": memory_id, "mode": mode, "payload": payload,
            "max_input_tokens": max_input_tokens})
        return doc["messages"]

    async def update_memory(self, memory_id: str, *, payload: Any = None,
                            model_response: str = "") -> None:
        await self._req("POST", f"/api/v1/context/memory/{memory_id}",
                        json={"payload": payload, "model_response": model_response})

    async def status(self) -> dict:
        return await self._req("GET", "/api/v1/status")

    async def workers(self) -> dict:
        return await self._req("GET", "/api/v1/workers")

    async def install_pack(self, manifest: dict) -> dict:
        return await self._req("POST", "/api/v1/packs", json=manifest)
