"""Typed gateway client (reference ``sdk/client/client.go:23-393``): the
programmatic integration surface for external tools and tests.

Async (httpx) with a small sync facade; covers jobs, workflows/runs,
approvals, DLQ, artifacts, context, policy, packs, and streaming
``llm.generate`` (docs/SERVING.md) over the gateway WS event tap.
"""
from __future__ import annotations

import asyncio
import json
import random
import time
from typing import Any, AsyncIterator, Optional

import httpx

from ..protocol.partition import partition_of  # noqa: F401 - re-export: lets
# partition-aware clients (load generators, shard-pinned tooling) pre-compute
# which scheduler shard will own a job id they submit

TERMINAL_JOB_STATES = {"SUCCEEDED", "FAILED", "CANCELLED", "TIMEOUT", "DENIED"}
TERMINAL_RUN_STATES = {"SUCCEEDED", "FAILED", "CANCELLED"}


def merge_stream_packet(
    n_seen: int, offset: Any, tokens: list
) -> tuple[list[int], int]:
    """Offset-dedupe one stream packet against an assembled sequence of
    ``n_seen`` tokens already yielded: indexes below ``n_seen`` are
    duplicates (a failed-over worker replays the streamed prefix at offset
    0), exactly ``n_seen`` extends the stream, and a gap above it is left
    for the authoritative terminal-result tail.  Packets may carry ANY
    number of tokens — a speculative-decoding burst lands as one multi-
    token packet and must merge exactly like k single-token packets.
    Returns ``(fresh_tokens, new_n_seen)``."""
    off = offset if isinstance(offset, int) and offset >= 0 else n_seen
    fresh: list[int] = []
    for i, t in enumerate(tokens):
        if off + i == n_seen:
            n_seen += 1
            fresh.append(int(t))
    return fresh, n_seen


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message


class Client:
    def __init__(
        self,
        base_url: str = "http://127.0.0.1:8081",
        *,
        api_key: str = "",
        principal_id: str = "",
        role: str = "",
        tenant_id: str = "",
        timeout_s: float = 30.0,
        retry_429: int = 3,
    ):
        """``retry_429`` bounds how many times a 429 (rate-limited or
        admission-shed — docs/ADMISSION.md) is retried.  The client honors
        the server's ``Retry-After`` with ±25% jitter instead of retrying
        immediately, so a shed burst de-synchronizes; 0 disables retries."""
        self._retry_429 = max(0, retry_429)
        headers = {}
        if api_key:
            headers["X-Api-Key"] = api_key
        if principal_id:
            headers["X-Principal-Id"] = principal_id
        if role:
            headers["X-Principal-Role"] = role
        if tenant_id:
            headers["X-Tenant-Id"] = tenant_id
        self._c = httpx.AsyncClient(base_url=base_url, headers=headers, timeout=timeout_s)

    async def close(self) -> None:
        await self._c.aclose()

    async def __aenter__(self) -> "Client":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    async def _req(self, method: str, path: str, **kw) -> Any:
        attempt = 0
        while True:
            r = await self._c.request(method, path, **kw)
            if r.status_code == 429 and attempt < self._retry_429:
                # honor the gateway's honest, headroom-derived Retry-After
                # with jitter — immediate retries would re-offer the very
                # load that got shed (docs/ADMISSION.md)
                attempt += 1
                await asyncio.sleep(self._retry_delay(r))
                continue
            try:
                body = r.json()
            except ValueError:
                body = {"raw": r.text}
            if r.status_code >= 400:
                raise ApiError(r.status_code, str(body.get("error", body)))
            return body

    @staticmethod
    def _retry_delay(r: httpx.Response) -> float:
        try:
            delay = float(r.headers.get("Retry-After", ""))
        except ValueError:
            delay = 0.5
        return min(30.0, max(0.05, delay)) * (1.0 + random.uniform(-0.25, 0.25))

    # -- jobs -----------------------------------------------------------
    async def submit_job(
        self,
        topic: str,
        payload: Any = None,
        *,
        metadata: Optional[dict] = None,
        labels: Optional[dict] = None,
        env: Optional[dict] = None,
        budget: Optional[dict] = None,
        priority: str = "BATCH",
        idempotency_key: str = "",
        memory_id: str = "",
        job_id: str = "",
    ) -> dict:
        """Submit one job.  ``job_id`` pins the id client-side (the sharded
        gateway stamps the owning scheduler partition from it — see
        :func:`partition_of`); empty lets the gateway mint one."""
        body: dict[str, Any] = {"topic": topic, "payload": payload, "priority": priority}
        if job_id:
            body["job_id"] = job_id
        if metadata:
            body["metadata"] = metadata
        if labels:
            body["labels"] = labels
        if env:
            body["env"] = env
        if budget:
            body["budget"] = budget
        if idempotency_key:
            body["idempotency_key"] = idempotency_key
        if memory_id:
            body["memory_id"] = memory_id
        return await self._req("POST", "/api/v1/jobs", json=body)

    async def submit_jobs(self, jobs: list[dict]) -> dict:
        """Bulk submit via ``POST /api/v1/jobs:batch``: each entry is a
        single-submit body; per-job verdicts come back positionally in
        ``jobs`` (accepted entries carry ``job_id``/``trace_id``)."""
        return await self._req("POST", "/api/v1/jobs:batch", json={"jobs": jobs})

    async def job_status(self, job_id: str, *, events: bool = False, result: bool = False) -> dict:
        q = []
        if events:
            q.append("events=true")
        if result:
            q.append("result=true")
        qs = ("?" + "&".join(q)) if q else ""
        return await self._req("GET", f"/api/v1/jobs/{job_id}{qs}")

    async def wait_job(self, job_id: str, *, timeout_s: float = 120.0, poll_s: float = 0.25) -> dict:
        t0 = time.monotonic()
        while True:
            doc = await self.job_status(job_id, result=True)
            if doc.get("state") in TERMINAL_JOB_STATES:
                return doc
            if time.monotonic() - t0 > timeout_s:
                raise TimeoutError(f"job {job_id} not terminal after {timeout_s}s")
            await asyncio.sleep(poll_s)

    async def cancel_job(self, job_id: str) -> dict:
        return await self._req("POST", f"/api/v1/jobs/{job_id}/cancel")

    # -- serving (llm.generate, docs/SERVING.md) ------------------------
    async def generate(
        self,
        tokens: list[int],
        *,
        topic: str = "job.tpu.generate",
        session_id: str = "",
        max_new_tokens: int = 16,
        eos_token: Optional[int] = None,
        stream: bool = True,
        labels: Optional[dict] = None,
        timeout_s: float = 120.0,
    ) -> AsyncIterator[int]:
        """Submit an ``llm.generate`` job and yield generated tokens.

        Streaming rides the gateway's ``/api/v1/stream`` WS tap: the worker
        publishes each decode step's tokens as ``status_hint="stream"``
        progress packets, which this helper filters by job id.  The WS is
        opened *before* the submit so the first tokens can't be missed.
        ``session_id`` keys the conversation: turns sharing it route to the
        worker holding the session's KV pages (scheduler session affinity).

        With ``stream=False`` (or when the WS upgrade is unavailable) the
        helper falls back to polling the terminal result and yields the full
        token list at once — same iterator contract, one burst."""
        payload: dict[str, Any] = {
            "op": "llm.generate",
            "tokens": [int(t) for t in tokens],
            "max_new_tokens": max_new_tokens,
            "stream": bool(stream),
        }
        if session_id:
            payload["session_id"] = session_id
        if eos_token is not None:
            payload["eos_token"] = int(eos_token)
        ws = session = None
        if stream:
            try:
                import aiohttp

                session = aiohttp.ClientSession()
                ws = await session.ws_connect(
                    str(self._c.base_url).rstrip("/") + "/api/v1/stream",
                    headers={k: v for k, v in self._c.headers.items()
                             if k.lower().startswith("x-")},
                    timeout=aiohttp.ClientWSTimeout(ws_close=10.0),
                )
            except Exception:  # noqa: BLE001 - WS is an upgrade, not a must
                if session is not None:
                    await session.close()
                ws = session = None
        try:
            if ws is None:
                payload["stream"] = False
                doc = await self.submit_job(topic, payload, labels=labels)
                final = await self.wait_job(doc["job_id"], timeout_s=timeout_s)
                for t in self._terminal_tokens(final, doc["job_id"]):
                    yield t
                return
            doc = await self.submit_job(topic, payload, labels=labels)
            job_id = doc["job_id"]
            n_seen = 0
            deadline = time.monotonic() + timeout_s
            while True:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(f"generate({job_id}) not terminal after {timeout_s}s")
                msg = await ws.receive(timeout=left)
                if msg.type.name not in ("TEXT", "BINARY"):
                    # tap closed under us: finish off the terminal result
                    final = await self.wait_job(job_id, timeout_s=max(1.0, left))
                    for t in self._terminal_tokens(final, job_id)[n_seen:]:
                        yield t
                    return
                evt = json.loads(msg.data)
                pkt = evt.get("packet") or {}
                pl = pkt.get("payload") or {}
                if pl.get("job_id") != job_id:
                    continue
                if pkt.get("kind") == "job_progress" and pl.get("status_hint") == "stream":
                    # dedupe by token offset: a failed-over session's new
                    # worker replays the already-streamed prefix at offset
                    # 0, so indexes below n_seen are duplicates to skip and
                    # exactly index n_seen extends the stream — the
                    # assembled sequence is exactly-once across worker
                    # crashes and migrations (docs/SERVING.md).  A gap
                    # (index above n_seen: a lost packet) is left for the
                    # authoritative terminal-result tail below.
                    fresh, n_seen = merge_stream_packet(
                        n_seen, pl.get("offset"), pl.get("tokens") or [])
                    for t in fresh:
                        yield t
                elif pkt.get("kind") == "job_result":
                    if pl.get("status") != "SUCCEEDED":
                        raise ApiError(
                            500,
                            f"generate {job_id} {pl.get('status')}: "
                            f"{pl.get('error_message', '')}",
                        )
                    # eos can land between progress packets: the terminal
                    # result is authoritative for the tail
                    final = await self.job_status(job_id, result=True)
                    toks = (final.get("result") or {}).get("tokens") or []
                    for t in toks[n_seen:]:
                        yield int(t)
                    return
        finally:
            if session is not None:
                await session.close()

    def _terminal_tokens(self, final: dict, job_id: str) -> list[int]:
        if final.get("state") != "SUCCEEDED":
            raise ApiError(500, f"generate {job_id} {final.get('state')}")
        return [int(t) for t in (final.get("result") or {}).get("tokens") or []]

    async def remediate_job(self, job_id: str, remediation_id: str = "") -> dict:
        return await self._req("POST", f"/api/v1/jobs/{job_id}/remediate",
                               json={"remediation_id": remediation_id})

    # -- approvals ------------------------------------------------------
    async def list_approvals(self) -> list[dict]:
        return (await self._req("GET", "/api/v1/approvals"))["approvals"]

    async def approve_job(self, job_id: str) -> dict:
        return await self._req("POST", f"/api/v1/approvals/{job_id}/approve")

    async def reject_job(self, job_id: str, reason: str = "") -> dict:
        return await self._req("POST", f"/api/v1/approvals/{job_id}/reject",
                               json={"reason": reason})

    # -- workflows / runs -----------------------------------------------
    async def put_workflow(self, doc: dict) -> dict:
        return await self._req("POST", "/api/v1/workflows", json=doc)

    async def start_run(self, workflow_id: str, input_value: Any = None, *,
                        idempotency_key: str = "", dry_run: bool = False) -> dict:
        headers = {"Idempotency-Key": idempotency_key} if idempotency_key else {}
        return await self._req("POST", f"/api/v1/workflows/{workflow_id}/runs",
                               json={"input": input_value, "dry_run": dry_run}, headers=headers)

    async def run_status(self, run_id: str) -> dict:
        return await self._req("GET", f"/api/v1/runs/{run_id}")

    async def wait_run(self, run_id: str, *, timeout_s: float = 300.0, poll_s: float = 0.25) -> dict:
        t0 = time.monotonic()
        while True:
            doc = await self.run_status(run_id)
            if doc.get("status") in TERMINAL_RUN_STATES:
                return doc
            if time.monotonic() - t0 > timeout_s:
                raise TimeoutError(f"run {run_id} not terminal after {timeout_s}s")
            await asyncio.sleep(poll_s)

    async def approve_step(self, run_id: str, step_id: str, *, approve: bool = True) -> dict:
        return await self._req("POST", f"/api/v1/runs/{run_id}/steps/{step_id}/approve",
                               json={"approve": approve})

    async def run_timeline(self, run_id: str) -> list[dict]:
        return (await self._req("GET", f"/api/v1/runs/{run_id}/timeline"))["timeline"]

    async def cancel_run(self, run_id: str) -> dict:
        return await self._req("POST", f"/api/v1/runs/{run_id}/cancel")

    async def rerun(self, run_id: str, from_step: str, *, dry_run: bool = False) -> dict:
        return await self._req("POST", f"/api/v1/runs/{run_id}/rerun",
                               json={"from_step": from_step, "dry_run": dry_run})

    # -- dlq / artifacts / context / misc --------------------------------
    async def list_dlq(self, offset: int = 0, limit: int = 50) -> dict:
        return await self._req("GET", f"/api/v1/dlq?offset={offset}&limit={limit}")

    async def retry_dlq(self, job_id: str) -> dict:
        return await self._req("POST", f"/api/v1/dlq/{job_id}/retry")

    async def put_artifact(self, data: bytes, *, retention: str = "standard") -> dict:
        return await self._req("POST", f"/api/v1/artifacts?retention={retention}", content=data)

    async def get_artifact(self, artifact_id: str) -> bytes:
        r = await self._c.get(f"/api/v1/artifacts/{artifact_id}")
        if r.status_code >= 400:
            raise ApiError(r.status_code, r.text)
        return r.content

    async def build_window(self, memory_id: str, *, mode: str = "RAW", payload: Any = None,
                           max_input_tokens: int = 4000) -> list[dict]:
        doc = await self._req("POST", "/api/v1/context/window", json={
            "memory_id": memory_id, "mode": mode, "payload": payload,
            "max_input_tokens": max_input_tokens})
        return doc["messages"]

    async def update_memory(self, memory_id: str, *, payload: Any = None,
                            model_response: str = "") -> None:
        await self._req("POST", f"/api/v1/context/memory/{memory_id}",
                        json={"payload": payload, "model_response": model_response})

    async def status(self) -> dict:
        return await self._req("GET", "/api/v1/status")

    async def workers(self) -> dict:
        return await self._req("GET", "/api/v1/workers")

    async def drain_worker(self, worker_id: str, *, reason: str = "") -> dict:
        """Gracefully drain a worker: it stops admitting, live-migrates its
        serving sessions to peers, finishes per-job work, then exits with
        zero CANCELLED sessions (docs/SERVING.md §Migration)."""
        return await self._req(
            "POST", f"/api/v1/workers/{worker_id}/drain",
            json={"reason": reason} if reason else {},
        )

    async def install_pack(self, manifest: dict) -> dict:
        return await self._req("POST", "/api/v1/packs", json=manifest)
