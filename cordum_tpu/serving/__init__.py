"""Serving subsystem: paged KV cache + continuous batching (docs/SERVING.md).

The micro-batcher (cordum_tpu/batching) coalesces *stateless* embed/infer
jobs; user-facing LLM traffic is *autoregressive decode* with per-session
state.  This package adds the serving path:

  * :class:`PageAllocator` — block-granular KV-page bookkeeping over a
    preallocated cache arena (page 0 reserved as the null page)
  * :class:`LlamaServingBackend` — the XLA side: ONE ragged paged-
    attention entry point (:class:`StepEntry` rows over a static flat
    token buffer) serving any mix of prefill chunks and decode steps in a
    single device call — one compiled program, no length/batch buckets
  * :class:`ServingEngine` — the continuous-batching loop: admits new
    sessions and retires finished ones every step, schedules chunked
    prefill *inside* the mixed step under a token budget, streams tokens,
    frees pages on retirement/cancel
  * :class:`MigrationServer` / :func:`migrate_session` — live KV-page
    session migration between workers over the statebus frame layer
    (graceful drain + crash failover, docs/SERVING.md §Migration)

``llm.generate`` jobs route here from the worker intake (see
``worker/runtime.py``); the scheduler pins a conversation's jobs to the
worker holding its KV pages via the ``cordum.session_key`` affinity map
(``controlplane/scheduler/strategy.py``).
"""
from .backend import LlamaServingBackend, StepEntry
from .engine import (
    GenRequest,
    ServingEngine,
    ServingStats,
    SessionCancelled,
    SessionMigrated,
    SessionRequeued,
)
from .migration import MigrationError, MigrationServer, migrate_session
from .pager import CacheExhausted, PageAllocator

__all__ = [
    "CacheExhausted",
    "GenRequest",
    "LlamaServingBackend",
    "MigrationError",
    "MigrationServer",
    "PageAllocator",
    "ServingEngine",
    "ServingStats",
    "SessionCancelled",
    "SessionMigrated",
    "SessionRequeued",
    "StepEntry",
    "migrate_session",
]
