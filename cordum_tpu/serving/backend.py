"""The XLA half of the serving path: bucketed prefill + paged decode steps.

One backend per worker process owns the KV-page arena (``models/llama``
``init_kv_pages``) and the jitted entry points.  Shape discipline keeps the
program count bounded (the batching/buckets ladder trick):

  * prefill compiles one program per prompt *length bucket* (pow2 ladder);
  * decode compiles one program per *batch bucket* — the page-table width is
    static, so join/leave only moves a session between batch buckets.

Both entry points are **blocking** (called from the worker's executor
threads) and serialize page-arena mutations under one lock: the functional
``.at[].set`` updates would silently drop each other's writes if a prefill
and a decode step interleaved on the same arrays.  Phase separation is the
engine's job (a prefill never rides *inside* a decode batch; see
docs/SERVING.md "Prefill/decode separation").
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Optional

import numpy as np

from ..batching.buckets import bucket_for, pow2_buckets
from ..models import llama


class LlamaServingBackend:
    def __init__(
        self,
        cfg: Optional[llama.LlamaConfig] = None,
        *,
        num_pages: int = 128,
        page_size: int = 16,
        max_context: int = 0,
        seed: int = 0,
        params_provider: Optional[Callable[[], Any]] = None,
    ) -> None:
        self.cfg = cfg or llama.LlamaConfig.tiny()
        self.page_size = max(1, page_size)
        self.num_pages = max(2, num_pages)
        # static page-table width: the worst-case per-sequence footprint
        self.max_context = min(
            max_context or self.cfg.max_seq_len, self.cfg.max_seq_len
        )
        self.pages_per_seq = -(-self.max_context // self.page_size)
        self._seed = seed
        self._params_provider = params_provider
        self._params: Any = None
        self._k_pages: Any = None
        self._v_pages: Any = None
        self._prefill_jit: Any = None
        self._decode_jit: Any = None
        self._prefill_buckets = pow2_buckets(8, self.max_context)
        self._compiled_shapes: set = set()  # observability: program count
        # page-arena mutation lock: prefill and decode both read-modify-write
        # the K/V arrays from executor threads
        self._dev_lock = threading.Lock()

    # ------------------------------------------------------------------
    def _ensure(self) -> None:
        if self._params is not None:
            return
        import jax

        if self._params_provider is not None:
            self._params = self._params_provider()
        else:
            self._params = llama.init_params(jax.random.PRNGKey(self._seed), self.cfg)
        self._k_pages, self._v_pages = llama.init_kv_pages(
            self.cfg, self.num_pages, self.page_size
        )
        cfg = self.cfg
        self._prefill_jit = jax.jit(lambda p, t: llama.prefill_forward(p, t, cfg))
        self._decode_jit = jax.jit(
            lambda p, kp, vp, toks, pos, pt: llama.decode_step(
                p, kp, vp, toks, pos, pt, cfg
            )
        )

    def compiled_programs(self) -> int:
        return len(self._compiled_shapes)

    def _clamp(self, row: list[int]) -> list[int]:
        vmax = self.cfg.vocab_size - 1
        return [min(max(0, int(t)), vmax) for t in row]

    # ------------------------------------------------------------------
    def prefill(self, prompt: list[int], pages: list[int]) -> int:
        """Run the prompt through the full forward, write its K/V into
        ``pages``, and return the first generated token.  Blocking; call
        from an executor thread."""
        import jax.numpy as jnp

        self._ensure()
        row = self._clamp(prompt)[: self.max_context]
        t = max(1, len(row))
        tb = bucket_for(t, self._prefill_buckets)
        batch = np.zeros((1, tb), np.int32)
        batch[0, : len(row)] = row
        # position → (page, slot); the padded tail scatters to the null page
        pos = np.arange(tb)
        page_ids = np.zeros((tb,), np.int32)
        page_arr = np.asarray(pages, np.int32)
        page_ids[:t] = page_arr[pos[:t] // self.page_size]
        slots = (pos % self.page_size).astype(np.int32)
        self._compiled_shapes.add(("prefill", tb))
        with self._dev_lock:
            logits, ks, vs = self._prefill_jit(self._params, jnp.asarray(batch))
            self._k_pages, self._v_pages = llama.scatter_prefill_kv(
                self._k_pages, self._v_pages, ks[:, 0], vs[:, 0],
                jnp.asarray(page_ids), jnp.asarray(slots),
            )
            first = int(jnp.argmax(logits[0, t - 1]))
        return first

    # ------------------------------------------------------------------
    def decode(self, entries: list[tuple[int, int, list[int]]]) -> list[int]:
        """One decode step for a ragged batch.

        ``entries``: per-session ``(last_token, position, pages)`` where
        ``position`` is the slot the last token occupies (== tokens cached
        so far).  Returns one next token per entry.  Blocking; call from an
        executor thread."""
        import jax.numpy as jnp

        self._ensure()
        b = len(entries)
        if b == 0:
            return []
        bp = 1 << (b - 1).bit_length()  # pad batch to the pow2 bucket
        tokens = np.zeros((bp,), np.int32)
        positions = np.zeros((bp,), np.int32)
        tables = np.zeros((bp, self.pages_per_seq), np.int32)  # null-page fill
        for i, (tok, pos, pages) in enumerate(entries):
            tokens[i] = min(max(0, int(tok)), self.cfg.vocab_size - 1)
            positions[i] = pos
            tables[i, : len(pages)] = pages
        self._compiled_shapes.add(("decode", bp))
        with self._dev_lock:
            nxt, self._k_pages, self._v_pages = self._decode_jit(
                self._params, self._k_pages, self._v_pages,
                jnp.asarray(tokens), jnp.asarray(positions), jnp.asarray(tables),
            )
            out = np.asarray(nxt)[:b].tolist()
        return out
