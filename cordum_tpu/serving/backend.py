"""The XLA half of the serving path: ONE ragged mixed prefill+decode entry.

One backend per worker process owns the KV-page arena (``models/llama``
``init_kv_pages``) and a single jitted program (``models/llama``
``ragged_step``).  Every device call — a decode step over the live
sessions, a chunk of some prompt's prefill, or any mix of the two — flows
through :meth:`step` with the same static operand shapes:

  * a flat token buffer of ``max_batch_tokens`` slots (decode last-tokens
    and prefill chunk tokens interleaved, tail padded onto the null page);
  * per-sequence metadata: page tables ``[max_seqs + 1, pages_per_seq]``
    (the +1 row is the all-null padding row), per-token sequence ids and
    positions, and each sequence's sampling index.

Because the shapes never change, XLA compiles exactly **one** program —
there is no prompt-length bucket ladder, no pow2 batch buckets, and no
recompile cliff when sessions join or leave (the Ragged Paged Attention
argument, PAPERS.md).  ``compiled_programs()`` and the
``cordum_serving_compile_total{entry}`` counter make that a measured
number, and ``last_step_compiled`` lets the capacity observatory keep
warmup compiles out of the steady-state throughput rows.

:meth:`step` is **blocking** (called from the worker's executor threads)
and serializes page-arena mutations under one lock: the functional
``.at[].set`` updates would silently drop each other's writes if two steps
interleaved on the same arrays.  The engine issues one step at a time, so
the lock is a safety net for the compat wrappers (:meth:`prefill` /
:meth:`decode`) that tests and benches drive directly.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Optional

import numpy as np

DEFAULT_MAX_SEQS = 16


@dataclass
class StepEntry:
    """One sequence's contribution to a mixed ragged step.

    A decode step feeds exactly one token (the session's last emitted
    token) at its current position; a prefill chunk feeds a slice of the
    prompt starting at ``start``.  ``sample=True`` asks for the next token
    from the last fed position (always for decode; only for the chunk that
    completes a prompt).

    ``draft > 0`` marks a speculative verification row (docs/SERVING.md
    §Speculative decoding): ``tokens`` is ``[last_token, d_1..d_k]`` — the
    session's last emitted token followed by ``draft`` drafted
    continuations — and :meth:`LlamaServingBackend.step` returns the
    per-position next-token predictions for ALL k+1 fed positions (a
    ``list[int]``) instead of the single sequence-final sample.  The row
    is prefill-shaped on the wire; only the result shape differs."""

    tokens: list[int]
    start: int  # global sequence position of tokens[0]
    pages: list[int]  # the session's page list (page-table row prefix)
    sample: bool = True
    phase: str = "decode"  # "prefill" | "decode" — observability + fakes
    key: str = ""  # session/job id — observability + fakes
    draft: int = 0  # >0: speculative row with this many drafted tokens


class LlamaServingBackend:
    # the ragged program returns per-position predictions for every buffer
    # row, so draft verification rows (StepEntry.draft > 0) are supported
    # natively — the engine gates its drafter on this capability flag
    # (test fakes without it keep the legacy single-sample step contract)
    supports_draft = True
    # sharded serving (serving/shard.py): follower ranks set this False and
    # compile a program whose lm_head is dead-code-eliminated — rank 0
    # alone pays sampling (docs/SERVING.md §Sharded serving)
    sample_output = True
    # observation tap: called with the entry list after every successful
    # step — the serving-gang leader broadcasts it so followers replay the
    # identical program against their head shards
    on_step: Optional[Callable[[list["StepEntry"]], None]] = None

    def __init__(
        self,
        cfg: Any = None,
        *,
        num_pages: int = 128,
        page_size: int = 16,
        max_context: int = 0,
        max_seqs: int = 0,
        max_batch_tokens: int = 0,
        seed: int = 0,
        params_provider: Optional[Callable[[], Any]] = None,
        metrics: Any = None,
    ) -> None:
        # lazy model import keeps this module (and the engine importing it
        # for StepEntry) jax-free until a real backend is constructed
        from ..models import llama

        self.cfg = cfg or llama.LlamaConfig.tiny()
        self.page_size = max(1, page_size)
        self.num_pages = max(2, num_pages)
        # static page-table width: the worst-case per-sequence footprint
        self.max_context = min(
            max_context or self.cfg.max_seq_len, self.cfg.max_seq_len
        )
        self.pages_per_seq = -(-self.max_context // self.page_size)
        # static ragged-step shapes: S sequence rows (+1 padding row) over a
        # T-slot flat token buffer.  T - S is the headroom prefill chunks
        # ride in when the decode set is full (the chunked-prefill budget).
        self.max_seqs = max(1, max_seqs or DEFAULT_MAX_SEQS)
        self.max_batch_tokens = max(
            self.max_seqs, max_batch_tokens or 2 * self.max_seqs
        )
        self._seed = seed
        self._params_provider = params_provider
        self._params: Any = None
        self._k_pages: Any = None
        self._v_pages: Any = None
        self._ragged_jit: Any = None
        self._compiled_shapes: set = set()  # observability: program count
        self._metrics = metrics
        self.last_step_compiled = False  # did the latest step() pay XLA?
        # page-arena mutation lock: steps read-modify-write the K/V arrays
        # from executor threads
        self._dev_lock = threading.Lock()

    # ------------------------------------------------------------------
    def _ensure(self) -> None:
        if self._params is not None:
            return
        import jax

        from ..models import llama

        if self._params_provider is not None:
            self._params = self._params_provider()
        else:
            self._params = llama.init_params(jax.random.PRNGKey(self._seed), self.cfg)
        self._k_pages, self._v_pages = llama.init_kv_pages(
            self.cfg, self.num_pages, self.page_size
        )
        # sharded-serving hook: a subclass may re-place params and arenas
        # onto a TP mesh (NamedSharding) before the program compiles
        self._params, self._k_pages, self._v_pages = self._place_state(
            self._params, self._k_pages, self._v_pages
        )
        cfg = self.cfg
        sample = bool(self.sample_output)
        # donate the page arenas on real accelerators so the in-place
        # update never copies the arena; CPU jax spams donation warnings
        donate = (jax.default_backend() != "cpu")
        self._ragged_jit = jax.jit(
            lambda p, kp, vp, toks, pos, pt, ts, oi: llama.ragged_step(
                p, kp, vp, toks, pos, pt, ts, oi, cfg, sample_logits=sample
            ),
            donate_argnums=(1, 2) if donate else (),
        )

    def _place_state(self, params: Any, k_pages: Any, v_pages: Any):
        """Device-placement hook (identity here).  ShardedServingBackend
        overrides it to apply the TP NamedSharding layout."""
        return params, k_pages, v_pages

    def compiled_programs(self) -> int:
        return len(self._compiled_shapes)

    def _clamp(self, row: list[int]) -> list[int]:
        vmax = self.cfg.vocab_size - 1
        return [min(max(0, int(t)), vmax) for t in row]

    # ------------------------------------------------------------------
    def step(self, entries: list[StepEntry]) -> list[Any]:
        """One ragged mixed prefill+decode device call.

        Returns one value per entry, aligned: the next token (``int``) for
        sampled entries, ``None`` for prefill chunks that do not complete
        their prompt, and the per-position prediction list (``list[int]``,
        one next-token argmax per fed position) for draft verification
        rows (``entry.draft > 0``).  Blocking; call from an executor
        thread."""
        import jax.numpy as jnp

        self._ensure()
        if not entries:
            return []
        t_buf, s_rows = self.max_batch_tokens, self.max_seqs
        if len(entries) > s_rows:
            raise ValueError(
                f"{len(entries)} sequences in one step; backend max_seqs is "
                f"{s_rows}"
            )
        total = sum(len(e.tokens) for e in entries)
        if total > t_buf:
            raise ValueError(
                f"{total} tokens in one step; backend max_batch_tokens is "
                f"{t_buf}"
            )
        tokens = np.zeros((t_buf,), np.int32)
        positions = np.zeros((t_buf,), np.int32)
        # padding tokens map to the padding row (all null pages): their
        # writes land on page 0 and no live sequence's gather can see them
        token_seq = np.full((t_buf,), s_rows, np.int32)
        tables = np.zeros((s_rows + 1, self.pages_per_seq), np.int32)
        out_idx = np.zeros((s_rows,), np.int32)
        ti = 0
        spans: list[tuple[int, int]] = []  # entry i's [lo, hi) buffer slots
        for i, e in enumerate(entries):
            row = self._clamp(e.tokens)
            n = len(row)
            if not n:
                raise ValueError("empty StepEntry.tokens")
            if e.start + n > self.max_context:
                raise ValueError(
                    f"entry spans positions [{e.start}, {e.start + n}); "
                    f"backend max_context is {self.max_context}"
                )
            tokens[ti:ti + n] = row
            positions[ti:ti + n] = np.arange(e.start, e.start + n)
            token_seq[ti:ti + n] = i
            tables[i, : len(e.pages)] = e.pages
            out_idx[i] = ti + n - 1
            spans.append((ti, ti + n))
            ti += n
        shape_key = ("ragged", t_buf, s_rows, self.pages_per_seq)
        self.last_step_compiled = shape_key not in self._compiled_shapes
        if self.last_step_compiled:
            self._compiled_shapes.add(shape_key)
            if self._metrics is not None:
                self._metrics.serving_compiles.inc(entry="ragged")
        with self._dev_lock:
            nxt, self._k_pages, self._v_pages = self._ragged_jit(
                self._params, self._k_pages, self._v_pages,
                jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(tables), jnp.asarray(token_seq),
                jnp.asarray(out_idx),
            )
            out = np.asarray(nxt)
        # out is [T] per-position predictions: a sampled entry's token is
        # the prediction after its LAST fed slot (== out_idx[i], the same
        # value the old sequence-final projection produced); a draft row
        # gets the whole span — one verification vote per fed position
        res: list[Any] = []
        for e, (lo, hi) in zip(entries, spans):
            if e.draft > 0:
                res.append([int(t) for t in out[lo:hi]])
            elif e.sample:
                res.append(int(out[hi - 1]))
            else:
                res.append(None)
        if self.on_step is not None:
            self.on_step(entries)
        return res

    # ------------------------------------------------------------------
    # live KV-page migration (serving/migration.py, docs/PROTOCOL.md §Page
    # transfer): pages leave and enter the arena at their TRUE lengths —
    # only the filled slots of each page ride the wire, float32-upcast so
    # the receiver can cast back into its own arena dtype exactly.
    def export_kv(
        self, pages: list[int], start_tok: int, end_tok: int
    ) -> list[dict]:
        """Records for the session pages covering positions
        ``[start_tok, end_tok)``.  ``pages`` is the session's full page
        list; record ``i`` is the page ORDINAL within it (the receiver maps
        ordinals onto its own freshly allocated arena blocks).  Blocking
        (device reads); call from an executor thread."""
        if end_tok <= start_tok:
            return []
        self._ensure()
        from ..models import llama

        ps = self.page_size
        first, last = start_tok // ps, -(-end_tok // ps)
        ords = list(range(first, min(last, len(pages))))
        used = [min(ps, end_tok - o * ps) for o in ords]
        # under the device lock: on donating backends a concurrent step
        # invalidates the arena buffers it was handed, so the gather must
        # not overlap a step's jit call (page CONTENT below end_tok is
        # stable either way — steps only write at the current positions)
        with self._dev_lock:
            blocks = llama.gather_kv_pages(
                self._k_pages, self._v_pages, [pages[o] for o in ords], used
            )
        return [
            {"i": o, "used": n, "k": k.tobytes(), "v": v.tobytes(),
             "shape": list(k.shape)}
            for o, n, (k, v) in zip(ords, used, blocks)
        ]

    def import_kv(self, pages: list[int], records: list[dict]) -> None:
        """Scatter migrated page records into freshly allocated arena
        blocks (``pages``, the receiving session's page list).  Blocking;
        call from an executor thread."""
        if not records:
            return
        self._ensure()
        from ..models import llama

        if any("heads" in rec for rec in records):
            # per-rank records from a serving-gang source (docs/SERVING.md
            # §Sharded serving): each rank exported its head slice of every
            # page — merge the slices back into full-head records, so ANY
            # backend (single-rank or gang) imports a gang export unchanged
            from .shard import merge_rank_records

            records = merge_rank_records(records)
        ids, blocks = [], []
        for rec in records:
            o = int(rec["i"])
            if not 0 <= o < len(pages):
                raise ValueError(f"page ordinal {o} outside {len(pages)} pages")
            shape = tuple(rec["shape"])
            k = np.frombuffer(rec["k"], np.float32).reshape(shape)
            v = np.frombuffer(rec["v"], np.float32).reshape(shape)
            ids.append(pages[o])
            blocks.append((k, v))
        with self._dev_lock:
            self._k_pages, self._v_pages = llama.scatter_kv_pages(
                self._k_pages, self._v_pages, ids, blocks
            )

    def copy_page(self, src: int, dst: int) -> None:
        """Duplicate physical page ``src`` into ``dst`` on device — the
        copy-on-write half of prefix sharing (docs/SERVING.md §Prefix
        cache and tiering).  The engine calls this before any position
        inside a shared page would be written: the writer gets its own
        copy, every other table keeps attending to the original.  One
        cached executable serves every CoW (traced page indices).
        Blocking; call from an executor thread."""
        self._ensure()
        from ..models import llama

        with self._dev_lock:
            self._k_pages, self._v_pages = llama.copy_kv_page(
                self._k_pages, self._v_pages, src, dst
            )

    # ------------------------------------------------------------------
    # compat conveniences over step() — tests and benches drive these; the
    # engine always assembles mixed steps itself.  Both ride the SAME
    # ragged program: there is nothing else to compile.
    def prefill(self, prompt: list[int], pages: list[int]) -> int:
        """Run a whole prompt through ragged prefill chunks (token-budget
        sized) and return the first generated token.  Blocking."""
        row = list(prompt)[: self.max_context]
        total = max(1, len(row)) or 1
        first: Optional[int] = None
        start = 0
        while start < total or first is None:
            chunk = row[start:start + self.max_batch_tokens] or [0]
            done = start + len(chunk) >= total
            (first,) = self.step([StepEntry(
                tokens=chunk, start=start, pages=pages, sample=done,
                phase="prefill",
            )])
            start += len(chunk)
            if done:
                break
        assert first is not None
        return first

    def decode(self, entries: list[tuple[int, int, list[int]]]) -> list[int]:
        """One decode step for a ragged batch of ``(last_token, position,
        pages)`` triples — one next token per entry.  Batches wider than
        the static shapes split across step() calls.  Blocking."""
        out: list[int] = []
        width = min(self.max_seqs, self.max_batch_tokens)
        for lo in range(0, len(entries), width):
            chunk = entries[lo:lo + width]
            res = self.step([StepEntry(
                tokens=[tok], start=pos, pages=pages, sample=True,
                phase="decode",
            ) for tok, pos, pages in chunk])
            out.extend(int(t) for t in res if t is not None)
        return out
