"""The continuous-batching decode loop (the serving subsystem's scheduler).

Lifecycle of an ``llm.generate`` session (docs/SERVING.md):

  * :meth:`ServingEngine.submit` parks the session in the **admission
    queue**; admission allocates its full worst-case page footprint
    (prompt + max_new_tokens) so an admitted session can never die
    mid-decode from cache pressure — exhaustion just delays admission;
  * admitted sessions **prefill** off the decode path (a separate XLA call
    on an executor thread, never inside a decode batch), bounded by
    ``max_concurrent_prefills`` so a burst of long prompts cannot starve
    in-flight decodes (the FlexNPU co-location policy, PAPERS.md);
  * prefilled sessions join the **decode set**: every step assembles one
    ragged batch from the per-session page tables, runs ONE XLA decode
    call, scatters tokens back, admits joiners and retires finishers —
    sessions join/leave mid-flight without perturbing each other's rows;
  * retirement (finish / cancel / failure) frees the session's pages back
    to the allocator and resolves the submit waiter.

Token streaming rides the session's ``on_tokens`` callback (the worker
publishes ``JobProgress`` packets with ``status_hint="stream"``); the
terminal ``JobResult`` carries the full token list for non-streaming
consumers.
"""
from __future__ import annotations

import asyncio
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Optional

from ..infra import logging as logx
from ..infra.metrics import Metrics
from ..obs.tracer import Tracer
from .pager import CacheExhausted, PageAllocator

# on_tokens(new_tokens, n_generated, done) — the streaming sink
TokenSink = Callable[[list[int], int, bool], Awaitable[None]]

DEFAULT_MAX_SESSIONS = 8
DEFAULT_MAX_NEW_TOKENS = 64


class SessionCancelled(Exception):
    """Session evicted by ``sys.job.cancel`` (queued, prefilling or
    decoding); the worker publishes an ordinary CANCELLED result."""


@dataclass
class GenRequest:
    """A decomposed ``llm.generate`` payload."""

    prompt: list[int]
    max_new_tokens: int = 16
    session_key: str = ""
    eos_token: Optional[int] = None
    stream: bool = True


@dataclass
class ServingStats:
    admitted: int = 0
    retired: int = 0
    cancelled: int = 0
    failed: int = 0
    steps: int = 0
    decoded_tokens: int = 0
    occupancy_sum: int = 0
    max_occupancy: int = 0
    admission_waits: int = 0  # admissions delayed by cache exhaustion
    # per-step wall time (seconds), capped ring for p50 inter-token latency
    step_seconds: deque = field(default_factory=lambda: deque(maxlen=4096))

    @property
    def mean_occupancy(self) -> float:
        return self.occupancy_sum / self.steps if self.steps else 0.0


@dataclass
class _Session:
    job_id: str
    req: GenRequest
    future: asyncio.Future
    on_tokens: Optional[TokenSink] = None
    trace_id: str = ""
    parent_span_id: str = ""
    pages: list[int] = field(default_factory=list)
    pos: int = 0  # sequence positions cached so far
    last_token: int = 0
    out_tokens: list[int] = field(default_factory=list)
    cancelled: bool = False
    enqueued_at: float = field(default_factory=time.monotonic)

    @property
    def done(self) -> bool:
        if len(self.out_tokens) >= self.req.max_new_tokens:
            return True
        eos = self.req.eos_token
        return eos is not None and bool(self.out_tokens) and self.out_tokens[-1] == eos


class ServingEngine:
    """One per worker; owns the allocator, the session table and the loop."""

    def __init__(
        self,
        backend: Any,
        *,
        run_blocking: Callable[..., Awaitable[Any]],
        max_sessions: int = DEFAULT_MAX_SESSIONS,
        max_new_tokens_cap: int = DEFAULT_MAX_NEW_TOKENS,
        max_concurrent_prefills: int = 1,
        metrics: Optional[Metrics] = None,
        tracer: Optional[Tracer] = None,
        capacity: Optional[Any] = None,
    ) -> None:
        self.backend = backend
        self.run_blocking = run_blocking  # worker.run_in_executor
        # capacity observatory (obs/capacity.py): each ragged decode step
        # reports delivered tokens at its padded-batch bucket
        self.capacity = capacity
        self.max_sessions = max(1, max_sessions)
        self.max_new_tokens_cap = max(1, max_new_tokens_cap)
        self.max_concurrent_prefills = max(1, max_concurrent_prefills)
        self.metrics = metrics
        self.tracer = tracer
        # the backend's static page-table width caps a session's lifetime
        # footprint; anything longer must be rejected at submit (the arena
        # may hold far more pages than one table row can address)
        self.max_context = int(getattr(backend, "max_context", 0) or 0)
        self.allocator = PageAllocator(backend.num_pages, backend.page_size)
        self.stats = ServingStats()
        self._pending: deque[_Session] = deque()
        self._prefilling: dict[str, _Session] = {}
        self._active: dict[str, _Session] = {}
        self._prefill_tasks: set[asyncio.Task] = set()
        self._wake = asyncio.Event()
        self._loop_task: Optional[asyncio.Task] = None
        self._closed = False

    # ------------------------------------------------------------------
    def parts(self, payload: Any) -> Optional[GenRequest]:
        """Decompose a job payload; None = not a serving job (the worker
        keeps its ordinary handler path)."""
        from ..protocol.types import SERVING_OPS

        if not isinstance(payload, dict) or payload.get("op") not in SERVING_OPS:
            return None
        tokens = payload.get("tokens")
        if not (
            isinstance(tokens, list) and tokens
            and all(isinstance(t, int) for t in tokens)
        ):
            return None
        try:
            max_new = int(payload.get("max_new_tokens", 16) or 16)
        except (TypeError, ValueError):
            # malformed payload is not a session: fall through to the
            # handler path, which raises the op's own descriptive error
            return None
        eos = payload.get("eos_token")
        return GenRequest(
            prompt=tokens,
            max_new_tokens=max(1, min(max_new, self.max_new_tokens_cap)),
            session_key=str(payload.get("session_id", "") or ""),
            eos_token=int(eos) if isinstance(eos, int) else None,
            stream=bool(payload.get("stream", True)),
        )

    # ------------------------------------------------------------------
    @property
    def session_count(self) -> int:
        return len(self._pending) + len(self._prefilling) + len(self._active)

    def queue_depth(self) -> int:
        return len(self._pending)

    def active_sessions(self) -> int:
        return len(self._active)

    # ------------------------------------------------------------------
    async def submit(
        self,
        gen: GenRequest,
        *,
        job_id: str,
        trace_id: str = "",
        parent_span_id: str = "",
        on_tokens: Optional[TokenSink] = None,
    ) -> dict[str, Any]:
        """Queue a session and await its completed generation."""
        if self._closed:
            raise RuntimeError("serving engine is stopped")
        total = len(gen.prompt) + gen.max_new_tokens
        if self.max_context and total > self.max_context:
            # beyond the backend's static page-table width: prefill would
            # silently truncate and the first decode step would poison the
            # whole batch — fail this job alone, before it becomes a session
            raise ValueError(
                f"request spans {total} tokens (prompt {len(gen.prompt)} + "
                f"{gen.max_new_tokens} new); backend max_context is "
                f"{self.max_context}"
            )
        footprint = self.allocator.pages_for(total)
        if footprint > self.allocator.capacity:
            raise ValueError(
                f"request needs {footprint} KV pages; cache holds "
                f"{self.allocator.capacity}"
            )
        sess = _Session(
            job_id=job_id, req=gen,
            future=asyncio.get_running_loop().create_future(),
            on_tokens=on_tokens if gen.stream else None,
            trace_id=trace_id, parent_span_id=parent_span_id,
        )
        self._pending.append(sess)
        self._ensure_loop()
        self._wake.set()
        tokens = await sess.future
        return {
            "tokens": tokens,
            "n_tokens": len(tokens),
            "session_key": gen.session_key,
            "finish_reason": (
                "eos" if gen.eos_token is not None and tokens
                and tokens[-1] == gen.eos_token else "length"
            ),
        }

    # ------------------------------------------------------------------
    def cancel(self, job_id: str) -> bool:
        """Evict a session wherever it is: admission queue (pages never
        allocated), prefilling, or the decode set (pages freed by the loop
        on the next tick).  Returns False when the job is not a live
        session."""
        for i, sess in enumerate(self._pending):
            if sess.job_id == job_id:
                del self._pending[i]
                # _retire keeps stats and the retirement metric in step
                # (pages were never allocated; free() is a no-op here)
                self._retire(sess, error=SessionCancelled(job_id))
                return True
        sess = self._prefilling.get(job_id) or self._active.get(job_id)
        if sess is not None:
            sess.cancelled = True  # loop/prefill task retires + frees pages
            self._wake.set()
            return True
        return False

    # ------------------------------------------------------------------
    def _ensure_loop(self) -> None:
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.ensure_future(self._decode_loop())
            self._loop_task.add_done_callback(self._on_loop_done)

    def _on_loop_done(self, task: asyncio.Task) -> None:
        """Decode-step failures are handled inside the loop; anything that
        still escapes must not strand live sessions on never-resolving
        futures — fail them loudly (each publishes an ordinary FAILED
        result) and let the next submit restart the loop."""
        if task.cancelled() or self._closed:
            return
        exc = task.exception()
        if exc is None:
            return
        logx.warn("decode loop crashed; failing live sessions", err=str(exc))
        for sess in [*self._pending, *self._prefilling.values(),
                     *self._active.values()]:
            self.stats.failed += 1
            self._retire(sess, error=exc)
        self._pending.clear()
        self._prefilling.clear()

    def _gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.serving_sessions.set(float(len(self._active)))
            self.metrics.serving_kv_pages_in_use.set(float(self.allocator.used_pages))

    def _admit(self) -> None:
        """Move pending sessions into prefill while pages and session slots
        allow; FIFO so exhaustion delays but never reorders admission."""
        while (
            self._pending
            and len(self._prefilling) < self.max_concurrent_prefills
            and len(self._active) + len(self._prefilling) < self.max_sessions
        ):
            sess = self._pending[0]
            if sess.cancelled:
                self._pending.popleft()
                self._retire(sess, error=SessionCancelled(sess.job_id))
                continue
            footprint = self.allocator.pages_for(
                len(sess.req.prompt) + sess.req.max_new_tokens
            )
            try:
                pages = self.allocator.alloc(sess.job_id, footprint)
            except CacheExhausted:
                self.stats.admission_waits += 1
                break  # head-of-line waits for a retirement to free pages
            self._pending.popleft()
            sess.pages = pages
            self._prefilling[sess.job_id] = sess
            self.stats.admitted += 1
            if self.metrics is not None:
                self.metrics.serving_admitted.inc()
            t = asyncio.ensure_future(self._prefill(sess))
            self._prefill_tasks.add(t)
            t.add_done_callback(self._prefill_tasks.discard)

    async def _prefill(self, sess: _Session) -> None:
        try:
            first = await self.run_blocking(
                self.backend.prefill, sess.req.prompt, sess.pages
            )
        except Exception as e:  # noqa: BLE001 - surfaces as the job's failure
            self._prefilling.pop(sess.job_id, None)
            self.stats.failed += 1
            self._retire(sess, error=e)
            self._wake.set()
            return
        self._prefilling.pop(sess.job_id, None)
        if sess.cancelled:
            self._retire(sess, error=SessionCancelled(sess.job_id))
            self._wake.set()
            return
        sess.pos = min(len(sess.req.prompt), self.backend.max_context)
        sess.last_token = first
        sess.out_tokens.append(first)
        await self._emit(sess, [first])
        if sess.done:
            self._retire(sess)
        else:
            self._active[sess.job_id] = sess
        self._gauge()
        self._wake.set()

    async def _emit(self, sess: _Session, new_tokens: list[int]) -> None:
        if sess.on_tokens is None:
            return
        try:
            await sess.on_tokens(new_tokens, len(sess.out_tokens), sess.done)
        except Exception as e:  # noqa: BLE001 - streaming is best-effort
            logx.warn("token stream sink failed", job_id=sess.job_id, err=str(e))

    def _retire(self, sess: _Session, error: Optional[BaseException] = None) -> None:
        self.allocator.free(sess.job_id)
        self._active.pop(sess.job_id, None)
        if error is None:
            self.stats.retired += 1
            if self.metrics is not None:
                self.metrics.serving_retired.inc(reason="finished")
            if not sess.future.done():
                sess.future.set_result(list(sess.out_tokens))
        else:
            if isinstance(error, SessionCancelled):
                self.stats.cancelled += 1
            if self.metrics is not None:
                self.metrics.serving_retired.inc(
                    reason="cancelled" if isinstance(error, SessionCancelled)
                    else "failed"
                )
            if not sess.future.done():
                sess.future.set_exception(error)

    # ------------------------------------------------------------------
    async def _decode_loop(self) -> None:
        """The continuous-batching loop: one ragged XLA call per step over
        every active session; admission and retirement happen between
        steps, never inside one."""
        while not self._closed:
            self._admit()
            # evict cancellations before assembling the batch
            for sess in [s for s in self._active.values() if s.cancelled]:
                self._retire(sess, error=SessionCancelled(sess.job_id))
            batch = list(self._active.values())
            if not batch:
                self._gauge()
                if not self._pending and not self._prefilling:
                    if self._closed:
                        return
                    self._wake.clear()
                    # re-check after clear: a submit may have landed between
                    # the emptiness check and the clear
                    if not (self._pending or self._prefilling or self._active):
                        await self._wake.wait()
                else:
                    await asyncio.sleep(0.001)  # prefill in flight: poll soon
                continue
            t0 = time.monotonic()
            entries = [(s.last_token, s.pos, s.pages) for s in batch]
            step_span = None
            if self.tracer is not None and batch[0].trace_id:
                oldest = min(batch, key=lambda s: s.enqueued_at)
                step_span = self.tracer.begin(
                    "decode-step", trace_id=oldest.trace_id,
                    parent_span_id=oldest.parent_span_id,
                    attrs={"occupancy": str(len(batch))},
                )
            try:
                next_tokens = await self.run_blocking(self.backend.decode, entries)
            except Exception as e:  # noqa: BLE001 - whole-step failure
                # a poisoned step fails every rider (pages freed); the next
                # tick starts clean — mirrors the batcher's isolation intent
                # without re-running autoregressive state per item
                logx.warn("decode step failed", occupancy=len(batch), err=str(e))
                if step_span is not None and self.tracer is not None:
                    step_span.attrs["error"] = type(e).__name__
                    await self.tracer.finish(step_span, status="ERROR")
                for sess in batch:
                    self.stats.failed += 1
                    self._retire(sess, error=e)
                continue
            dt = time.monotonic() - t0
            self.stats.steps += 1
            self.stats.decoded_tokens += len(batch)
            self.stats.occupancy_sum += len(batch)
            self.stats.max_occupancy = max(self.stats.max_occupancy, len(batch))
            self.stats.step_seconds.append(dt)
            if self.capacity is not None:
                # one step decodes one token per rider; bucket = the pow2
                # batch bucket the XLA program actually ran at
                self.capacity.observe(
                    "llm.generate", device_s=dt,
                    bucket=str(1 << max(0, len(batch) - 1).bit_length()),
                    items=len(batch), tokens=len(batch),
                )
            retired_this_step = 0
            emits = []
            for sess, tok in zip(batch, next_tokens):
                sess.pos += 1
                sess.last_token = int(tok)
                sess.out_tokens.append(int(tok))
                emits.append(self._emit(sess, [int(tok)]))
                if sess.done or sess.cancelled:
                    retired_this_step += 1
                    self._retire(
                        sess,
                        error=SessionCancelled(sess.job_id) if sess.cancelled else None,
                    )
            if emits:
                await asyncio.gather(*emits)
            if self.metrics is not None:
                self.metrics.serving_batch_occupancy.observe(float(len(batch)))
                self.metrics.serving_inter_token.observe(dt)
            if step_span is not None and self.tracer is not None:
                step_span.attrs["retired"] = str(retired_this_step)
                step_span.attrs["step_ms"] = f"{dt * 1000:.2f}"
                await self.tracer.finish(step_span)
            self._gauge()
            # yield to the loop so intake/cancel/heartbeat tasks run between
            # steps even under a saturated decode set
            await asyncio.sleep(0)

    # ------------------------------------------------------------------
    async def stop(self) -> None:
        """Evict every session (CANCELLED) and stop the loop — worker
        shutdown; generations are conversation turns, not batch jobs, so
        draining them could take unboundedly long."""
        self._closed = True
        self._wake.set()
        for sess in list(self._pending):
            if not sess.future.done():
                sess.future.set_exception(SessionCancelled(sess.job_id))
        self._pending.clear()
        for sess in [*self._prefilling.values(), *self._active.values()]:
            sess.cancelled = True
            self._retire(sess, error=SessionCancelled(sess.job_id))
        self._prefilling.clear()
        if self._loop_task is not None:
            self._loop_task.cancel()
            try:
                await self._loop_task
            except asyncio.CancelledError:
                pass
            except Exception as e:  # noqa: BLE001 - logged, never swallowed
                logx.warn("decode loop crashed during shutdown", err=str(e))
            self._loop_task = None
        for t in list(self._prefill_tasks):
            t.cancel()
