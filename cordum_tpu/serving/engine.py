"""The continuous-batching loop (the serving subsystem's scheduler).

Lifecycle of an ``llm.generate`` session (docs/SERVING.md):

  * :meth:`ServingEngine.submit` parks the session in the **admission
    queue**; admission allocates its full worst-case page footprint
    (prompt + max_new_tokens) so an admitted session can never die
    mid-decode from cache pressure — exhaustion just delays admission;
  * admitted sessions join the step loop immediately: their prompts
    **prefill in chunks inside the mixed step**, riding the token-budget
    headroom left after the decode rows (the FlexNPU co-location policy,
    PAPERS.md, realized the Ragged Paged Attention way — prefill and
    decode share ONE device call instead of racing for the device lock
    from separate executor threads);
  * every step assembles one ragged batch — one decode row per prefilled
    session plus up to ``max_concurrent_prefills`` prompt chunks within
    the backend's flat token budget — runs ONE XLA call (the single
    compiled program), scatters tokens back, admits joiners and retires
    finishers; sessions join/leave mid-stream without perturbing each
    other's rows and without recompiling anything;
  * retirement (finish / cancel / failure) frees the session's pages back
    to the allocator and resolves the submit waiter.

Token streaming rides the session's ``on_tokens`` callback (the worker
publishes ``JobProgress`` packets with ``status_hint="stream"``); the
terminal ``JobResult`` carries the full token list for non-streaming
consumers.
"""
from __future__ import annotations

import asyncio
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Optional

from ..infra import logging as logx
from ..infra.metrics import Metrics
from ..obs.tracer import Tracer
from .backend import StepEntry
from .pager import CacheExhausted, PageAllocator
from .prefixcache import PrefixCache, PrefixNode
from .tiering import SessionTiering

# on_tokens(new_tokens, n_generated, done) — the streaming sink
TokenSink = Callable[[list[int], int, bool], Awaitable[None]]

DEFAULT_MAX_SESSIONS = 8
DEFAULT_MAX_NEW_TOKENS = 64
# prefill chunks co-scheduled into one mixed step: more rows admit faster,
# but each chunk spends flat-buffer slots the decode rows also want
DEFAULT_MAX_CONCURRENT_PREFILLS = 2
# SLO classes whose prefill chunks take the step budget first
# (docs/ADMISSION.md §Serving)
INTERACTIVE_CLASSES = frozenset({"INTERACTIVE", "CRITICAL"})
# speculative decoding (docs/SERVING.md §Speculative decoding): default
# draft length cap, the per-session acceptance EWMA that throttles the
# next step's draft length, and the engine-level EWMA the capacity block
# publishes as spec_accept_rate
DEFAULT_DRAFT_K = 4
SPEC_EWMA_ALPHA = 0.4
SPEC_FLEET_ALPHA = 0.2


class SessionCancelled(Exception):
    """Session evicted by ``sys.job.cancel`` (queued, prefilling or
    decoding); the worker publishes an ordinary CANCELLED result."""


class SessionMigrated(Exception):
    """Session live-migrated to a peer worker (docs/SERVING.md §Migration,
    drain, and failover): the target owns the token stream and the terminal
    result now — the local waiter publishes NOTHING."""


class SessionRequeued(Exception):
    """Session handed back to the scheduler for failover (drain with no
    migration target, crashed decode loop): the worker publishes a
    non-terminal ``SESSION_REQUEUE`` result and the scheduler re-dispatches
    with the already-streamed tokens as a forced-decode prefix — bounded by
    the attempts counter, FAILED only past the cap."""


class SessionHibernated(Exception):
    """Session frozen whole and tiered into the host-RAM cold arena
    (docs/SERVING.md §Prefix cache and tiering): a later
    ``restore_hibernated`` on this worker owns the token stream and the
    terminal result — the local waiter publishes NOTHING (the live-
    migration contract, pointed at ourselves)."""


@dataclass
class GenRequest:
    """A decomposed ``llm.generate`` payload."""

    prompt: list[int]
    max_new_tokens: int = 16
    session_key: str = ""
    eos_token: Optional[int] = None
    stream: bool = True
    # SLO class (JobRequest.priority, stamped by the worker intake): batch
    # prefill chunks yield step-budget headroom to interactive ones
    # (docs/ADMISSION.md §Serving)
    job_class: str = "BATCH"
    # failover resume (LABEL_RESUME_TOKENS): tokens a previous worker
    # already generated and streamed for this job.  They prefill as a
    # forced-decode prefix (prompt + resume ride the chunked prefill path),
    # count toward max_new_tokens, and replay at offset 0 so stream
    # consumers deduping by offset see an exactly-once sequence.
    resume_tokens: list[int] = field(default_factory=list)


@dataclass
class ServingStats:
    admitted: int = 0
    retired: int = 0
    cancelled: int = 0
    failed: int = 0
    steps: int = 0
    decoded_tokens: int = 0  # generated tokens (decode rows + first tokens)
    prefill_tokens: int = 0  # prompt tokens fed through mixed-step chunks
    prefill_chunks: int = 0
    migrated_out: int = 0  # sessions live-migrated to a peer worker
    migrated_in: int = 0  # sessions adopted from a peer worker
    requeued: int = 0  # sessions handed back to the scheduler for failover
    prefix_hits: int = 0  # admissions that mapped cached shared-prefix pages
    prefix_misses: int = 0
    prefix_hit_tokens: int = 0  # prompt tokens whose prefill was skipped
    cow_copies: int = 0  # copy-on-write page duplications
    drafted_tokens: int = 0  # speculative tokens proposed into draft rows
    accepted_tokens: int = 0  # drafts verified and kept (bonus excluded)
    rolled_back_tokens: int = 0  # drafts rejected; write positions rolled back
    spec_steps: int = 0  # steps that carried at least one draft row
    hibernated_out: int = 0  # live sessions tiered whole to the cold arena
    restored_in: int = 0  # live sessions restored from the cold arena
    occupancy_sum: int = 0
    max_occupancy: int = 0
    admission_waits: int = 0  # admissions delayed by cache exhaustion
    # per-step wall time (seconds), capped ring for inter-token p50/p99
    step_seconds: deque = field(default_factory=lambda: deque(maxlen=4096))
    # submit → first sampled token (seconds), capped ring for TTFT p50
    # (resume/migrated-in sessions excluded: their first token belongs to
    # a previous worker's clock)
    ttft_seconds: deque = field(default_factory=lambda: deque(maxlen=4096))

    @property
    def mean_occupancy(self) -> float:
        return self.occupancy_sum / self.steps if self.steps else 0.0


@dataclass
class _Session:
    job_id: str
    req: GenRequest
    future: asyncio.Future
    on_tokens: Optional[TokenSink] = None
    trace_id: str = ""
    parent_span_id: str = ""
    pages: list[int] = field(default_factory=list)
    pos: int = 0  # sequence positions cached so far
    prefill_pos: int = 0  # prompt tokens fed so far (== pos until prefilled)
    last_token: int = 0
    out_tokens: list[int] = field(default_factory=list)
    cancelled: bool = False
    # frozen = mid-migration: the step loop must not advance this session
    # (decode pauses only for the final freeze-and-delta chunk)
    frozen: bool = False
    # post-prefill hand-off (docs/SERVING.md §Disaggregation): the
    # on_prefill_done hook fires at most once per session
    handoff_signaled: bool = False
    # governor immunity: a migrated-in session may not be rebalanced again
    # before this monotonic stamp (the anti-ping-pong cooldown)
    immune_until: float = 0.0
    # speculative decoding: the session's acceptance EWMA (throttles the
    # next step's draft length; optimistic start so drafts flow at once)
    # and the tokens the drafter planned for the upcoming step
    accept_ewma: float = 1.0
    draft_plan: list[int] = field(default_factory=list)
    enqueued_at: float = field(default_factory=time.monotonic)

    @property
    def prefill_seq(self) -> list[int]:
        """What prefill must feed: the prompt plus the forced-decode resume
        prefix MINUS its last token (failover replay, docs/SERVING.md).
        The final resume token stays ``last_token``: the first post-resume
        step is then an ordinary decode row feeding it at the next
        position — the exact state a live-migrated session resumes from,
        so the continuation token is sampled with decode semantics, not a
        prefill-completion sample."""
        seq = self.req.prompt + self.req.resume_tokens
        return seq[:-1] if self.req.resume_tokens else seq

    @property
    def prefilled(self) -> bool:
        return self.prefill_pos >= len(self.prefill_seq)

    @property
    def done(self) -> bool:
        if len(self.out_tokens) >= self.req.max_new_tokens:
            return True
        eos = self.req.eos_token
        return eos is not None and bool(self.out_tokens) and self.out_tokens[-1] == eos


class ServingEngine:
    """One per worker; owns the allocator, the session table and the loop."""

    def __init__(
        self,
        backend: Any,
        *,
        run_blocking: Callable[..., Awaitable[Any]],
        max_sessions: int = DEFAULT_MAX_SESSIONS,
        max_new_tokens_cap: int = DEFAULT_MAX_NEW_TOKENS,
        max_concurrent_prefills: int = DEFAULT_MAX_CONCURRENT_PREFILLS,
        metrics: Optional[Metrics] = None,
        tracer: Optional[Tracer] = None,
        capacity: Optional[Any] = None,
        handoff_threshold_tokens: int = 0,
        migrate_in_cooldown_s: float = 30.0,
        prefix_cache: bool = True,
        hibernate_after_s: float = 0.0,
        speculative: bool = False,
        draft_k: int = DEFAULT_DRAFT_K,
        drafter: Optional[Callable[[list[int], int], list[int]]] = None,
    ) -> None:
        self.backend = backend
        self.run_blocking = run_blocking  # worker.run_in_executor
        # post-prefill hand-off (docs/SERVING.md §Disaggregation): the owner
        # (the worker) sets this to a callable(job_id); the loop invokes it
        # once per session when its prompt finishes prefilling — or earlier,
        # once prefill crosses handoff_threshold_tokens (>0) — so a
        # prefill-roled worker can ship the session to a decode worker
        # while the KV pages are hot
        self.on_prefill_done: Optional[Callable[[str], None]] = None
        self.handoff_threshold_tokens = max(0, handoff_threshold_tokens)
        # governor anti-ping-pong: sessions adopted via install_session are
        # immune to pick_rebalance_sessions for this window (drain ignores
        # it — a draining worker must move everything)
        self.migrate_in_cooldown_s = max(0.0, migrate_in_cooldown_s)
        # capacity observatory (obs/capacity.py): each ragged step reports
        # delivered tokens at the static flat-buffer bucket, with warmup
        # compiles flagged so steady-state rows exclude them
        self.capacity = capacity
        self.max_sessions = max(1, max_sessions)
        self.max_new_tokens_cap = max(1, max_new_tokens_cap)
        self.max_concurrent_prefills = max(1, max_concurrent_prefills)
        self.metrics = metrics
        self.tracer = tracer
        # the backend's static page-table width caps a session's lifetime
        # footprint; anything longer must be rejected at submit (the arena
        # may hold far more pages than one table row can address)
        self.max_context = int(getattr(backend, "max_context", 0) or 0)
        # the flat token buffer bounds decode rows + prefill chunk tokens
        # per step; every admitted session must at least fit a decode row
        self.step_tokens = int(
            getattr(backend, "max_batch_tokens", 0) or 2 * self.max_sessions
        )
        self.max_sessions = min(
            self.max_sessions,
            int(getattr(backend, "max_seqs", 0) or self.max_sessions),
            self.step_tokens,
        )
        self.allocator = PageAllocator(backend.num_pages, backend.page_size)
        # prefix cache + session tiering (docs/SERVING.md §Prefix cache and
        # tiering): the radix index over cached full-page prefixes, and the
        # hibernate/restore machinery that tiers idle resident state to the
        # host-RAM cold arena.  hibernate_after_s <= 0 disables the sweep
        # (the cache still shares; pressure is handled by LRU eviction).
        # Sharing also requires the backend's page-copy primitive (CoW):
        # without one a shared page could never be duplicated on divergent
        # write, so the cache is disabled outright rather than half-armed —
        # arena-less test fakes recompute K/V from the tokens actually fed,
        # so a silent prefill skip would change their outputs.
        can_share = prefix_cache and callable(getattr(backend, "copy_page", None))
        self.prefix: Optional[PrefixCache] = (
            PrefixCache(self.allocator, metrics=metrics)
            if can_share else None
        )
        self.tiering: Optional[SessionTiering] = (
            SessionTiering(
                self.prefix,
                hibernate_after_s=hibernate_after_s,
                export_page=self._export_prefix_page,
                metrics=metrics,
            )
            if self.prefix is not None else None
        )
        # speculative decoding (docs/SERVING.md §Speculative decoding):
        # the self-speculative drafter proposes k tokens per decoding
        # session per step; verification rides the same ragged program as
        # prefill-shaped draft rows with per-position sampling.  Gated on
        # the backend's per-position prediction support — fakes and legacy
        # backends without ``supports_draft`` keep the exact legacy step
        # shape (byte-for-byte: no draft rows are ever assembled).
        self.speculative = bool(speculative) and bool(
            getattr(backend, "supports_draft", False)
        )
        self.draft_k = max(1, int(draft_k or DEFAULT_DRAFT_K))
        self._drafter = drafter or self._ngram_draft
        # engine-level acceptance EWMA — the capacity block publishes it
        # as spec_accept_rate so the placer can route speculable traffic
        self.spec_accept_ewma = 0.0
        self._tiering_task: Optional[asyncio.Task] = None
        self.stats = ServingStats()
        self._pending: deque[_Session] = deque()
        self._active: dict[str, _Session] = {}
        self._wake = asyncio.Event()
        self._loop_task: Optional[asyncio.Task] = None
        self._closed = False
        # job ids riding the step currently on the device: a migration
        # freeze is complete only once the in-flight step (which may still
        # produce one token for the session) has scattered its results
        self._in_step: frozenset[str] = frozenset()

    # ------------------------------------------------------------------
    def parts(self, payload: Any) -> Optional[GenRequest]:
        """Decompose a job payload; None = not a serving job (the worker
        keeps its ordinary handler path)."""
        from ..protocol.types import SERVING_OPS

        if not isinstance(payload, dict) or payload.get("op") not in SERVING_OPS:
            return None
        tokens = payload.get("tokens")
        if not (
            isinstance(tokens, list) and tokens
            and all(isinstance(t, int) for t in tokens)
        ):
            return None
        try:
            max_new = int(payload.get("max_new_tokens", 16) or 16)
        except (TypeError, ValueError):
            # malformed payload is not a session: fall through to the
            # handler path, which raises the op's own descriptive error
            return None
        eos = payload.get("eos_token")
        return GenRequest(
            prompt=tokens,
            max_new_tokens=max(1, min(max_new, self.max_new_tokens_cap)),
            session_key=str(payload.get("session_id", "") or ""),
            eos_token=int(eos) if isinstance(eos, int) else None,
            stream=bool(payload.get("stream", True)),
        )

    # ------------------------------------------------------------------
    @property
    def session_count(self) -> int:
        return len(self._pending) + len(self._active)

    def queue_depth(self) -> int:
        return len(self._pending)

    def active_sessions(self) -> int:
        return len(self._active)

    # ------------------------------------------------------------------
    async def submit(
        self,
        gen: GenRequest,
        *,
        job_id: str,
        trace_id: str = "",
        parent_span_id: str = "",
        on_tokens: Optional[TokenSink] = None,
    ) -> dict[str, Any]:
        """Queue a session and await its completed generation."""
        if self._closed:
            raise RuntimeError("serving engine is stopped")
        total = len(gen.prompt) + gen.max_new_tokens
        if self.max_context and total > self.max_context:
            # beyond the backend's static page-table width: prefill would
            # silently truncate and the session would poison its step —
            # fail this job alone, before it becomes a session
            raise ValueError(
                f"request spans {total} tokens (prompt {len(gen.prompt)} + "
                f"{gen.max_new_tokens} new); backend max_context is "
                f"{self.max_context}"
            )
        footprint = self.allocator.pages_for(total)
        if footprint > self.allocator.capacity:
            raise ValueError(
                f"request needs {footprint} KV pages; cache holds "
                f"{self.allocator.capacity}"
            )
        sess = _Session(
            job_id=job_id, req=gen,
            future=asyncio.get_running_loop().create_future(),
            on_tokens=on_tokens if gen.stream else None,
            trace_id=trace_id, parent_span_id=parent_span_id,
        )
        if gen.resume_tokens:
            # forced-decode resume: the prefix counts as already-generated
            # output; prefill feeds prompt + prefix and decoding continues
            # from the prefix's last token
            sess.out_tokens = list(gen.resume_tokens)
            sess.last_token = gen.resume_tokens[-1]
        self._pending.append(sess)
        self._ensure_loop()
        self._wake.set()
        tokens = await sess.future
        return self.result_doc(gen, tokens)

    @staticmethod
    def result_doc(gen: GenRequest, tokens: list[int]) -> dict[str, Any]:
        """The terminal result payload for a finished generation — shared by
        :meth:`submit` and the migrated-session adoption path."""
        return {
            "tokens": tokens,
            "n_tokens": len(tokens),
            "session_key": gen.session_key,
            "finish_reason": (
                "eos" if gen.eos_token is not None and tokens
                and tokens[-1] == gen.eos_token else "length"
            ),
        }

    # ------------------------------------------------------------------
    def cancel(self, job_id: str) -> bool:
        """Evict a session wherever it is: admission queue (pages never
        allocated) or the step loop — prefilling or decoding, the pages are
        freed by the loop on its next tick.  Returns False when the job is
        not a live session."""
        for i, sess in enumerate(self._pending):
            if sess.job_id == job_id:
                del self._pending[i]
                # _retire keeps stats and the retirement metric in step
                # (pages were never allocated; free() is a no-op here)
                self._retire(sess, error=SessionCancelled(job_id))
                return True
        sess = self._active.get(job_id)
        if sess is not None:
            sess.cancelled = True  # the loop retires + frees pages
            self._wake.set()
            return True
        return False

    # ------------------------------------------------------------------
    def _ensure_loop(self) -> None:
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.ensure_future(self._decode_loop())
            self._loop_task.add_done_callback(self._on_loop_done)
        if (
            self.tiering is not None and self.tiering.hibernate_after_s > 0
            and (self._tiering_task is None or self._tiering_task.done())
        ):
            self._tiering_task = asyncio.ensure_future(self._tiering_loop())

    async def _tiering_loop(self) -> None:
        """Periodic hibernate sweep — its own task because idle resident
        conversations are exactly the ones generating no steps: the decode
        loop is parked on its wake event while they cool down."""
        assert self.tiering is not None
        interval = max(0.05, min(1.0, self.tiering.hibernate_after_s / 4))
        while not self._closed:
            await asyncio.sleep(interval)
            if self._closed:
                return
            try:
                await self.tiering.sweep()
            except Exception as e:  # noqa: BLE001 - sweep is best-effort
                logx.warn("hibernate sweep failed", err=str(e))

    async def _export_prefix_page(self, page: int) -> Optional[dict]:
        """One full arena page as a PR 12 migration record — the tiering
        sweep's export half (None = the backend has no arena to export)."""
        fn = getattr(self.backend, "export_kv", None)
        if fn is None:
            return None
        recs = await self.run_blocking(fn, [page], 0, self.allocator.page_size)
        return recs[0] if recs else None

    def _on_loop_done(self, task: asyncio.Task) -> None:
        """Step failures are handled inside the loop; anything that still
        escapes must not strand live sessions on never-resolving futures —
        hand them back to the scheduler for failover (each publishes a
        non-terminal SESSION_REQUEUE result; the attempts counter bounds the
        retries, so a deterministic crasher still ends FAILED past the cap)
        and let the next submit restart the loop."""
        if task.cancelled() or self._closed:
            return
        exc = task.exception()
        if exc is None:
            return
        logx.warn("decode loop crashed; requeueing live sessions", err=str(exc))
        for sess in [*self._pending, *self._active.values()]:
            self._retire(sess, error=SessionRequeued(
                f"decode loop crashed: {exc}"
            ))
        self._pending.clear()

    def _gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.serving_sessions.set(float(len(self._active)))
            self.metrics.serving_kv_pages_in_use.set(float(self.allocator.used_pages))

    async def _admit(self) -> None:
        """Move pending sessions straight into the step loop while pages
        and session slots allow; FIFO so exhaustion delays but never
        reorders admission.  An admitted session needs no separate prefill
        phase — its prompt chunks ride the next steps' token budget.

        Prefix-cache hook (docs/SERVING.md §Prefix cache and tiering): the
        longest cached page-aligned prefix of the prompt maps its physical
        pages straight into the new session's table — prefill starts at
        the divergence point.  Cold nodes on the hit path restore from the
        host-RAM arena first (the hibernate restore), and exhaustion
        LRU-evicts zero-refcount cached prefixes before the head-of-line
        admission gives up and waits."""
        while self._pending and len(self._active) < self.max_sessions:
            sess = self._pending[0]
            if sess.cancelled:
                self._pending.popleft()
                self._retire(sess, error=SessionCancelled(sess.job_id))
                continue
            footprint = self.allocator.pages_for(
                len(sess.req.prompt) + sess.req.max_new_tokens
            )
            shared: list[int] = []
            hit_tokens = 0
            if self.prefix is not None and not sess.req.resume_tokens:
                nodes = await self._restore_nodes(
                    self.prefix.match(sess.prefill_seq)
                )
                if self._closed:
                    break  # stop() raced the restore await
                if sess.cancelled:
                    continue  # loop head pops + retires it
                # keep only the unbroken warm head of the path (a restore
                # may have truncated it, or an eviction raced the await)
                for node in nodes:
                    if node.dropped or not node.warm:
                        break
                    shared.append(node.page)
                # at least one token must be fed through prefill so the
                # completing chunk has a position to sample from; a hit
                # ending exactly at the prompt end re-feeds the final
                # token into shared territory (the CoW guard copies that
                # page before the step writes it)
                hit_tokens = min(
                    len(shared) * self.allocator.page_size,
                    len(sess.prefill_seq) - 1,
                )
                if hit_tokens < 1:
                    shared, hit_tokens = [], 0
            try:
                pages = self._alloc_with_evict(
                    sess.job_id, footprint - len(shared), shared
                )
            except CacheExhausted:
                self.stats.admission_waits += 1
                break  # head-of-line waits for a retirement to free pages
            self._pending.popleft()
            sess.pages = pages
            if hit_tokens > 0:
                # the skipped positions' K/V already sits in the shared
                # pages (identical token prefix ⇒ identical K/V — the
                # radix path IS the key); chunked prefill picks up at the
                # divergence point via prefill_pos
                sess.prefill_pos = hit_tokens
                sess.pos = hit_tokens
                self.stats.prefix_hits += 1
                self.stats.prefix_hit_tokens += hit_tokens
                self.prefix.stats.hits += 1
                self.prefix.stats.hit_tokens += hit_tokens
                if self.metrics is not None:
                    self.metrics.serving_prefix.inc(outcome="hit")
                    self.metrics.serving_prefix_tokens.inc(float(hit_tokens))
            elif self.prefix is not None and not sess.req.resume_tokens:
                self.stats.prefix_misses += 1
                self.prefix.stats.misses += 1
                if self.metrics is not None:
                    self.metrics.serving_prefix.inc(outcome="miss")
            if self.tiering is not None and sess.req.session_key:
                self.tiering.touch(sess.req.session_key)
            self._active[sess.job_id] = sess
            self.stats.admitted += 1
            if self.metrics is not None:
                self.metrics.serving_admitted.inc()
            if sess.out_tokens and sess.on_tokens is not None:
                # failover resume: replay the already-streamed prefix at
                # offset 0 — consumers dedupe by offset, so a client that
                # saw the original stream skips it and one that missed
                # packets in the crash window backfills
                asyncio.ensure_future(self._emit(sess, list(sess.out_tokens)))
            if sess.done:
                # the crash landed after the final token: nothing left to
                # decode — finish straight from the resume prefix
                self._retire(sess)

    def _alloc_with_evict(
        self, owner: str, n_fresh: int, shared: list[int]
    ) -> list[int]:
        """Admission alloc with the exhaustion hook: LRU-evict cached
        prefixes only the cache still references to cover the shortfall,
        then retry once.  The hit path's own pages are shielded with an
        extra reference while evicting, so the eviction scan can never
        free a page this very admission is about to map."""
        try:
            return self.allocator.alloc(owner, n_fresh, shared=shared)
        except CacheExhausted:
            if self.prefix is None:
                raise
            need = n_fresh - self.allocator.free_pages
            if shared:
                self.allocator.retain(shared)
            try:
                if self.prefix.evict(need) < need:
                    raise
                return self.allocator.alloc(owner, n_fresh, shared=shared)
            finally:
                if shared:
                    self.allocator.release(shared)

    async def _restore_nodes(
        self, nodes: list[PrefixNode]
    ) -> list[PrefixNode]:
        """Re-warm the cold nodes on a matched path (hibernate restore):
        allocate a fresh page, scatter the host-RAM record back, promote.
        The path truncates at the first node that cannot restore (no
        import support, exhaustion even after eviction, or an eviction
        racing the scatter).  The pause — what the turn waits before its
        prefill can start — feeds
        ``cordum_serving_hibernate_pause_seconds``."""
        out: list[PrefixNode] = []
        t0 = None
        imp = getattr(self.backend, "import_kv", None)
        for node in nodes:
            if node.dropped:
                break
            if node.warm:
                out.append(node)
                continue
            if imp is None or node.record is None or self.prefix is None:
                break
            if t0 is None:
                t0 = time.monotonic()
            try:
                (page,) = self.allocator.alloc_raw(1)
            except CacheExhausted:
                if self.prefix.evict(1) < 1:
                    break
                try:
                    (page,) = self.allocator.alloc_raw(1)
                except CacheExhausted:
                    break
            try:
                await self.run_blocking(imp, [page], [dict(node.record, i=0)])
            except Exception as e:  # noqa: BLE001 - keep the record, skip the hit
                self.allocator.release([page])
                logx.warn("prefix restore failed", err=str(e))
                break
            if node.dropped:
                self.allocator.release([page])
                break
            self.prefix.promote(node, page)
            if self.tiering is not None:
                self.tiering.stats.restored_pages += 1
            out.append(node)
        if t0 is not None and self.metrics is not None:
            self.metrics.serving_hibernate_pause.observe(time.monotonic() - t0)
        return out

    async def _emit(self, sess: _Session, new_tokens: list[int]) -> None:
        if sess.on_tokens is None:
            return
        try:
            await sess.on_tokens(new_tokens, len(sess.out_tokens), sess.done)
        except Exception as e:  # noqa: BLE001 - streaming is best-effort
            logx.warn("token stream sink failed", job_id=sess.job_id, err=str(e))

    def _retire(self, sess: _Session, error: Optional[BaseException] = None) -> None:
        if (
            error is None and self.prefix is not None
            and not self._closed and not sess.cancelled and sess.pages
        ):
            # retain the finished conversation's full pages under their
            # token path: the next turn (same history + new suffix) maps
            # them instead of re-prefilling.  Register BEFORE the
            # allocator drops the session's references, so a shared page
            # never transits the free list (the retain/release ordering
            # the property suite pins down).  Positions [0, pos) were
            # written; their tokens are prompt + generated output minus
            # the never-fed final sample.
            covered = (sess.req.prompt + sess.out_tokens)[:sess.pos]
            self.prefix.register(covered, sess.pages)
            if self.tiering is not None and sess.req.session_key:
                self.tiering.note_turn(sess.req.session_key, covered)
        self.allocator.free(sess.job_id)
        self._active.pop(sess.job_id, None)
        if error is None:
            self.stats.retired += 1
            if self.metrics is not None:
                self.metrics.serving_retired.inc(reason="finished")
            if not sess.future.done():
                sess.future.set_result(list(sess.out_tokens))
        else:
            if isinstance(error, SessionCancelled):
                reason = "cancelled"
                self.stats.cancelled += 1
            elif isinstance(error, SessionMigrated):
                reason = "migrated"
                self.stats.migrated_out += 1
            elif isinstance(error, SessionHibernated):
                reason = "hibernated"
                self.stats.hibernated_out += 1
            elif isinstance(error, SessionRequeued):
                reason = "requeued"
                self.stats.requeued += 1
            else:
                reason = "failed"
            if self.metrics is not None:
                self.metrics.serving_retired.inc(reason=reason)
            if not sess.future.done():
                sess.future.set_exception(error)

    # ------------------------------------------------------------------
    @staticmethod
    def _ngram_draft(history: list[int], k: int) -> list[int]:
        """Prompt-lookup drafting — the zero-extra-weights self-speculative
        drafter: find the most recent earlier occurrence of the history's
        final n-gram and propose the tokens that followed it.  Longest gram
        first (a longer match is stronger evidence the continuation
        repeats), most-recent-first within a gram so loops and templates
        match their latest iteration.  Returns ``[]`` when nothing matches:
        the session decodes a plain single-token row this step."""
        n = len(history)
        for g in (3, 2, 1):
            if n <= g:
                continue
            tail = history[-g:]
            # bounded lookback keeps a very long conversation O(window)
            lo = max(0, n - g - 512)
            for i in range(n - g - 1, lo - 1, -1):
                if history[i:i + g] == tail:
                    cont = history[i + g:i + g + k]
                    if cont:
                        return cont
        return []

    def _plan_drafts(self) -> None:
        """Propose draft continuations for every decoding session — BEFORE
        CoW resolution (the write span must cover the planned draft
        positions) and before assembly (which trims plans to the step's
        flat-buffer budget).  The per-session acceptance EWMA throttles the
        proposal length: a session whose drafts keep verifying ramps to
        ``draft_k``, one whose drafts keep rejecting decays to single-token
        probes.  The length clamp ``k <= remaining - 1`` guarantees a fully
        accepted burst (k drafts + the bonus token) never overshoots
        ``max_new_tokens`` — and therefore never writes outside the
        session's admitted page footprint."""
        if not self.speculative:
            return
        for sess in self._active.values():
            sess.draft_plan = []
            if not sess.prefilled or sess.frozen or sess.cancelled:
                continue
            room = sess.req.max_new_tokens - len(sess.out_tokens)
            k_cap = min(self.draft_k, room - 1)
            if k_cap < 1:
                continue
            k = 1 + int(round(sess.accept_ewma * (k_cap - 1)))
            history = sess.req.prompt + sess.out_tokens
            try:
                plan = self._drafter(history, k)
            except Exception as e:  # noqa: BLE001 - drafting is best-effort
                logx.warn("drafter failed", job_id=sess.job_id, err=str(e))
                plan = []
            sess.draft_plan = [int(t) for t in plan[:k]]

    # ------------------------------------------------------------------
    async def _resolve_cow(self) -> frozenset[str]:
        """Copy-on-write guard (docs/SERVING.md §Prefix cache and
        tiering): before assembling a step, any page a session is about
        to WRITE that another table — or the prefix cache — still
        references is duplicated onto a fresh page and swapped into this
        session's table only.  Full-page-only caching makes the trigger
        rare (a prefix hit ending exactly at the prompt end re-feeds one
        token into shared territory), but the guard is what makes sharing
        safe by construction instead of by keying convention.  Returns
        job ids that must sit this step out (no fresh page even after
        dropping the cache's own reference)."""
        skip: set[str] = set()
        ps = self.allocator.page_size
        for sess in list(self._active.values()):
            if sess.frozen or sess.cancelled or sess.job_id not in self._active:
                continue
            if sess.prefilled:
                # a draft row writes positions [pos, pos + k]: the span may
                # cross into the next page (or start inside a shared prefix
                # page), so every page it touches gets the CoW guard
                hi = sess.pos + len(sess.draft_plan)
                write_pages = range(sess.pos // ps, hi // ps + 1)
            else:
                lo = sess.prefill_pos // ps
                hi = min(
                    len(sess.prefill_seq) - 1,
                    sess.prefill_pos + self.step_tokens - 1,
                ) // ps
                write_pages = range(lo, hi + 1)
            for idx in write_pages:
                if idx >= len(sess.pages):
                    break
                if self.allocator.refcount(sess.pages[idx]) <= 1:
                    continue
                if not await self._cow(sess, idx):
                    skip.add(sess.job_id)
                    break
        return frozenset(skip)

    async def _cow(self, sess: _Session, idx: int) -> bool:
        """Give ``sess`` a private copy of page-table slot ``idx``.
        Cheapest first: under exhaustion (or when the cache is the only
        other holder left) dropping the cache's reference may already
        make this session the sole owner — no copy, no fresh page."""
        old = sess.pages[idx]
        copy = getattr(self.backend, "copy_page", None)
        if copy is None:
            # arena-less backends (test fakes) have no page contents to
            # copy and no way to share them — nothing to protect
            return True
        if self.allocator.free_pages < 1 and self.prefix is not None:
            self.prefix.drop_subtree(old)
            if self.allocator.refcount(old) <= 1:
                return True
        try:
            (fresh,) = self.allocator.alloc_raw(1)
        except CacheExhausted:
            if self.prefix is not None:
                self.prefix.drop_subtree(old)
                if self.allocator.refcount(old) <= 1:
                    return True
            return False
        await self.run_blocking(copy, old, fresh)
        if sess.cancelled or sess.job_id not in self._active:
            self.allocator.release([fresh])  # retired during the copy
            return True
        self.allocator.swap_owned(sess.job_id, old, fresh)
        sess.pages[idx] = fresh
        self.allocator.release([old])
        self.stats.cow_copies += 1
        if self.metrics is not None:
            self.metrics.serving_cow_copies.inc()
        return True

    # ------------------------------------------------------------------
    def _assemble(
        self, skip: frozenset = frozenset()
    ) -> tuple[list[StepEntry], list[tuple[_Session, int, bool, list[int]]]]:
        """Build one mixed step: a decode row for every prefilled session
        (with its planned draft tokens appended while the budget lasts),
        then prompt chunks for prefilling ones (admission order) within the
        flat token budget and the per-step chunk cap.  Returns the entries
        plus aligned ``(session, chunk_len, samples, draft_tokens)``
        bookkeeping.  ``skip`` rows sit this step out (CoW starved for a
        fresh page)."""
        entries: list[StepEntry] = []
        rows: list[tuple[_Session, int, bool, list[int]]] = []
        budget = self.step_tokens
        chunks = 0
        decoding = [
            # frozen = mid-migration freeze-and-delta: the session's pages
            # are being shipped; its rows sit this step (and the next) out
            s for s in self._active.values()
            if s.prefilled and not s.frozen and s.job_id not in skip
        ]
        # draft budget: the flat-buffer slots left after every decode row's
        # base token.  While prompts are waiting to prefill, drafts take at
        # most half the leftover so speculation can never starve admission
        # latency — the prefill chunks below ride the rest.
        waiting = any(
            not s.prefilled and not s.frozen and s.job_id not in skip
            for s in self._active.values()
        )
        spare = budget - len(decoding)
        draft_budget = (
            (spare // 2 if waiting else spare) if self.speculative else 0
        )
        for sess in decoding:
            plan = sess.draft_plan[:draft_budget] if draft_budget > 0 else []
            sess.draft_plan = []
            entries.append(StepEntry(
                tokens=[sess.last_token, *plan], start=sess.pos,
                pages=sess.pages, sample=True, phase="decode",
                key=sess.job_id, draft=len(plan),
            ))
            rows.append((sess, 1 + len(plan), True, plan))
            budget -= 1 + len(plan)
            draft_budget -= len(plan)
        # prefill candidates ride interactive-first (stable within a class,
        # so admission order still breaks ties): under load the leftover
        # token budget goes to interactive prompts and BATCH prefill waits —
        # batch decode rows above keep their single-token slots, only new
        # batch prompt ingestion is deprioritized (docs/ADMISSION.md)
        prefilling = [
            s for s in self._active.values()
            if not s.prefilled and not s.frozen and s.job_id not in skip
        ]
        prefilling.sort(
            key=lambda s: 0 if s.req.job_class in INTERACTIVE_CLASSES else 1
        )
        for sess in prefilling:
            if budget <= 0 or chunks >= self.max_concurrent_prefills:
                break
            # the prefill sequence is prompt + any forced-decode resume
            # prefix (minus its last token, which decodes as a normal row);
            # the completing chunk samples only for resume-free sessions
            # with output still to generate
            seq = sess.prefill_seq
            chunk = min(budget, len(seq) - sess.prefill_pos)
            completes = sess.prefill_pos + chunk >= len(seq)
            samples = (
                completes and not sess.done and not sess.req.resume_tokens
            )
            entries.append(StepEntry(
                tokens=seq[sess.prefill_pos:sess.prefill_pos + chunk],
                start=sess.prefill_pos, pages=sess.pages,
                sample=samples, phase="prefill",
                key=sess.job_id,
            ))
            rows.append((sess, chunk, samples, []))
            budget -= chunk
            chunks += 1
        return entries, rows

    async def _decode_loop(self) -> None:
        """The continuous-batching loop: one ragged XLA call per step over
        every active session — decode rows and prefill chunks mixed;
        admission and retirement happen between steps, never inside one."""
        while not self._closed:
            await self._admit()
            # evict cancellations before assembling the batch
            for sess in [s for s in self._active.values() if s.cancelled]:
                self._retire(sess, error=SessionCancelled(sess.job_id))
            if not self._active:
                self._gauge()
                if not self._pending:
                    if self._closed:
                        return
                    self._wake.clear()
                    # re-check after clear: a submit may have landed between
                    # the emptiness check and the clear
                    if not (self._pending or self._active):
                        await self._wake.wait()
                else:
                    await asyncio.sleep(0.001)  # pages freeing: poll soon
                continue
            self._plan_drafts()
            entries, rows = self._assemble(await self._resolve_cow())
            if not entries:  # defensive: all rows parked past the budget
                await asyncio.sleep(0.001)
                continue
            t0 = time.monotonic()
            step_span = None
            if self.tracer is not None and rows[0][0].trace_id:
                oldest = min((r[0] for r in rows), key=lambda s: s.enqueued_at)
                step_span = self.tracer.begin(
                    "decode-step", trace_id=oldest.trace_id,
                    parent_span_id=oldest.parent_span_id,
                    attrs={"occupancy": str(len(rows))},
                )
            self._in_step = frozenset(s.job_id for s, _, _, _ in rows)
            try:
                results = await self.run_blocking(self.backend.step, entries)
            except Exception as e:  # noqa: BLE001 - whole-step failure
                # a poisoned step fails every rider (pages freed); the next
                # tick starts clean — mirrors the batcher's isolation intent
                # without re-running autoregressive state per item
                self._in_step = frozenset()
                logx.warn("serving step failed", occupancy=len(rows), err=str(e))
                if step_span is not None and self.tracer is not None:
                    step_span.attrs["error"] = type(e).__name__
                    await self.tracer.finish(step_span, status="ERROR")
                for sess, _, _, _ in rows:
                    self.stats.failed += 1
                    self._retire(sess, error=e)
                continue
            dt = time.monotonic() - t0
            generated = 0
            prefill_fed = 0
            retired_this_step = 0
            step_drafted = 0
            step_accepted = 0
            emits = []
            retires = []
            for (sess, chunk, samples, drafted), tok in zip(rows, results):
                if drafted:
                    # speculative verification row: the backend returned
                    # one next-token prediction per fed position.  Accept
                    # the longest draft prefix the model agrees with, then
                    # the bonus token — the prediction after the last
                    # accepted draft, which is exactly what a sequential
                    # decode would have sampled next (so the burst is
                    # token-identical to the oracle by construction).
                    preds = [int(t) for t in tok]
                    a = 0
                    while a < len(drafted) and drafted[a] == preds[a]:
                        a += 1
                    burst = drafted[:a] + [preds[a]]
                    eos = sess.req.eos_token
                    if eos is not None and eos in burst:
                        burst = burst[:burst.index(eos) + 1]
                    rejected = len(drafted) - a
                    step_drafted += len(drafted)
                    step_accepted += a
                    frac = a / len(drafted)
                    sess.accept_ewma += SPEC_EWMA_ALPHA * (
                        frac - sess.accept_ewma
                    )
                    self.spec_accept_ewma += SPEC_FLEET_ALPHA * (
                        frac - self.spec_accept_ewma
                    )
                    self.stats.drafted_tokens += len(drafted)
                    self.stats.accepted_tokens += a
                    self.stats.rolled_back_tokens += rejected
                    if self.metrics is not None:
                        self.metrics.serving_spec_drafted.inc(
                            float(len(drafted)))
                        self.metrics.serving_spec_accepted.inc(float(a))
                        if rejected:
                            self.metrics.serving_spec_rolled_back.inc(
                                float(rejected))
                    # page write-position rollback: pos advances over the
                    # verified burst ONLY.  Rejected draft positions sit at
                    # >= the new pos; every later step writes its own K/V
                    # there before any gather runs (writes precede gathers
                    # inside the ragged program, and positions are consumed
                    # contiguously), so the arena never serves speculated
                    # garbage.
                    first = not sess.out_tokens
                    sess.pos += len(burst)
                    sess.last_token = burst[-1]
                    sess.out_tokens.extend(burst)
                    generated += len(burst)
                    if first:
                        self.stats.ttft_seconds.append(
                            time.monotonic() - sess.enqueued_at
                        )
                    emits.append(self._emit(sess, burst))
                else:
                    if sess.prefilled:
                        sess.pos += 1  # decode row: wrote its token at pos
                    else:
                        sess.prefill_pos += chunk
                        sess.pos = sess.prefill_pos
                        prefill_fed += chunk
                        self.stats.prefill_chunks += 1
                    if samples and tok is not None:
                        t = int(tok)
                        sess.last_token = t
                        sess.out_tokens.append(t)
                        generated += 1
                        if len(sess.out_tokens) == 1:
                            # first token of a locally born session: TTFT
                            # (resume prefixes pre-populate out_tokens, so
                            # migrated/resumed sessions never land here)
                            self.stats.ttft_seconds.append(
                                time.monotonic() - sess.enqueued_at
                            )
                        emits.append(self._emit(sess, [t]))
                if sess.done or sess.cancelled:
                    retired_this_step += 1
                    # deferred below the emit gather: the future must not
                    # resolve before the session's final token packet is
                    # delivered, or a submitter that stops the engine the
                    # moment submit() returns races the stream's tail (the
                    # exactly-once contract spec bursts lean on)
                    retires.append(sess)
                elif (
                    self.on_prefill_done is not None
                    and not sess.handoff_signaled
                    and not sess.frozen
                    and (sess.prefilled or (
                        self.handoff_threshold_tokens > 0
                        and sess.prefill_pos >= self.handoff_threshold_tokens
                    ))
                ):
                    # post-prefill hand-off trigger: the prompt finished
                    # prefilling (or crossed the threshold mid-prefill) and
                    # the session still has tokens to generate — the hook
                    # fires once; the owner decides whether/where to migrate
                    sess.handoff_signaled = True
                    try:
                        self.on_prefill_done(sess.job_id)
                    except Exception as e:  # noqa: BLE001 - policy is best-effort
                        logx.warn("prefill-done hook failed",
                                  job_id=sess.job_id, err=str(e))
            self.stats.steps += 1
            self.stats.decoded_tokens += generated
            self.stats.prefill_tokens += prefill_fed
            if step_drafted:
                self.stats.spec_steps += 1
            self.stats.occupancy_sum += len(rows)
            self.stats.max_occupancy = max(self.stats.max_occupancy, len(rows))
            self.stats.step_seconds.append(dt)
            if self.capacity is not None:
                # one mixed step at the backend's static flat-buffer shape;
                # warmup compiles are flagged so the steady-state tokens/s
                # rows in the capacity matrix exclude them.  The step's
                # device time is apportioned by delivered tokens between
                # prompt ingestion (the OP_SERVING_PREFILL row) and token
                # generation (the llm.generate row), so prefill tokens/s
                # and decode tokens/s are separately measurable — the
                # disaggregation policy's two placement signals
                # (docs/SERVING.md §Disaggregation)
                from ..protocol.types import OP_SERVING_PREFILL

                compiled = bool(getattr(self.backend, "last_step_compiled",
                                        False))
                total_toks = generated + prefill_fed
                if prefill_fed:
                    self.capacity.observe(
                        OP_SERVING_PREFILL,
                        device_s=dt * prefill_fed / total_toks,
                        bucket=str(self.step_tokens),
                        items=prefill_fed, tokens=prefill_fed,
                        compiled=compiled,
                    )
                if generated or not prefill_fed:
                    self.capacity.observe(
                        "llm.generate",
                        device_s=(dt * generated / total_toks
                                  if total_toks else dt),
                        bucket=str(self.step_tokens),
                        items=generated, tokens=generated,
                        compiled=compiled,
                    )
            if emits:
                await asyncio.gather(*emits)
            for sess in retires:
                self._retire(
                    sess,
                    error=SessionCancelled(sess.job_id)
                    if sess.cancelled else None,
                )
            # every token of this step is appended AND emitted: a freeze
            # waiting on wait_quiesced() now sees a fully consistent session
            self._in_step = frozenset()
            if self.metrics is not None:
                self.metrics.serving_batch_occupancy.observe(float(len(rows)))
                self.metrics.serving_inter_token.observe(dt)
            if step_span is not None and self.tracer is not None:
                step_span.attrs["retired"] = str(retired_this_step)
                step_span.attrs["prefill_tokens"] = str(prefill_fed)
                step_span.attrs["step_ms"] = f"{dt * 1000:.2f}"
                if self.speculative:
                    step_span.attrs["drafted"] = str(step_drafted)
                    step_span.attrs["accepted"] = str(step_accepted)
                await self.tracer.finish(step_span)
            self._gauge()
            # yield to the loop so intake/cancel/heartbeat tasks run between
            # steps even under a saturated decode set
            await asyncio.sleep(0)

    # ------------------------------------------------------------------
    # live migration (serving/migration.py, docs/SERVING.md §Migration,
    # drain, and failover).  The engine side is deliberately mechanical:
    # describe → stream stable pages live → freeze → export the delta →
    # complete (retire as SessionMigrated) or unfreeze on failure.
    # ------------------------------------------------------------------
    def session_ids(self) -> list[str]:
        """Every live session, decoding first (pending last): the order a
        drain migrates them in — decoding sessions carry KV state worth
        moving; pending ones are requeued cheaply."""
        return [*self._active.keys(), *(s.job_id for s in self._pending)]

    def pick_rebalance_sessions(self, n: int = 1) -> list[str]:
        """Cheapest movable sessions for a governor rebalance
        (docs/SERVING.md §Disaggregation): active, unfrozen, uncancelled,
        and past their migrated-in cooldown — a session the governor (or a
        hand-off) just placed here is immune, so skew oscillation can
        never ping-pong it.  Cheapest = fewest live pages, then oldest
        (smallest) position — the least KV state to ship; sessions still
        prefilling qualify (they are the cheapest of all, and migration
        resumes prefill on the target).  Drain uses :meth:`session_ids`
        instead and ignores immunity (a draining worker must move
        everything)."""
        now = time.monotonic()
        cands = [
            s for s in self._active.values()
            if not s.frozen and not s.cancelled
            and not s.done and s.immune_until <= now
        ]
        cands.sort(key=lambda s: (len(s.pages), s.pos))
        return [s.job_id for s in cands[:max(0, n)]]

    def describe_session(self, job_id: str) -> Optional[dict[str, Any]]:
        """The session's immutable metadata (the migration hello frame);
        None when it is not actively decoding here."""
        sess = self._active.get(job_id)
        if sess is None or sess.cancelled:
            return None
        req = sess.req
        return {
            "job_id": sess.job_id,
            "prompt": list(req.prompt),
            "resume_tokens": list(req.resume_tokens),
            "max_new_tokens": req.max_new_tokens,
            "session_key": req.session_key,
            "eos_token": req.eos_token,
            "stream": req.stream,
            "trace_id": sess.trace_id,
            "page_size": self.allocator.page_size,
            "n_pages": len(sess.pages),
        }

    def export_state(self, job_id: str) -> Optional[dict[str, Any]]:
        """The session's mutable decode state — valid only once frozen and
        quiesced (the commit frame's ``state``)."""
        sess = self._active.get(job_id)
        if sess is None:
            return None
        return {
            "pos": sess.pos,
            "prefill_pos": sess.prefill_pos,
            "out_tokens": list(sess.out_tokens),
            "last_token": sess.last_token,
        }

    async def export_pages(
        self, job_id: str, start_tok: int, end_tok: int
    ) -> list[dict]:
        """Page records covering positions ``[start_tok, end_tok)`` at
        their true lengths (backends without an arena export nothing — the
        receiver rebuilds from the metadata via ``restore_session``)."""
        sess = self._active.get(job_id)
        fn = getattr(self.backend, "export_kv", None)
        if sess is None or fn is None:
            return []
        return await self.run_blocking(fn, sess.pages, start_tok, end_tok)

    def freeze_session(self, job_id: str) -> bool:
        """Pause the session's decode (it sits out subsequent steps);
        False when it is not actively decoding here."""
        sess = self._active.get(job_id)
        if sess is None or sess.cancelled:
            return False
        sess.frozen = True
        return True

    def unfreeze_session(self, job_id: str) -> None:
        """Resume a frozen session (migration failed: decode continues
        locally as if nothing happened)."""
        sess = self._active.get(job_id)
        if sess is not None:
            sess.frozen = False
            self._wake.set()

    async def wait_quiesced(self, job_id: str) -> None:
        """Block until the in-flight step (which may still produce one
        token for a just-frozen session) has scattered its results."""
        while job_id in self._in_step:
            await asyncio.sleep(0.002)

    def complete_migration(self, job_id: str) -> bool:
        """The target committed: retire locally as migrated — the waiter
        publishes nothing (the target owns stream + terminal result)."""
        sess = self._active.get(job_id)
        if sess is None:
            return False
        self._retire(sess, error=SessionMigrated(job_id))
        return True

    async def hibernate_session(self, job_id: str) -> bool:
        """Freeze a live session and tier it whole into the host-RAM cold
        arena — the local analogue of live migration (same record format,
        no peer): freeze → quiesce → export state + pages → retire
        ``reason="hibernated"``.  The submit waiter gets
        :class:`SessionHibernated` and publishes nothing;
        :meth:`restore_hibernated` later owns the token stream and the
        terminal result.  False when the session is not live here (or
        tiering is disabled)."""
        if self.tiering is None:
            return False
        meta = self.describe_session(job_id)
        if meta is None or not self.freeze_session(job_id):
            return False
        try:
            await self.wait_quiesced(job_id)
            state = self.export_state(job_id)
            if state is None:
                return False
            records = await self.export_pages(job_id, 0, int(state["pos"]))
        except BaseException:
            self.unfreeze_session(job_id)
            raise
        sess = self._active.get(job_id)
        if sess is None or sess.cancelled:
            self.unfreeze_session(job_id)
            return False
        self.tiering.arena.put(job_id, {
            "meta": meta, "state": state, "records": records,
        })
        self._retire(sess, error=SessionHibernated(job_id))
        if self.metrics is not None:
            self.metrics.serving_hibernate.inc(event="hibernated")
        return True

    async def restore_hibernated(
        self,
        job_id: str,
        *,
        on_tokens: Optional[TokenSink] = None,
    ) -> asyncio.Future:
        """Re-admit a hibernated session from the cold arena via the
        existing :meth:`install_session` path; carried tokens replay at
        offset 0, so offset-deduping stream consumers see an exactly-once
        sequence across the gap.  Raises ``KeyError`` when the arena has
        no such session; on install failure (exhaustion) the cold doc is
        put back, restorable later."""
        if self.tiering is None:
            raise KeyError(job_id)
        doc = self.tiering.arena.pop(job_id)
        if doc is None:
            raise KeyError(job_id)
        meta, state = doc["meta"], doc["state"]
        eos = meta.get("eos_token")
        req = GenRequest(
            prompt=[int(t) for t in meta["prompt"]],
            max_new_tokens=int(meta["max_new_tokens"]),
            session_key=str(meta.get("session_key", "")),
            eos_token=int(eos) if isinstance(eos, int) else None,
            stream=bool(meta.get("stream", True)),
            resume_tokens=[int(t) for t in meta.get("resume_tokens") or []],
        )
        t0 = time.monotonic()
        try:
            fut = await self.install_session(
                req, job_id=job_id, state=state, records=doc["records"],
                trace_id=str(meta.get("trace_id", "")),
                on_tokens=on_tokens, origin="hibernate",
            )
        except BaseException:
            self.tiering.arena.put(job_id, doc)
            raise
        self.stats.restored_in += 1
        if self.metrics is not None:
            self.metrics.serving_hibernate.inc(event="restored")
            self.metrics.serving_hibernate_pause.observe(time.monotonic() - t0)
        return fut

    def requeue(self, job_id: str, reason: str = "") -> bool:
        """Hand a session (pending or active) back to the scheduler for
        failover — the drain fallback when no peer can take its pages."""
        for i, sess in enumerate(self._pending):
            if sess.job_id == job_id:
                del self._pending[i]
                self._retire(sess, error=SessionRequeued(reason or job_id))
                return True
        sess = self._active.get(job_id)
        if sess is None:
            return False
        self._retire(sess, error=SessionRequeued(reason or job_id))
        return True

    async def install_session(
        self,
        req: GenRequest,
        *,
        job_id: str,
        state: dict[str, Any],
        records: list[dict],
        trace_id: str = "",
        parent_span_id: str = "",
        on_tokens: Optional[TokenSink] = None,
        origin: str = "migration",
    ) -> asyncio.Future:
        """Adopt a migrated-in session: allocate fresh arena blocks,
        scatter the shipped page records into them, and resume decoding
        exactly where the source froze.  Raises (``CacheExhausted`` /
        ``ValueError``) when this worker cannot take it — the source then
        falls back to a scheduler requeue.  Returns the session's result
        future (token list).  ``origin="hibernate"`` (the
        :meth:`restore_hibernated` path) books the adoption under the
        hibernate counters instead of the migration ones."""
        if self._closed:
            raise RuntimeError("serving engine is stopped")
        if job_id in self._active or any(
            s.job_id == job_id for s in self._pending
        ):
            raise ValueError(f"session {job_id} already live on this worker")
        total = len(req.prompt) + req.max_new_tokens
        if self.max_context and total > self.max_context:
            raise ValueError(
                f"migrated session spans {total} tokens; backend max_context "
                f"is {self.max_context}"
            )
        if len(self._active) >= self.max_sessions:
            raise CacheExhausted(
                f"{len(self._active)} active sessions; max {self.max_sessions}"
            )
        pages = self.allocator.alloc(job_id, self.allocator.pages_for(total))
        try:
            imp = getattr(self.backend, "import_kv", None)
            if imp is not None and records:
                await self.run_blocking(imp, pages, records)
        except BaseException:
            self.allocator.free(job_id)
            raise
        sess = _Session(
            job_id=job_id, req=req,
            future=asyncio.get_running_loop().create_future(),
            on_tokens=on_tokens if req.stream else None,
            trace_id=trace_id, parent_span_id=parent_span_id,
        )
        sess.pages = pages
        sess.pos = int(state.get("pos", 0) or 0)
        sess.prefill_pos = int(state.get("prefill_pos", 0) or 0)
        sess.out_tokens = [int(t) for t in state.get("out_tokens") or []]
        sess.last_token = int(state.get("last_token", 0) or 0)
        # anti-ping-pong cooldown: a just-adopted session may not be picked
        # for another governor rebalance until the window passes
        sess.immune_until = time.monotonic() + self.migrate_in_cooldown_s
        # a migrated-in session never re-fires the source's hand-off hook:
        # it is already where the policy put it
        sess.handoff_signaled = True
        # arena-less backends (test fakes) rebuild their per-session decode
        # state from the metadata instead of imported pages
        restore = getattr(self.backend, "restore_session", None)
        if restore is not None:
            restore(job_id, sess.prefill_seq, sess.prefill_pos)
        self._active[job_id] = sess
        self.stats.admitted += 1
        if origin == "migration":
            self.stats.migrated_in += 1
        if self.metrics is not None:
            self.metrics.serving_admitted.inc()
            if origin == "migration":
                self.metrics.serving_migrations.inc(role="in", outcome="ok")
        if sess.out_tokens and sess.on_tokens is not None:
            # replay the carried tokens at offset 0: dedupe-by-offset makes
            # it a no-op for clients that saw them and a backfill for
            # clients that lost packets in the handover window
            asyncio.ensure_future(self._emit(sess, list(sess.out_tokens)))
        if sess.done:
            self._retire(sess)
        else:
            self._ensure_loop()
            self._wake.set()
        self._gauge()
        return sess.future

    # ------------------------------------------------------------------
    # cordum: single-flight -- sole caller is the owning runner's shutdown path; the cancel/await/None teardown is idempotent
    async def stop(self) -> None:
        """Evict every session (CANCELLED) and stop the loop — worker
        shutdown; generations are conversation turns, not batch jobs, so
        draining them could take unboundedly long."""
        self._closed = True
        self._wake.set()
        if self._tiering_task is not None:
            self._tiering_task.cancel()
            try:
                await self._tiering_task
            except asyncio.CancelledError:
                pass
            except Exception as e:  # noqa: BLE001 - logged, never swallowed
                logx.warn("tiering sweep crashed during shutdown", err=str(e))
            self._tiering_task = None
        for sess in list(self._pending):
            if not sess.future.done():
                sess.future.set_exception(SessionCancelled(sess.job_id))
        self._pending.clear()
        for sess in list(self._active.values()):
            sess.cancelled = True
            self._retire(sess, error=SessionCancelled(sess.job_id))
        if self._loop_task is not None:
            self._loop_task.cancel()
            try:
                await self._loop_task
            except asyncio.CancelledError:
                pass
            except Exception as e:  # noqa: BLE001 - logged, never swallowed
                logx.warn("decode loop crashed during shutdown", err=str(e))
            self._loop_task = None
