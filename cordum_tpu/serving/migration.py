"""Live KV-page migration: the session-transfer protocol (docs/PROTOCOL.md
§Page transfer; docs/SERVING.md §Migration, drain, and failover).

A serving session's state — KV pages at their true lengths, page table
shape, positions, prefill progress, sampled tokens — streams worker→worker
as msgpack records over the statebus frame layer (``infra/frames``), the
same ``[4-byte BE length][msgpack array]`` framing the AOF-shipping
replication link uses.  The shape is deliberately the PR 8 pattern: pages
as records, a ``(session, offset)`` handshake so a severed link resumes
where it left off, and a final **freeze-and-delta** step so decode pauses
only for the last chunk:

  1. ``["hello", {session, meta}]`` → ``["ok", {session, offset}]`` — the
     receiver reports how many page records it already holds (0 for a
     fresh transfer; its partial count when the sender reconnects after a
     sever), and the sender resumes from there.
  2. ``["page", {session, offset, rec}]`` — one page record per frame,
     offset-sequenced.  Only pages FULLY below the decode position ride
     this live phase: they are immutable while the session keeps decoding,
     so the bulk of the KV cache ships with zero pause.
  3. ``["commit", {session, offset, state, delta}]`` — the sender freezes
     the session (it sits out the step loop), waits for the in-flight step
     to quiesce, then ships the remaining dirty pages plus the mutable
     decode state in one frame.  The receiver scatters everything into
     freshly allocated arena blocks, resumes the session, and replies
     ``["done", {session}]`` — from which point it owns the token stream
     and the terminal result.  ``["error", {session, msg}]`` aborts; the
     sender unfreezes and falls back to a scheduler requeue.
  4. ``["abort", {session}]`` — sender-side abandonment (session finished
     or was cancelled mid-transfer); the receiver drops its partial state.

The resumed session is token-identical to an unmigrated one: greedy decode
over the same pages at the same positions (property-tested against the
sequential oracle in tests/test_serving_failover.py).
"""
from __future__ import annotations

import asyncio
import time
from typing import Any, Awaitable, Callable, Optional

from ..infra import logging as logx
from ..infra.frames import encode_frame, read_frame

# install(meta, state, records) — adopt a committed session (worker side)
InstallFn = Callable[[dict, dict, list], Awaitable[None]]

DEFAULT_TIMEOUT_S = 30.0


class MigrationError(Exception):
    """The transfer failed (refused, capacity, protocol mismatch); the
    sender falls back to a scheduler requeue — never a lost session."""


class _Partial:
    """Page records received so far for one in-flight session transfer
    (survives connection drops: the (session, offset) resume state)."""

    __slots__ = ("meta", "records", "started_at")

    def __init__(self, meta: dict) -> None:
        self.meta = meta
        self.records: list[dict] = []
        self.started_at = time.monotonic()


class MigrationServer:
    """Per-worker listener adopting migrated-in sessions.

    Binds ``host:port`` (port 0 = OS-assigned; the worker advertises the
    bound address via its heartbeat ``cordum.migrate_addr`` label) and
    drives the receive side of the protocol above.  ``install`` is the
    worker's adoption callback — it raises to refuse (capacity, duplicate,
    stopped), which surfaces to the sender as an ``error`` frame."""

    def __init__(
        self,
        install: InstallFn,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        metrics: Any = None,
        partial_ttl_s: float = 120.0,
    ) -> None:
        self.install = install
        self.host = host
        self.port = port
        self.metrics = metrics
        self.partial_ttl_s = partial_ttl_s
        self._server: Optional[asyncio.base_events.Server] = None
        self._partial: dict[str, _Partial] = {}

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._on_conn, self.host, self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        logx.info("migration listener up", addr=self.addr)

    # cordum: single-flight -- sole caller is the owning runner's shutdown path; the cancel/await/None teardown is idempotent
    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        self._partial.clear()

    def _gc_partials(self) -> None:
        cutoff = time.monotonic() - self.partial_ttl_s
        for sid in [s for s, p in self._partial.items() if p.started_at < cutoff]:
            del self._partial[sid]

    async def _on_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        async def reply(frame: list) -> None:
            writer.write(encode_frame(frame))
            await writer.drain()

        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    return
                op, body = frame[0], frame[1] if len(frame) > 1 else {}
                sid = str(body.get("session", ""))
                if op == "hello":
                    self._gc_partials()
                    part = self._partial.get(sid)
                    if part is None:
                        part = self._partial[sid] = _Partial(body.get("meta") or {})
                    else:
                        part.meta = body.get("meta") or part.meta
                    await reply(["ok", {"session": sid,
                                        "offset": len(part.records)}])
                elif op == "page":
                    part = self._partial.get(sid)
                    if part is None:
                        await reply(["error", {"session": sid,
                                               "msg": "no hello for session"}])
                        continue
                    off = int(body.get("offset", -1))
                    if off == len(part.records):
                        part.records.append(body.get("rec") or {})
                    elif off > len(part.records):
                        await reply(["error", {
                            "session": sid,
                            "msg": f"page offset {off} skips "
                                   f"{len(part.records)}"}])
                    # off < len(records): duplicate from a resume replay — drop
                elif op == "commit":
                    part = self._partial.pop(sid, None)
                    if part is None:
                        await reply(["error", {"session": sid,
                                               "msg": "no transfer state"}])
                        continue
                    off = int(body.get("offset", -1))
                    if off != len(part.records):
                        await reply(["error", {
                            "session": sid,
                            "msg": f"commit at offset {off}, have "
                                   f"{len(part.records)} records"}])
                        continue
                    records = [*part.records, *(body.get("delta") or [])]
                    try:
                        await self.install(
                            part.meta, body.get("state") or {}, records
                        )
                    except Exception as e:  # noqa: BLE001 - refusal → sender fallback
                        logx.warn("migration install refused",
                                  session=sid, err=str(e))
                        await reply(["error", {"session": sid, "msg": str(e)}])
                        continue
                    await reply(["done", {"session": sid}])
                elif op == "abort":
                    self._partial.pop(sid, None)
                else:
                    await reply(["error", {"session": sid,
                                           "msg": f"unknown op {op!r}"}])
        except asyncio.CancelledError:
            raise
        except (ConnectionError, OSError):
            pass  # sender reconnects and resumes from its acked offset
        finally:
            try:
                writer.close()
            except (OSError, RuntimeError):
                pass


async def _rpc(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    frame: list,
    *,
    timeout_s: float,
) -> list:
    writer.write(encode_frame(frame))
    await writer.drain()
    reply = await asyncio.wait_for(read_frame(reader), timeout_s)
    if reply is None:
        raise ConnectionError("migration peer closed mid-handshake")
    if reply[0] == "error":
        raise MigrationError(str((reply[1] or {}).get("msg", "refused")))
    return reply


async def migrate_session(
    engine: Any,
    job_id: str,
    host: str,
    port: int,
    *,
    meta_extra: Optional[dict] = None,
    metrics: Any = None,
    timeout_s: float = DEFAULT_TIMEOUT_S,
    max_attempts: int = 2,
) -> bool:
    """Drive one session's live migration to ``host:port``.

    Returns True once the target committed (the session is retired locally
    as migrated — publish nothing); False on any failure, with the session
    unfrozen and decoding locally again so the caller can fall back to a
    scheduler requeue.  A connection drop during the live page phase
    reconnects and resumes from the receiver's acked offset (the
    ``(session, offset)`` handshake)."""
    meta = engine.describe_session(job_id)
    if meta is None:
        if metrics is not None:
            metrics.serving_migration_failures.inc(reason="no_session")
        return False
    if meta_extra:
        meta.update(meta_extra)
    ps = int(meta["page_size"])
    frozen = False
    t_freeze = 0.0
    outcome = "failed"
    fail_reason = "unknown"
    try:
        for attempt in range(max_attempts):
            reader = writer = None
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(host, port), timeout_s
                )
                ok = await _rpc(reader, writer,
                                ["hello", {"session": job_id, "meta": meta}],
                                timeout_s=timeout_s)
                offset = int(ok[1]["offset"])
                # live phase: stream every page fully below the current
                # decode position — immutable while the session keeps
                # decoding, so the bulk ships with zero pause
                state = engine.export_state(job_id)
                if state is None:
                    fail_reason = "session_gone"
                    await _abort(writer, job_id)
                    return False
                stable_tok = (int(state["pos"]) // ps) * ps
                if offset * ps < stable_tok:
                    for rec in await engine.export_pages(
                        job_id, offset * ps, stable_tok
                    ):
                        writer.write(encode_frame(
                            ["page", {"session": job_id, "offset": offset,
                                      "rec": rec}]))
                        offset += 1
                    await writer.drain()
                # freeze-and-delta: decode pauses only from here to `done`
                if not engine.freeze_session(job_id):
                    fail_reason = "session_gone"
                    await _abort(writer, job_id)
                    return False
                frozen = True
                t_freeze = time.monotonic()
                await engine.wait_quiesced(job_id)
                state = engine.export_state(job_id)
                if state is None:  # cancelled while freezing
                    fail_reason = "session_gone"
                    await _abort(writer, job_id)
                    return False
                delta = await engine.export_pages(
                    job_id, stable_tok, max(int(state["pos"]), stable_tok)
                )
                await _rpc(reader, writer, ["commit", {
                    "session": job_id, "offset": offset,
                    "state": state, "delta": delta,
                }], timeout_s=timeout_s)
                pause = time.monotonic() - t_freeze
                engine.complete_migration(job_id)
                frozen = False
                outcome = "ok"
                if metrics is not None:
                    metrics.serving_migration_pause.observe(pause)
                logx.info("session migrated out", job_id=job_id,
                          target=f"{host}:{port}", pages=offset,
                          pause_ms=round(pause * 1000, 2))
                return True
            except (ConnectionError, OSError, asyncio.TimeoutError) as e:
                fail_reason = (
                    "timeout" if isinstance(e, asyncio.TimeoutError) else "io"
                )
                # freeze reached: no resume — unfreeze and let the caller
                # requeue (the receiver's partial state GCs)
                if frozen or attempt + 1 >= max_attempts:
                    logx.warn("migration failed", job_id=job_id, err=str(e))
                    return False
                logx.warn("migration link lost; resuming", job_id=job_id,
                          err=str(e))
            except MigrationError as e:
                fail_reason = "refused"
                logx.warn("migration refused", job_id=job_id, err=str(e))
                return False
            finally:
                if writer is not None:
                    try:
                        writer.close()
                    except (OSError, RuntimeError):
                        pass
        return False
    finally:
        if frozen:
            engine.unfreeze_session(job_id)
        if metrics is not None:
            metrics.serving_migrations.inc(role="out", outcome=outcome)
            if outcome != "ok":
                # the {reason} split (refused | timeout | io | session_gone
                # | unknown) tells an operator WHY hand-offs fail — the
                # callers (hand-off, rebalance, drain) additionally retry
                # once against their next-best target before falling back
                metrics.serving_migration_failures.inc(reason=fail_reason)


async def _abort(writer: asyncio.StreamWriter, job_id: str) -> None:
    try:
        writer.write(encode_frame(["abort", {"session": job_id}]))
        await writer.drain()
    except (ConnectionError, OSError):
        pass
