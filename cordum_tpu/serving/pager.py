"""Block-granular KV-page allocator (the bookkeeping half of the paged cache).

The arena itself (the ``[L, num_pages, page_size, kvh, hd]`` K/V arrays)
lives in the serving backend; this allocator owns which *page indices*
belong to which session.  Design points:

  * **page 0 is the null page** — never handed out.  Padding rows of the
    ragged decode batch and padded page-table tails point at it, so their
    writes land in slots no live sequence attends to.
  * **exhaustion is an admission signal, not an error path** — the serving
    engine calls :meth:`alloc` at admission time for the session's full
    worst-case footprint (prompt + max_new_tokens), so a session admitted
    once can never die mid-decode from cache pressure;
    :class:`CacheExhausted` parks the session in the admission queue.
  * **isolation by masking, not zeroing** — freed pages return to the free
    list dirty.  A later owner only ever attends to positions it wrote
    (the decode mask cuts every k_pos > position), so stale data is
    unreachable; ``tests/test_serving.py`` proves reuse never leaks across
    sessions.
  * single-owner, event-loop-confined: no internal locking (the serving
    engine is the only caller and runs on the worker's loop).
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field


class CacheExhausted(Exception):
    """Not enough free KV pages for the requested allocation."""


@dataclass
class PagerStats:
    allocs: int = 0
    frees: int = 0
    exhaustions: int = 0
    peak_pages_in_use: int = 0


class PageAllocator:
    """Free-list allocator over ``num_pages`` arena pages of ``page_size``
    token slots each.  Page 0 is reserved (null page)."""

    NULL_PAGE = 0

    def __init__(self, num_pages: int, page_size: int) -> None:
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is the null page)")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.num_pages = num_pages
        self.page_size = page_size
        self._free: deque[int] = deque(range(1, num_pages))
        self._owned: dict[str, list[int]] = {}
        self.stats = PagerStats()

    # ------------------------------------------------------------------
    @property
    def capacity(self) -> int:
        """Usable pages (the null page is not allocatable)."""
        return self.num_pages - 1

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.capacity - len(self._free)

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens`` sequence positions."""
        return max(1, -(-n_tokens // self.page_size))

    def owner_pages(self, owner: str) -> list[int]:
        return list(self._owned.get(owner, ()))

    def fits(self, n_pages: int) -> bool:
        return n_pages <= len(self._free)

    # ------------------------------------------------------------------
    def alloc(self, owner: str, n_pages: int) -> list[int]:
        """Allocate ``n_pages`` to ``owner`` (cumulative per owner).

        Raises :class:`CacheExhausted` without allocating anything when the
        free list cannot cover the request (all-or-nothing, so a failed
        admission never strands partial pages)."""
        if n_pages < 1:
            raise ValueError("n_pages must be >= 1")
        if n_pages > len(self._free):
            self.stats.exhaustions += 1
            raise CacheExhausted(
                f"{n_pages} pages requested, {len(self._free)} free "
                f"(capacity {self.capacity})"
            )
        pages = [self._free.popleft() for _ in range(n_pages)]
        self._owned.setdefault(owner, []).extend(pages)
        self.stats.allocs += 1
        self.stats.peak_pages_in_use = max(
            self.stats.peak_pages_in_use, self.used_pages
        )
        return pages

    def free(self, owner: str) -> int:
        """Return every page owned by ``owner`` to the free list; returns
        the count (0 for an unknown owner — freeing twice is a no-op, not
        an error, because cancel and retirement can race benignly)."""
        pages = self._owned.pop(owner, None)
        if not pages:
            return 0
        self._free.extend(pages)
        self.stats.frees += 1
        return len(pages)
