"""Block-granular KV-page allocator (the bookkeeping half of the paged cache).

The arena itself (the ``[L, num_pages, page_size, kvh, hd]`` K/V arrays)
lives in the serving backend; this allocator owns which *page indices*
belong to which session.  Design points:

  * **page 0 is the null page** — never handed out.  Padding rows of the
    ragged decode batch and padded page-table tails point at it, so their
    writes land in slots no live sequence attends to.
  * **exhaustion is an admission signal, not an error path** — the serving
    engine calls :meth:`alloc` at admission time for the session's full
    worst-case footprint (prompt + max_new_tokens), so a session admitted
    once can never die mid-decode from cache pressure;
    :class:`CacheExhausted` parks the session in the admission queue.
  * **isolation by masking, not zeroing** — freed pages return to the free
    list dirty.  A later owner only ever attends to positions it wrote
    (the decode mask cuts every k_pos > position), so stale data is
    unreachable; ``tests/test_serving.py`` proves reuse never leaks across
    sessions.
  * **refcounted sharing** (docs/SERVING.md §Prefix cache and tiering):
    a physical page may back more than one page table at once — prefix
    hits map cached pages into new sessions, and the prefix cache itself
    holds a reference while a prefix is resident.  Every page on loan
    carries an explicit refcount; a page returns to the free list only
    when the count hits zero.  :meth:`retain` / :meth:`release` raise on
    unreferenced pages, so a double free or a share of a freed page fails
    loudly instead of silently aliasing the free list (the latent hazard
    ISSUE 18 names — reachability arguments alone cannot survive
    aliasing).  ``tests/test_prefix_tiering.py`` property-tests the
    invariant: no page is ever both free and referenced, and no refcount
    ever goes negative.
  * single-owner, event-loop-confined: no internal locking (the serving
    engine is the only caller and runs on the worker's loop).
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field


class CacheExhausted(Exception):
    """Not enough free KV pages for the requested allocation."""


class PageAccountingError(RuntimeError):
    """Refcount invariant violated: double free, share of an unreferenced
    page, or a release that would drive a refcount negative.  Always a
    caller bug — the allocator raises instead of corrupting the free list."""


@dataclass
class PagerStats:
    allocs: int = 0
    frees: int = 0
    exhaustions: int = 0
    peak_pages_in_use: int = 0
    shares: int = 0  # retain() calls: pages mapped into a second+ table


class PageAllocator:
    """Free-list allocator over ``num_pages`` arena pages of ``page_size``
    token slots each.  Page 0 is reserved (null page)."""

    NULL_PAGE = 0

    def __init__(self, num_pages: int, page_size: int) -> None:
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is the null page)")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.num_pages = num_pages
        self.page_size = page_size
        self._free: deque[int] = deque(range(1, num_pages))
        self._owned: dict[str, list[int]] = {}
        # page -> live reference count; absence means the page is on the
        # free list (or is the null page).  Counts only reach zero through
        # release(), which moves the page back to the free list atomically
        # with deleting its entry — so "in _refs" and "on _free" partition
        # the arena at every step.
        self._refs: dict[int, int] = {}
        self.stats = PagerStats()

    # ------------------------------------------------------------------
    @property
    def capacity(self) -> int:
        """Usable pages (the null page is not allocatable)."""
        return self.num_pages - 1

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.capacity - len(self._free)

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens`` sequence positions."""
        return max(1, -(-n_tokens // self.page_size))

    def owner_pages(self, owner: str) -> list[int]:
        return list(self._owned.get(owner, ()))

    def fits(self, n_pages: int) -> bool:
        return n_pages <= len(self._free)

    def refcount(self, page: int) -> int:
        """Live references on ``page`` (0 = free / null)."""
        return self._refs.get(page, 0)

    def referenced_pages(self) -> set[int]:
        """Every page with a live reference (any table or the prefix
        cache) — the complement of the free list over the usable arena."""
        return set(self._refs)

    # ------------------------------------------------------------------
    def alloc(
        self, owner: str, n_pages: int, *, shared: list[int] | None = None
    ) -> list[int]:
        """Allocate ``n_pages`` fresh pages to ``owner`` (cumulative per
        owner), optionally mapping ``shared`` already-referenced pages in
        front of them (a prefix hit: the owner's table starts with the
        cached prefix pages, then its own fresh tail).

        Raises :class:`CacheExhausted` without allocating anything — and
        without touching ``shared`` refcounts — when the free list cannot
        cover the request (all-or-nothing, so a failed admission never
        strands partial pages or dangling references)."""
        shared = list(shared or ())
        if n_pages < 0 or (n_pages == 0 and not shared):
            raise ValueError("n_pages must be >= 1 (or shared pages given)")
        if n_pages > len(self._free):
            self.stats.exhaustions += 1
            raise CacheExhausted(
                f"{n_pages} pages requested, {len(self._free)} free "
                f"(capacity {self.capacity})"
            )
        if shared:
            self.retain(shared)  # raises before any free-list mutation
        pages = [self._free.popleft() for _ in range(n_pages)]
        for p in pages:
            self._refs[p] = 1
        self._owned.setdefault(owner, []).extend(shared + pages)
        self.stats.allocs += 1
        self.stats.peak_pages_in_use = max(
            self.stats.peak_pages_in_use, self.used_pages
        )
        return shared + pages

    def alloc_raw(self, n_pages: int) -> list[int]:
        """Allocate pages carrying a bare reference and no owner record —
        the prefix cache and the CoW path settle these via
        :meth:`retain` / :meth:`release` directly instead of :meth:`free`.
        All-or-nothing like :meth:`alloc`."""
        if n_pages < 1:
            raise ValueError("n_pages must be >= 1")
        if n_pages > len(self._free):
            self.stats.exhaustions += 1
            raise CacheExhausted(
                f"{n_pages} pages requested, {len(self._free)} free "
                f"(capacity {self.capacity})"
            )
        pages = [self._free.popleft() for _ in range(n_pages)]
        for p in pages:
            self._refs[p] = 1
        self.stats.allocs += 1
        self.stats.peak_pages_in_use = max(
            self.stats.peak_pages_in_use, self.used_pages
        )
        return pages

    def retain(self, pages: list[int]) -> None:
        """Add one reference to each page (mapping it into another table).
        Raises :class:`PageAccountingError` on any unreferenced page —
        sharing a freed page would alias the free list."""
        for p in pages:
            if p not in self._refs:
                raise PageAccountingError(
                    f"retain of unreferenced page {p} (free or null)"
                )
        for p in pages:
            self._refs[p] += 1
        if pages:
            self.stats.shares += 1

    def release(self, pages: list[int]) -> int:
        """Drop one reference from each page; pages reaching zero return
        to the free list.  Returns how many pages were actually freed.
        Raises :class:`PageAccountingError` on an unreferenced page (the
        double-free / negative-refcount guard)."""
        freed = 0
        for p in pages:
            rc = self._refs.get(p, 0)
            if rc <= 0:
                raise PageAccountingError(
                    f"release of unreferenced page {p} (double free)"
                )
            if rc == 1:
                del self._refs[p]
                self._free.append(p)
                freed += 1
            else:
                self._refs[p] = rc - 1
        return freed

    def swap_owned(self, owner: str, old: int, new: int) -> None:
        """Replace ``old`` with ``new`` in the owner's page list (the CoW
        page-table swap).  Reference counts are the caller's to settle —
        this only fixes which pages :meth:`free` will release."""
        pages = self._owned.get(owner)
        if pages is None or old not in pages:
            raise PageAccountingError(
                f"swap_owned: owner {owner!r} does not hold page {old}"
            )
        pages[pages.index(old)] = new

    def free(self, owner: str) -> int:
        """Drop the owner's reference on every page it holds (shared pages
        survive under their remaining references); returns the count of
        pages actually freed (0 for an unknown owner — freeing twice is a
        no-op, not an error, because cancel and retirement can race
        benignly)."""
        pages = self._owned.pop(owner, None)
        if not pages:
            return 0
        freed = self.release(pages)
        self.stats.frees += 1
        return freed

    # ------------------------------------------------------------------
    def check_consistency(
        self, live_tables: dict[str, list[int]] | None = None
    ) -> None:
        """Assert the accounting invariants (test/debug hook; the property
        suite calls this after every random interleaving step):

          * free list and refcount table partition the usable arena —
            no page is both free and referenced, none is lost;
          * every refcount is positive;
          * the null page is never free, owned, or referenced;
          * every page in every live table (``live_tables`` — e.g. the
            engine's session page tables) carries a reference.
        """
        free = list(self._free)
        free_set = set(free)
        if len(free) != len(free_set):
            raise PageAccountingError("free list holds duplicate pages")
        overlap = free_set & set(self._refs)
        if overlap:
            raise PageAccountingError(
                f"pages both free and referenced: {sorted(overlap)[:8]}"
            )
        for p, rc in self._refs.items():
            if rc <= 0:
                raise PageAccountingError(f"non-positive refcount {rc} on page {p}")
        usable = set(range(1, self.num_pages))
        if free_set | set(self._refs) != usable:
            lost = usable - free_set - set(self._refs)
            raise PageAccountingError(f"pages lost from accounting: {sorted(lost)[:8]}")
        if self.NULL_PAGE in free_set or self.NULL_PAGE in self._refs:
            raise PageAccountingError("null page entered circulation")
        for owner, pages in (live_tables or {}).items():
            for p in pages:
                if p != self.NULL_PAGE and self._refs.get(p, 0) < 1:
                    raise PageAccountingError(
                        f"table {owner!r} maps unreferenced page {p}"
                    )
