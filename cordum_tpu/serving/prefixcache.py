"""Radix prefix cache: copy-on-write shared-prefix KV pages (ISSUE 18).

Every chat turn re-sends the whole conversation, and a thousand sessions
share the same system prompt — yet each one pays full prefill.  Because
``models/llama.ragged_step`` gathers only its own page-table row, two
sessions can point at the SAME physical page for free (the Ragged Paged
Attention argument, PAPERS.md); this module is the control-plane index
that makes that safe:

  * **radix keying** — a trie keyed by page-sized chunks of token ids.
    Each node maps one full page of tokens to one physical arena page;
    the PATH to a node is part of the key, because a page's K/V depends
    on every position before it (attention).  Only FULL pages are ever
    cached — a partial page's slots would be written by the next turn's
    divergent suffix, and full-page-only keying makes shared pages
    structurally read-only (the engine's CoW guard covers the one edge
    case where a hit ends exactly on a page boundary).
  * **refcounts, not reachability** — the cache holds one allocator
    reference per warm node (``PageAllocator.retain``); sessions mapping
    the page hold their own.  A page returns to the free list only at
    refcount zero, so eviction and retirement can interleave freely.
  * **LRU eviction under exhaustion** — the admission path calls
    :meth:`evict` when the free list cannot cover a footprint; eviction
    drops least-recently-used leaves whose page only the cache still
    references (dropping a page a live session shares would free
    nothing).  Cold leaves are dropped only when they block a warm
    ancestor — host-RAM records are cheap to keep.
  * **two tiers per node** — a node is *warm* (``page`` set, device
    resident) or *cold* (``record`` set: the PR 12 migration-format page
    record in host RAM, docs/PROTOCOL.md §Cold arena).  The tiering
    sweep (serving/tiering.py) demotes idle warm nodes; the engine's
    admission path re-warms cold nodes it hits (alloc + scatter).

The engine (serving/engine.py) drives everything from the worker's event
loop; like the allocator, this class does no internal locking.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from .pager import PageAllocator


@dataclass
class PrefixNode:
    """One cached full page of tokens, keyed by its path from the root."""

    chunk: tuple[int, ...]
    parent: Optional["PrefixNode"] = None
    depth: int = 0  # page ordinal: root=0, first chunk node=1, ...
    page: int = 0  # physical arena page when warm (0 = not warm)
    record: Optional[dict] = None  # PR 12 page record when cold
    children: dict = field(default_factory=dict)
    last_used: float = 0.0
    dropped: bool = False  # evicted while someone awaited on it

    @property
    def warm(self) -> bool:
        return self.page != 0

    @property
    def cold(self) -> bool:
        return self.record is not None and self.page == 0


@dataclass
class PrefixStats:
    hits: int = 0  # lookups matching >= 1 full page
    misses: int = 0
    hit_tokens: int = 0  # prompt tokens whose prefill was skipped
    registered_pages: int = 0
    evicted_pages: int = 0  # warm pages LRU-evicted back to the free list
    dropped_cold: int = 0  # cold records discarded
    hibernated_pages: int = 0  # warm -> cold demotions
    restored_pages: int = 0  # cold -> warm promotions


class PrefixCache:
    """Trie over token-id prefixes → refcounted physical pages."""

    def __init__(
        self,
        allocator: PageAllocator,
        *,
        metrics=None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.allocator = allocator
        self.page_size = allocator.page_size
        self.metrics = metrics
        self.clock = clock
        self._root = PrefixNode(chunk=())
        self._by_page: dict[int, PrefixNode] = {}  # warm nodes by page
        self.stats = PrefixStats()

    # ------------------------------------------------------------------
    @property
    def warm_pages(self) -> int:
        return len(self._by_page)

    @property
    def cold_pages(self) -> int:
        return sum(1 for n in self._walk() if n.cold)

    def _walk(self):
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            yield node

    def _gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.serving_prefix_pages.set(float(len(self._by_page)))

    # ------------------------------------------------------------------
    def match(self, tokens: list[int], *, touch: bool = True) -> list[PrefixNode]:
        """The longest cached path of full-page chunks prefixing
        ``tokens`` — warm AND cold nodes (the caller re-warms cold ones,
        truncating the match where a restore cannot proceed).  Touches
        every matched node (MRU), so an in-progress admission's path is
        never the eviction victim; observers (tier accounting) pass
        ``touch=False`` so reading residency never resets idleness."""
        now = self.clock()
        ps = self.page_size
        out: list[PrefixNode] = []
        node = self._root
        for i in range(len(tokens) // ps):
            child = node.children.get(tuple(tokens[i * ps:(i + 1) * ps]))
            if child is None:
                break
            if touch:
                child.last_used = now
            out.append(child)
            node = child
        return out

    def register(self, tokens: list[int], pages: list[int]) -> int:
        """Retain a retiring session's full pages under their token path.
        ``tokens`` are the positions actually written (prompt + generated
        output minus the never-fed final sample); ``pages`` the session's
        page table.  Existing warm nodes are kept (their page holds the
        identical K/V — same tokens, same deterministic forward pass);
        existing cold nodes re-warm from the live page for free.  Returns
        how many pages were newly retained."""
        now = self.clock()
        ps = self.page_size
        node = self._root
        fresh = 0
        for i in range(min(len(tokens) // ps, len(pages))):
            key = tuple(tokens[i * ps:(i + 1) * ps])
            child = node.children.get(key)
            if child is None:
                self.allocator.retain([pages[i]])
                child = PrefixNode(
                    chunk=key, parent=node, depth=i + 1,
                    page=pages[i], last_used=now,
                )
                node.children[key] = child
                self._by_page[pages[i]] = child
                fresh += 1
            else:
                child.last_used = now
                if child.cold:
                    # the retiring session carries this page live: adopt
                    # it instead of paying a restore scatter later
                    self.allocator.retain([pages[i]])
                    child.page = pages[i]
                    child.record = None
                    self._by_page[pages[i]] = child
                    self.stats.restored_pages += 1
                    fresh += 1
            node = child
        self.stats.registered_pages += fresh
        self._gauge()
        return fresh

    # ------------------------------------------------------------------
    def _leaves(self) -> list[PrefixNode]:
        return [n for n in self._walk() if not n.children]

    def _drop_leaf(self, node: PrefixNode) -> int:
        """Remove a childless node; returns device pages freed (0/1)."""
        freed = 0
        if node.warm:
            self._by_page.pop(node.page, None)
            freed = self.allocator.release([node.page])
            node.page = 0
            self.stats.evicted_pages += 1
            if self.metrics is not None:
                self.metrics.serving_prefix_evictions.inc(reason="capacity")
        elif node.cold:
            node.record = None
            self.stats.dropped_cold += 1
            if self.metrics is not None:
                self.metrics.serving_hibernate.inc(event="dropped")
        node.dropped = True
        if node.parent is not None:
            node.parent.children.pop(node.chunk, None)
        return freed

    def evict(self, n_pages: int, *, reason: str = "capacity") -> int:
        """LRU-evict cached prefixes until ``n_pages`` device pages are
        back on the free list (the exhaustion/admission-queue hook).
        Only pages the cache alone references are eligible — releasing a
        page a live session still maps frees nothing.  Cold leaves are
        dropped only when no warm leaf is evictable (they may be blocking
        a warm ancestor).  Returns pages actually freed."""
        freed = 0
        while freed < n_pages:
            warm = [
                n for n in self._leaves()
                if n.warm and self.allocator.refcount(n.page) == 1
            ]
            if warm:
                freed += self._drop_leaf(min(warm, key=lambda n: n.last_used))
                continue
            cold = [n for n in self._leaves() if not n.warm]
            if not cold:
                break  # every remaining leaf is shared by a live session
            self._drop_leaf(min(cold, key=lambda n: n.last_used))
        self._gauge()
        return freed

    def drop_subtree(self, page: int) -> int:
        """Drop the node holding ``page`` and everything under it (the
        CoW-under-exhaustion escape hatch: releasing the cache's
        reference may make the writer the sole owner, so no copy — and no
        fresh page — is needed).  Returns device pages freed."""
        node = self._by_page.get(page)
        if node is None:
            return 0
        freed = 0
        stack = [node]
        post: list[PrefixNode] = []
        while stack:
            n = stack.pop()
            post.append(n)
            stack.extend(n.children.values())
        for n in reversed(post):
            n.children.clear()
            freed += self._drop_leaf(n)
        self._gauge()
        return freed

    # ------------------------------------------------------------------
    # tiering hooks (serving/tiering.py drives these)
    def hibernate_candidates(self, cutoff: float) -> list[PrefixNode]:
        """Warm nodes idle since before ``cutoff`` that only the cache
        references — safe to demote without touching any live table."""
        return sorted(
            (
                n for n in self._walk()
                if n.warm and n.last_used < cutoff
                and self.allocator.refcount(n.page) == 1
            ),
            key=lambda n: n.last_used,
        )

    def demote(self, node: PrefixNode, record: dict) -> bool:
        """Finish hibernating ``node``: swap its device page for the
        exported ``record`` and release the page.  Returns False (no
        release) when the node was evicted or gained a live sharer while
        the export was in flight — the caller simply keeps it warm."""
        if node.dropped or not node.warm:
            return False
        if self.allocator.refcount(node.page) > 1:
            return False
        self._by_page.pop(node.page, None)
        self.allocator.release([node.page])
        node.record = record
        node.page = 0
        self.stats.hibernated_pages += 1
        if self.metrics is not None:
            self.metrics.serving_hibernate.inc(event="hibernated")
        self._gauge()
        return True

    def promote(self, node: PrefixNode, page: int) -> None:
        """Finish restoring ``node``: the caller scattered its record
        into freshly allocated ``page`` (carrying a bare reference)."""
        node.page = page
        node.record = None
        node.last_used = self.clock()
        self._by_page[page] = node
        self.stats.restored_pages += 1
        if self.metrics is not None:
            self.metrics.serving_hibernate.inc(event="restored")
        self._gauge()
