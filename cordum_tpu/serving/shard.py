"""Tensor-parallel serving over a gang of workers — the sharded half of the
serving backend (docs/SERVING.md §Sharded serving).

One session set, N ranks: a ``serving`` gang (docs/GANG.md) reserves N
co-located workers all-or-nothing through the DeviceLedger, the members
rendezvous, and every rank runs the SAME ragged mixed prefill+decode
program (``models/llama.ragged_step``) over its slice of the model:

  * weights shard Megatron-style per :func:`~cordum_tpu.models.llama.
    param_specs` (column-parallel qkv/gate, row-parallel out/down);
  * both KV page arenas shard by attention head —
    ``[L, num_pages, page_size, kvh, hd]`` split on ``kvh`` — matching the
    column-parallel wk/wv layout so page writes and gathers stay local;
  * **rank 0 alone pays sampling**: follower ranks compile with
    ``sample_logits=False`` (the lm_head projection + argmax are
    dead-code-eliminated) and own nothing but their arena shard.  Rank 0
    owns token streaming, admission, and the session registry.

Mesh construction is capability-gated: on real multi-chip hardware
:func:`rank_mesh` builds the jax.distributed / multi-device TP mesh and the
arenas genuinely split; on the 1-chip CPU CI host every rank holds a FULL
local replica on a trivial mesh (the PR 15 gang-training fallback) — the
rank-role split, the replay protocol, the per-rank record format and the
compile-count ceiling are all still exercised for real, only the memory
saving is simulated.

Per-rank migration records: :meth:`ShardedServingBackend.export_kv` slices
every PR 12 page record along the head axis and stamps a
``rank``/``tp``/``heads: [lo, hi)`` header;
:func:`merge_rank_records` (called from the base backend's ``import_kv``)
reassembles full-head records from any rank order — so drain, failover,
hand-off, hibernation and the prefix cache keep working when a session's
pages live on N arenas, and a gang export imports into a single-rank
backend (and vice versa) unchanged.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Optional

import numpy as np

from .backend import LlamaServingBackend, StepEntry

__all__ = [
    "heads_for_rank",
    "slice_rank_record",
    "merge_rank_records",
    "entry_to_wire",
    "entry_from_wire",
    "ShardedServingBackend",
    "ServingGangGroup",
]


def heads_for_rank(n_kv_heads: int, tp: int, rank: int) -> tuple[int, int]:
    """The contiguous ``[lo, hi)`` KV-head slice rank ``rank`` owns under a
    ``tp``-way split.  Heads must divide evenly — ragged head splits would
    break the NamedSharding layout."""
    if tp < 1 or not 0 <= rank < tp:
        raise ValueError(f"rank {rank} outside tp={tp}")
    if n_kv_heads % tp:
        raise ValueError(f"{n_kv_heads} kv heads not divisible by tp={tp}")
    per = n_kv_heads // tp
    return rank * per, (rank + 1) * per


def slice_rank_record(rec: dict, rank: int, tp: int, lo: int, hi: int) -> dict:
    """One rank's head slice of a full PR 12 page record.  The wire shape
    stays ``[L, used, heads, hd]`` float32; the header grows ``rank`` /
    ``tp`` / ``heads=[lo, hi)`` so the importer knows where the slice
    lands."""
    shape = tuple(rec["shape"])
    k = np.frombuffer(rec["k"], np.float32).reshape(shape)[:, :, lo:hi]
    v = np.frombuffer(rec["v"], np.float32).reshape(shape)[:, :, lo:hi]
    return {
        "i": rec["i"], "used": rec["used"],
        "k": np.ascontiguousarray(k).tobytes(),
        "v": np.ascontiguousarray(v).tobytes(),
        "shape": list(k.shape),
        "rank": rank, "tp": tp, "heads": [lo, hi],
    }


def merge_rank_records(records: list[dict]) -> list[dict]:
    """Reassemble full-head page records from a per-rank gang export.

    Groups by page ordinal ``i``, orders each group by ``heads[0]``,
    concatenates along the head axis, and checks the slices tile the head
    dimension exactly (contiguous, no gap, no overlap).  Plain full-head
    records pass through untouched, so a mixed list (e.g. a gang export
    appended to a single-rank prefix) merges correctly too."""
    plain = [r for r in records if "heads" not in r]
    sliced = [r for r in records if "heads" in r]
    by_ord: dict[int, list[dict]] = {}
    for rec in sliced:
        by_ord.setdefault(int(rec["i"]), []).append(rec)
    out = list(plain)
    for o, group in sorted(by_ord.items()):
        group = sorted(group, key=lambda r: int(r["heads"][0]))
        ks, vs, cursor = [], [], 0
        for rec in group:
            lo, hi = (int(x) for x in rec["heads"])
            if lo != cursor:
                raise ValueError(
                    f"page {o}: head slice [{lo}, {hi}) does not start at "
                    f"{cursor} — rank records missing or overlapping"
                )
            shape = tuple(rec["shape"])
            ks.append(np.frombuffer(rec["k"], np.float32).reshape(shape))
            vs.append(np.frombuffer(rec["v"], np.float32).reshape(shape))
            cursor = hi
        tp = int(group[0].get("tp", len(group)))
        if len(group) != tp:
            raise ValueError(
                f"page {o}: {len(group)} rank slices for tp={tp}"
            )
        k = np.concatenate(ks, axis=2)
        v = np.concatenate(vs, axis=2)
        out.append({
            "i": o, "used": int(group[0]["used"]),
            "k": np.ascontiguousarray(k).tobytes(),
            "v": np.ascontiguousarray(v).tobytes(),
            "shape": list(k.shape),
        })
    out.sort(key=lambda r: int(r["i"]))
    return out


# ---------------------------------------------------------------------------
# StepEntry wire codec — the serving-gang replay protocol rides GangMsg
# (kind="step") stats dicts, so entries must round-trip through msgpack
# ---------------------------------------------------------------------------


def entry_to_wire(e: StepEntry) -> dict:
    return {
        "tokens": [int(t) for t in e.tokens], "start": int(e.start),
        "pages": [int(p) for p in e.pages], "sample": bool(e.sample),
        "phase": e.phase, "key": e.key, "draft": int(e.draft),
    }


def entry_from_wire(d: dict) -> StepEntry:
    return StepEntry(
        tokens=list(d.get("tokens") or []), start=int(d.get("start", 0)),
        pages=list(d.get("pages") or []), sample=bool(d.get("sample", True)),
        phase=str(d.get("phase", "decode")), key=str(d.get("key", "")),
        draft=int(d.get("draft", 0)),
    )


def rank_mesh(tp: int):
    """The TP mesh this rank's program runs over.

    On hardware with enough devices this is the real ``tp``-way mesh
    (multi-host when ``jax.distributed`` has been initialized — every
    process then contributes its local chips to the global device list).
    On the CPU CI host (1 device) it degenerates to a size-1 mesh and the
    rank holds a full replica — the PR 15 gang fallback."""
    import jax

    from ..parallel.mesh import simple_mesh

    n = len(jax.devices())
    if tp > 1 and n >= tp and n % tp == 0:
        return simple_mesh(tp)
    return simple_mesh(1)


def init_distributed(coordinator: str, num_processes: int, process_id: int) -> bool:
    """Join the multi-host ``jax.distributed`` mesh — the real-hardware
    rendezvous path (one call per gang member before the first device op).
    Returns False (and leaves the local backend untouched) when the runtime
    lacks distributed support or the coordinator is unreachable, which is
    the expected outcome on the CPU CI host."""
    try:
        import jax

        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
        return True
    except Exception:  # noqa: BLE001 - CPU CI / already-initialized fallback
        return False


class ShardedServingBackend(LlamaServingBackend):
    """One rank of a tensor-parallel serving gang.

    Identical step semantics to :class:`LlamaServingBackend` — same static
    shapes, same ONE compiled program (per rank) — plus:

      * ``rank``/``tp`` identity and the rank's ``[lo, hi)`` KV-head slice;
      * weights + arenas placed with NamedSharding over :func:`rank_mesh`
        (full local replica on the 1-chip CI fallback);
      * follower ranks (``rank > 0``) compile with ``sample_logits=False``
        — lm_head never runs there;
      * :meth:`export_kv` emits per-rank head-sliced records (the importer
        side needs no override: the base ``import_kv`` merges them).
    """

    def __init__(self, cfg: Any = None, *, rank: int = 0, tp: int = 1,
                 sample_output: Optional[bool] = None, **kw: Any) -> None:
        super().__init__(cfg, **kw)
        self.rank = int(rank)
        self.tp = max(1, int(tp))
        self.heads = heads_for_rank(self.cfg.n_kv_heads, self.tp, self.rank)
        # rank 0 owns sampling unless the caller says otherwise (the
        # in-process oracle in bench --tp samples on every rank to prove
        # follower outputs are genuinely unused)
        self.sample_output = (self.rank == 0) if sample_output is None else bool(sample_output)
        self.mesh: Any = None

    def _place_state(self, params: Any, k_pages: Any, v_pages: Any):
        from ..models import llama

        self.mesh = rank_mesh(self.tp)
        return llama.shard_serving_state(
            params, k_pages, v_pages, self.cfg, self.mesh
        )

    def export_kv(self, pages: list[int], start_tok: int, end_tok: int) -> list[dict]:
        """This rank's head slice of every page record.  A gang's full
        export is the concatenation over ranks — any order; the importer's
        merge sorts by ``heads``.  With ``tp == 1`` the plain full-head
        records ship unchanged."""
        records = super().export_kv(pages, start_tok, end_tok)
        if self.tp <= 1:
            return records
        lo, hi = self.heads
        return [slice_rank_record(r, self.rank, self.tp, lo, hi) for r in records]


class ServingGangGroup:
    """An in-process TP serving gang: rank 0 (the leader, sampling) plus
    ``tp - 1`` followers, driven lock-step and quacking like a single
    backend — the engine, bench ``--tp`` and the property suite use it
    exactly where a :class:`LlamaServingBackend` goes.

    Every rank replays the identical entry batch, so the arenas stay in
    step by construction; step results come from the leader alone (the
    followers' zero buffers are discarded — on real hardware they are never
    even materialized).  Cross-process gangs (worker/gang.py
    ``_run_serving``) are this same loop with the follower ``step()`` calls
    shipped over the bus as ``GangMsg(kind="step")``.
    """

    supports_draft = True
    on_step: Optional[Callable[[list[StepEntry]], None]] = None

    def __init__(self, cfg: Any = None, *, tp: int = 2, metrics: Any = None,
                 **kw: Any) -> None:
        if tp < 1:
            raise ValueError(f"tp={tp}")
        # metrics ride on the leader only: the group is ONE serving
        # position, and per-rank compile counts stay observable through
        # compiled_per_rank()
        self.ranks = [
            ShardedServingBackend(
                cfg, rank=r, tp=tp, metrics=metrics if r == 0 else None, **kw
            )
            for r in range(tp)
        ]
        self.tp = tp
        self._lock = threading.Lock()

    # -- backend facade ------------------------------------------------
    @property
    def leader(self) -> ShardedServingBackend:
        return self.ranks[0]

    @property
    def cfg(self):
        return self.leader.cfg

    @property
    def page_size(self) -> int:
        return self.leader.page_size

    @property
    def num_pages(self) -> int:
        return self.leader.num_pages

    @property
    def max_context(self) -> int:
        return self.leader.max_context

    @property
    def pages_per_seq(self) -> int:
        return self.leader.pages_per_seq

    @property
    def max_seqs(self) -> int:
        return self.leader.max_seqs

    @property
    def max_batch_tokens(self) -> int:
        return self.leader.max_batch_tokens

    @property
    def last_step_compiled(self) -> bool:
        # any rank paying XLA makes the step a warmup step for the
        # capacity observatory's steady-state filter
        return any(r.last_step_compiled for r in self.ranks)

    def compiled_programs(self) -> int:
        return self.leader.compiled_programs()

    def compiled_per_rank(self) -> list[int]:
        return [r.compiled_programs() for r in self.ranks]

    # -- lock-step execution -------------------------------------------
    def step(self, entries: list[StepEntry]) -> list[Any]:
        with self._lock:
            res = self.leader.step(entries)
            for follower in self.ranks[1:]:
                follower.step(entries)
        if self.on_step is not None:
            self.on_step(entries)
        return res

    def export_kv(self, pages: list[int], start_tok: int, end_tok: int) -> list[dict]:
        out: list[dict] = []
        with self._lock:
            for r in self.ranks:
                out.extend(r.export_kv(pages, start_tok, end_tok))
        return out

    def import_kv(self, pages: list[int], records: list[dict]) -> None:
        # each rank imports the merged full-head records; on real sharded
        # hardware the device_put under NamedSharding lands only the local
        # head slice on each rank's chips
        with self._lock:
            for r in self.ranks:
                r.import_kv(pages, records)

    def copy_page(self, src: int, dst: int) -> None:
        with self._lock:
            for r in self.ranks:
                r.copy_page(src, dst)

    # -- compat conveniences (same contracts as the base backend) ------
    def prefill(self, prompt: list[int], pages: list[int]) -> int:
        return LlamaServingBackend.prefill(self, prompt, pages)  # type: ignore[arg-type]

    def decode(self, entries: list[tuple[int, int, list[int]]]) -> list[int]:
        return LlamaServingBackend.decode(self, entries)  # type: ignore[arg-type]
