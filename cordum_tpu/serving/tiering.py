"""Session tiering: hibernate idle conversations to a host-RAM cold arena.

The other half of ISSUE 18.  Prefix caching (serving/prefixcache.py)
keeps a retired conversation's KV pages resident so the next turn skips
their prefill — but resident-in-HBM caps how many conversations a worker
can hold at ``serving_cache_pages``.  Chat think time is measured in
minutes; device memory should not be.  This module tiers idle resident
state down:

  * **hibernate** — :class:`SessionTiering.sweep` demotes warm prefix
    nodes idle past ``hibernate_after_s``: each page is exported with the
    exact PR 12 migration record format (``{"i", "used", "k", "v",
    "shape"}``, float32-upcast — docs/PROTOCOL.md §Page transfer) into
    host RAM and released back to the allocator.  The trie keeps the node
    as *cold*; max-resident-sessions becomes a cold-storage bound, not an
    HBM bound.
  * **restore** — the next turn's admission (same ``cordum.session_key``,
    routed back here by the pinned affinity entry) hits the cold node:
    the engine allocates a fresh page, scatters the record back, and the
    prefill skip proceeds as if the page had never left.  The restore
    pause is the alloc + scatter, measured by
    ``cordum_serving_hibernate_pause_seconds``.
  * **live sessions** — :class:`ColdArena` also stores whole frozen
    sessions (``ServingEngine.hibernate_session``): freeze → quiesce →
    export state + pages → retire ``reason="hibernated"``; restore rides
    the existing ``install_session`` path and replays carried tokens at
    offset 0, so offset-deduping stream consumers see an exactly-once
    sequence across the gap.

The registry also answers "how many conversations are resident here" for
the capacity beacon (warm = every page of the newest turn still in the
device arena; cold = at least one page tiered out).
"""
from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Optional

import msgpack

from ..infra import logging as logx
from .prefixcache import PrefixCache


class ColdArena:
    """Host-RAM store for hibernated state, with byte accounting.

    Keys are opaque (the engine uses ``job_id`` for live sessions); a
    value is the migration-format doc ``{"meta", "state", "records"}``.
    A per-process byte cap would slot in here; for now the bound is the
    host's RAM, which is the point — it is not the device arena."""

    def __init__(self) -> None:
        self._store: dict[str, dict] = {}
        self.bytes = 0

    @staticmethod
    def _doc_bytes(doc: dict) -> int:
        return sum(
            len(rec.get("k", b"")) + len(rec.get("v", b""))
            for rec in doc.get("records", ())
        )

    def put(self, key: str, doc: dict) -> None:
        self.pop(key)
        self._store[key] = doc
        self.bytes += self._doc_bytes(doc)

    def get(self, key: str) -> Optional[dict]:
        return self._store.get(key)

    def pop(self, key: str) -> Optional[dict]:
        doc = self._store.pop(key, None)
        if doc is not None:
            self.bytes -= self._doc_bytes(doc)
        return doc

    def __contains__(self, key: str) -> bool:
        return key in self._store

    def __len__(self) -> int:
        return len(self._store)


COLD_TIER_PREFIX = "serving:cold:"


class StatebusColdTier(ColdArena):
    """Cold arena mirrored through the statebus KV so hibernated sessions
    survive a worker restart (``serving_cold_tier: statebus``,
    docs/SERVING.md §Session tiering).

    The RAM copy stays authoritative on the hot path — ``put``/``pop``/
    ``get`` cost exactly what :class:`ColdArena` costs — while every
    mutation is journaled to ``serving:cold:<worker_id>:<key>`` by a
    fire-and-forget drain task (hibernation must never block on the bus;
    a persist failure only narrows restart durability, counted in
    ``persist_errors``).  On boot the worker calls :meth:`load` after
    ``start()``: surviving keys re-populate the mirror, the normal
    ``restore_hibernated`` path re-admits them on the session's next
    turn, and the live copy always wins over a stale journal.  Docs are
    msgpack — the PR 12 record format is bytes + scalars by design, so
    the page payloads round-trip without re-encoding."""

    def __init__(self, kv, *, prefix: str = COLD_TIER_PREFIX,
                 worker_id: str = "") -> None:
        super().__init__()
        self.kv = kv
        scope = f"{worker_id}:" if worker_id else ""
        self.prefix = f"{prefix}{scope}"
        # key -> doc (persist) or None (delete); insertion order preserved
        self._dirty: dict[str, Optional[dict]] = {}
        self._drain_task: Optional[asyncio.Task] = None
        self.persist_errors = 0
        self.loaded = 0

    # -- hot path (sync, mirrors ColdArena) ----------------------------
    def put(self, key: str, doc: dict) -> None:
        super().put(key, doc)
        self._mark(key, doc)

    def pop(self, key: str) -> Optional[dict]:
        doc = super().pop(key)
        if doc is not None:
            self._mark(key, None)
        return doc

    def _mark(self, key: str, doc: Optional[dict]) -> None:
        self._dirty[key] = doc
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # no loop (sync tests): flush() persists later
        if self._drain_task is None or self._drain_task.done():
            self._drain_task = loop.create_task(self._drain())

    # -- bus side ------------------------------------------------------
    async def _drain(self) -> None:
        while self._dirty:
            key, doc = next(iter(self._dirty.items()))
            del self._dirty[key]
            try:
                if doc is None:
                    await self.kv.delete(self.prefix + key)
                else:
                    await self.kv.set(
                        self.prefix + key,
                        msgpack.packb(doc, use_bin_type=True),
                    )
            except Exception as e:  # noqa: BLE001 - durability is best-effort
                self.persist_errors += 1
                logx.warn("cold-tier persist failed", key=key, err=str(e))

    async def flush(self) -> None:
        """Await every pending persist — the deterministic hook tests and
        the drain path use before asserting on the bus copy."""
        while self._dirty or (
            self._drain_task is not None and not self._drain_task.done()
        ):
            if self._drain_task is not None and not self._drain_task.done():
                await self._drain_task
            elif self._dirty:
                await self._drain()

    async def load(self) -> int:
        """Re-populate the RAM mirror from the journal (worker boot, after
        the bus is up).  A key already live in RAM wins over the journal;
        an unreadable doc is dropped and counted.  Returns docs loaded."""
        n = 0
        for full in await self.kv.keys(self.prefix):
            key = full[len(self.prefix):]
            if key in self:
                continue
            raw = await self.kv.get(full)
            if raw is None:
                continue
            try:
                doc = msgpack.unpackb(raw, raw=False)
            except Exception as e:  # noqa: BLE001 - a bad doc must not block boot
                self.persist_errors += 1
                logx.warn("cold-tier doc unreadable", key=key, err=str(e))
                continue
            ColdArena.put(self, key, doc)  # mirror only: no re-persist
            n += 1
        self.loaded += n
        if n:
            logx.info("cold tier restored", docs=n, bytes=self.bytes)
        return n


@dataclass
class _Resident:
    """One conversation with restorable KV on this worker."""

    tokens: tuple  # the newest turn's written positions (the trie path key)
    last_used: float
    turns: int = 0
    cold_notified: bool = False  # on_hibernated fired for the current idle


@dataclass
class TieringStats:
    sweeps: int = 0
    hibernated_pages: int = 0
    restored_pages: int = 0
    sessions_hibernated: int = 0  # resident entries that went cold
    sessions_restored: int = 0


class SessionTiering:
    """Owns the resident-session registry and the hibernate sweep; one
    per engine, driven from the worker's event loop."""

    def __init__(
        self,
        cache: PrefixCache,
        *,
        hibernate_after_s: float = 0.0,
        export_page: Optional[
            Callable[[int], Awaitable[Optional[dict]]]
        ] = None,
        metrics=None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.cache = cache
        self.hibernate_after_s = max(0.0, hibernate_after_s)
        # async page -> PR 12 record (the engine wraps backend.export_kv);
        # None = backend cannot export (test fakes): sweep is a no-op
        self.export_page = export_page
        self.metrics = metrics
        self.clock = clock
        self.arena = ColdArena()  # live-session cold storage
        self._resident: dict[str, _Resident] = {}
        self.stats = TieringStats()
        # the worker publishes SessionMoved(reason="hibernated") from this
        # hook so the scheduler pins the session's affinity entry past the
        # normal TTL (strategy.py SESSION_HIBERNATE_TTL_S) — a cold
        # session routed elsewhere would silently re-prefill from scratch
        self.on_hibernated: Optional[Callable[[str], None]] = None
        self.on_restored: Optional[Callable[[str], None]] = None

    # ------------------------------------------------------------------
    @property
    def resident_sessions(self) -> int:
        return len(self._resident)

    def tier_counts(self) -> tuple[int, int]:
        """(warm, cold) resident conversations.  Walks each entry's trie
        path — O(resident × pages/session), called from the capacity
        beacon's periodic snapshot, not any per-token path."""
        warm = 0
        for ent in self._resident.values():
            nodes = self.cache.match(list(ent.tokens), touch=False)
            if nodes and all(n.warm for n in nodes):
                warm += 1
        return warm, len(self._resident) - warm

    def note_turn(self, session_key: str, tokens: list[int]) -> None:
        """A turn for ``session_key`` just retired with its full pages
        registered in the prefix cache: (re)mark the conversation
        resident.  ``tokens`` are the turn's written positions — the key
        the next turn's prompt will extend."""
        if not session_key:
            return
        ent = self._resident.get(session_key)
        if ent is None:
            ent = self._resident[session_key] = _Resident(
                tokens=(), last_used=0.0
            )
        ent.tokens = tuple(tokens)
        ent.last_used = self.clock()
        ent.turns += 1
        ent.cold_notified = False
        self._gauge()

    def touch(self, session_key: str) -> None:
        """A new turn arrived: the conversation is active again."""
        ent = self._resident.get(session_key)
        if ent is not None:
            ent.last_used = self.clock()
            was_cold = ent.cold_notified
            ent.cold_notified = False
            if was_cold:
                self.stats.sessions_restored += 1
                if self.on_restored is not None:
                    try:
                        self.on_restored(session_key)
                    except Exception as e:  # noqa: BLE001 - hook is best-effort
                        logx.warn("on_restored hook failed", err=str(e))

    def forget(self, session_key: str) -> None:
        self._resident.pop(session_key, None)
        self._gauge()

    def _gauge(self) -> None:
        if self.metrics is not None:
            warm, cold = self.tier_counts()
            self.metrics.serving_resident_sessions.set(float(warm), tier="warm")
            self.metrics.serving_resident_sessions.set(float(cold), tier="cold")

    # ------------------------------------------------------------------
    async def sweep(self, now: Optional[float] = None) -> int:
        """Demote warm prefix nodes idle past the threshold: export each
        page to its host-RAM record, then release the device page.  The
        export awaits the device, so every demotion re-checks that the
        node was not evicted — and did not gain a live sharer — while the
        gather was in flight (``PrefixCache.demote`` refuses otherwise).
        Returns pages demoted."""
        if self.hibernate_after_s <= 0 or self.export_page is None:
            return 0
        now = self.clock() if now is None else now
        cutoff = now - self.hibernate_after_s
        demoted = 0
        for node in self.cache.hibernate_candidates(cutoff):
            try:
                record = await self.export_page(node.page)
            except Exception as e:  # noqa: BLE001 - keep the node warm
                logx.warn("hibernate export failed", page=node.page, err=str(e))
                continue
            if record is None:
                continue
            if self.cache.demote(node, record):
                demoted += 1
        self.stats.sweeps += 1
        self.stats.hibernated_pages += demoted
        if demoted:
            self._notify_cold(now)
            self._gauge()
        return demoted

    def _notify_cold(self, now: float) -> None:
        """Fire on_hibernated once per conversation that just went cold
        (any path node demoted) — the affinity-pinning trigger."""
        cutoff = now - self.hibernate_after_s
        for key, ent in self._resident.items():
            if ent.cold_notified or ent.last_used >= cutoff:
                continue
            nodes = self.cache.match(list(ent.tokens), touch=False)
            if not nodes or all(n.warm for n in nodes):
                continue
            ent.cold_notified = True
            self.stats.sessions_hibernated += 1
            if self.on_hibernated is not None:
                try:
                    self.on_hibernated(key)
                except Exception as e:  # noqa: BLE001 - hook is best-effort
                    logx.warn("on_hibernated hook failed", err=str(e))
