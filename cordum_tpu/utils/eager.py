"""Eager coroutine completion: skip task/timer plumbing for coroutines that
never actually suspend.

Most hot-path awaits in the 1×1 control plane complete synchronously — an
in-process safety kernel with a warm cache, a MemoryKV op on an uncontended
lock, a loopback-bus publish with no slow subscriber.  Wrapping each of
those in ``asyncio.wait_for``/``asyncio.gather`` still costs a Task object,
a TimerHandle, and two loop callbacks per call, which was a measurable
slice of the scheduler hot path (ISSUE 6).

``eager(coro)`` advances a coroutine to its first *real* suspension point:

* completed → ``(True, result)`` — no Task, no timer, no loop round trip;
* suspended → ``(False, continuation)`` where the continuation is an
  awaitable that resumes the already-started coroutine with full exception
  and cancellation pass-through (the same protocol a Task speaks).

Synchronous exceptions propagate out of ``eager`` exactly as they would out
of the first ``await``.

CONTEXTVAR CAVEAT: the eager phase runs in the *caller's* context while the
continuation runs in whatever Task later drives it.  A coroutine that holds
a contextvar across its first suspension therefore executes split across
two contexts — ``ContextVar.reset(token)`` would raise.  Only use ``eager``
on coroutines whose contextvar windows are suspension-free (the tracer's
span context uses value-restore, not tokens, to stay benign here).
"""
from __future__ import annotations

import types
from typing import Any, Coroutine


def eager(coro: Coroutine) -> tuple[bool, Any]:
    """Run ``coro`` to its first suspension.  → ``(True, result)`` if it
    finished synchronously, else ``(False, continuation_awaitable)``."""
    try:
        first = coro.send(None)
    except StopIteration as si:
        return True, si.value
    return False, _drive(coro, first)


@types.coroutine
def _drive(coro: Coroutine, fut: Any):
    """Continue a coroutine that already yielded its first future.

    Pass-through of the Task protocol: re-yield each future the coroutine
    parks on, feed results back in, forward thrown exceptions (including
    cancellation) so ``finally`` blocks inside ``coro`` run normally."""
    while True:
        try:
            value = yield fut
        except BaseException as e:  # noqa: BLE001 - full pass-through
            try:
                fut = coro.throw(e)
            except StopIteration as si:
                return si.value
            continue
        try:
            fut = coro.send(value)
        except StopIteration as si:
            return si.value


async def eager_gather(coros: list[Coroutine]) -> None:
    """Gather for fire-and-forget coroutines that usually complete eagerly:
    each runs synchronously to its first real suspension; only the ones
    that actually suspend get Tasks.  Results are discarded (call sites
    handle their own errors); a synchronous exception propagates
    immediately, like the first ``await`` of a plain gather."""
    import asyncio

    conts: list[Any] = []
    for c in coros:
        done, r = eager(c)
        if not done:
            conts.append(r)
    if conts:
        await asyncio.gather(*conts)
