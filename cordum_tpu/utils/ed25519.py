"""Pure-Python Ed25519 (RFC 8032) — verify-first fallback for signed policy
bundles.

The safety kernel's signed-policy path normally verifies with the
``cryptography`` backend; on hosts without it (minimal TPU worker images),
verification must still be possible — otherwise "library missing" silently
degrades into deny-all forever even when a valid signed policy is present.
This module is stdlib-only (``hashlib`` + big ints) and fast enough for the
kernel's cold reload path (~1 ms/verify on CPython 3.10).

Signing support exists for tests and tooling; production signing should use
the ``cryptography`` backend or an external signer.
"""
from __future__ import annotations

import hashlib
import os

_P = 2**255 - 19
_L = 2**252 + 27742317777372353535851937790883648493
_D = -121665 * pow(121666, _P - 2, _P) % _P
_I = pow(2, (_P - 1) // 4, _P)

Point = tuple[int, int, int, int]  # extended homogeneous (X, Y, Z, T)


def _inv(x: int) -> int:
    return pow(x, _P - 2, _P)


def _xrecover(y: int) -> int:
    xx = (y * y - 1) * _inv(_D * y * y + 1) % _P
    x = pow(xx, (_P + 3) // 8, _P)
    if (x * x - xx) % _P != 0:
        x = x * _I % _P
    if (x * x - xx) % _P != 0:
        raise ValueError("point not on curve")
    if x % 2 != 0:
        x = _P - x
    return x


_BY = 4 * _inv(5) % _P
_BX = _xrecover(_BY)
_BASE: Point = (_BX, _BY, 1, _BX * _BY % _P)
_ZERO: Point = (0, 1, 1, 0)


def _add(p: Point, q: Point) -> Point:
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % _P
    b = (y1 + x1) * (y2 + x2) % _P
    c = t1 * 2 * _D % _P * t2 % _P
    d = z1 * 2 * z2 % _P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % _P, g * h % _P, f * g % _P, e * h % _P)


def _scalarmult(p: Point, e: int) -> Point:
    q = _ZERO
    while e:
        if e & 1:
            q = _add(q, p)
        p = _add(p, p)
        e >>= 1
    return q


def _compress(p: Point) -> bytes:
    x, y, z, _ = p
    zi = _inv(z)
    x, y = x * zi % _P, y * zi % _P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _on_curve(p: Point) -> bool:
    x, y, z, t = p
    return (
        z % _P != 0
        and x * y % _P == z * t % _P
        and (y * y - x * x - z * z - _D * t * t) % _P == 0
    )


def _decompress(s: bytes) -> Point:
    if len(s) != 32:
        raise ValueError("point must be 32 bytes")
    n = int.from_bytes(s, "little")
    y = n & ((1 << 255) - 1)
    if y >= _P:
        raise ValueError("y coordinate out of range")
    x = _xrecover(y)
    if x & 1 != n >> 255:
        x = _P - x
    pt: Point = (x, y, 1, x * y % _P)
    if not _on_curve(pt):
        raise ValueError("point not on curve")
    return pt


def _clamp(h32: bytes) -> int:
    a = int.from_bytes(h32, "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def _hint(*chunks: bytes) -> int:
    return int.from_bytes(hashlib.sha512(b"".join(chunks)).digest(), "little")


def public_key(seed: bytes) -> bytes:
    """Derive the 32-byte public key from a 32-byte seed."""
    if len(seed) != 32:
        raise ValueError("seed must be 32 bytes")
    h = hashlib.sha512(seed).digest()
    return _compress(_scalarmult(_BASE, _clamp(h[:32])))


def sign(seed: bytes, message: bytes) -> bytes:
    """Detached 64-byte Ed25519 signature of ``message`` under ``seed``."""
    h = hashlib.sha512(seed).digest()
    a, prefix = _clamp(h[:32]), h[32:]
    pub = _compress(_scalarmult(_BASE, a))
    r = _hint(prefix, message) % _L
    r_enc = _compress(_scalarmult(_BASE, r))
    k = _hint(r_enc, pub, message) % _L
    s = (r + k * a) % _L
    return r_enc + s.to_bytes(32, "little")


def verify(public_key_bytes: bytes, signature: bytes, message: bytes) -> bool:
    """True iff ``signature`` is a valid Ed25519 signature of ``message``.

    Malformed keys/signatures return False (never raise): callers treat any
    verification problem as fail-closed.
    """
    try:
        if len(signature) != 64:
            return False
        a_pt = _decompress(public_key_bytes)
        r_pt = _decompress(signature[:32])
        s = int.from_bytes(signature[32:], "little")
        if s >= _L:
            return False
        k = _hint(signature[:32], _compress(a_pt), message) % _L
        lhs = _scalarmult(_BASE, s)
        rhs = _add(r_pt, _scalarmult(a_pt, k))
        return _compress(lhs) == _compress(rhs)
    except ValueError:
        return False


class SigningKey:
    """Minimal stand-in for ``cryptography``'s Ed25519PrivateKey (tests/tools)."""

    def __init__(self, seed: bytes | None = None):
        self._seed = seed if seed is not None else os.urandom(32)
        if len(self._seed) != 32:
            raise ValueError("seed must be 32 bytes")

    def sign(self, message: bytes) -> bytes:
        return sign(self._seed, message)

    def public_key_bytes(self) -> bytes:
        return public_key(self._seed)
