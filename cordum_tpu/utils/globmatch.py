"""Glob matching for policy topic patterns and bus subjects.

Policy rules match topics with shell-style globs (``job.*`` matches
``job.default`` but also ``job.a.b`` under fnmatch semantics; the reference
uses Go ``path.Match``-style matching where ``*`` does not cross ``.``).
We implement segment-aware matching: ``*`` matches exactly one dot-delimited
token, ``>`` matches one-or-more trailing tokens (NATS semantics), and a
pattern without wildcards must match exactly.  ``glob_match`` additionally
supports ``*`` inside a token (prefix/suffix globs like ``deploy-*``).
"""
from __future__ import annotations

import fnmatch


def subject_match(pattern: str, subject: str) -> bool:
    """NATS-style subject matching: ``*`` = one token, ``>`` = tail."""
    if pattern == subject:
        return True
    ptoks = pattern.split(".")
    stoks = subject.split(".")
    for i, p in enumerate(ptoks):
        if p == ">":
            return len(stoks) >= i + 1
        if i >= len(stoks):
            return False
        if p != "*" and p != stoks[i]:
            return False
    return len(ptoks) == len(stoks)


def glob_match(pattern: str, value: str) -> bool:
    """Policy-style glob: fnmatch per dot-segment; bare ``*``/``>`` wildcards.

    ``job.*`` matches ``job.echo`` but not ``job.a.b``;
    ``job.>`` matches any deeper subject; ``deploy-*`` matches ``deploy-prod``.
    """
    if pattern == value or pattern in ("*", "**", ">"):
        return True
    ptoks = pattern.split(".")
    vtoks = value.split(".")
    for i, p in enumerate(ptoks):
        if p == ">":
            return len(vtoks) >= i + 1
        if i >= len(vtoks):
            return False
        if not fnmatch.fnmatchcase(vtoks[i], p):
            return False
    return len(ptoks) == len(vtoks)
