"""ID and time helpers used across the control plane."""
from __future__ import annotations

import itertools
import os
import time
import uuid


def new_id() -> str:
    """Random job/run/trace identifier (UUID4, canonical string form)."""
    return str(uuid.uuid4())


# Span-id generation sits on the scheduler hot path (5+ spans per job), where
# uuid4's os.urandom call per id was measurable at bench job rates.  Spans
# only need process-lifetime uniqueness, not unpredictability: one random
# 64-bit prefix per process + a counter.
_FAST_PREFIX = os.urandom(8).hex()
_FAST_CTR = itertools.count(1)


def fast_id() -> str:
    """Cheap unique id (random process prefix + counter) for span ids and
    other identifiers that need uniqueness, not entropy per call."""
    return f"{_FAST_PREFIX}{next(_FAST_CTR):012x}"


def now_us() -> int:
    """Current wall time in microseconds (job-store timestamp unit)."""
    return time.time_ns() // 1_000


def now_s() -> float:
    return time.time()


def now_ms() -> int:
    return time.time_ns() // 1_000_000
