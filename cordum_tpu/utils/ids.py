"""ID and time helpers used across the control plane."""
from __future__ import annotations

import time
import uuid


def new_id() -> str:
    """Random job/run/trace identifier (UUID4, canonical string form)."""
    return str(uuid.uuid4())


def now_us() -> int:
    """Current wall time in microseconds (job-store timestamp unit)."""
    return time.time_ns() // 1_000


def now_s() -> float:
    return time.time()


def now_ms() -> int:
    return time.time_ns() // 1_000_000
