"""Worker-side gang execution: rendezvous barrier, SPMD step replication,
and MPMD pipeline stages (docs/GANG.md).

A gang member job arrives on the worker's direct subject carrying the
scheduler-stamped ``cordum.gang_*`` labels (gang id, rank, size, member
list).  The member then:

1. subscribes its gang's ``sys.job.gang.<gang_id>`` subject and **beacons**
   ``GangMsg(kind="ready")`` every few hundred ms until it has seen every
   rank's beacon (fan-out subjects are not durable, so beacons repeat
   instead of relying on delivery order) — the rendezvous barrier;
2. a barrier timeout, a peer's abort, a cancel, or any local failure
   aborts the WHOLE gang: the member publishes ``kind="abort"``, peers
   stop between steps, and the scheduler releases every reserved device
   and requeues the job;
3. past the barrier it runs the **step program**:

   * **SPMD** (``mesh.pp <= 1`` or ``workers != pp``): every member runs
     the identical training program (:class:`~..worker.training.TrainRunner`
     — dense llama / moe / pipeline families) over its own slice's mesh.
     In production multi-host JAX this is one global mesh coordinated by
     ``jax.distributed``; in this CPU reproduction each member owns a full
     mesh replica and the control plane supplies what the paper's central
     controller does — admission, rendezvous, and failure semantics.
   * **MPMD pipeline** (``workers == mesh.pp > 1``): rank ``r`` owns stage
     ``r`` of the decoder (rank 0 also embeds, the last rank owns the LM
     head and the loss).  Forward activations and backward cotangents flow
     between neighbor ranks as ``kind="stage"`` messages over the bus
     (the statebus frame layer in a wire deployment) in the classic
     fill/drain GPipe schedule; every rank applies SGD to its own stage —
     stage-per-worker pipeline training driven by a central controller,
     per "Scaling DL Training with MPMD Pipeline Parallelism" (PAPERS.md).

4. on success the member publishes ``kind="done"`` with its stats; the
   scheduler aggregates all ranks into the job's single terminal result.
   Members never publish ``JobResult`` themselves — the gang owns exactly
   one job id.
"""
from __future__ import annotations

import asyncio
import collections
import contextlib
import time
from typing import Any

import numpy as np

from ..infra import logging as logx
from ..protocol import subjects as subj
from ..protocol.types import (
    BusPacket,
    GangMsg,
    JobRequest,
    LABEL_GANG_ID,
    LABEL_GANG_MEMBERS,
    LABEL_GANG_RANK,
    LABEL_GANG_SIZE,
    SERVING_OPS,
)

DEFAULT_RENDEZVOUS_TIMEOUT_S = 10.0
DEFAULT_PEER_TIMEOUT_S = 30.0
BEACON_INTERVAL_S = 0.25
_DONE_CACHE_CAP = 128


class GangAborted(Exception):
    """The gang is over (peer abort / barrier timeout / cancel) — unwind
    without publishing a member result."""


class _GangSession:
    """One member's live view of its gang: the ready set, the abort latch,
    and tag-addressed mailboxes for MPMD stage traffic."""

    def __init__(self, gang_id: str, job_id: str, rank: int, size: int,
                 trace_id: str = "") -> None:
        self.gang_id = gang_id
        self.job_id = job_id
        self.rank = rank
        self.size = size
        self.trace_id = trace_id
        self.ready: set[int] = {rank}
        self.barrier = asyncio.Event()
        self.abort = asyncio.Event()
        self.abort_reason = ""
        self._mail: dict[str, asyncio.Future] = {}
        # serving-gang replay stream (kind="step"): rank 0's broadcast
        # entry batches, drained in seq order by the follower loop
        self.steps: collections.deque[GangMsg] = collections.deque()
        self.step_event = asyncio.Event()

    def on_msg(self, msg: GangMsg) -> None:
        if msg.kind == "ready":
            self.ready.add(msg.rank)
            if len(self.ready) >= self.size:
                self.barrier.set()
        elif msg.kind == "step":
            self.steps.append(msg)
            self.step_event.set()
        elif msg.kind == "abort":
            self.abort_reason = self.abort_reason or (msg.reason or "abort")
            self.abort.set()
            for fut in self._mail.values():
                if not fut.done():
                    fut.set_exception(GangAborted(self.abort_reason))
        elif msg.kind == "stage" and msg.to_rank == self.rank:
            fut = self._mail.setdefault(
                msg.tag, asyncio.get_running_loop().create_future()
            )
            if not fut.done():
                fut.set_result((bytes(msg.data or b""), list(msg.shape or [])))

    def check_abort(self) -> None:
        if self.abort.is_set():
            raise GangAborted(self.abort_reason or "abort")

    async def recv(self, tag: str, timeout_s: float) -> tuple[bytes, list[int]]:
        """Await the stage message addressed by ``tag``.  A peer that died
        mid-step surfaces as a timeout → the member aborts the gang."""
        self.check_abort()
        fut = self._mail.setdefault(
            tag, asyncio.get_running_loop().create_future()
        )
        try:
            return await asyncio.wait_for(asyncio.shield(fut), timeout_s)
        except asyncio.TimeoutError:
            raise GangAborted(f"peer_timeout:{tag}") from None
        finally:
            self._mail.pop(tag, None)


class GangRunner:
    """Executes gang member jobs for one worker (attached via
    ``Worker.attach_gang``)."""

    def __init__(
        self,
        worker,
        *,
        trainer=None,
        rendezvous_timeout_s: float = DEFAULT_RENDEZVOUS_TIMEOUT_S,
        peer_timeout_s: float = DEFAULT_PEER_TIMEOUT_S,
        beacon_interval_s: float = BEACON_INTERVAL_S,
    ) -> None:
        self.worker = worker
        self.trainer = trainer
        self.rendezvous_timeout_s = rendezvous_timeout_s
        self.peer_timeout_s = peer_timeout_s
        self.beacon_interval_s = beacon_interval_s
        self._sessions: dict[str, _GangSession] = {}
        self._tasks: set[asyncio.Task] = set()
        # live serving gangs this worker is a member of, keyed by gang id —
        # the worker's telemetry beacon folds these into the capacity plane
        # so the fleet renders ONE fused row per gang (obs/capacity.py)
        self._serving_gangs: dict[str, dict] = {}
        # done-report cache: a member packet redelivered after completion
        # republishes the recorded GangMsg instead of re-running the step
        # program (the worker-level completed-result idempotence, gang-shaped)
        self._done: dict[str, GangMsg] = {}

    async def stop(self) -> None:
        for t in list(self._tasks):
            t.cancel()
        for t in list(self._tasks):
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await t
        self._tasks.clear()

    # ------------------------------------------------------------------
    @staticmethod
    def is_member(req: JobRequest) -> bool:
        return LABEL_GANG_ID in (req.labels or {})

    async def handle(
        self, req: JobRequest, payload: Any, *,
        trace_id: str = "", parent_span_id: str = "",
    ) -> None:
        """Run one gang member job end-to-end.  Publishes only GangMsg
        traffic — never a JobResult (the scheduler owns the job's single
        terminal result)."""
        labels = req.labels or {}
        gang_id = labels.get(LABEL_GANG_ID, "")
        try:
            rank = int(labels.get(LABEL_GANG_RANK, "-1"))
            size = int(labels.get(LABEL_GANG_SIZE, "0"))
        except ValueError:
            rank, size = -1, 0
        if not gang_id or rank < 0 or size < 1:
            logx.warn("malformed gang member labels", job_id=req.job_id)
            return
        cached = self._done.get(req.job_id)
        if cached is not None and cached.gang_id == gang_id:
            await self._publish(gang_id, cached, trace_id)
            return
        existing = self._sessions.get(req.job_id)
        if existing is not None:
            if existing.gang_id == gang_id:
                return  # redelivery of an in-flight member
            # a FRESH gang attempt for the same job: the old session's gang
            # was aborted and it is tearing down — wait it out (bounded; the
            # abort latch breaks spin/step loops promptly) so the new
            # attempt isn't mistaken for a redelivery
            deadline = time.monotonic() + self.rendezvous_timeout_s
            while self._sessions.get(req.job_id) is existing:
                if time.monotonic() > deadline:
                    logx.warn("stale gang session blocks new attempt",
                              job_id=req.job_id, old_gang=existing.gang_id,
                              new_gang=gang_id)
                    return  # the scheduler's rendezvous backstop retries
                await asyncio.sleep(0.02)
        t = asyncio.ensure_future(self._run_member(
            req, payload, gang_id, rank, size,
            trace_id=trace_id, parent_span_id=parent_span_id,
        ))
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)
        await t

    async def _publish(self, gang_id: str, msg: GangMsg, trace_id: str) -> None:
        await self.worker.bus.publish(
            subj.gang_subject(gang_id),
            BusPacket.wrap(msg, trace_id=trace_id,
                           sender_id=self.worker.worker_id),
        )

    async def _run_member(
        self, req: JobRequest, payload: Any, gang_id: str, rank: int, size: int,
        *, trace_id: str, parent_span_id: str,
    ) -> None:
        from .runtime import JobContext

        worker = self.worker
        ctx = JobContext(request=req, payload=payload, worker=worker)
        session = _GangSession(gang_id, req.job_id, rank, size,
                               trace_id=trace_id)
        self._sessions[req.job_id] = session
        worker._active[req.job_id] = ctx
        worker._mark_busy()

        async def _on_gang_pkt(subject: str, pkt: BusPacket) -> None:
            self._route(session, pkt)

        sub = await worker.bus.subscribe(subj.gang_subject(gang_id), _on_gang_pkt)
        tracer = worker.tracer
        exec_span = tracer.begin(
            "gang-execute", trace_id=trace_id, parent_span_id=parent_span_id,
            attrs={"job_id": req.job_id, "gang_id": gang_id,
                   "rank": str(rank), "worker_id": worker.worker_id},
        )
        beacon = asyncio.ensure_future(self._beacon_loop(session, trace_id))
        abort_reason = ""
        try:
            rdv_span = tracer.begin(
                "gang-rendezvous", trace_id=trace_id,
                parent_span_id=exec_span.span_id,
                attrs={"gang_id": gang_id, "rank": str(rank)},
            )
            t0 = time.monotonic()
            await self._barrier(session, ctx)
            waited = time.monotonic() - t0
            rdv_span.attrs["members"] = str(size)
            await tracer.finish(rdv_span)
            metrics = getattr(worker, "gang_metrics", None)
            if metrics is not None:
                metrics.gang_rendezvous_seconds.observe(waited)

            step_span = tracer.begin(
                "gang-step", trace_id=trace_id,
                parent_span_id=exec_span.span_id,
                attrs={"gang_id": gang_id, "rank": str(rank)},
            )
            stats = await self._run_program(session, ctx, payload)
            if stats.get("loss") is not None:
                step_span.attrs["loss"] = f"{stats['loss']:.4f}"
            step_span.attrs["mode"] = str(stats.get("mode", ""))
            await tracer.finish(step_span)

            done = GangMsg(
                gang_id=gang_id, job_id=req.job_id, kind="done", rank=rank,
                worker_id=worker.worker_id, stats=stats,
            )
            if len(self._done) > _DONE_CACHE_CAP:
                self._done.clear()
            self._done[req.job_id] = done
            await self._publish(gang_id, done, trace_id)
            exec_span.attrs["status"] = "DONE"
            await tracer.finish(exec_span)
        except GangAborted as e:
            abort_reason = str(e) or "abort"
            exec_span.attrs["status"] = "ABORTED"
            exec_span.attrs["reason"] = abort_reason
            await tracer.finish(exec_span, status="ERROR")
            if not session.abort.is_set():
                # locally-originated abort (timeout/cancel): tell the gang
                await self._publish(gang_id, GangMsg(
                    gang_id=gang_id, job_id=req.job_id, kind="abort",
                    rank=rank, worker_id=worker.worker_id,
                    reason=abort_reason,
                ), trace_id)
        except asyncio.CancelledError:
            # worker shutdown / simulated crash: die silently, exactly like
            # SIGKILL — the scheduler watchdog recovers the gang
            raise
        except Exception as e:  # noqa: BLE001 - member failure aborts the gang
            abort_reason = f"member_failed:{type(e).__name__}"
            logx.warn("gang member failed", job_id=req.job_id,
                      gang_id=gang_id, rank=rank, err=str(e))
            exec_span.attrs["status"] = "FAILED"
            exec_span.attrs["error"] = type(e).__name__
            await tracer.finish(exec_span, status="ERROR")
            await self._publish(gang_id, GangMsg(
                gang_id=gang_id, job_id=req.job_id, kind="abort", rank=rank,
                worker_id=worker.worker_id, reason=abort_reason,
            ), trace_id)
        finally:
            beacon.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await beacon
            sub.unsubscribe()
            self._sessions.pop(req.job_id, None)
            worker._active.pop(req.job_id, None)
            worker._mark_idle()

    def _route(self, session: _GangSession, pkt: BusPacket) -> None:
        msg = pkt.gang_msg
        if msg is not None and pkt.sender_id != self.worker.worker_id:
            session.on_msg(msg)

    async def _beacon_loop(self, session: _GangSession, trace_id: str) -> None:
        """Re-publish the ready beacon until the barrier passes: fan-out
        subjects are not durable, so a beacon that raced a peer's subscribe
        is simply repeated."""
        msg = GangMsg(
            gang_id=session.gang_id, job_id=session.job_id, kind="ready",
            rank=session.rank, worker_id=self.worker.worker_id,
        )
        # beacon for the member's whole lifetime, not just until OUR barrier
        # passes: a peer that subscribed late (stale-session teardown, slow
        # dispatch) must still be able to complete ITS barrier — stopping at
        # first passage loses the race where A hears B but B never heard A.
        # The task is cancelled in the member's finally block.
        while not session.abort.is_set():
            await self._publish(session.gang_id, msg, trace_id)
            await asyncio.sleep(self.beacon_interval_s)

    async def _barrier(self, session: _GangSession, ctx) -> None:
        deadline = time.monotonic() + self.rendezvous_timeout_s
        while not session.barrier.is_set():
            session.check_abort()
            if ctx.cancelled.is_set():
                raise GangAborted("cancelled")
            if time.monotonic() > deadline:
                raise GangAborted(
                    f"rendezvous_timeout:rank{session.rank}:"
                    f"saw{len(session.ready)}of{session.size}"
                )
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(session.barrier.wait(), 0.1)

    # ------------------------------------------------------------------
    # step programs
    # ------------------------------------------------------------------
    async def _run_program(
        self, session: _GangSession, ctx, payload: Any
    ) -> dict:
        payload = payload if isinstance(payload, dict) else {}
        op = str(payload.get("op", "train"))
        gang_stanza = payload.get("gang") if isinstance(payload.get("gang"), dict) else {}
        if op in SERVING_OPS or str(gang_stanza.get("kind", "")) == "serving":
            return await self._run_serving(session, ctx, payload)
        if op == "train":
            mesh_req = payload.get("mesh") or {}
            pp = int(mesh_req.get("pp", 1) or 1)
            if session.size > 1 and pp == session.size:
                return await self._run_mpmd(session, ctx, payload)
            return await self._run_spmd(session, ctx, payload)
        if op == "gang_test":
            return await self._run_gang_test(session, ctx, payload)
        # barrier-only member (echo-class): proves the reserve→rendezvous→
        # result pipeline without device work — the bench's gang_jobs_per_sec
        return {"op": op, "mode": "barrier", "rank": session.rank}

    def _abort_poll(self, session: _GangSession, ctx):
        return lambda: session.abort.is_set() or ctx.cancelled.is_set()

    async def _run_spmd(self, session: _GangSession, ctx, payload: dict) -> dict:
        """Every member runs the identical training program over its own
        mesh (dense dp×tp×sp, moe dp×tp×ep, or the shard_map pipeline)."""
        if self.trainer is None:
            raise RuntimeError("gang runner has no trainer attached")
        cancelled = self._abort_poll(session, ctx)
        out = await self.worker.run_in_executor(
            lambda: self.trainer.train(payload, cancelled=cancelled)
        )
        session.check_abort()
        if ctx.cancelled.is_set():
            raise GangAborted("cancelled")
        if not out.get("completed", False):
            # the poll broke the loop: whoever set it owns the reason
            raise GangAborted(session.abort_reason or "cancelled")
        return {**out, "mode": "spmd", "rank": session.rank,
                "loss": out.get("final_loss")}

    async def _run_gang_test(
        self, session: _GangSession, ctx, payload: dict
    ) -> dict:
        """Validation/chaos op: spin for ``spin_s`` checking the abort latch
        between slices, failing outright on workers named in
        ``fail_workers`` — the harness the gang fault tests drive."""
        if self.worker.worker_id in (payload.get("fail_workers") or []):
            raise RuntimeError("gang_test: injected member failure")
        spin_s = float(payload.get("spin_s", 0.0) or 0.0)
        deadline = time.monotonic() + spin_s
        while time.monotonic() < deadline:
            session.check_abort()
            if ctx.cancelled.is_set():
                raise GangAborted("cancelled")
            await asyncio.sleep(0.02)
        return {"op": "gang_test", "mode": "spin", "rank": session.rank,
                "spin_s": spin_s}

    # ------------------------------------------------------------------
    # serving gangs: tensor-parallel ragged serving over the gang
    # (docs/SERVING.md §Sharded serving)
    # ------------------------------------------------------------------
    def serving_gang_doc(self) -> dict:
        """This worker's live serving-gang membership for the telemetry
        beacon (empty dict = not serving in a gang).  Rank 0's doc carries
        the measured fused throughput; follower docs carry only identity +
        their arena headroom (the fleet fuses min-of-ranks)."""
        for doc in self._serving_gangs.values():
            out = dict(doc)
            cb = out.pop("_live", None)
            if callable(cb):
                with contextlib.suppress(Exception):
                    out.update(cb())
            return out
        return {}

    def _serving_backend(self, session: _GangSession, payload: dict):
        """Build this rank's sharded backend from the payload's sizing
        knobs.  Every rank derives IDENTICAL params (same seed, same cfg) —
        on real hardware NamedSharding keeps only the local head slice
        resident; on the 1-chip CI fallback each rank holds a replica."""
        import dataclasses

        import jax.numpy as jnp

        from ..models import llama
        from ..serving.shard import ShardedServingBackend

        dtype_name = str(payload.get("dtype", "float32") or "float32")
        cfg = dataclasses.replace(
            llama.LlamaConfig.tiny(),
            dtype=jnp.bfloat16 if dtype_name == "bfloat16" else jnp.float32,
        )
        max_seqs = max(1, int(payload.get("max_sessions", 4) or 4))
        return ShardedServingBackend(
            cfg,
            rank=session.rank,
            tp=session.size,
            num_pages=max(2, int(payload.get("cache_pages", 64) or 64)),
            page_size=max(1, int(payload.get("page_size", 16) or 16)),
            max_seqs=max_seqs,
            max_batch_tokens=max_seqs + max(
                1, int(payload.get("prefill_budget", 16) or 16)),
            seed=int(payload.get("seed", 0) or 0),
        )

    async def _run_serving(
        self, session: _GangSession, ctx, payload: dict
    ) -> dict:
        """One serving-gang member.  Rank 0 runs the REAL serving engine
        (admission, session registry, token streaming) over its shard and
        broadcasts every ragged step's entry batch as ``kind="step"``;
        follower ranks replay the identical batches against their shards —
        same program, same arena trajectory, no lm_head (docs/SERVING.md
        §Sharded serving)."""
        labels = (ctx.request.labels or {})
        members = [m for m in labels.get(LABEL_GANG_MEMBERS, "").split(",") if m]
        backend = self._serving_backend(session, payload)
        metrics = getattr(self.worker, "gang_metrics", None)
        doc: dict[str, Any] = {
            "gang_id": session.gang_id,
            "rank": session.rank,
            "size": session.size,
            "members": members,
            "pages_total": backend.num_pages,
        }
        self._serving_gangs[session.gang_id] = doc
        if metrics is not None:
            metrics.serving_gang_members.set(
                float(session.size), gang=session.gang_id)
        try:
            if session.rank == 0:
                return await self._serve_leader(session, ctx, payload, backend)
            return await self._serve_follower(session, ctx, backend)
        finally:
            # linger_s keeps the fused row visible after the job finishes
            # (platform_smoke scrapes capacity while the gang is winding
            # down); the abort latch cuts the linger short
            linger = float(payload.get("linger_s", 0.0) or 0.0)
            deadline = time.monotonic() + linger
            while time.monotonic() < deadline and not session.abort.is_set():
                await asyncio.sleep(0.05)
            self._serving_gangs.pop(session.gang_id, None)
            if metrics is not None:
                metrics.serving_gang_members.set(0.0, gang=session.gang_id)

    async def _serve_leader(
        self, session: _GangSession, ctx, payload: dict, backend
    ) -> dict:
        from ..serving.engine import GenRequest as EngineGenRequest
        from ..serving.engine import ServingEngine
        from ..serving.shard import entry_to_wire

        worker = self.worker
        loop = asyncio.get_running_loop()
        metrics = getattr(worker, "gang_metrics", None)
        seq = 0

        def _broadcast(entries) -> None:
            # called from the step's executor thread, after the device call
            # lands: ship the EXACT entry batch so followers replay the
            # same compiled program.  Blocking on the publish keeps the
            # replay stream ordered and applies natural backpressure.
            nonlocal seq
            msg = GangMsg(
                gang_id=session.gang_id, job_id=session.job_id, kind="step",
                rank=0, worker_id=worker.worker_id,
                stats={"seq": seq,
                       "entries": [entry_to_wire(e) for e in entries]},
            )
            seq += 1
            asyncio.run_coroutine_threadsafe(
                self._publish(session.gang_id, msg, session.trace_id), loop
            ).result()
            if metrics is not None:
                metrics.serving_gang_steps.inc(role="lead")

        backend.on_step = _broadcast
        engine = ServingEngine(
            backend,
            run_blocking=worker.run_in_executor,
            max_sessions=backend.max_seqs,
            max_new_tokens_cap=int(payload.get("max_new_tokens", 16) or 16),
            metrics=metrics,
            tracer=worker.tracer,
            # CoW page copies happen outside step() and would not replay on
            # followers — the gang engine runs with prefix sharing off (the
            # single-worker engines keep it; a broadcast copy_page protocol
            # is the upgrade path)
            prefix_cache=False,
            speculative=bool(payload.get("speculative", False)),
            draft_k=int(payload.get("draft_k", 0) or 0) or 4,
        )
        prompts = payload.get("prompts")
        if not isinstance(prompts, list) or not prompts:
            one = payload.get("prompt") or payload.get("tokens") or [1, 2, 3]
            prompts = [one]
        prompts = [[int(t) for t in p] for p in prompts if p][: backend.max_seqs]
        max_new = int(payload.get("max_new_tokens", 16) or 16)
        live = {"t0": time.monotonic(), "tokens": 0}

        def _live() -> dict:
            free = engine.allocator.free_pages
            dt = max(1e-6, time.monotonic() - live["t0"])
            return {"pages_free": free,
                    "tokens_per_s": round(live["tokens"] / dt, 3)}

        self._serving_gangs[session.gang_id]["_live"] = _live

        def _sink(first: bool):
            base = worker._token_sink(
                session.job_id,
                EngineGenRequest(prompt=[], max_new_tokens=max_new),
            ) if first else None

            async def sink(new_tokens, n_generated, done):
                live["tokens"] += len(new_tokens)
                if metrics is not None and new_tokens:
                    metrics.serving_gang_stream_tokens.inc(
                        len(new_tokens), rank="0")
                if base is not None:
                    await base(new_tokens, n_generated, done)

            return sink

        async def _drive() -> list[dict]:
            subs = [
                engine.submit(
                    EngineGenRequest(
                        prompt=p, max_new_tokens=max_new,
                        stream=(i == 0),
                    ),
                    job_id=session.job_id if i == 0
                    else f"{session.job_id}#{i}",
                    trace_id=session.trace_id,
                    on_tokens=_sink(first=(i == 0)),
                )
                for i, p in enumerate(prompts)
            ]
            return await asyncio.gather(*subs)

        drive = asyncio.ensure_future(_drive())
        abort_w = asyncio.ensure_future(session.abort.wait())
        cancel_w = asyncio.ensure_future(ctx.cancelled.wait())
        try:
            done, _ = await asyncio.wait(
                {drive, abort_w, cancel_w},
                return_when=asyncio.FIRST_COMPLETED,
            )
            if drive not in done:
                drive.cancel()
                with contextlib.suppress(asyncio.CancelledError, Exception):
                    await drive
                raise GangAborted(session.abort_reason or "cancelled")
            results = await drive
        finally:
            for w in (abort_w, cancel_w):
                w.cancel()
            with contextlib.suppress(Exception):
                await engine.stop()
            # the shutdown marker releases the follower replay loops
            with contextlib.suppress(Exception):
                await self._publish(session.gang_id, GangMsg(
                    gang_id=session.gang_id, job_id=session.job_id,
                    kind="step", rank=0, worker_id=worker.worker_id,
                    stats={"seq": seq, "final": True},
                ), session.trace_id)
        elapsed = max(1e-6, time.monotonic() - live["t0"])
        total = sum(len(r.get("tokens") or []) for r in results)
        return {
            "mode": "serving", "rank": 0, "tp": session.size,
            "sessions": len(results), "tokens": total,
            "tokens_per_s": round(total / elapsed, 3),
            "steps": seq, "compiled": backend.compiled_programs(),
            "results": results,
        }

    async def _serve_follower(
        self, session: _GangSession, ctx, backend
    ) -> dict:
        """Replay rank 0's entry batches in seq order until the shutdown
        marker.  The bus preserves per-publisher order, but the loop
        reorders defensively — a replayed batch must never run early (the
        arenas would diverge)."""
        from ..serving.shard import entry_from_wire

        metrics = getattr(self.worker, "gang_metrics", None)
        expected = 0
        pending: dict[int, dict] = {}
        replayed = 0
        while True:
            session.check_abort()
            if ctx.cancelled.is_set():
                raise GangAborted("cancelled")
            while session.steps:
                msg = session.steps.popleft()
                s = int((msg.stats or {}).get("seq", -1))
                if s >= expected:
                    pending[s] = msg.stats or {}
            progressed = False
            while expected in pending:
                stats = pending.pop(expected)
                expected += 1
                progressed = True
                if stats.get("final"):
                    return {
                        "mode": "serving", "rank": session.rank,
                        "tp": session.size, "steps_replayed": replayed,
                        "compiled": backend.compiled_programs(),
                    }
                entries = [entry_from_wire(d)
                           for d in (stats.get("entries") or [])]
                if entries:
                    await self.worker.run_in_executor(
                        lambda e=entries: backend.step(e))
                    replayed += 1
                    if metrics is not None:
                        metrics.serving_gang_steps.inc(role="replay")
            if progressed or session.steps:
                continue
            session.step_event.clear()
            if session.steps:
                continue
            try:
                await asyncio.wait_for(
                    session.step_event.wait(), self.peer_timeout_s)
            except asyncio.TimeoutError:
                raise GangAborted(f"peer_timeout:step{expected}") from None

    # ------------------------------------------------------------------
    # MPMD pipeline: one stage per worker, activations over the bus
    # ------------------------------------------------------------------
    async def _run_mpmd(self, session: _GangSession, ctx, payload: dict) -> dict:
        import jax

        rank, size = session.rank, session.size
        state = await self.worker.run_in_executor(
            lambda: _mpmd_build(payload, rank, size)
        )
        steps = int(payload.get("steps", 1) or 1)
        micro = max(1, int(payload.get("microbatches", 1) or 1))
        batch = int(payload.get("batch", 4) or 4)
        batch = max(micro, (batch // micro) * micro)
        seq = int(payload.get("seq", 16) or 16)
        lr = float(payload.get("lr", 1e-3) or 1e-3)
        losses: list[float] = []
        send_trace = session.trace_id  # stage msgs ride the job trace
        for step in range(steps):
            session.check_abort()
            if ctx.cancelled.is_set():
                raise GangAborted("cancelled")
            # every rank derives the SAME tokens deterministically — only
            # activations/cotangents cross the wire, never the batch
            key = jax.random.PRNGKey(1000 + step)
            tokens = np.asarray(jax.random.randint(
                key, (batch, seq), 0, state["vocab"]))
            mbs = tokens.reshape(micro, batch // micro, seq)
            vjps: list[Any] = []
            grads = None
            mb_losses: list[float] = []
            # fill: forward every microbatch through my stage
            for m in range(micro):
                tag_in = f"fwd:{step}:{m}:{rank}"
                if rank == 0:
                    x = None
                else:
                    data, shape = await session.recv(tag_in, self.peer_timeout_s)
                    x = np.frombuffer(data, np.float32).reshape(shape)
                out = await self.worker.run_in_executor(
                    lambda x=x, m=m: _mpmd_forward(state, mbs[m], x)
                )
                if rank == size - 1:
                    loss, g_params, _g_x_unused = out
                    mb_losses.append(float(loss))
                    vjps.append(out)
                else:
                    y, vjp = out
                    vjps.append(vjp)
                    await self._send_stage(
                        session, f"fwd:{step}:{m}:{rank + 1}", rank + 1,
                        np.asarray(y, np.float32), send_trace)
            # drain: cotangents flow back, each rank accumulates its grads
            for m in range(micro):
                if rank == size - 1:
                    loss, g_params, g_x = vjps[m]
                    if g_x is not None:
                        await self._send_stage(
                            session, f"bwd:{step}:{m}:{rank - 1}", rank - 1,
                            np.asarray(g_x, np.float32), send_trace)
                else:
                    data, shape = await session.recv(
                        f"bwd:{step}:{m}:{rank}", self.peer_timeout_s)
                    g_y = np.frombuffer(data, np.float32).reshape(shape)
                    g_params, g_x = await self.worker.run_in_executor(
                        lambda v=vjps[m], g=g_y: _mpmd_backward(v, g)
                    )
                    if rank > 0 and g_x is not None:
                        await self._send_stage(
                            session, f"bwd:{step}:{m}:{rank - 1}", rank - 1,
                            np.asarray(g_x, np.float32), send_trace)
                grads = (g_params if grads is None
                         else jax.tree.map(lambda a, b: a + b, grads, g_params))
            state["params"] = await self.worker.run_in_executor(
                lambda g=grads: _mpmd_sgd(state["params"], g, lr / micro)
            )
            if mb_losses:
                losses.append(sum(mb_losses) / len(mb_losses))
        return {
            "mode": "mpmd",
            "rank": rank,
            "steps_done": steps,
            "mesh": {"pp": size, "dp": 1},
            "microbatches": micro,
            "loss": losses[-1] if losses else None,
            "loss_first": losses[0] if losses else None,
        }

    async def _send_stage(
        self, session: _GangSession, tag: str, to_rank: int,
        arr: np.ndarray, trace_id: str,
    ) -> None:
        await self._publish(session.gang_id, GangMsg(
            gang_id=session.gang_id, job_id=session.job_id, kind="stage",
            rank=session.rank, to_rank=to_rank, tag=tag,
            data=arr.tobytes(), shape=list(arr.shape),
            worker_id=self.worker.worker_id,
        ), trace_id)


# ---------------------------------------------------------------------------
# MPMD stage math (plain float32 JAX; executor-thread blocking calls)
# ---------------------------------------------------------------------------


def _mpmd_build(payload: dict, rank: int, size: int) -> dict:
    """Deterministically initialize THIS rank's stage slice: every rank
    builds the same stacked pipeline params from the same seed and keeps
    only its stage (rank 0 the embedding, the last rank the head)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from ..models import llama, pipeline

    base = dataclasses.replace(llama.LlamaConfig.tiny(), dtype=jnp.float32)
    if base.n_layers % size:
        raise ValueError(
            f"pipeline needs n_layers {base.n_layers} divisible by pp={size}"
        )
    cfg = pipeline.PipelineConfig(base=base, n_stages=size, n_microbatches=1)
    full = pipeline.init_params(
        jax.random.PRNGKey(int(payload.get("seed", 0) or 0)), cfg)
    params: dict = {
        "stage": jax.tree.map(lambda p: jnp.asarray(p[rank]), full["stages"]),
    }
    if rank == 0:
        params["embed"] = full["embed"]
    if rank == size - 1:
        params["final_norm"] = full["final_norm"]
        params["lm_head"] = full["lm_head"]
    return {"params": params, "base": base, "vocab": base.vocab_size,
            "rank": rank, "size": size}


def _mpmd_forward(state: dict, tokens_mb: np.ndarray, x_in):
    """One microbatch through this rank's stage.

    * rank 0: ``(activation, vjp)`` — vjp w.r.t. params only (tokens carry
      no gradient).
    * middle: ``(activation, vjp)`` — vjp w.r.t. (params, input).
    * last: ``(loss, param_grads, input_cotangent)`` — the backward starts
      here, so the full value-and-grad happens in one call.
    """
    import jax
    import jax.numpy as jnp

    from ..models.llama import rms_norm
    from ..models.pipeline import _stage_apply

    base = state["base"]
    params = state["params"]
    rank, size = state["rank"], state["size"]
    tokens = jnp.asarray(tokens_mb)
    mb, t = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (mb, t))

    if rank == 0:
        def fwd0(p):
            x = p["embed"][tokens].astype(jnp.float32)
            return _stage_apply(p["stage"], x, positions, base)

        y, vjp = jax.vjp(fwd0, params)
        return np.asarray(jax.block_until_ready(y), np.float32), vjp

    x = jnp.asarray(x_in, jnp.float32)
    if rank < size - 1:
        def fwd(p, a):
            return _stage_apply(p["stage"], a, positions, base)

        y, vjp = jax.vjp(fwd, params, x)
        return np.asarray(jax.block_until_ready(y), np.float32), vjp

    def loss_fn(p, a):
        y = _stage_apply(p["stage"], a, positions, base)
        h = rms_norm(y, p["final_norm"], base.norm_eps)
        logits = (h @ p["lm_head"]).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        nll = -jnp.take_along_axis(
            logp, tokens[:, 1:][..., None], axis=-1)[..., 0]
        return jnp.mean(nll)

    (loss, (g_params, g_x)) = (
        jax.value_and_grad(loss_fn, argnums=(0, 1))(params, x)
    )
    jax.block_until_ready(loss)
    return float(loss), g_params, np.asarray(g_x, np.float32)


def _mpmd_backward(vjp, g_y: np.ndarray):
    """Pull the received cotangent through this rank's forward: returns
    (param grads, input cotangent — None on rank 0)."""
    import jax
    import jax.numpy as jnp

    out = vjp(jnp.asarray(g_y, jnp.float32))
    if len(out) == 1:  # rank 0: vjp was params-only
        return out[0], None
    g_params, g_x = out
    jax.block_until_ready(g_params)
    return g_params, np.asarray(g_x, np.float32)


def _mpmd_sgd(params: dict, grads, lr: float) -> dict:
    import jax

    if grads is None:
        return params
    return jax.tree.map(lambda p, g: p - lr * g, params, grads)


__all__ = ["GangRunner", "GangAborted"]
