"""Built-in JAX job handlers for the TPU worker pool.

Job payloads arrive via context pointers as JSON: ``{"op": ..., ...}``.
Each handler maps a control-plane job onto an XLA computation:

  * ``echo``        — the hello-pack contract (reference
                      ``examples/hello-worker-go/main.go:44-90``): return the
                      context payload
  * ``matmul``      — batched bf16 matmul benchmark op (MXU saturation)
  * ``embed``       — batch text embedding (context-engine compute path)
  * ``infer``       — Llama-family forward step (greedy next-token scoring)
  * ``train_step``  — one SPMD training step over the worker's mesh

Handlers are pure-async wrappers that push the actual XLA work onto the
worker's executor thread so heartbeats/cancel keep flowing while the chip
crunches.  jitted callables are cached per (op, shape-bucket).
"""
from __future__ import annotations

import functools
import time
from typing import Any, Optional

import numpy as np

from ..infra import logging as logx
from .runtime import JobContext, Worker


class HandlerError(Exception):
    pass


def _maybe_timer(timer, **attrs: str):
    """``ctx.device_timer`` when the caller passed one, else a no-op CM —
    TPUCompute stays usable outside a traced JobContext (bench, tests)."""
    if timer is not None:
        return timer("device", **attrs)
    import contextlib

    return contextlib.nullcontext()


async def echo_handler(ctx: JobContext) -> Any:
    """Return the job context payload (plus a marker, like the hello worker)."""
    return {"echo": ctx.payload, "worker": ctx.worker.worker_id}


# ---------------------------------------------------------------------------


class TPUCompute:
    """Lazily-initialized JAX compute state shared by the TPU handlers.

    Holds the device mesh, the embedder, an optional Llama model, and jit
    caches.  Created once per worker process (the slice owner).
    """

    def __init__(self, *, tp: int = 1, embedder_cfg=None, llama_cfg=None, seed: int = 0):
        import jax

        from ..models.embedder import Embedder, EmbedderConfig
        from ..models import llama as llama_mod
        from ..parallel.mesh import simple_mesh

        self.jax = jax
        n_dev = len(jax.devices())
        self.mesh = simple_mesh(min(tp, n_dev) if n_dev % min(tp, n_dev) == 0 else 1)
        self.embedder = Embedder(embedder_cfg or EmbedderConfig(), seed=seed, mesh=self.mesh)
        self.llama_cfg = llama_cfg or llama_mod.LlamaConfig.tiny()
        self._llama_params = None
        self._llama_fwd = None
        self._matmul_cache: dict[tuple, Any] = {}
        self._batch_shapes: set[tuple] = set()  # compile_cached span attr
        self._seed = seed

    # -- matmul -----------------------------------------------------------
    def matmul(self, b: int, n: int, k: int, m: int, iters: int = 1, dtype: str = "bfloat16",
               timer=None):
        import jax
        import jax.numpy as jnp

        key = (b, n, k, m, iters, dtype)
        fn = self._matmul_cache.get(key)
        compiled = fn is not None  # device span attr: compile vs cached split
        if fn is None:
            dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32

            @jax.jit
            def run(x, y, y_back):
                # carry shape must stay (b, n, k) across iterations, so each
                # step goes k→m→k through two matmuls
                def body(i, acc):
                    return jnp.tanh((acc @ y) @ y_back)

                acc = jax.lax.fori_loop(0, iters, body, x)
                return acc @ y  # final projection to (b, n, m)

            fn = (run, dt)
            self._matmul_cache[key] = fn
        run, dt = fn
        kx, ky, kb = jax.random.split(jax.random.PRNGKey(self._seed), 3)
        x = jax.random.normal(kx, (b, n, k), dt)
        y = jax.random.normal(ky, (k, m), dt)
        y_back = jax.random.normal(kb, (m, k), dt)
        with _maybe_timer(timer, op="matmul", compile_cached=str(compiled).lower(),
                          items=str(b), bucket=f"{n}x{k}x{m}"):
            out = jax.block_until_ready(run(x, y, y_back))
        return {
            "shape": list(out.shape),
            "checksum": float(jnp.sum(out.astype(jnp.float32))),
            "flops": 2.0 * b * n * k * m * (2 * iters + 1),
        }

    # -- llama ------------------------------------------------------------
    def _ensure_llama(self):
        if self._llama_params is None:
            import jax

            from ..models import llama as llama_mod

            self._llama_params = llama_mod.init_params(
                jax.random.PRNGKey(self._seed), self.llama_cfg
            )
            cfg = self.llama_cfg

            @jax.jit
            def fwd(params, tokens):
                return llama_mod.forward(params, tokens, cfg)

            self._llama_fwd = fwd

    def infer(self, tokens: list[list[int]], max_len: Optional[int] = None, timer=None):
        import jax.numpy as jnp
        import numpy as np

        compiled = self._llama_params is not None
        self._ensure_llama()
        cfg = self.llama_cfg
        t = max(len(r) for r in tokens)
        t = min(max_len or cfg.max_seq_len, max(t, 1))
        batch = np.zeros((len(tokens), t), np.int32)
        lens = []
        for i, row in enumerate(tokens):
            row = [min(x, cfg.vocab_size - 1) for x in row[:t]]
            batch[i, : len(row)] = row
            lens.append(max(1, len(row)))
        with _maybe_timer(timer, op="infer", compile_cached=str(compiled).lower(),
                          items=str(len(tokens)), bucket=str(t)):
            logits = self._llama_fwd(self._llama_params, jnp.asarray(batch))
            # score each row at ITS last real token (causal attention makes
            # this invariant to right-padding, so per-job and micro-batched
            # inference agree bit-for-bit in exact arithmetic)
            last = logits[jnp.arange(len(tokens)), jnp.asarray(lens) - 1]
            next_tokens = np.asarray(jnp.argmax(last, axis=-1)).tolist()
        return {"next_tokens": next_tokens, "seq_len": t}

    # -- micro-batch entry points -----------------------------------------
    def embed_batch(self, texts: list[str], *, seq_len: int = 0,
                    batch_buckets=None, timer=None):
        """One padded XLA call embedding many jobs' texts: sequence dim
        trimmed to the queue's length bucket, batch dim padded up to a
        power-of-two bucket so XLA keeps one program per (batch, seq)
        bucket pair."""
        import numpy as np

        from ..batching.buckets import bucket_for, pow2_buckets
        from ..models.embedder import batch_tokenize

        cfg = self.embedder.cfg
        ids, mask = batch_tokenize(texts, cfg, max_len=seq_len or cfg.max_len)
        b = len(texts)
        bpad = bucket_for(b, batch_buckets or pow2_buckets(1, 256))
        if bpad > b:
            ids = np.pad(ids, ((0, bpad - b), (0, 0)))
            mask = np.pad(mask, ((0, bpad - b), (0, 0)))
        shape = ("embed", bpad, ids.shape[1])
        compiled = shape in self._batch_shapes
        self._batch_shapes.add(shape)
        with _maybe_timer(timer, op="embed_batch", compile_cached=str(compiled).lower(),
                          items=str(b), bucket=str(ids.shape[1])):
            out = self.embedder.embed_tokens(ids, mask)
        return np.asarray(out)[:b]

    def infer_batch(self, rows: list[list[int]], *, seq_len: int = 0,
                    batch_buckets=None, timer=None):
        """One padded XLA call scoring many jobs' rows; each row's next
        token is gathered at its own last real position (causal attention
        makes the right-padding inert).  Returns (next_tokens, seq_len)."""
        import jax.numpy as jnp
        import numpy as np

        from ..batching.buckets import bucket_for, pow2_buckets

        self._ensure_llama()
        cfg = self.llama_cfg
        t = min(max(1, seq_len or max((len(r) for r in rows), default=1)), cfg.max_seq_len)
        b = len(rows)
        bpad = bucket_for(b, batch_buckets or pow2_buckets(1, 256))
        batch = np.zeros((bpad, t), np.int32)
        lens = np.ones((bpad,), np.int32)
        for i, row in enumerate(rows):
            row = [min(x, cfg.vocab_size - 1) for x in row[:t]]
            batch[i, : len(row)] = row
            lens[i] = max(1, len(row))
        shape = ("infer", bpad, t)
        compiled = shape in self._batch_shapes
        self._batch_shapes.add(shape)
        with _maybe_timer(timer, op="infer_batch", compile_cached=str(compiled).lower(),
                          items=str(b), bucket=str(t)):
            logits = self._llama_fwd(self._llama_params, jnp.asarray(batch))
            last = logits[jnp.arange(bpad), jnp.asarray(lens) - 1]
            next_tokens = np.asarray(jnp.argmax(last, axis=-1))[:b].tolist()
        return next_tokens, t


def make_tpu_handlers(compute: TPUCompute):
    """Build the op-dispatching default handler backed by `compute`."""

    async def handler(ctx: JobContext) -> Any:
        payload = ctx.payload or {}
        if not isinstance(payload, dict):
            raise HandlerError(f"payload must be a JSON object, got {type(payload).__name__}")
        op = payload.get("op", "echo")
        ctx.check_cancelled()
        if op == "echo":
            return {"echo": payload, "worker": ctx.worker.worker_id}
        if op == "matmul":
            return await ctx.worker.run_in_executor(
                functools.partial(
                    compute.matmul,
                    int(payload.get("b", 8)),
                    int(payload.get("n", 512)),
                    int(payload.get("k", 512)),
                    int(payload.get("m", 512)),
                    int(payload.get("iters", 1)),
                    str(payload.get("dtype", "bfloat16")),
                    timer=ctx.device_timer,
                )
            )
        if op == "embed":
            texts = payload.get("texts")
            if not isinstance(texts, list) or not all(isinstance(t, str) for t in texts):
                raise HandlerError("embed op requires texts: list[str]")

            def _embed():
                with ctx.device_timer("device", op="embed", items=str(len(texts))):
                    return compute.embedder.embed(texts)

            vecs = await ctx.worker.run_in_executor(_embed)
            return {"embeddings": np.asarray(vecs).tolist(), "dim": int(vecs.shape[1])}
        if op == "infer":
            tokens = payload.get("tokens")
            if not isinstance(tokens, list):
                raise HandlerError("infer op requires tokens: list[list[int]]")
            return await ctx.worker.run_in_executor(
                functools.partial(
                    compute.infer, tokens, payload.get("max_len"), timer=ctx.device_timer
                )
            )
        if op == "llm.generate":
            # serving jobs route through the worker's serving engine BEFORE
            # the handler path (runtime._on_job); landing here means the
            # engine is not attached or the payload shape is invalid
            serving = ctx.worker.serving
            if serving is None:
                raise HandlerError(
                    "llm.generate requires the serving engine (WORKER_SERVING=1)"
                )
            raise HandlerError(
                "llm.generate requires tokens: non-empty list[int] "
                "(plus optional session_id/max_new_tokens/eos_token/stream)"
            )
        if op == "train":
            import asyncio

            from .training import TrainRunner

            loop = asyncio.get_running_loop()

            def report(frac, msg):
                asyncio.run_coroutine_threadsafe(ctx.progress(100 * frac, msg), loop)

            runner = TrainRunner()
            return await ctx.worker.run_in_executor(
                functools.partial(
                    runner.train, payload,
                    cancelled=ctx.cancelled.is_set, progress=report,
                )
            )
        raise HandlerError(f"unknown op {op!r}")

    return handler


def make_micro_batcher(
    compute: TPUCompute,
    worker: Worker,
    *,
    max_batch_rows: int = 32,
    max_wait_ms: float = 25.0,
    metrics=None,
):
    """Build the worker's micro-batcher over ``compute``'s batch entry
    points: payload decomposition (``parts_fn``) + the padded-XLA flush.
    Invalid payload shapes decompose to None so they keep the per-job
    handler path and fail with the op's own pointed error."""
    import numpy as np

    from ..batching.buckets import pow2_buckets
    from ..batching.engine import BatchParts, MicroBatcher
    from ..models.embedder import token_count

    ecfg = compute.embedder.cfg
    lcfg = compute.llama_cfg

    def parts_fn(payload) -> "BatchParts | None":
        if not isinstance(payload, dict):
            return None
        op = payload.get("op")
        if op == "embed":
            texts = payload.get("texts")
            if isinstance(texts, list) and texts and all(isinstance(t, str) for t in texts):
                return BatchParts(
                    "embed", texts, len(texts),
                    max(token_count(t, ecfg) for t in texts),
                )
        elif op == "infer":
            tokens = payload.get("tokens")
            if payload.get("max_len"):
                return None  # explicit padding request: keep per-job semantics
            if (
                isinstance(tokens, list) and tokens
                and all(isinstance(r, list) and r
                        and all(isinstance(x, int) for x in r) for r in tokens)
            ):
                length = min(max(len(r) for r in tokens), lcfg.max_seq_len)
                return BatchParts("infer", tokens, len(tokens), length)
        return None

    async def flush_fn(op, bucket, items):
        if op == "embed":
            texts = [t for it in items for t in it.rows]

            def run_embed():
                return compute.embed_batch(texts, seq_len=bucket)

            t0 = time.perf_counter()
            vecs = await worker.run_in_executor(run_embed)
            # one flush = one coalesced XLA call delivering len(texts) items
            # at this length bucket — the capacity matrix's batched-embed row
            worker.capacity.observe(
                "embed", device_s=time.perf_counter() - t0,
                bucket=str(bucket), items=len(texts),
            )
            out, i = [], 0
            for it in items:
                out.append({
                    "embeddings": np.asarray(vecs[i:i + it.n_rows]).tolist(),
                    "dim": int(vecs.shape[1]),
                    "batched": True,
                })
                i += it.n_rows
            return out
        if op == "infer":
            rows = [r for it in items for r in it.rows]

            def run_infer():
                return compute.infer_batch(rows, seq_len=bucket)

            t0 = time.perf_counter()
            toks, t = await worker.run_in_executor(run_infer)
            worker.capacity.observe(
                "infer", device_s=time.perf_counter() - t0,
                bucket=str(bucket), items=len(rows),
            )
            out, i = [], 0
            for it in items:
                out.append({
                    "next_tokens": toks[i:i + it.n_rows],
                    "seq_len": t,
                    "batched": True,
                })
                i += it.n_rows
            return out
        raise HandlerError(f"unbatchable op {op!r}")

    seq_cap = max(ecfg.max_len, min(lcfg.max_seq_len, 512))
    return MicroBatcher(
        flush_fn,
        parts_fn=parts_fn,
        max_batch_rows=max_batch_rows,
        max_wait_ms=max_wait_ms,
        len_buckets=pow2_buckets(16, seq_cap),
        metrics=metrics,
        tracer=worker.tracer,
    )


def make_serving_engine(
    compute: TPUCompute,
    worker: Worker,
    *,
    cache_pages: int = 128,
    page_size: int = 16,
    max_sessions: int = 8,
    max_new_tokens: int = 64,
    max_concurrent_prefills: int = 2,
    prefill_budget: int = 16,
    handoff_tokens: int = 0,
    prefix_cache: bool = True,
    hibernate_after_s: float = 0.0,
    speculative: bool = True,
    draft_k: int = 0,
    cold_tier: str = "",
    metrics=None,
):
    """Build the worker's continuous-batching serving engine over a paged
    Llama backend that shares ``compute``'s model params (one copy of the
    weights per worker process; the KV page arena is the serving addition).

    The backend's static ragged-step shapes are sized here: ``max_sessions``
    sequence rows over a flat token buffer of ``max_sessions +
    prefill_budget`` slots, so a full decode set always fits and prefill
    chunks ride the remaining ``prefill_budget`` tokens per step.
    """
    from ..serving.backend import LlamaServingBackend
    from ..serving.engine import ServingEngine

    def params_provider():
        compute._ensure_llama()
        return compute._llama_params

    backend = LlamaServingBackend(
        compute.llama_cfg,
        num_pages=cache_pages,
        page_size=page_size,
        max_seqs=max_sessions,
        max_batch_tokens=max_sessions + max(1, prefill_budget),
        params_provider=params_provider,
        metrics=metrics,
    )
    engine = ServingEngine(
        backend,
        run_blocking=worker.run_in_executor,
        max_sessions=max_sessions,
        max_new_tokens_cap=max_new_tokens,
        max_concurrent_prefills=max_concurrent_prefills,
        handoff_threshold_tokens=handoff_tokens,
        prefix_cache=prefix_cache,
        hibernate_after_s=hibernate_after_s,
        speculative=speculative,
        # draft_k == 0 means "engine default" so config files can omit it
        **({"draft_k": draft_k} if draft_k > 0 else {}),
        metrics=metrics,
        tracer=worker.tracer,
        capacity=worker.capacity,
    )
    if cold_tier == "statebus" and engine.tiering is not None:
        # journal hibernated sessions through the statebus KV so they
        # survive a restart; cmd.worker awaits arena.load() post-start
        from ..serving.tiering import StatebusColdTier

        engine.tiering.arena = StatebusColdTier(
            worker.store.kv, worker_id=worker.worker_id,
        )
    return engine


def attach_default_tpu_worker(
    worker: Worker,
    *,
    tp: int = 1,
    batching: bool = True,
    max_batch_rows: int = 32,
    max_batch_wait_ms: float = 25.0,
    serving: bool = True,
    serving_cache_pages: int = 128,
    serving_page_size: int = 16,
    serving_max_sessions: int = 8,
    serving_max_new_tokens: int = 64,
    serving_prefill_budget: int = 16,
    serving_handoff_tokens: int = 0,
    serving_prefix_cache: bool = True,
    serving_hibernate_after_s: float = 0.0,
    serving_speculative: bool = True,
    serving_draft_k: int = 0,
    serving_cold_tier: str = "",
    gang: bool = True,
    gang_rendezvous_timeout_s: float = 10.0,
    gang_peer_timeout_s: float = 30.0,
    metrics=None,
    **kw,
) -> TPUCompute:
    """Wire the standard TPU op handlers (and, by default, the micro-batcher
    over the batchable ops, the llm.generate serving engine, and the gang
    runner for multi-chip gang member jobs) onto a worker."""
    compute = TPUCompute(tp=tp, **kw)
    worker.register_default(make_tpu_handlers(compute))
    if batching:
        worker.attach_batcher(make_micro_batcher(
            compute, worker,
            max_batch_rows=max_batch_rows, max_wait_ms=max_batch_wait_ms,
            metrics=metrics,
        ))
    if serving:
        worker.attach_serving(make_serving_engine(
            compute, worker,
            cache_pages=serving_cache_pages, page_size=serving_page_size,
            max_sessions=serving_max_sessions,
            max_new_tokens=serving_max_new_tokens,
            prefill_budget=serving_prefill_budget,
            handoff_tokens=serving_handoff_tokens,
            prefix_cache=serving_prefix_cache,
            hibernate_after_s=serving_hibernate_after_s,
            speculative=serving_speculative,
            draft_k=serving_draft_k,
            cold_tier=serving_cold_tier,
            metrics=metrics,
        ))
    if gang:
        from .gang import GangRunner
        from .training import TrainRunner

        worker.attach_gang(GangRunner(
            worker,
            trainer=TrainRunner(),
            rendezvous_timeout_s=gang_rendezvous_timeout_s,
            peer_timeout_s=gang_peer_timeout_s,
        ), metrics=metrics)
    return compute
