"""TPU worker runtime: the in-tree worker that executes jobs as JAX/XLA
computations.

Recreates the reference worker runtime contract (``sdk/runtime/worker.go``):
queue-subscribe pool subjects + the direct ``worker.<id>.jobs`` subject,
``max_parallel_jobs`` semaphore, per-job cancel events fed by
``sys.job.cancel``, periodic heartbeats with live load, result status
inferred from handler outcome, ``progress()`` helper.

TPU-native deltas (the north star's in-tree TPU worker):
  * the worker owns its slice: one process per slice, handlers run JAX
    computations in a thread-pool executor so the asyncio loop keeps
    heartbeating while XLA blocks (SURVEY §7 "TPU worker process model")
  * heartbeats carry slice telemetry (device kind, chip count, topology,
    HBM use, duty-cycle estimate) for slice-aware scheduling
  * cooperative cancel: handlers receive a :class:`JobContext` whose
    ``cancelled`` event they may poll between jitted steps
  * micro-batching: with a batcher attached (``attach_batcher``), batchable
    jobs (embed/infer) bypass the per-job semaphore, queue per
    (op, length-bucket), and flush as one padded XLA call — results still
    publish as ordinary per-job ``JobResult``s (docs/BATCHING.md)
"""
from __future__ import annotations

import asyncio
import itertools
import random
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Optional

from ..batching.engine import BatchCancelled, BatchParts, MicroBatcher
from ..infra import logging as logx
from ..infra.bus import Bus
from ..infra.memstore import MemoryStore
from ..obs.capacity import CapacityProfiler
from ..obs.tracer import Tracer
from ..protocol import subjects as subj
from ..protocol.types import (
    BusPacket,
    ERROR_SESSION_REQUEUE,
    Heartbeat,
    JobCancel,
    JobProgress,
    JobRequest,
    JobResult,
    JobState,
    LABEL_DECODE_TOKENS_PER_S,
    LABEL_KV_PAGES_FREE,
    LABEL_MIGRATE_ADDR,
    LABEL_PARTITION,
    LABEL_RESUME_TOKENS,
    LABEL_SERVING_ROLE,
    SERVING_ROLE_MIXED,
    SERVING_ROLE_PREFILL,
    SERVING_ROLES,
    STATUS_HINT_STREAM,
    SessionMoved,
    Span,
)
from ..serving.engine import (
    GenRequest,
    ServingEngine,
    SessionCancelled,
    SessionHibernated,
    SessionMigrated,
    SessionRequeued,
)
from ..serving.migration import MigrationError, MigrationServer, migrate_session
from ..utils.ids import new_id
from .gang import GangRunner

HEARTBEAT_INTERVAL_S = 10.0

# sentinel: payload not yet fetched from the memory store
_UNFETCHED = object()


class JobCancelled(Exception):
    pass


@dataclass
class JobContext:
    """Handed to job handlers: payload + progress/cancel plumbing."""

    request: JobRequest
    payload: Any
    worker: "Worker"
    cancelled: asyncio.Event = field(default_factory=asyncio.Event)
    started_at: float = field(default_factory=time.monotonic)
    # (name, start_us, end_us, attrs) tuples recorded by device_timer();
    # emitted as child spans of the execute span after the handler returns
    device_records: list = field(default_factory=list)

    def check_cancelled(self) -> None:
        if self.cancelled.is_set():
            raise JobCancelled(self.request.job_id)

    async def progress(self, percent: float, message: str = "") -> None:
        await self.worker.publish_progress(self.request.job_id, percent, message)

    def device_timer(self, name: str = "device", **attrs: str):
        """Sync context manager timing device work (the wall time around
        ``block_until_ready``).  Safe from executor threads: it only appends
        to a list; the event loop publishes the spans when the job ends."""
        from ..utils.ids import now_us

        class _Timer:
            def __enter__(timer):  # noqa: N805 - inner helper
                timer.t0 = now_us()
                return timer

            def __exit__(timer, et, ev, tb) -> None:  # noqa: N805
                rec_attrs = dict(attrs)
                if et is not None:
                    rec_attrs["error"] = et.__name__
                self.device_records.append((name, timer.t0, now_us(), rec_attrs))

        return _Timer()


# Handlers may be ``async def`` (must not block the loop — use
# ``ctx.worker.run_in_executor`` for blocking JAX work) or plain ``def``
# (automatically dispatched to the worker's thread pool so a blocking
# computation can never stall heartbeats/cancel delivery).
Handler = Callable[[JobContext], Any]


class Worker:
    def __init__(
        self,
        *,
        bus: Bus,
        store: MemoryStore,
        worker_id: str,
        pool: str = "default",
        topics: Optional[list[str]] = None,
        capabilities: Optional[list[str]] = None,
        labels: Optional[dict[str, str]] = None,
        max_parallel_jobs: int = 4,
        heartbeat_interval_s: float = HEARTBEAT_INTERVAL_S,
        region: str = "",
        serving_role: str = SERVING_ROLE_MIXED,
    ):
        self.bus = bus
        self.store = store
        self.worker_id = worker_id
        self.pool = pool
        self.topics = topics or []
        self.capabilities = capabilities or []
        self.labels = labels or {}
        self.max_parallel_jobs = max_parallel_jobs
        self.heartbeat_interval_s = heartbeat_interval_s
        self.region = region
        self._handlers: dict[str, Handler] = {}
        self._default_handler: Optional[Handler] = None
        self._sem = asyncio.Semaphore(max_parallel_jobs)
        self._active: dict[str, JobContext] = {}
        # published-result cache: a redelivered job republishes its recorded
        # result instead of re-running the work (reference worker behavior
        # under at-least-once delivery, docs/AGENT_PROTOCOL.md)
        self._completed: dict[str, JobResult] = {}
        self._completed_cap = 512
        self._subs: list = []
        # pool-topic subscriptions kept separate: drain drops ONLY these
        # (the direct/cancel subjects stay live for in-flight work)
        self._topic_subs: list = []
        self._hb_task: Optional[asyncio.Task] = None
        self._executor = ThreadPoolExecutor(max_workers=max_parallel_jobs, thread_name_prefix=f"{worker_id}-jax")
        self.tracer = Tracer("worker", bus)
        # optional micro-batcher (cordum_tpu/batching): batchable jobs bypass
        # the per-job semaphore and coalesce into bucketed XLA calls
        self._batcher: Optional[MicroBatcher] = None
        # optional serving engine (cordum_tpu/serving): llm.generate jobs
        # bypass the semaphore too — the engine's admission control (page
        # budget + max_sessions) bounds concurrency, and a session parked in
        # the decode loop must not starve the per-job lanes
        self._serving: Optional[ServingEngine] = None
        # serving session failover (docs/SERVING.md §Migration, drain, and
        # failover): the migration listener adopting peer sessions, the
        # peer map (fed by fan-out heartbeats) drain picks targets from,
        # and the drain state machine
        self._migration: Optional[MigrationServer] = None
        self._peers: dict[str, dict] = {}
        self._session_partition: dict[str, str] = {}
        # prefill/decode disaggregation (docs/SERVING.md §Disaggregation):
        # a "prefill"-roled worker hands sessions to a decode peer once
        # their prompts finish prefilling (or cross the engine's token
        # threshold); "decode" workers adopt them; "mixed" does both and
        # never hands off.  The role rides heartbeats + capacity beacons.
        self.serving_role = (
            serving_role if serving_role in SERVING_ROLES
            else SERVING_ROLE_MIXED
        )
        self._handoffs: set[str] = set()  # sessions with a hand-off in flight
        # batch preemption (docs/ADMISSION.md §Preemption): jobs still
        # waiting for an intake semaphore slot can be asked to give it back
        # — the waiter future wins the race against the acquire and the job
        # returns to the scheduler as a non-terminal SESSION_REQUEUE
        self._preempt_waiters: dict[str, asyncio.Future] = {}
        # gang scheduling (docs/GANG.md): member jobs (cordum.gang_id label)
        # route to the gang runner — rendezvous barrier + SPMD/MPMD step
        # program; members publish GangMsg traffic, never JobResults
        self._gang: Optional[GangRunner] = None
        self.gang_metrics = None
        self._draining = False
        self._drained = asyncio.Event()
        self._drain_task: Optional[asyncio.Task] = None
        self._telemetry = _device_telemetry()
        # capacity observatory (ISSUE 10): online per-(op, bucket) device
        # profiles published in the telemetry beacon's `capacity` block
        self.capacity = CapacityProfiler(self._telemetry["device_kind"] or "cpu")
        self._busy_since: Optional[float] = None
        self._busy_accum = 0.0
        self._window_start = time.monotonic()

    # ------------------------------------------------------------------
    def register(self, topic: str, handler: Handler) -> None:
        """Register a handler for a topic (exact or used as fallback via
        :meth:`register_default`)."""
        self._handlers[topic] = handler

    def register_default(self, handler: Handler) -> None:
        self._default_handler = handler

    def attach_batcher(self, batcher: MicroBatcher) -> None:
        """Wire a micro-batcher between job intake and the XLA handlers.
        Jobs whose payload the batcher recognizes (``batcher.parts``) are
        queued and flushed as one padded XLA call; everything else keeps the
        per-job handler path."""
        self._batcher = batcher

    @property
    def batcher(self) -> Optional[MicroBatcher]:
        return self._batcher

    def attach_serving(self, serving: ServingEngine) -> None:
        """Wire a serving engine between job intake and the decode loop.
        Jobs whose payload it recognizes (``serving.parts``) become decode
        sessions; everything else keeps the per-job handler path."""
        self._serving = serving
        if self.serving_role == SERVING_ROLE_PREFILL:
            # post-prefill hand-off (docs/SERVING.md §Disaggregation): the
            # engine fires once per session when its prompt finishes
            # prefilling (or crosses serving_handoff_tokens); we pick the
            # decode peer with the most KV headroom × steady decode rate
            serving.on_prefill_done = self._on_prefill_done
        # capacity beacon gauges: KV-page/arena headroom + decode occupancy
        # (read at snapshot time, never on the decode hot path)
        alloc = serving.allocator

        def _kv_headroom() -> dict:
            doc = {
                "pages_total": alloc.num_pages - 1,  # page 0 is the null page
                "pages_free": alloc.free_pages,
                "pages_in_use": alloc.used_pages,
            }
            if serving.prefix is not None:
                # prefix-cache residency (docs/SERVING.md §Prefix cache and
                # tiering): cached full-page prefixes still in the device
                # arena, and cold pages tiered out to host RAM
                doc["prefix_pages"] = serving.prefix.warm_pages
                doc["prefix_cold_pages"] = serving.prefix.cold_pages
            return doc

        self.capacity.set_kv_headroom(_kv_headroom)
        stats = serving.stats

        def _occupancy() -> dict:
            doc = {
                "decode_mean": round(stats.mean_occupancy, 3),
                "decode_max": stats.max_occupancy,
                "active_sessions": serving.active_sessions(),
            }
            if serving.prefix is not None:
                pf = serving.prefix.stats
                looked = pf.hits + pf.misses
                doc["prefix_hits"] = pf.hits
                doc["prefix_hit_rate"] = (
                    round(pf.hits / looked, 3) if looked else 0.0
                )
            if serving.tiering is not None:
                warm, cold = serving.tiering.tier_counts()
                doc["resident_warm"] = warm
                doc["resident_cold"] = cold
                doc["hibernated_sessions"] = len(serving.tiering.arena)
            if serving.speculative:
                # speculative acceptance (docs/SERVING.md §Speculative
                # decoding): the engine-level EWMA rides the existing
                # occupancy block, so the capacity matrix and the placer's
                # speculable-hint preference need no new ingest schema —
                # absence of the key IS the "speculation disabled" signal
                doc["spec_accept_rate"] = round(serving.spec_accept_ewma, 3)
            return doc

        self.capacity.set_occupancy(_occupancy)
        if serving.tiering is not None:
            # affinity keepalive (docs/SERVING.md §Prefix cache and tiering):
            # a hibernated conversation must route back HERE next turn — the
            # cold record is host-local — so the scheduler pins its affinity
            # entry past the normal TTL; restoring unpins it again
            serving.tiering.on_hibernated = (
                lambda key: self._publish_tier_move(key, "hibernated")
            )
            serving.tiering.on_restored = (
                lambda key: self._publish_tier_move(key, "restored")
            )

    def _publish_tier_move(self, session_key: str, reason: str) -> None:
        """Announce a tiering transition for ``session_key`` on the moved
        subject.  reason="hibernated" makes the scheduler pin the affinity
        entry (strategy.py SESSION_HIBERNATE_TTL_S); "restored" reverts it
        to the normal TTL.  Fire-and-forget like the migration
        announcement — a lost packet only risks a cold re-prefill."""
        if not session_key:
            return
        asyncio.ensure_future(self.bus.publish(
            subj.SERVING_MOVED,
            BusPacket.wrap(SessionMoved(
                job_id="",
                session_key=session_key,
                from_worker=self.worker_id,
                to_worker=self.worker_id,
                reason=reason,
            ), sender_id=self.worker_id),
        ))

    @property
    def serving(self) -> Optional[ServingEngine]:
        return self._serving

    def attach_gang(self, runner: GangRunner, *, metrics=None) -> None:
        """Wire a gang runner between job intake and the step programs.
        Jobs carrying the scheduler-stamped gang labels bypass the handler
        path (and the intake semaphore — the gang's device reservation is
        the concurrency bound)."""
        self._gang = runner
        self.gang_metrics = metrics

    @property
    def gang(self) -> Optional[GangRunner]:
        return self._gang

    async def run_in_executor(self, fn, *args):
        """Run a blocking JAX computation off the event loop."""
        return await asyncio.get_running_loop().run_in_executor(self._executor, fn, *args)

    # ------------------------------------------------------------------
    async def start(self) -> None:
        self._subs.append(
            await self.bus.subscribe(subj.direct_subject(self.worker_id), self._on_job, queue=self.worker_id)
        )
        for topic in self.topics:
            self._topic_subs.append(await self.bus.subscribe(topic, self._on_job, queue=self.pool))
        self._subs.append(await self.bus.subscribe(subj.CANCEL, self._on_cancel))
        self._subs.append(await self.bus.subscribe(subj.DRAIN, self._on_drain))
        self._subs.append(await self.bus.subscribe(subj.PREEMPT, self._on_preempt))
        if self._serving is not None:
            # live-migration listener + the peer map drain targets come
            # from (fan-out heartbeats carry each peer's listener address
            # and KV-page headroom)
            self._migration = MigrationServer(
                self._adopt_session, metrics=self._serving.metrics
            )
            await self._migration.start()
            self._subs.append(
                await self.bus.subscribe(subj.HEARTBEAT, self._on_peer_heartbeat)
            )
            self._subs.append(
                await self.bus.subscribe(subj.SERVING_REBALANCE,
                                         self._on_rebalance)
            )
        self._hb_task = asyncio.ensure_future(self._heartbeat_loop())
        await self.send_heartbeat()

    # cordum: single-flight -- sole caller is the owning runner's shutdown path; the cancel/await/None teardown is idempotent
    async def stop(self) -> None:
        if self._hb_task:
            self._hb_task.cancel()
            try:
                await self._hb_task
            except asyncio.CancelledError:
                pass
            except Exception as e:  # noqa: BLE001 - logged, never swallowed
                logx.warn("heartbeat loop crashed during shutdown", err=str(e))
        for s in [*self._subs, *self._topic_subs]:
            s.unsubscribe()
        self._subs = []
        self._topic_subs = []
        if self._migration is not None:
            await self._migration.stop()
            self._migration = None
        if self._batcher is not None:
            await self._batcher.stop()  # drain queued batches before the pool dies
        if self._serving is not None:
            await self._serving.stop()  # evict sessions (they publish CANCELLED)
        if self._gang is not None:
            await self._gang.stop()  # cancel member tasks (crash semantics:
            # no abort published — the scheduler watchdog recovers the gang)
        self._executor.shutdown(wait=False)

    # ------------------------------------------------------------------
    async def _on_cancel(self, subject: str, pkt: BusPacket) -> None:
        c = pkt.job_cancel
        if c is None or not c.job_id:
            return
        if c.job_id in self._active:
            self._active[c.job_id].cancelled.set()
        if self._batcher is not None:
            # still waiting in a batch queue: pull it out so it does not ride
            # in the flush; its waiter raises BatchCancelled and the job
            # publishes an ordinary CANCELLED result
            self._batcher.cancel(c.job_id)
        if self._serving is not None:
            # stateful cancel: evict the session from the decode loop (or
            # the admission queue) and free its KV pages; its waiter raises
            # SessionCancelled → ordinary CANCELLED result
            self._serving.cancel(c.job_id)

    async def _on_preempt(self, subject: str, pkt: BusPacket) -> None:
        """Batch-job preemption (docs/ADMISSION.md §Preemption): hand the
        job back to the scheduler where that is cheap and safe — a serving
        session requeues mid-decode (its pages free immediately and its
        streamed tokens ride the failover resume prefix), a job still
        waiting for an intake slot gives the slot up.  A handler already
        executing on the device is NOT interrupted: the request is simply
        ignored and the governor moves on."""
        p = pkt.job_preempt
        if p is None or not p.job_id:
            return
        waiter = self._preempt_waiters.get(p.job_id)
        if waiter is not None and not waiter.done():
            waiter.set_result(p.reason or "preempted")
            return
        if self._serving is not None and p.job_id in self._active:
            # requeue only if it really is a live session here (requeue()
            # returns False for unknown ids, so this is belt-and-braces)
            self._serving.requeue(p.job_id, "preempted")

    # ------------------------------------------------------------------
    # graceful drain + session migration (docs/SERVING.md §Migration,
    # drain, and failover)
    # ------------------------------------------------------------------
    async def _on_drain(self, subject: str, pkt: BusPacket) -> None:
        wd = pkt.worker_drain
        if wd is None or (wd.worker_id and wd.worker_id != self.worker_id):
            return
        logx.info("drain requested", worker_id=self.worker_id,
                  requested_by=wd.requested_by, reason=wd.reason)
        if self._drain_task is None:
            self._drain_task = asyncio.ensure_future(self.drain())

    async def _on_peer_heartbeat(self, subject: str, pkt: BusPacket) -> None:
        hb = pkt.heartbeat
        if hb is None or not hb.worker_id or hb.worker_id == self.worker_id:
            return
        addr = (hb.labels or {}).get(LABEL_MIGRATE_ADDR, "")
        if not addr:
            return
        labels = hb.labels or {}
        try:
            pages_free = int(labels.get(LABEL_KV_PAGES_FREE, "0") or 0)
        except ValueError:
            pages_free = 0
        try:
            decode_tps = float(labels.get(LABEL_DECODE_TOKENS_PER_S, "0") or 0)
        except ValueError:
            decode_tps = 0.0
        if len(self._peers) > 1024:
            self._peers.clear()  # unbounded-fleet guard
        self._peers[hb.worker_id] = {
            "addr": addr,
            "pages_free": pages_free,
            # hand-off targets rank by headroom × steady decode tokens/s
            # (the peer's own capacity-profiler measurement)
            "decode_tps": decode_tps,
            "role": labels.get(LABEL_SERVING_ROLE, SERVING_ROLE_MIXED),
            "draining": bool(hb.draining),
            "seen": time.monotonic(),
        }

    def _live_peers(self, *, exclude: tuple = ()) -> list[tuple[str, dict]]:
        window = max(30.0, 3 * self.heartbeat_interval_s)
        now = time.monotonic()
        return [
            (wid, p) for wid, p in self._peers.items()
            if not p["draining"] and now - p["seen"] <= window
            and wid not in exclude
        ]

    def _ranked_drain_peers(self) -> list[tuple[str, str]]:
        """Every live, non-draining peer as ``(worker_id, addr)``, most
        free KV pages first — drain targets (any role beats a requeue)."""
        peers = self._live_peers()
        peers.sort(key=lambda e: e[1]["pages_free"], reverse=True)
        return [(wid, p["addr"]) for wid, p in peers]

    def _ranked_handoff_peers(
        self, *, exclude: tuple = ()
    ) -> list[tuple[str, str]]:
        """Decode-capable peers ranked by KV-page headroom × steady decode
        tokens/s (docs/SERVING.md §Disaggregation) — the hand-off and
        rebalance target order.  Prefill-roled peers are excluded (their
        step budget is ingestion capacity); an unmeasured decode rate
        counts as 1.0 so a fresh decode worker still ranks by headroom."""
        peers = [
            (wid, p) for wid, p in self._live_peers(exclude=exclude)
            if p.get("role", SERVING_ROLE_MIXED) != SERVING_ROLE_PREFILL
            and p["pages_free"] > 0
        ]
        peers.sort(
            key=lambda e: e[1]["pages_free"] * max(e[1]["decode_tps"], 1.0),
            reverse=True,
        )
        return [(wid, p["addr"]) for wid, p in peers]

    async def _migrate_with_retry(
        self,
        job_id: str,
        targets: list[tuple[str, str]],
        *,
        reason: str = "handoff",
    ) -> tuple[bool, bool]:
        """Drive one session migration with ONE jittered retry against the
        next-best target (docs/SERVING.md §Disaggregation) — a single
        handshake failure must not silently abandon the move.  Returns
        ``(moved, used_retry)``; on False the session keeps decoding
        locally (the callers decide between local decode and requeue)."""
        serving = self._serving
        if serving is None:
            return False, False
        for attempt, (peer_id, addr) in enumerate(targets[:2]):
            if serving.describe_session(job_id) is None:
                return False, attempt > 0  # finished/cancelled meanwhile
            if attempt > 0:
                # jittered back-off before the fallback target: lets a
                # transiently wedged listener drain, and decorrelates
                # concurrent hand-offs retrying into the same peer
                await asyncio.sleep(random.uniform(0.05, 0.25))
            host, _, port = addr.rpartition(":")
            try:
                moved = await migrate_session(
                    serving, job_id, host, int(port),
                    meta_extra={
                        "partition": self._session_partition.get(job_id, ""),
                        "from_worker": self.worker_id,
                        "move_reason": reason,
                    },
                    metrics=serving.metrics,
                )
            except Exception as e:  # noqa: BLE001 - try the next target
                logx.warn("migration attempt crashed", job_id=job_id,
                          target=addr, err=str(e))
                moved = False
            if moved:
                return True, attempt > 0
        return False, len(targets) > 1

    # ------------------------------------------------------------------
    # post-prefill hand-off + decode rebalancing (docs/SERVING.md
    # §Disaggregation)
    # ------------------------------------------------------------------
    def _on_prefill_done(self, job_id: str) -> None:
        """Engine hook (fires once per session, from the decode loop): a
        prefill-roled worker ships the freshly prefilled session to the
        best decode peer.  Non-blocking — the loop keeps stepping while
        the live page phase streams."""
        if self._draining or self._closed_for_handoff(job_id):
            return
        self._handoffs.add(job_id)
        asyncio.ensure_future(self._handoff_session(job_id))

    def _closed_for_handoff(self, job_id: str) -> bool:
        return self._serving is None or job_id in self._handoffs

    async def _handoff_session(self, job_id: str) -> None:
        serving = self._serving
        metrics = serving.metrics if serving is not None else None
        try:
            peers = self._ranked_handoff_peers()
            if not peers:
                # no decode-capable peer: decode continues locally — the
                # policy degrades to co-location, never breaks the session
                if metrics is not None:
                    metrics.serving_handoffs.inc(outcome="no_peer")
                return
            moved, retried = await self._migrate_with_retry(
                job_id, peers, reason="handoff")
            if metrics is not None:
                outcome = (
                    ("retried_ok" if retried else "ok") if moved else "failed"
                )
                metrics.serving_handoffs.inc(outcome=outcome)
        finally:
            self._handoffs.discard(job_id)

    async def _on_rebalance(self, subject: str, pkt: BusPacket) -> None:
        """The decode rebalancer's move request: migrate our cheapest
        sessions (fewest live pages, oldest decode position; cooldown-
        immune sessions excluded — no ping-pong) toward the named
        headroom target, with the next-best peer as the jittered
        fallback."""
        rb = pkt.session_rebalance
        serving = self._serving
        if (
            rb is None or rb.worker_id != self.worker_id
            or serving is None or self._draining
        ):
            return
        metrics = serving.metrics
        job_ids = serving.pick_rebalance_sessions(max(1, rb.max_sessions))
        if not job_ids:
            if metrics is not None:
                metrics.serving_rebalances.inc(stage="no_sessions")
            return
        fallbacks = self._ranked_handoff_peers(
            exclude=(rb.target_worker, self.worker_id))
        targets = [(rb.target_worker, rb.target_addr), *fallbacks]
        for job_id in job_ids:
            moved, _ = await self._migrate_with_retry(
                job_id, targets, reason="rebalance")
            if metrics is not None:
                metrics.serving_rebalances.inc(
                    stage="moved" if moved else "failed")

    @property
    def draining(self) -> bool:
        return self._draining

    async def drain(self, timeout_s: float = 60.0) -> None:
        """Graceful drain: stop admitting, live-migrate every serving
        session to the peer with the most KV headroom (scheduler requeue as
        the fallback — zero CANCELLED sessions either way), let per-job
        work finish, and beacon ``draining`` so the scheduler deregisters
        this worker and evicts its affinity entries.  Idempotent; the
        caller (cmd/worker) exits once it returns."""
        if self._draining:
            await self._drained.wait()
            return
        self._draining = True
        logx.info("worker draining", worker_id=self.worker_id,
                  sessions=self._serving.session_count if self._serving else 0,
                  active_jobs=len(self._active))
        try:
            # the draining heartbeat deregisters us and evicts our
            # session/batch affinity BEFORE sessions start moving, so no
            # new turn races its session's migration
            await self.send_heartbeat()
        except Exception:  # noqa: BLE001 - beacon loss must not stop the drain
            logx.warn("draining heartbeat failed", worker_id=self.worker_id)
        for s in self._topic_subs:
            s.unsubscribe()
        self._topic_subs = []
        if self._serving is not None:
            for job_id in list(self._serving.session_ids()):
                moved = False
                # most-KV-headroom peer first, one jittered retry against
                # the next-best (any role beats a requeue when draining)
                peers = self._ranked_drain_peers()
                if peers and self._serving.describe_session(job_id) is not None:
                    moved, _ = await self._migrate_with_retry(
                        job_id, peers, reason="drain")
                if not moved:
                    # pending sessions (no KV state) and unmigratable ones
                    # go back to the scheduler — re-dispatched, not killed
                    self._serving.requeue(job_id, "worker draining")
        deadline = time.monotonic() + timeout_s
        while self._active and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        if self._active:
            logx.warn("drain timeout with jobs still active",
                      worker_id=self.worker_id, jobs=len(self._active))
        try:
            await self.send_heartbeat()  # final draining beacon
        except Exception as e:  # noqa: BLE001 - beacon loss must not stop the drain
            logx.warn("final draining heartbeat failed",
                      worker_id=self.worker_id, err=str(e))
        logx.info("worker drained", worker_id=self.worker_id)
        self._drained.set()

    async def wait_drained(self) -> None:
        await self._drained.wait()

    async def _adopt_session(self, meta: dict, state: dict, records: list) -> None:
        """Migration-listener install callback: adopt a peer's session —
        scatter its shipped pages into our arena and resume decoding.
        Raises to refuse (the sender falls back to a scheduler requeue)."""
        serving = self._serving
        if serving is None or self._draining:
            raise MigrationError("worker not accepting sessions")
        job_id = str(meta.get("job_id", ""))
        if not job_id:
            raise MigrationError("migration meta missing job_id")
        if job_id in self._completed:
            raise MigrationError(f"job {job_id} already completed here")
        eos = meta.get("eos_token")
        gen = GenRequest(
            prompt=[int(t) for t in meta.get("prompt") or []],
            max_new_tokens=int(meta.get("max_new_tokens", 16) or 16),
            session_key=str(meta.get("session_key", "") or ""),
            eos_token=int(eos) if isinstance(eos, int) else None,
            stream=bool(meta.get("stream", True)),
            resume_tokens=[int(t) for t in meta.get("resume_tokens") or []],
        )
        trace_id = str(meta.get("trace_id", "") or "")
        fut = await serving.install_session(
            gen, job_id=job_id, state=state, records=records,
            trace_id=trace_id, on_tokens=self._token_sink(job_id, gen),
        )
        self._session_partition[job_id] = str(meta.get("partition", "") or "")
        asyncio.ensure_future(self._finish_adopted(job_id, gen, trace_id, fut))
        # ownership announcement (docs/SERVING.md §Disaggregation): the
        # scheduler retargets the session's affinity so follow-up turns and
        # cancels route here; fire-and-forget — a lost announcement only
        # degrades to lazy eviction + re-election
        asyncio.ensure_future(self.bus.publish(
            subj.SERVING_MOVED,
            BusPacket.wrap(SessionMoved(
                job_id=job_id,
                session_key=gen.session_key,
                from_worker=str(meta.get("from_worker", "") or ""),
                to_worker=self.worker_id,
                reason=str(meta.get("move_reason", "") or ""),
            ), trace_id=trace_id, sender_id=self.worker_id),
        ))

    async def _finish_adopted(
        self, job_id: str, gen: GenRequest, trace_id: str, fut: asyncio.Future
    ) -> None:
        """Await an adopted session and publish its terminal result — the
        half of ``_run_job`` a migrated-in job still needs (the source
        worker's waiter publishes nothing once migration commits)."""
        t0 = time.monotonic()
        partition = self._session_partition.pop(job_id, "")
        status = JobState.SUCCEEDED.value
        error_code = error_message = result_ptr = ""
        try:
            tokens = await fut
            out = ServingEngine.result_doc(gen, tokens)
            result_ptr = await self.store.put_result(job_id, out)
        except SessionMigrated:
            return  # chained onward migration: the next owner publishes
        except SessionHibernated:
            return  # tiered to the cold arena: the restore path publishes
        except SessionRequeued as e:
            await self._publish_requeue(job_id, str(e) or "requeued",
                                        trace_id=trace_id, partition=partition)
            return
        except SessionCancelled:
            status = JobState.CANCELLED.value
            error_code, error_message = "CANCELLED", "cancelled"
        except Exception as e:  # noqa: BLE001 - adopted session failed
            status = JobState.FAILED.value
            error_code = type(e).__name__
            error_message = str(e) or error_code
        res = JobResult(
            job_id=job_id,
            status=status,
            result_ptr=result_ptr,
            worker_id=self.worker_id,
            execution_ms=int((time.monotonic() - t0) * 1000),
            error_code=error_code,
            error_message=error_message,
        )
        self._completed[job_id] = res
        await self.bus.publish(
            subj.stamped_result_subject(partition),
            BusPacket.wrap(res, trace_id=trace_id, sender_id=self.worker_id),
        )

    async def restore_session(self, job_id: str, *, trace_id: str = "") -> bool:
        """Thaw a live session hibernated by ``ServingEngine.hibernate_session``
        and resume publishing its stream + terminal result from this worker
        (the half the hibernate retirement deliberately skipped).  Returns
        False when the cold arena holds no such session."""
        serving = self._serving
        if serving is None or serving.tiering is None:
            return False
        doc = serving.tiering.arena.get(job_id)
        if doc is None:
            return False
        meta = doc.get("meta") or {}
        eos = meta.get("eos_token")
        gen = GenRequest(
            prompt=[int(t) for t in meta.get("prompt") or []],
            max_new_tokens=int(meta.get("max_new_tokens", 16) or 16),
            session_key=str(meta.get("session_key", "") or ""),
            eos_token=int(eos) if isinstance(eos, int) else None,
            stream=bool(meta.get("stream", True)),
            resume_tokens=[int(t) for t in meta.get("resume_tokens") or []],
        )
        fut = await serving.restore_hibernated(
            job_id, on_tokens=self._token_sink(job_id, gen)
        )
        asyncio.ensure_future(self._finish_adopted(job_id, gen, trace_id, fut))
        return True

    async def _publish_requeue(
        self, job_id: str, reason: str, *, trace_id: str = "", partition: str = ""
    ) -> None:
        """Hand a job back to the scheduler: a NON-terminal RUNNING result
        with ``error_code=SESSION_REQUEUE`` asks for failover re-dispatch
        (bounded by the attempts counter) instead of recording a terminal
        state — used by drain-without-target and the crashed decode loop."""
        res = JobResult(
            job_id=job_id,
            status=JobState.RUNNING.value,
            worker_id=self.worker_id,
            error_code=ERROR_SESSION_REQUEUE,
            error_message=reason,
            labels={"cordum.bus_msg_id":
                    f"requeue-{job_id}-{time.monotonic_ns()}"},
        )
        await self.bus.publish(
            subj.stamped_result_subject(partition),
            BusPacket.wrap(res, trace_id=trace_id, sender_id=self.worker_id),
        )

    async def _on_job(self, subject: str, pkt: BusPacket) -> None:
        req = pkt.job_request
        if req is None or not req.job_id:
            return
        if (
            self._draining
            and req.job_id not in self._active
            and req.job_id not in self._completed
        ):
            if self._gang is not None and GangRunner.is_member(req):
                # a gang member landing mid-drain is dropped silently: the
                # scheduler's gang watchdog sees the draining heartbeat and
                # aborts/requeues the WHOLE gang (a SESSION_REQUEUE here
                # would wrongly single-worker-redispatch the gang job)
                return
            # new work routed here mid-drain (affinity raced the draining
            # beacon): hand it straight back for failover re-dispatch
            await self._publish_requeue(
                req.job_id, "worker draining", trace_id=pkt.trace_id,
                partition=(req.labels or {}).get(LABEL_PARTITION, ""),
            )
            return
        if self._gang is not None and GangRunner.is_member(req):
            # gang member: rendezvous + step program, no intake semaphore
            # (the gang's device reservation is the concurrency bound) and
            # no JobResult (the scheduler aggregates member reports)
            payload = (
                await self.store.get_pointer(req.context_ptr)
                if req.context_ptr else None
            )
            await self._gang.handle(
                req, payload, trace_id=pkt.trace_id, parent_span_id=pkt.span_id,
            )
            return
        payload: Any = _UNFETCHED
        batch_parts: Optional[BatchParts] = None
        gen_req: Optional[GenRequest] = None
        if (
            (self._batcher is not None or self._serving is not None)
            and req.job_id not in self._active
            and req.job_id not in self._completed
            # explicit topic/adapter handlers win over the batch/serving path
            and self._handlers.get(req.topic) is None
            and self._handlers.get(req.adapter_id) is None
        ):
            payload = await self.store.get_pointer(req.context_ptr) if req.context_ptr else None
            if self._batcher is not None:
                batch_parts = self._batcher.parts(payload)
            if batch_parts is None and self._serving is not None:
                gen_req = self._serving.parts(payload)
                if gen_req is not None:
                    # the SLO class rides into the decode loop: batch
                    # prefill chunks yield step-budget headroom to
                    # interactive ones (docs/ADMISSION.md §Serving)
                    gen_req.job_class = req.priority or "BATCH"
                    rt = (req.labels or {}).get(LABEL_RESUME_TOKENS, "")
                    if rt:
                        # failover re-dispatch: the scheduler stamped the
                        # tokens the dead worker already streamed — they
                        # prefill as a forced-decode prefix and replay at
                        # offset 0 (docs/SERVING.md §Migration)
                        try:
                            gen_req.resume_tokens = [
                                int(t) for t in rt.split(",") if t
                            ][: gen_req.max_new_tokens]
                        except ValueError:
                            gen_req.resume_tokens = []
        if batch_parts is not None or gen_req is not None:
            # batchable/serving: no semaphore slot — a queued job must not
            # starve the per-job lanes while it waits for batch-mates (or
            # sits in the decode loop); the batcher's window / the serving
            # engine's admission control bound the actual device concurrency
            await self._run_job(
                req, trace_id=pkt.trace_id, parent_span_id=pkt.span_id,
                payload=payload, batch_parts=batch_parts, gen_req=gen_req,
            )
            return
        # per-job path: the semaphore acquire races a preemption waiter so a
        # BATCH job still queued for a slot can give it back under
        # interactive pressure (docs/ADMISSION.md §Preemption).  Once the
        # slot is held, the job is no longer preemptible.
        waiter: asyncio.Future = asyncio.get_running_loop().create_future()
        self._preempt_waiters[req.job_id] = waiter
        acquire = asyncio.ensure_future(self._sem.acquire())
        try:
            await asyncio.wait(
                {acquire, waiter}, return_when=asyncio.FIRST_COMPLETED
            )
        finally:
            self._preempt_waiters.pop(req.job_id, None)
        if acquire.done() and not acquire.cancelled():
            waiter.cancel()
            try:
                await self._run_job(
                    req, trace_id=pkt.trace_id, parent_span_id=pkt.span_id,
                    payload=payload,
                )
            finally:
                self._sem.release()
            return
        acquire.cancel()
        await self._publish_requeue(
            req.job_id, "preempted: yielded intake slot", trace_id=pkt.trace_id,
            partition=(req.labels or {}).get(LABEL_PARTITION, ""),
        )

    async def _run_job(
        self,
        req: JobRequest,
        *,
        trace_id: str = "",
        parent_span_id: str = "",
        payload: Any = _UNFETCHED,
        batch_parts: Optional[BatchParts] = None,
        gen_req: Optional[GenRequest] = None,
    ) -> None:
        if req.job_id in self._active:
            return  # redelivery of an in-flight job
        cached = self._completed.get(req.job_id)
        if cached is not None:
            # already ran: republish the recorded result, don't redo the work;
            # fresh bus msg-id so the republish survives the dedupe window
            copy = JobResult.from_dict(cached.to_dict())
            copy.labels = dict(copy.labels or {})
            copy.labels["cordum.bus_msg_id"] = f"republish-{req.job_id}-{time.monotonic_ns()}"
            await self.bus.publish(
                self._result_subject(req),
                BusPacket.wrap(copy, trace_id=trace_id, sender_id=self.worker_id),
            )
            return
        if payload is _UNFETCHED:
            payload = await self.store.get_pointer(req.context_ptr) if req.context_ptr else None
        ctx = JobContext(request=req, payload=payload, worker=self)
        self._active[req.job_id] = ctx
        self._mark_busy()
        # execute span: the worker-side leg of the trace (parent = the
        # scheduler's dispatch span carried on the job packet)
        exec_span = self.tracer.begin(
            "execute",
            trace_id=trace_id,
            parent_span_id=parent_span_id,
            attrs={"job_id": req.job_id, "topic": req.topic, "worker_id": self.worker_id},
        )
        t0 = time.monotonic()
        status = JobState.SUCCEEDED.value
        error_code = error_message = ""
        result_ptr = ""
        migrated = False
        hibernated = False
        requeue_reason = ""
        if gen_req is not None:
            # remembered for drain-time migration (the commit frame carries
            # the partition so the adopting worker's result routes home)
            self._session_partition[req.job_id] = (
                (req.labels or {}).get(LABEL_PARTITION, "")
            )
        try:
            if gen_req is not None and self._serving is not None:
                # serving path: park as a decode session; the continuous-
                # batching loop streams tokens via progress packets and the
                # terminal result carries the full list
                exec_span.attrs["serving"] = "true"
                out = await self._serving.submit(
                    gen_req,
                    job_id=req.job_id,
                    trace_id=trace_id,
                    parent_span_id=exec_span.span_id,
                    on_tokens=self._token_sink(req.job_id, gen_req),
                )
                exec_span.attrs["n_tokens"] = str(out.get("n_tokens", 0))
            elif batch_parts is not None and self._batcher is not None:
                # micro-batch path: park in the (op, bucket) queue and await
                # the scattered slice of the flushed XLA call.  The flush
                # writes batch_size / batch_queue_wait_ms straight into the
                # execute span's attrs via the sink.
                exec_span.attrs["batched"] = "true"
                out = await self._batcher.submit(
                    batch_parts.op,
                    batch_parts.rows,
                    job_id=req.job_id,
                    length=batch_parts.length,
                    n_rows=batch_parts.n_rows,
                    trace_id=trace_id,
                    parent_span_id=exec_span.span_id,
                    attr_sink=exec_span.attrs,
                )
            else:
                handler = self._handlers.get(req.topic) or self._handlers.get(req.adapter_id) or self._default_handler
                if handler is None:
                    raise RuntimeError(f"no handler for topic {req.topic!r}")
                import inspect

                if inspect.iscoroutinefunction(handler):
                    out = await handler(ctx)
                else:
                    # sync handler: enforce executor dispatch so blocking JAX
                    # work cannot stall the loop (heartbeats keep flowing)
                    out = await self.run_in_executor(handler, ctx)
                    if inspect.isawaitable(out):  # sync fn returned a coroutine
                        out = await out
            if out is not None:
                result_ptr = await self.store.put_result(req.job_id, out)
        except (JobCancelled, BatchCancelled, SessionCancelled):
            status = JobState.CANCELLED.value
            error_code, error_message = "CANCELLED", "cancelled"
        except SessionMigrated:
            migrated = True  # the target worker owns stream + result now
        except SessionHibernated:
            hibernated = True  # cold arena owns it; restore publishes
        except SessionRequeued as e:
            requeue_reason = str(e) or "requeued"
        except asyncio.CancelledError:
            status = JobState.CANCELLED.value
            error_code, error_message = "CANCELLED", "worker shutdown"
        except Exception as e:  # noqa: BLE001 - handler failure → FAILED result
            status = JobState.FAILED.value
            error_code = type(e).__name__
            error_message = str(e) or traceback.format_exc(limit=3)
        finally:
            self._active.pop(req.job_id, None)
            self._mark_idle()
        self._session_partition.pop(req.job_id, None)
        if migrated or hibernated or requeue_reason:
            # none of these outcomes is terminal here: a migrated session's
            # target publishes everything; a hibernated one publishes from
            # the restore path (restore_session); a requeued one goes back
            # to the scheduler as a non-terminal SESSION_REQUEUE result —
            # no completed-cache entry, so a later redelivery can re-run it
            if not migrated and not hibernated:
                await self._publish_requeue(
                    req.job_id, requeue_reason, trace_id=trace_id,
                    partition=(req.labels or {}).get(LABEL_PARTITION, ""),
                )
            exec_span.attrs["status"] = (
                "MIGRATED" if migrated
                else "HIBERNATED" if hibernated else "REQUEUED"
            )
            await self.tracer.finish(exec_span)
            return
        exec_span.attrs["status"] = status
        if error_code:
            exec_span.attrs["error_code"] = error_code
        await self.tracer.finish(
            exec_span,
            status="OK" if status == JobState.SUCCEEDED.value else "ERROR",
        )
        # device-time spans recorded by handlers (wall time around
        # block_until_ready, compile/host split in attrs when known)
        for name, start_us, end_us, attrs in ctx.device_records:
            await self.tracer.emit(Span(
                span_id=new_id(),
                parent_span_id=exec_span.span_id,
                trace_id=trace_id,
                name=name,
                service="worker",
                start_us=start_us,
                end_us=end_us,
                attrs={"job_id": req.job_id, **attrs},
            ))
        # capacity observatory: successful per-job-path work feeds the
        # device profiler (the micro-batch flush and the serving decode loop
        # feed it directly — observing those jobs here would double count)
        if (
            status == JobState.SUCCEEDED.value
            and batch_parts is None
            and gen_req is None
        ):
            self._observe_capacity(req, payload, ctx.device_records,
                                   time.monotonic() - t0)
        res = JobResult(
            job_id=req.job_id,
            status=status,
            result_ptr=result_ptr,
            worker_id=self.worker_id,
            execution_ms=int((time.monotonic() - t0) * 1000),
            error_code=error_code,
            error_message=error_message,
        )
        self._completed[req.job_id] = res
        if len(self._completed) > self._completed_cap:
            for k in list(itertools.islice(self._completed, self._completed_cap // 2)):
                del self._completed[k]
        await self.bus.publish(
            self._result_subject(req),
            BusPacket.wrap(
                res, trace_id=trace_id, sender_id=self.worker_id,
                span_id=exec_span.span_id, parent_span_id=exec_span.parent_span_id,
            ),
        )

    def _observe_capacity(
        self, req: JobRequest, payload: Any, device_records: list, wall_s: float
    ) -> None:
        """Feed one finished per-job-path job into the capacity profiler:
        device-timer records when the handler produced them (true device
        time, compile split, items/bucket attrs), otherwise the execute wall
        time as the host-op service time."""
        op = ""
        if isinstance(payload, dict):
            op = str(payload.get("op") or "")
        op = op or req.topic
        fed = False
        for name, start_us, end_us, attrs in device_records:
            if attrs.get("error"):
                continue  # a raised timer block is not delivered capacity
            try:
                items = int(attrs.get("items", "1") or 1)
            except (TypeError, ValueError):
                items = 1
            self.capacity.observe(
                attrs.get("op") or op,
                device_s=max(0, end_us - start_us) / 1e6,
                bucket=str(attrs.get("bucket", "-") or "-"),
                items=items,
                compiled=attrs.get("compile_cached") == "false",
            )
            fed = True
        if not fed:
            # no device timer (echo-class host ops): wall time still tells
            # the matrix what this worker delivers for the op
            self.capacity.observe(op, device_s=wall_s, items=1)

    @staticmethod
    def _result_subject(req: JobRequest) -> str:
        """Sharded schedulers stamp their partition on the dispatch; echoing
        it routes the result straight to the owning shard (no forwarding)."""
        return subj.stamped_result_subject((req.labels or {}).get(LABEL_PARTITION, ""))

    # ------------------------------------------------------------------
    def _token_sink(self, job_id: str, gen: GenRequest):
        """The serving engine's streaming callback: each decode step's new
        tokens ride a JobProgress packet with ``status_hint="stream"`` —
        relayed to WS consumers by the gateway tap, skipped by the
        scheduler's event persistence."""
        if not gen.stream:
            return None
        total = max(1, gen.max_new_tokens)

        async def sink(new_tokens: list[int], n_generated: int, done: bool) -> None:
            await self.bus.publish(
                subj.PROGRESS,
                BusPacket.wrap(
                    JobProgress(
                        job_id=job_id,
                        percent=min(100.0, 100.0 * n_generated / total),
                        status_hint=STATUS_HINT_STREAM,
                        worker_id=self.worker_id,
                        tokens=list(new_tokens),
                        # the packet's position in the session's FULL token
                        # sequence: failover replays the streamed prefix at
                        # offset 0, and consumers dedupe by offset so the
                        # assembled stream is exactly-once
                        offset=max(0, n_generated - len(new_tokens)),
                    ),
                    sender_id=self.worker_id,
                ),
            )

        return sink

    async def publish_progress(self, job_id: str, percent: float, message: str = "") -> None:
        await self.bus.publish(
            subj.PROGRESS,
            BusPacket.wrap(
                JobProgress(job_id=job_id, percent=percent, message=message, worker_id=self.worker_id),
                sender_id=self.worker_id,
            ),
        )

    # ------------------------------------------------------------------
    def telemetry_health(self) -> dict:
        """Health beacon for the fleet telemetry exporter (cmd/worker):
        live load + the stateful engines' occupancy."""
        out = {
            "role": "worker",
            "worker_id": self.worker_id,
            "pool": self.pool,
            "active_jobs": len(self._active),
            "max_parallel_jobs": self.max_parallel_jobs,
            "duty_cycle_pct": round(self._duty_cycle_peek(), 1),
            # capacity observatory: delta-encoded per-(op, bucket) device
            # profiles — the fleet aggregator folds these into the op ×
            # worker throughput matrix (docs/OBSERVABILITY.md)
            "capacity": self.capacity.snapshot(),
        }
        if self._serving is not None:
            out["serving_sessions"] = self._serving.active_sessions()
            # disaggregation placement signals (docs/SERVING.md
            # §Disaggregation): the role and drain flag ride the capacity
            # block so the scheduler's CapacityView and the fleet capacity
            # doc read them with the same staleness bound as the rates
            out["serving_role"] = self.serving_role
            out["capacity"]["serving_role"] = self.serving_role
            if self._draining:
                out["capacity"]["draining"] = True
        if self._gang is not None:
            # serving-gang membership (docs/SERVING.md §Sharded serving):
            # rank 0 beacons the fused throughput, followers their arena
            # headroom — the fleet folds all ranks into ONE capacity row
            gang_doc = self._gang.serving_gang_doc()
            if gang_doc:
                out["capacity"]["serving_gang"] = gang_doc
        if self._draining:
            out["draining"] = True
        return out

    def _duty_cycle_peek(self) -> float:
        """Duty cycle over the current window WITHOUT resetting it (the
        heartbeat's `_duty_cycle` owns the reset)."""
        now = time.monotonic()
        busy = self._busy_accum
        if self._busy_since is not None:
            busy += now - self._busy_since
        return min(100.0, 100.0 * busy / max(now - self._window_start, 1e-6))

    # ------------------------------------------------------------------
    def _mark_busy(self) -> None:
        if self._busy_since is None and self._active:
            self._busy_since = time.monotonic()

    def _mark_idle(self) -> None:
        if self._busy_since is not None and not self._active:
            self._busy_accum += time.monotonic() - self._busy_since
            self._busy_since = None

    def _duty_cycle(self) -> float:
        """Fraction of the heartbeat window the slice was executing jobs."""
        now = time.monotonic()
        busy = self._busy_accum
        if self._busy_since is not None:
            busy += now - self._busy_since
        window = max(now - self._window_start, 1e-6)
        self._busy_accum = 0.0
        self._window_start = now
        if self._busy_since is not None:
            self._busy_since = now
        return min(100.0, 100.0 * busy / window)

    def build_heartbeat(self) -> Heartbeat:
        tele = self._telemetry
        hbm_used, hbm_total = tele["hbm"]()
        labels = dict(self.labels)
        if self._migration is not None and self._serving is not None:
            # peers live-migrate serving sessions here; the free-page count
            # is the KV-headroom signal drain target selection ranks by,
            # and the role + steady decode rate let prefill workers rank
            # hand-off targets (docs/SERVING.md §Disaggregation)
            labels[LABEL_MIGRATE_ADDR] = self._migration.addr
            labels[LABEL_KV_PAGES_FREE] = str(self._serving.allocator.free_pages)
            labels[LABEL_SERVING_ROLE] = self.serving_role
            labels[LABEL_DECODE_TOKENS_PER_S] = (
                f"{self.capacity.steady_tokens_per_s('llm.generate'):.1f}"
            )
        return Heartbeat(
            worker_id=self.worker_id,
            region=self.region,
            type="tpu" if tele["is_tpu"] else "cpu",
            active_jobs=len(self._active),
            max_parallel_jobs=self.max_parallel_jobs,
            capabilities=list(self.capabilities),
            pool=self.pool,
            labels=labels,
            draining=self._draining,
            cpu_load=_host_cpu_load(),
            tpu_duty_cycle=self._duty_cycle(),
            hbm_used_gb=hbm_used,
            hbm_total_gb=hbm_total,
            device_kind=tele["device_kind"],
            chip_count=tele["chip_count"],
            slice_topology=tele["topology"],
            devices_healthy=tele["healthy"](),
        )

    async def send_heartbeat(self) -> None:
        await self.bus.publish(
            subj.HEARTBEAT, BusPacket.wrap(self.build_heartbeat(), sender_id=self.worker_id)
        )

    async def _heartbeat_loop(self) -> None:
        while True:
            await asyncio.sleep(self.heartbeat_interval_s)
            try:
                await self.send_heartbeat()
            except Exception:
                logx.warn("heartbeat publish failed", worker_id=self.worker_id)


def _host_cpu_load() -> float:
    """Host CPU pressure as a 0-100 %: 1-minute load average normalized by
    core count.  The least-loaded strategy folds it into the worker score
    (strategy.py load_score) and treats ≥90 as overloaded — so workers
    sharing a host with unrelated heavy processes stop winning placement.
    CORDUM_HOST_LOAD=0 disables it (hermetic tests: the suite itself
    saturates single-core CI hosts, which must not flip every worker to
    overloaded)."""
    import os

    if os.environ.get("CORDUM_HOST_LOAD", "1") == "0":
        return 0.0
    try:
        return min(100.0, 100.0 * os.getloadavg()[0] / (os.cpu_count() or 1))
    except (OSError, AttributeError):  # pragma: no cover - non-POSIX
        return 0.0


def _device_telemetry() -> dict:
    """Slice telemetry probes; degrades gracefully off-TPU and when JAX is
    not yet initialized."""
    try:
        import jax

        devs = jax.devices()
        from ..parallel.mesh import hbm_stats, slice_topology

        kind = devs[0].device_kind if devs else ""
        return {
            "is_tpu": devs[0].platform == "tpu" if devs else False,
            "device_kind": kind,
            "chip_count": len(devs),
            "topology": slice_topology(devs),
            "hbm": lambda: hbm_stats(devs),
            "healthy": lambda: _devices_alive(devs),
        }
    except Exception:
        return {
            "is_tpu": False,
            "device_kind": "",
            "chip_count": 0,
            "topology": "",
            "hbm": lambda: (0.0, 0.0),
            "healthy": lambda: True,
        }


def _devices_alive(devs) -> bool:
    """Liveness probe: a trivial computation must complete on each device."""
    try:
        import jax.numpy as jnp
        import jax

        for d in devs[:1]:  # probing one device per beat keeps it cheap
            jax.block_until_ready(jax.device_put(jnp.zeros((1,)), d) + 1)
        return True
    except Exception:
        return False
