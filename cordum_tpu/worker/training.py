"""Long-running training jobs on the TPU worker: checkpoint/resume +
progress + cooperative cancel.

The reference has no tensor checkpoints (control-plane durability only);
SURVEY §5 "Checkpoint/resume" calls for worker-side orbax-style
checkpointing for long JAX jobs as the new capability.  This module runs a
multi-step training loop for any registered model family (dense / moe /
pipeline), saving orbax checkpoints every ``checkpoint_every`` steps so a
re-dispatched job (worker crash, preemption, reconciler timeout → DLQ
retry) resumes from the latest step instead of restarting.

Job payload::

    {"op": "train", "model": "llama-tiny", "steps": 100,
     "batch": 8, "seq": 64, "checkpoint_every": 20,
     "run_name": "exp1", "mesh": {"tp": 2, "sp": 1}}

Also: :func:`profile_trace` — the JAX profiler hook (SURVEY §5 tracing:
"add JAX profiler/XLA dump hooks at the worker"): wraps a jitted call in a
``jax.profiler.trace`` so the trace lands in the artifact directory.
"""
from __future__ import annotations

import os
import time
from typing import Any, Optional

import numpy as np

from ..infra import logging as logx

DEFAULT_CKPT_ROOT = os.environ.get("CORDUM_CKPT_DIR", "/tmp/cordum-ckpt")


class TrainRunner:
    """Builds and runs checkpointed training loops (one per model family)."""

    def __init__(self, *, ckpt_root: str = DEFAULT_CKPT_ROOT):
        self.ckpt_root = ckpt_root

    # -- model family registry ------------------------------------------
    def _build(self, payload: dict):
        import jax

        from ..models import llama, moe, pipeline
        from ..parallel.mesh import MeshSpec, build_mesh

        model = str(payload.get("model", "llama-tiny"))
        mesh_req = payload.get("mesh") or {}
        n_dev = len(jax.devices())

        def safe(n):
            n = int(n)
            return n if n > 0 and n_dev % n == 0 else 1

        tp, sp, ep, pp = (safe(mesh_req.get(k, 1)) for k in ("tp", "sp", "ep", "pp"))
        if model.startswith("llama"):
            cfg = llama.LlamaConfig.tiny() if "tiny" in model else llama.LlamaConfig()
            mesh = build_mesh(MeshSpec(dp=-1, tp=tp, sp=sp))
            init, step = llama.make_train_step(cfg, mesh)
            vocab = cfg.vocab_size
        elif model.startswith("moe"):
            cfg = moe.MoEConfig.tiny()
            mesh = build_mesh(MeshSpec(dp=-1, tp=tp, ep=ep or 1))
            init, step = moe.make_train_step(cfg, mesh)
            vocab = cfg.base.vocab_size
        elif model.startswith("pipeline"):
            base = llama.LlamaConfig.tiny()
            pp = pp if pp > 1 else (2 if n_dev % 2 == 0 else 1)
            cfg = pipeline.PipelineConfig(base=base, n_stages=pp,
                                          n_microbatches=int(payload.get("microbatches", 2)))
            mesh = build_mesh(MeshSpec(dp=-1, pp=pp))
            init, step = pipeline.make_train_step(cfg, mesh)
            vocab = base.vocab_size
        else:
            raise ValueError(f"unknown model family {model!r}")
        return init, step, mesh, vocab, model

    # -- checkpointing ---------------------------------------------------
    def _ckpt_dir(self, run_name: str) -> str:
        return os.path.join(self.ckpt_root, run_name)

    def _make_manager(self, run_name: str):
        import orbax.checkpoint as ocp

        path = self._ckpt_dir(run_name)
        os.makedirs(path, exist_ok=True)
        return ocp.CheckpointManager(
            path, options=ocp.CheckpointManagerOptions(max_to_keep=3, create=True)
        )

    # -- the loop --------------------------------------------------------
    def train(self, payload: dict, *, cancelled=None, progress=None) -> dict:
        """Runs synchronously (call from the worker executor thread).
        ``cancelled``: callable → bool; ``progress``: callable(frac, msg)."""
        import jax
        import jax.numpy as jnp
        import orbax.checkpoint as ocp

        init, step, mesh, vocab, model = self._build(payload)
        steps = int(payload.get("steps", 10))
        dp = mesh.shape.get("dp", 1)
        mb = int(payload.get("microbatches", 2)) if model.startswith("pipeline") else 1
        batch = int(payload.get("batch", max(2, dp * 2)))
        # batch must divide dp (and microbatches for pipeline): round up
        quantum = dp * mb
        batch = max(quantum, ((batch + quantum - 1) // quantum) * quantum)
        seq = int(payload.get("seq", 32))
        ckpt_every = int(payload.get("checkpoint_every", 0))
        run_name = str(payload.get("run_name", "default"))

        params, opt_state = init(jax.random.PRNGKey(int(payload.get("seed", 0))))
        start_step = 0
        mgr = None
        if ckpt_every > 0:
            mgr = self._make_manager(run_name)
            latest = mgr.latest_step()
            if latest is not None:
                try:
                    restored = mgr.restore(
                        latest,
                        args=ocp.args.StandardRestore({"params": params, "opt_state": opt_state}),
                    )

                    def replace_like(template, value):
                        if not hasattr(value, "shape"):
                            return value
                        from jax.sharding import NamedSharding

                        sharding = getattr(template, "sharding", None)
                        # only commit to mesh-wide shardings; leave scalars /
                        # single-device leaves uncommitted so jit places them
                        host = np.asarray(value)  # break any committed placement
                        if isinstance(sharding, NamedSharding):
                            return jax.device_put(jnp.asarray(host, template.dtype), sharding)
                        return jnp.asarray(host, getattr(template, "dtype", None))

                    params = jax.tree.map(replace_like, params, restored["params"])
                    opt_state = jax.tree.map(replace_like, opt_state, restored["opt_state"])
                    start_step = latest
                    logx.info("resumed from checkpoint", run=run_name, step=latest)
                except Exception:
                    logx.warn("checkpoint restore failed; starting fresh", run=run_name)

        from ..models import pipeline as pipeline_mod

        is_pipeline = model.startswith("pipeline")
        losses = []
        t0 = time.monotonic()
        fixed_batch = bool(payload.get("fixed_batch", False))
        for i in range(start_step, steps):
            if cancelled is not None and cancelled():
                break
            key = jax.random.PRNGKey(1000 if fixed_batch else 1000 + i)
            tokens = jax.random.randint(key, (batch, seq), 0, vocab)
            if is_pipeline:
                tokens = pipeline_mod.microbatch(tokens, int(payload.get("microbatches", 2)))
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
            if mgr is not None and (i + 1) % ckpt_every == 0:
                mgr.save(
                    i + 1,
                    args=ocp.args.StandardSave(
                        {"params": jax.tree.map(np.asarray, params),
                         "opt_state": jax.tree.map(
                             lambda x: np.asarray(x) if hasattr(x, "shape") else x, opt_state)}
                    ),
                )
                mgr.wait_until_finished()
            if progress is not None:
                progress((i + 1) / steps, f"step {i + 1}/{steps} loss={losses[-1]:.4f}")
        done = start_step + len(losses)
        return {
            "model": model,
            "mesh": {k: int(v) for k, v in mesh.shape.items()},
            "resumed_from": start_step,
            "steps_done": done,
            "completed": done >= steps,
            "final_loss": losses[-1] if losses else None,
            "loss_first": losses[0] if losses else None,
            "seconds": round(time.monotonic() - t0, 3),
            "checkpointed": mgr is not None,
        }


def profile_trace(fn, *args, trace_dir: str = "/tmp/cordum-jax-trace"):
    """Run ``fn(*args)`` under the JAX profiler; returns (result, trace_dir).
    The trace directory can be uploaded as an artifact for offline
    inspection (tensorboard / xprof)."""
    import jax

    os.makedirs(trace_dir, exist_ok=True)
    with jax.profiler.trace(trace_dir):
        out = fn(*args)
        jax.block_until_ready(out)
    return out, trace_dir
