"""Workflow engine: deterministic step state machine over the run store.

Recreates the reference engine's behavior (``core/workflow/engine.go``,
1809 LoC) in asyncio:

  * step scheduling in the reference order: DAG ``depends_on`` gating →
    condition gate → built-ins (approval / condition / delay / notify)
    inline → ``for_each`` fan-out with ``max_parallel`` throttling and child
    pre-creation → job dispatch with job id ``runID:stepID@attempt``
  * results: attempt parsing, duplicate suppression, retry with exponential
    backoff (parked via ``next_retry_at_us``, resumed by the reconciler),
    output-schema validation, inline-result capture (≤256 KiB) into run
    context ``steps.<id>`` plus optional ``output_path`` graft, child
    aggregation, run-status rollup (a failed child fails the run unless the
    step declares ``on_error: continue``)
  * ``approve_step`` resumes approval-parked runs; ``cancel_run`` broadcasts
    JobCancel for in-flight jobs; ``rerun_from`` resets a step and its
    dependent closure into a fresh run; dry runs label dispatched jobs
  * ``${...}`` template expansion over ``{input, ctx, steps, item}``
"""
from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Optional

from ..infra import logging as logx
from ..infra.bus import Bus
from ..infra.configsvc import ConfigService
from ..infra.memstore import MemoryStore
from ..infra.metrics import Metrics
from ..infra.schemareg import SchemaRegistry
from ..obs.tracer import Tracer
from ..protocol import subjects as subj
from ..protocol.types import (
    BATCHABLE_OPS,
    BusPacket,
    ENV_EFFECTIVE_CONFIG,
    JobCancel,
    JobMetadata,
    JobRequest,
    JobResult,
    JobState,
    LABEL_BATCH_KEY,
    LABEL_DRY_RUN,
    LABEL_OP,
    LABEL_SESSION_KEY,
    LABEL_SLO_CLASS,
    Priority,
    SERVING_OPS,
    SPAN_ERROR,
    SPAN_OK,
    Span,
    SystemAlert,
)
from ..utils.ids import new_id, now_us
from . import models as M
from .eval import evaluate, expand_templates, set_path, truthy
from .models import Step, StepRun, TimelineEvent, Workflow, WorkflowRun
from .store import WorkflowStore

MAX_INLINE_RESULT_BYTES = 256 * 1024


class WorkflowError(Exception):
    pass


def make_job_id(run_id: str, step_key: str, attempt: int) -> str:
    return f"{run_id}:{step_key}@{attempt}"


def split_job_id(job_id: str) -> tuple[str, str, int]:
    """→ (run_id, step_key, attempt); raises ValueError for non-wf job ids."""
    head, _, attempt = job_id.rpartition("@")
    run_id, _, step_key = head.partition(":")
    if not run_id or not step_key or not attempt.isdigit():
        raise ValueError(f"not a workflow job id: {job_id!r}")
    return run_id, step_key, int(attempt)


def run_session_key(run: WorkflowRun) -> str:
    """The per-run serving session key: an explicit run label wins, else a
    run-scoped default.  Every ``llm.generate`` step of the run carries it,
    so turn N routes via session affinity to the worker already holding the
    session's KV pages (docs/WORKFLOWS.md §Session continuity)."""
    return run.labels.get(LABEL_SESSION_KEY) or f"wf:{run.run_id}"


_PRIORITY_VALUES = frozenset(p.value for p in Priority)


def child_key(step_id: str, index: int) -> str:
    return f"{step_id}#{index}"


def parse_child_key(step_key: str) -> tuple[str, Optional[int]]:
    if "#" in step_key:
        sid, _, idx = step_key.partition("#")
        return sid, int(idx) if idx.isdigit() else None
    return step_key, None


class Engine:
    def __init__(
        self,
        *,
        store: WorkflowStore,
        bus: Bus,
        mem: MemoryStore,
        schemas: Optional[SchemaRegistry] = None,
        configsvc: Optional[ConfigService] = None,
        metrics: Optional[Metrics] = None,
        instance_id: str = "wf-engine-0",
        context_svc: Any = None,
    ):
        self.store = store
        self.bus = bus
        self.mem = mem
        self.schemas = schemas
        self.configsvc = configsvc
        self.metrics = metrics or Metrics()
        self.instance_id = instance_id
        self.tracer = Tracer("workflow-engine", bus)
        # ContextService executing context.update / context.window steps
        # in-engine; its embedder submits embed jobs to the worker pool, so
        # the heavy leg still rides micro-batching (docs/WORKFLOWS.md)
        self.context_svc = context_svc
        self._context_tasks: set = set()

    # ------------------------------------------------------------------
    # run lifecycle
    # ------------------------------------------------------------------
    async def start_run(
        self,
        workflow_id: str,
        input_value: Any = None,
        *,
        org_id: str = "",
        idempotency_key: str = "",
        dry_run: bool = False,
        labels: Optional[dict[str, str]] = None,
        max_concurrent_runs: int = 0,
    ) -> WorkflowRun:
        wf = await self.store.get_workflow(workflow_id)
        if wf is None:
            raise WorkflowError(f"unknown workflow {workflow_id!r}")
        if self.schemas is not None and wf.input_schema_id:
            errs = await self.schemas.validate_id(wf.input_schema_id, input_value)
            if errs:
                raise WorkflowError(f"input schema validation failed: {errs}")
        if max_concurrent_runs and org_id:
            active = await self.store.count_active_runs(org_id)
            if active >= max_concurrent_runs:
                raise WorkflowError(
                    f"org {org_id} at max concurrent runs ({max_concurrent_runs})"
                )
        run_id = new_id()
        run = WorkflowRun(
            run_id=run_id,
            workflow_id=workflow_id,
            org_id=org_id or wf.org_id,
            status=M.RUNNING,
            input=input_value,
            context={"input": input_value, "steps": {}},
            steps={sid: StepRun(step_id=sid) for sid in wf.steps},
            created_at_us=now_us(),
            dry_run=dry_run,
            labels=labels or {},
            # one trace per run: every step-dispatch span parents under the
            # root span emitted at run end, so the whole agent loop renders
            # as a single waterfall with per-step critical-path blame
            trace_id=new_id(),
            root_span_id=new_id(),
        )
        # resolve the SLO class once and pin it as a run label (a caller
        # label override wins over the workflow default); every dispatched
        # JobRequest.priority reads it back
        slo = (run.labels.get(LABEL_SLO_CLASS) or wf.slo_class or "").upper()
        if slo in _PRIORITY_VALUES:
            run.labels[LABEL_SLO_CLASS] = slo
        self.metrics.workflow_runs.inc(status="STARTED")
        if idempotency_key:
            # persist the run shell BEFORE claiming the key: the loser of the
            # setnx race must always be able to read the winner's run
            await self.store.put_run(run)
            fresh, existing = await self.store.try_set_run_idempotency(idempotency_key, run_id)
            if not fresh:
                await self.store.delete_run(run_id)
                winner = await self.store.get_run(existing)
                if winner is not None:
                    return winner
        await self._timeline(run, "", "run_started", workflow_id)
        await self.schedule_ready(run, wf)
        await self._rollup_and_save(run, wf)
        return run

    # ------------------------------------------------------------------
    # scheduling
    # ------------------------------------------------------------------
    def _scope(self, run: WorkflowRun, item: Any = None, index: Optional[int] = None) -> dict:
        scope = {
            "input": run.context.get("input"),
            "ctx": run.context,
            "steps": run.context.get("steps", {}),
            "item": item,
        }
        if index is not None:
            scope["foreach_index"] = index
        return scope

    def _deps_satisfied(self, run: WorkflowRun, wf: Workflow, step: Step) -> bool:
        for dep in step.depends_on:
            sr = run.steps.get(dep)
            if sr is None:
                return False
            if sr.status in (M.SUCCEEDED, M.SKIPPED):
                continue
            dstep = wf.steps.get(dep)
            # continue-on-error: a FAILED dep still unblocks dependents
            if sr.status == M.FAILED and dstep and dstep.on_error == "continue":
                continue
            return False
        return True

    def _deps_failed(self, run: WorkflowRun, wf: Workflow, step: Step) -> bool:
        """A dep in a terminal failure state (not continue-on-error) means
        this step can never run."""
        for dep in step.depends_on:
            sr = run.steps.get(dep)
            if sr is None:
                return True
            dstep = wf.steps.get(dep)
            if sr.status in (M.FAILED, M.CANCELLED) and not (
                dstep and dstep.on_error == "continue"
            ):
                return True
        return False

    async def schedule_ready(self, run: WorkflowRun, wf: Optional[Workflow] = None) -> None:
        """One scheduling wave (reference scheduleReady, engine.go:453-827)."""
        if run.status in M.RUN_TERMINAL or run.status == M.WAITING_APPROVAL:
            return
        wf = wf or await self.store.get_workflow(run.workflow_id)
        if wf is None:
            return
        progress = True
        while progress:
            progress = False
            for sid, step in wf.steps.items():
                sr = run.steps.get(sid)
                if sr is None:
                    continue  # definition gained a step after this run started
                if sr.status != M.PENDING:
                    # for_each parents may need more children dispatched
                    if sr.status == M.RUNNING and step.for_each:
                        await self._dispatch_pending_children(run, wf, step, sr)
                    continue
                if self._deps_failed(run, wf, step):
                    sr.status = M.SKIPPED
                    sr.error = "dependency failed"
                    await self._timeline(run, sid, "step_skipped", "dependency failed")
                    progress = True
                    continue
                if not self._deps_satisfied(run, wf, step):
                    continue
                if step.condition and not truthy(evaluate(step.condition, self._scope(run))):
                    sr.status = M.SKIPPED
                    await self._timeline(run, sid, "step_skipped", "condition false")
                    progress = True
                    continue
                started = await self._start_step(run, wf, step, sr)
                progress = progress or started
                if run.status == M.WAITING_APPROVAL:
                    return  # approval pauses the wave

    async def _start_step(self, run: WorkflowRun, wf: Workflow, step: Step, sr: StepRun) -> bool:
        sid = step.id
        if step.type == "approval":
            sr.status = M.WAITING_APPROVAL
            run.status = M.WAITING_APPROVAL
            await self._timeline(run, sid, "approval_required", "")
            return True
        if step.type == "condition":
            value = truthy(evaluate(step.condition or str(step.input or ""), self._scope(run)))
            sr.status = M.SUCCEEDED
            sr.finished_at_us = now_us()
            self._inline_result(run, sid, {"value": value}, step)
            await self._timeline(run, sid, "condition_evaluated", str(value))
            return True
        if step.type == "delay":
            wake = self._delay_wake_us(step)
            if wake <= now_us():
                sr.status = M.SUCCEEDED
                sr.finished_at_us = now_us()
                await self._timeline(run, sid, "delay_elapsed", "")
            else:
                sr.status = M.WAITING
                sr.wake_at_us = wake
                await self._timeline(run, sid, "delay_started", str(wake))
            return True
        if step.type == "notify":
            msg = expand_templates(step.notify_message, self._scope(run))
            alert = SystemAlert(
                severity=step.notify_severity,
                source=f"workflow:{run.workflow_id}",
                message=str(msg),
                labels={"run_id": run.run_id, "step_id": sid},
            )
            await self.bus.publish(subj.WORKFLOW_EVENT, BusPacket.wrap(alert, sender_id=self.instance_id))
            sr.status = M.SUCCEEDED
            sr.finished_at_us = now_us()
            await self._timeline(run, sid, "notified", str(msg)[:120])
            return True
        if step.for_each:
            items = evaluate(step.for_each, self._scope(run))
            if not isinstance(items, list):
                sr.status = M.FAILED
                sr.error = f"for_each did not yield a list: {step.for_each!r}"
                await self._timeline(run, sid, "step_failed", sr.error)
                return True
            # pre-create all children, then dispatch up to max_parallel
            sr.children = {
                str(i): StepRun(step_id=child_key(sid, i)) for i in range(len(items))
            }
            sr.status = M.SUCCEEDED if not items else M.RUNNING
            sr.started_at_us = now_us()
            run.context.setdefault("_foreach_items", {})[sid] = items
            await self._timeline(run, sid, "fanout_started", f"{len(items)} children")
            await self._dispatch_pending_children(run, wf, step, sr)
            return True
        # plain job-dispatch step
        await self._dispatch_job(run, step, sr, key=sid, item=None, index=None)
        return True

    async def _dispatch_pending_children(
        self, run: WorkflowRun, wf: Workflow, step: Step, sr: StepRun
    ) -> None:
        items = (run.context.get("_foreach_items") or {}).get(step.id)
        if items is None:
            return
        active = sum(1 for c in sr.children.values() if c.status in (M.RUNNING, M.WAITING))
        limit = step.max_parallel or len(items)
        for i, item in enumerate(items):
            if active >= limit:
                break
            child = sr.children[str(i)]
            if child.status != M.PENDING:
                continue
            await self._dispatch_job(
                run, step, child, key=child_key(step.id, i), item=item, index=i
            )
            active += 1

    async def _dispatch_job(
        self,
        run: WorkflowRun,
        step: Step,
        sr: StepRun,
        *,
        key: str,
        item: Any,
        index: Optional[int],
    ) -> None:
        sr.attempts += 1
        sr.status = M.RUNNING
        sr.started_at_us = sr.started_at_us or now_us()
        job_id = make_job_id(run.run_id, key, sr.attempts)
        sr.job_id = job_id
        scope = self._scope(run, item=item, index=index)
        payload = expand_templates(step.input, scope)
        op = str(payload.get("op", "")) if isinstance(payload, dict) else ""
        if op in SERVING_OPS and not payload.get("session_id"):
            # agent-loop continuity: default the serving session to the
            # per-run key, so turn N of the loop prefills once and every
            # later turn routes (session affinity) to the worker already
            # holding the pages — no cold prefill across turns
            payload["session_id"] = run_session_key(run)
        if index is not None:
            payload = {"item": item, "foreach_index": index, "input": payload}
        if self.schemas is not None and step.input_schema_id:
            errs = await self.schemas.validate_id(step.input_schema_id, payload)
            if errs:
                sr.status = M.FAILED
                sr.error = f"input schema validation failed: {errs}"
                await self._timeline(run, key, "step_failed", sr.error)
                return
        if op in M.CONTEXT_STEP_OPS:
            # context.* steps execute in-engine against the ContextService;
            # the embeds inside still ride the worker pool (BusEmbedder) as
            # micro-batched jobs.  Completion feeds back through the normal
            # result path so run locking applies unchanged.
            await self.mem.put_context(job_id, payload)
            self._spawn_context_step(run, step, job_id, payload, key)
            self.metrics.workflow_steps.inc(topic=step.topic)
            await self._timeline(run, key, "step_dispatched", job_id)
            return
        req = await self._build_job_request(run, step, job_id, payload, index, op=op)
        # step-dispatch spans parent under the run's root span — the whole
        # run is ONE trace; scheduler/worker legs attach below via the
        # packet's span context
        async with self.tracer.span(
            "step-dispatch",
            trace_id=run.trace_id or new_id(),
            parent_span_id=run.root_span_id,
            attrs={"run_id": run.run_id, "step": key, "job_id": job_id},
        ) as sp:
            await self.mem.put_context(job_id, payload)
            await self.bus.publish(
                subj.SUBMIT,
                BusPacket.wrap(
                    req, trace_id=sp.trace_id, sender_id=self.instance_id,
                    span_id=sp.span_id,
                ),
            )
        self.metrics.workflow_steps.inc(topic=step.topic)
        await self._timeline(run, key, "step_dispatched", job_id)

    async def _build_job_request(
        self, run: WorkflowRun, step: Step, job_id: str, payload: Any,
        index: Optional[int], op: str = "",
    ) -> JobRequest:
        """Reference buildJobRequest (engine.go:1320-1415): step meta →
        JobMetadata, route labels, dry-run label, effective-config env —
        plus the gateway submit path's routing labels (op / session key) and
        the run's SLO class as the job priority."""
        labels = dict(step.route_labels)
        labels.update(run.labels)
        if run.dry_run:
            labels[LABEL_DRY_RUN] = "true"
        # mirror gateway _submit_one label stamping: consumers (throughput
        # matrix, session/batch affinity) never read the payload behind the
        # context pointer
        if op and LABEL_OP not in labels:
            labels[LABEL_OP] = op
        if op in SERVING_OPS and LABEL_SESSION_KEY not in labels:
            labels[LABEL_SESSION_KEY] = run_session_key(run)
        if op in SERVING_OPS and LABEL_BATCH_KEY not in labels:
            # template co-location (docs/SERVING.md §Prefix cache and
            # tiering): every run of one workflow template opens with the
            # same templated prompt, so batch affinity steers their first
            # turns onto one worker where the radix prefix cache turns the
            # shared prefill into a hit (later turns ride session affinity)
            labels[LABEL_BATCH_KEY] = f"wf-tpl:{run.workflow_id}"
        if op in BATCHABLE_OPS and LABEL_BATCH_KEY not in labels:
            labels[LABEL_BATCH_KEY] = op
        env: dict[str, str] = {}
        if index is not None:
            env["foreach_index"] = str(index)
        if self.configsvc is not None:
            snap = await self.configsvc.effective_snapshot(
                org=run.org_id, workflow=run.workflow_id
            )
            env[ENV_EFFECTIVE_CONFIG] = snap["config"]
        meta = None
        if step.meta:
            meta = JobMetadata(
                capability=str(step.meta.get("capability", "")),
                risk_tags=list(step.meta.get("risk_tags") or []),
                requires=list(step.meta.get("requires") or []),
                pack_id=str(step.meta.get("pack_id", "")),
            )
        slo = labels.get(LABEL_SLO_CLASS, "")
        return JobRequest(
            job_id=job_id,
            topic=step.topic,
            priority=slo if slo in _PRIORITY_VALUES else Priority.BATCH.value,
            context_ptr=f"kv://ctx:{job_id}",
            tenant_id=run.org_id,
            labels=labels,
            env=env,
            workflow_id=run.workflow_id,
            run_id=run.run_id,
            metadata=meta,
        )

    # ------------------------------------------------------------------
    # context.* steps (docs/WORKFLOWS.md §Context engine on the pool)
    # ------------------------------------------------------------------
    def _spawn_context_step(
        self, run: WorkflowRun, step: Step, job_id: str, payload: dict, key: str
    ) -> None:
        """Run a context.* step as a background task.  The task publishes a
        normal JobResult on ``sys.workflow.step.result`` when done, so the
        queue-group consumer applies it under the run lock exactly like a
        worker result (multi-replica safe) while the scheduler — which never
        saw these jobs — stays out of the loop; embedded/unit setups without
        a result consumer get the result applied directly."""
        task = asyncio.ensure_future(
            self._run_context_step(run, step, job_id, payload, key)
        )
        self._context_tasks.add(task)
        task.add_done_callback(self._context_tasks.discard)

    async def drain_context_steps(self) -> None:
        """Await in-flight context.* executor tasks (tests / benches)."""
        while self._context_tasks:
            await asyncio.gather(*list(self._context_tasks), return_exceptions=True)

    async def _run_context_step(
        self, run: WorkflowRun, step: Step, job_id: str, payload: dict, key: str
    ) -> None:
        res = JobResult(job_id=job_id, worker_id=self.instance_id)
        sp = self.tracer.begin(
            "context-execute",
            trace_id=run.trace_id,
            parent_span_id=run.root_span_id,
            attrs={"run_id": run.run_id, "step": key,
                   "op": str(payload.get("op", ""))},
        )
        t0 = time.monotonic()
        try:
            if self.context_svc is None:
                raise WorkflowError("no context service wired into this engine")
            coro = self._execute_context_op(run, payload)
            if step.timeout_sec > 0:
                output = await asyncio.wait_for(coro, step.timeout_sec)
            else:
                output = await coro
            res.result_ptr = await self.mem.put_result(job_id, output)
            res.status = JobState.SUCCEEDED.value
        except asyncio.TimeoutError:
            res.status = JobState.TIMEOUT.value
            res.error_code = "CONTEXT_TIMEOUT"
            res.error_message = f"context step exceeded {step.timeout_sec}s"
        except Exception as e:  # noqa: BLE001 - becomes a step failure
            res.status = JobState.FAILED.value
            res.error_code = "CONTEXT_STEP"
            res.error_message = str(e)
        res.execution_ms = int((time.monotonic() - t0) * 1000)
        ok = res.status == JobState.SUCCEEDED.value
        await self.tracer.finish(sp, status=SPAN_OK if ok else SPAN_ERROR)
        if self.bus.has_listener(subj.STEP_RESULT):
            await self.bus.publish(
                subj.STEP_RESULT,
                BusPacket.wrap(res, trace_id=run.trace_id,
                               sender_id=self.instance_id, span_id=sp.span_id),
            )
        else:
            await self.handle_job_result(res)

    async def _execute_context_op(self, run: WorkflowRun, payload: dict) -> Any:
        """``context.update`` appends chat events / (re-)indexes RAG chunks;
        ``context.window`` builds the model window.  The memory defaults to
        the run's session key so an agent loop reads the memory it wrote."""
        svc = self.context_svc
        op = str(payload.get("op", ""))
        memory_id = str(payload.get("memory_id") or run_session_key(run))
        if op == "context.update":
            await svc.update_memory(
                memory_id,
                user_payload=payload.get("user_payload"),
                model_response=str(payload.get("model_response", "")),
                mode=str(payload.get("mode", "CHAT")),
            )
            embedded = 0
            chunks = payload.get("chunks")
            if chunks:
                embedded = await svc.put_chunks(memory_id, list(chunks))
            if payload.get("summary"):
                await svc.set_summary(memory_id, str(payload["summary"]))
            return {"memory_id": memory_id, "updated": True, "embedded": embedded}
        if op == "context.window":
            msgs = await svc.build_window(
                memory_id,
                mode=str(payload.get("mode", "CHAT")),
                payload=payload.get("payload", payload.get("query")),
                max_input_tokens=int(payload.get("max_input_tokens", 0) or 4000),
            )
            return {
                "memory_id": memory_id,
                "messages": [m.to_dict() for m in msgs],
                "message_count": len(msgs),
            }
        raise WorkflowError(f"unknown context op {op!r}")

    @staticmethod
    def _delay_wake_us(step: Step) -> int:
        if step.delay_until:
            try:
                return int(float(step.delay_until) * 1e6)
            except ValueError:
                import datetime as dt

                t = dt.datetime.fromisoformat(step.delay_until.replace("Z", "+00:00"))
                return int(t.timestamp() * 1e6)
        return now_us() + int(step.delay_sec * 1e6)

    # ------------------------------------------------------------------
    # results
    # ------------------------------------------------------------------
    async def handle_job_result(self, res: JobResult) -> bool:
        """Apply a worker result to its run; returns True if it was a
        workflow job this engine advanced."""
        try:
            run_id, step_key, attempt = split_job_id(res.job_id)
        except ValueError:
            return False
        run = await self.store.get_run(run_id)
        if run is None:
            return False
        wf = await self.store.get_workflow(run.workflow_id)
        if wf is None:
            return False
        sid, child_idx = parse_child_key(step_key)
        step = wf.steps.get(sid)
        parent = run.steps.get(sid)
        if step is None or parent is None:
            return False
        sr = parent if child_idx is None else parent.children.get(str(child_idx))
        if sr is None:
            return False
        marker = f"{res.job_id}"
        if marker in sr.processed_results:
            return True  # duplicate result (redelivery) — already applied
        if attempt != sr.attempts:
            return True  # stale attempt
        if sr.status in M.STEP_TERMINAL:
            return True
        sr.processed_results.append(marker)
        sr.processed_results = sr.processed_results[-8:]  # bounded dedupe window

        status = res.status
        if status == JobState.SUCCEEDED.value:
            output = None
            if res.result_ptr:
                output = await self.mem.get_pointer(res.result_ptr)
            if self.schemas is not None and step.output_schema_id:
                errs = await self.schemas.validate_id(step.output_schema_id, output)
                if errs:
                    await self._apply_failure(run, step, sr, f"output schema: {errs}")
                    await self._after_result(run, wf, step, parent, sr)
                    return True
            sr.status = M.SUCCEEDED
            sr.finished_at_us = now_us()
            if child_idx is None:
                self._inline_result(run, sid, output, step)
            else:
                self._inline_child_result(run, sid, child_idx, output)
            await self._timeline(run, step_key, "step_succeeded", res.job_id)
        elif status in (JobState.FAILED.value, JobState.TIMEOUT.value):
            await self._apply_failure(run, step, sr, res.error_message or status)
        elif status == JobState.CANCELLED.value:
            sr.status = M.CANCELLED
            sr.finished_at_us = now_us()
            await self._timeline(run, step_key, "step_cancelled", res.job_id)
        elif status == JobState.DENIED.value:
            sr.status = M.FAILED
            sr.error = f"denied: {res.error_message}"
            sr.finished_at_us = now_us()
            await self._timeline(run, step_key, "step_denied", res.error_message)
        else:
            return True  # non-terminal hint

        if sr.status in M.STEP_TERMINAL and sr.started_at_us:
            # wall-clock step latency (dispatch → terminal result), with the
            # run trace as exemplar so a slow bucket resolves to a waterfall
            self.metrics.workflow_step_seconds.observe(
                max(0.0, ((sr.finished_at_us or now_us()) - sr.started_at_us) / 1e6),
                exemplar=run.trace_id, topic=step.topic,
            )
        await self._after_result(run, wf, step, parent, sr)
        return True

    async def _apply_failure(self, run: WorkflowRun, step: Step, sr: StepRun, err: str) -> None:
        """Retry with exponential backoff or mark FAILED (reference
        applyResult/shouldRetry/computeBackoff, engine.go:1524-1595)."""
        retry = step.retry
        if retry and sr.attempts <= retry.max_retries:
            backoff = min(
                retry.backoff_sec * (retry.multiplier ** (sr.attempts - 1)),
                retry.max_backoff_sec,
            )
            sr.status = M.WAITING
            sr.error = err
            sr.next_retry_at_us = now_us() + int(backoff * 1e6)
            await self._timeline(
                run, sr.step_id, "step_retry_scheduled", f"attempt {sr.attempts} failed: {err}"
            )
        else:
            sr.status = M.FAILED
            sr.error = err
            sr.finished_at_us = now_us()
            await self._timeline(run, sr.step_id, "step_failed", err)

    async def _after_result(
        self, run: WorkflowRun, wf: Workflow, step: Step, parent: StepRun, sr: StepRun
    ) -> None:
        if sr is not parent:
            self._aggregate_children(run, step, parent)
            if parent.status == M.RUNNING:
                await self._dispatch_pending_children(run, wf, step, parent)
        await self.schedule_ready(run, wf)
        await self._rollup_and_save(run, wf)

    def _aggregate_children(self, run: WorkflowRun, step: Step, parent: StepRun) -> None:
        """Reference aggregateChildren (engine.go:1623-1645)."""
        children = parent.children.values()
        if any(c.status in (M.PENDING, M.RUNNING, M.WAITING) for c in children):
            return
        failed = [c for c in children if c.status in (M.FAILED, M.CANCELLED)]
        parent.finished_at_us = now_us()
        if failed and step.on_error != "continue":
            parent.status = M.FAILED
            parent.error = f"{len(failed)} child step(s) failed"
        else:
            parent.status = M.SUCCEEDED
            outputs = (run.context.get("steps", {}).get(step.id) or {}).get("children", [])
            self._inline_result(run, step.id, {"children": outputs, "count": len(parent.children)}, step)

    def _inline_result(self, run: WorkflowRun, step_id: str, output: Any, step: Step) -> None:
        """Inline result ≤256KiB into run context steps.<id> + output_path."""
        try:
            size = len(json.dumps(output)) if output is not None else 0
        except (TypeError, ValueError):
            output, size = {"unserializable": True}, 0
        if size > MAX_INLINE_RESULT_BYTES:
            output = {"truncated": True, "bytes": size}
        steps_ctx = run.context.setdefault("steps", {})
        prior = steps_ctx.get(step_id)
        if isinstance(prior, dict) and isinstance(output, dict) and "children" in prior and "children" in output:
            pass  # aggregation result replaces child list wholesale
        steps_ctx[step_id] = output
        if step.output_path:
            set_path(run.context, step.output_path, output)

    def _inline_child_result(self, run: WorkflowRun, step_id: str, index: int, output: Any) -> None:
        steps_ctx = run.context.setdefault("steps", {})
        slot = steps_ctx.setdefault(step_id, {})
        if not isinstance(slot, dict) or "children" not in slot:
            slot = {"children": []}
            steps_ctx[step_id] = slot
        children = slot["children"]
        while len(children) <= index:
            children.append(None)
        try:
            if output is not None and len(json.dumps(output)) > MAX_INLINE_RESULT_BYTES:
                output = {"truncated": True}
        except (TypeError, ValueError):
            output = {"unserializable": True}
        children[index] = output

    # ------------------------------------------------------------------
    # rollup
    # ------------------------------------------------------------------
    async def _rollup_and_save(self, run: WorkflowRun, wf: Workflow) -> None:
        was_terminal = run.status in M.RUN_TERMINAL
        self._update_run_status(run, wf)
        if run.status in M.RUN_TERMINAL and not was_terminal:
            await self._finish_run(run)
        await self.store.put_run(run)

    async def _finish_run(self, run: WorkflowRun) -> None:
        """The run just went terminal: count it and emit the run-root span
        (explicit start = run creation), closing the one-trace-per-run
        waterfall every step-dispatch/execute span parented under."""
        self.metrics.workflow_runs.inc(status=run.status)
        if run.trace_id and run.root_span_id:
            await self.tracer.emit(
                Span(
                    span_id=run.root_span_id,
                    trace_id=run.trace_id,
                    name="workflow-run",
                    service="workflow-engine",
                    start_us=run.created_at_us,
                    end_us=run.finished_at_us or now_us(),
                    attrs={
                        "run_id": run.run_id,
                        "workflow_id": run.workflow_id,
                        "status": run.status,
                    },
                )
            )

    def _update_run_status(self, run: WorkflowRun, wf: Workflow) -> None:
        """Reference updateRunStatus (engine.go:1647-1699)."""
        if run.status in M.RUN_TERMINAL:
            return
        statuses = {sid: sr.status for sid, sr in run.steps.items()}
        hard_failed = [
            sid
            for sid, st in statuses.items()
            if st == M.FAILED and wf.steps.get(sid) and wf.steps[sid].on_error != "continue"
        ]
        if hard_failed:
            run.status = M.FAILED
            run.error = f"step(s) failed: {', '.join(sorted(hard_failed))}"
            run.finished_at_us = now_us()
            return
        if any(st == M.CANCELLED for st in statuses.values()):
            run.status = M.CANCELLED
            run.finished_at_us = now_us()
            return
        if any(sr.status == M.WAITING_APPROVAL for sr in run.steps.values()):
            run.status = M.WAITING_APPROVAL
            return
        if all(st in M.STEP_TERMINAL for st in statuses.values()):
            run.status = M.SUCCEEDED
            run.finished_at_us = now_us()
            return
        if any(
            sr.status == M.WAITING and (sr.wake_at_us or sr.next_retry_at_us)
            for sr in run.steps.values()
        ):
            run.status = M.WAITING
            return
        run.status = M.RUNNING

    # ------------------------------------------------------------------
    # approvals / cancel / resume
    # ------------------------------------------------------------------
    async def approve_step(
        self, run_id: str, step_id: str, *, approve: bool, approved_by: str = ""
    ) -> WorkflowRun:
        run = await self.store.get_run(run_id)
        if run is None:
            raise WorkflowError(f"unknown run {run_id!r}")
        sr = run.steps.get(step_id)
        if sr is None or sr.status != M.WAITING_APPROVAL:
            raise WorkflowError(f"step {step_id!r} is not awaiting approval")
        wf = await self.store.get_workflow(run.workflow_id)
        sr.finished_at_us = now_us()
        run.status = M.RUNNING  # un-park so the scheduling wave can settle deps
        if approve:
            sr.status = M.SUCCEEDED
            await self._timeline(run, step_id, "approved", approved_by)
        else:
            sr.status = M.FAILED
            sr.error = f"rejected by {approved_by or 'admin'}"
            await self._timeline(run, step_id, "rejected", approved_by)
        await self.schedule_ready(run, wf)
        await self._rollup_and_save(run, wf)
        return run

    async def cancel_run(self, run_id: str, *, reason: str = "") -> WorkflowRun:
        run = await self.store.get_run(run_id)
        if run is None:
            raise WorkflowError(f"unknown run {run_id!r}")
        if run.status in M.RUN_TERMINAL:
            return run
        wf = await self.store.get_workflow(run.workflow_id)
        for sid, sr in run.steps.items():
            for target in [sr, *sr.children.values()]:
                if target.status in (M.RUNNING,) and target.job_id:
                    await self.bus.publish(
                        subj.CANCEL,
                        BusPacket.wrap(
                            JobCancel(job_id=target.job_id, reason=reason or "run cancelled"),
                            sender_id=self.instance_id,
                        ),
                    )
                if target.status not in M.STEP_TERMINAL:
                    target.status = M.CANCELLED
                    target.finished_at_us = now_us()
        run.status = M.CANCELLED
        run.error = reason
        run.finished_at_us = now_us()
        await self._finish_run(run)
        await self._timeline(run, "", "run_cancelled", reason)
        await self.store.put_run(run)
        return run

    async def rerun_from(
        self, run_id: str, step_id: str, *, dry_run: bool = False
    ) -> WorkflowRun:
        """New run seeded from an existing one, with ``step_id`` and its
        dependent closure reset (reference RerunFrom, engine.go:85-151)."""
        src = await self.store.get_run(run_id)
        if src is None:
            raise WorkflowError(f"unknown run {run_id!r}")
        wf = await self.store.get_workflow(src.workflow_id)
        if wf is None or step_id not in wf.steps:
            raise WorkflowError(f"unknown step {step_id!r}")
        closure = self._dependent_closure(wf, step_id)
        run = WorkflowRun(
            run_id=new_id(),
            workflow_id=src.workflow_id,
            org_id=src.org_id,
            status=M.RUNNING,
            input=src.input,
            context=json.loads(json.dumps(src.context)),
            created_at_us=now_us(),
            dry_run=dry_run,
            labels=dict(src.labels),
            # a rerun is a fresh trace: the re-executed closure renders as
            # its own waterfall, linked back via the rerun_from timeline row
            trace_id=new_id(),
            root_span_id=new_id(),
        )
        for sid in wf.steps:
            if sid in closure:
                run.steps[sid] = StepRun(step_id=sid)
                run.context.get("steps", {}).pop(sid, None)
            else:
                run.steps[sid] = StepRun.from_dict(src.steps[sid].to_dict())
        await self._timeline(run, step_id, "rerun_from", run_id)
        await self.schedule_ready(run, wf)
        await self._rollup_and_save(run, wf)
        return run

    @staticmethod
    def _dependent_closure(wf: Workflow, step_id: str) -> set[str]:
        closure = {step_id}
        changed = True
        while changed:
            changed = False
            for sid, step in wf.steps.items():
                if sid not in closure and any(d in closure for d in step.depends_on):
                    closure.add(sid)
                    changed = True
        return closure

    async def resume_due(self, run_id: str) -> bool:
        """Wake delay steps whose time has come and re-dispatch parked
        retries (called by the reconciler).  Returns True if progressed."""
        run = await self.store.get_run(run_id)
        if run is None or run.status in M.RUN_TERMINAL:
            return False
        wf = await self.store.get_workflow(run.workflow_id)
        if wf is None:
            return False
        now = now_us()
        progressed = False
        for sid, sr in run.steps.items():
            step = wf.steps.get(sid)
            if step is None:
                continue  # definition lost this step after the run started
            targets = [sr, *sr.children.values()]
            for t in targets:
                if t.status != M.WAITING:
                    continue
                if t.wake_at_us and t.wake_at_us <= now:
                    t.status = M.SUCCEEDED
                    t.finished_at_us = now
                    await self._timeline(run, t.step_id, "delay_elapsed", "")
                    progressed = True
                elif t.next_retry_at_us and t.next_retry_at_us <= now:
                    t.next_retry_at_us = 0
                    sid_key, idx = parse_child_key(t.step_id)
                    items = (run.context.get("_foreach_items") or {}).get(sid_key)
                    item = items[idx] if (items is not None and idx is not None) else None
                    await self._dispatch_job(
                        run, step, t, key=t.step_id, item=item, index=idx
                    )
                    progressed = True
        if progressed:
            await self.schedule_ready(run, wf)
            await self._rollup_and_save(run, wf)
        return progressed

    # ------------------------------------------------------------------
    async def _timeline(self, run: WorkflowRun, step_id: str, event: str, detail: str) -> None:
        await self.store.append_timeline(
            TimelineEvent(run_id=run.run_id, step_id=step_id, event=event, detail=str(detail))
        )
