"""Tiny expression language for workflow conditions and ``for_each``
(reference ``core/workflow/eval.go:17-216``): literals, dot-paths over the
scope, ``length()`` / ``first()`` helpers, comparisons, ``!`` negation.

Scope = ``{"input": …, "ctx": …, "steps": …, "item": …}``.

Also implements ``${...}`` template expansion for step inputs (reference
``core/workflow/engine.go:873-964``): a string that is exactly one template
is replaced by the resolved *value* (preserving type); templates embedded in
larger strings are stringified.
"""
from __future__ import annotations

import json
import re
from typing import Any

_COMPARATORS = ("==", "!=", ">=", "<=", ">", "<")
_NUM_RE = re.compile(r"^-?\d+(\.\d+)?$")


class EvalError(Exception):
    pass


def resolve_path(scope: Any, path: str) -> Any:
    """Dot-path lookup over dicts/lists; missing → None."""
    cur = scope
    for part in path.split("."):
        if cur is None:
            return None
        if isinstance(cur, dict):
            cur = cur.get(part)
        elif isinstance(cur, list):
            try:
                cur = cur[int(part)]
            except (ValueError, IndexError):
                return None
        else:
            return None
    return cur


def _parse_operand(scope: dict[str, Any], text: str) -> Any:
    text = text.strip()
    if not text:
        return None
    if text.startswith("length(") and text.endswith(")"):
        v = _parse_operand(scope, text[len("length("):-1])
        try:
            return len(v)  # type: ignore[arg-type]
        except TypeError:
            return 0
    if text.startswith("first(") and text.endswith(")"):
        v = _parse_operand(scope, text[len("first("):-1])
        if isinstance(v, (list, tuple)) and v:
            return v[0]
        return None
    if (text.startswith('"') and text.endswith('"')) or (
        text.startswith("'") and text.endswith("'")
    ):
        return text[1:-1]
    if text in ("true", "True"):
        return True
    if text in ("false", "False"):
        return False
    if text in ("null", "None"):
        return None
    if _NUM_RE.match(text):
        return float(text) if "." in text else int(text)
    return resolve_path(scope, text)


def truthy(v: Any) -> bool:
    if v is None:
        return False
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return v != 0
    if isinstance(v, str):
        return v != "" and v.lower() != "false"
    if isinstance(v, (list, dict)):
        return len(v) > 0
    return True


def evaluate(expr: str, scope: dict[str, Any]) -> Any:
    """Evaluate an expression against the scope."""
    expr = (expr or "").strip()
    if not expr:
        return True
    if expr.startswith("!"):
        return not truthy(evaluate(expr[1:], scope))
    for op in _COMPARATORS:
        # split on the first comparator occurrence outside quotes
        idx = _find_op(expr, op)
        if idx >= 0:
            left = _parse_operand(scope, expr[:idx])
            right = _parse_operand(scope, expr[idx + len(op):])
            return _compare(left, right, op)
    return _parse_operand(scope, expr)


def _find_op(expr: str, op: str) -> int:
    in_quote = ""
    i = 0
    while i < len(expr) - len(op) + 1:
        c = expr[i]
        if in_quote:
            if c == in_quote:
                in_quote = ""
        elif c in "\"'":
            in_quote = c
        elif expr[i : i + len(op)] == op:
            # avoid matching ">" inside ">=" etc.
            if op in (">", "<") and i + 1 < len(expr) and expr[i + 1] == "=":
                i += 1
                continue
            if op == "!" :
                pass
            return i
        i += 1
    return -1


def _compare(a: Any, b: Any, op: str) -> bool:
    if op == "==":
        return _coerced(a) == _coerced(b)
    if op == "!=":
        return _coerced(a) != _coerced(b)
    try:
        af, bf = float(a), float(b)  # type: ignore[arg-type]
    except (TypeError, ValueError):
        af, bf = str(a), str(b)  # lexicographic fallback
    if op == ">":
        return af > bf
    if op == "<":
        return af < bf
    if op == ">=":
        return af >= bf
    if op == "<=":
        return af <= bf
    raise EvalError(f"unknown comparator {op}")


def _coerced(v: Any) -> Any:
    # numbers compare numerically whether int or float
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return float(v)
    return v


# ---------------------------------------------------------------------------
# ${...} templates
# ---------------------------------------------------------------------------

_TEMPLATE_RE = re.compile(r"\$\{([^}]*)\}")


def expand_templates(value: Any, scope: dict[str, Any]) -> Any:
    """Recursively expand ``${expr}`` in strings/dicts/lists."""
    if isinstance(value, str):
        m = _TEMPLATE_RE.fullmatch(value.strip())
        if m:
            return evaluate(m.group(1), scope)

        def sub(match: re.Match) -> str:
            v = evaluate(match.group(1), scope)
            if isinstance(v, (dict, list)):
                return json.dumps(v)
            return "" if v is None else str(v)

        return _TEMPLATE_RE.sub(sub, value)
    if isinstance(value, dict):
        return {k: expand_templates(v, scope) for k, v in value.items()}
    if isinstance(value, list):
        return [expand_templates(v, scope) for v in value]
    return value


def set_path(target: dict, path: str, value: Any) -> None:
    """Graft ``value`` at dot-path in ``target`` (creating dicts)."""
    parts = path.split(".")
    cur = target
    for p in parts[:-1]:
        nxt = cur.get(p)
        if not isinstance(nxt, dict):
            nxt = {}
            cur[p] = nxt
        cur = nxt
    cur[parts[-1]] = value
