"""Workflow data model (reference ``core/workflow/models.go:8-180``).

A workflow is a DAG of steps keyed by id.  Built-in step types are
interpreted by the engine (approval / condition / delay / notify); every
other type dispatches as a job on the step's topic.  ``for_each`` is a
modifier on a dispatching step that fans out one child per item with
``max_parallel`` throttling.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ..utils.ids import now_us

BUILTIN_STEP_TYPES = {"approval", "condition", "delay", "notify"}

# Workflow SLO classes: mirror protocol.types.Priority values (kept as a
# local literal so the model layer stays dependency-free).  The class rides
# into every dispatched JobRequest.priority, so a whole agent swarm can be
# shed on the admission ladder before one interactive loop degrades.
SLO_CLASSES = ("INTERACTIVE", "BATCH", "CRITICAL")

# ops the engine executes in-process against the ContextService (the embeds
# themselves still run as pool jobs); every other op dispatches on the bus
CONTEXT_STEP_OPS = ("context.update", "context.window")

# run / step statuses
PENDING = "PENDING"
RUNNING = "RUNNING"
WAITING = "WAITING"        # delay steps / parked retries
WAITING_APPROVAL = "WAITING_APPROVAL"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
CANCELLED = "CANCELLED"
SKIPPED = "SKIPPED"        # condition gate false

RUN_TERMINAL = {SUCCEEDED, FAILED, CANCELLED}
STEP_TERMINAL = {SUCCEEDED, FAILED, CANCELLED, SKIPPED}


@dataclass
class RetryPolicy:
    max_retries: int = 0
    backoff_sec: float = 1.0
    multiplier: float = 2.0
    max_backoff_sec: float = 300.0


@dataclass
class Step:
    id: str = ""
    type: str = "worker"          # builtin type or job-dispatch type
    topic: str = ""
    depends_on: list[str] = field(default_factory=list)
    condition: str = ""           # expression gate; false → SKIPPED
    for_each: str = ""            # expression yielding a list → fan-out
    max_parallel: int = 0         # 0 = unlimited children at once
    input: Any = None             # templated payload (${...} expansion)
    input_schema_id: str = ""
    output_schema_id: str = ""
    output_path: str = ""         # where to graft the result in run ctx
    meta: dict[str, Any] = field(default_factory=dict)  # → JobMetadata
    route_labels: dict[str, str] = field(default_factory=dict)
    retry: Optional[RetryPolicy] = None
    timeout_sec: float = 0.0
    delay_sec: float = 0.0        # delay steps
    delay_until: str = ""         # RFC3339 or unix seconds
    notify_message: str = ""      # notify steps
    notify_severity: str = "info"
    on_error: str = ""            # "continue" → failure doesn't fail the run

    @classmethod
    def from_dict(cls, sid: str, d: dict[str, Any]) -> "Step":
        retry = None
        if d.get("retry"):
            r = d["retry"]
            retry = RetryPolicy(
                max_retries=int(r.get("max_retries", 0)),
                backoff_sec=float(r.get("backoff_sec", 1.0)),
                multiplier=float(r.get("multiplier", 2.0)),
                max_backoff_sec=float(r.get("max_backoff_sec", 300.0)),
            )
        return cls(
            id=sid,
            type=str(d.get("type", "worker")),
            topic=str(d.get("topic", "")),
            depends_on=list(d.get("depends_on") or []),
            condition=str(d.get("condition", "")),
            for_each=str(d.get("for_each", "")),
            max_parallel=int(d.get("max_parallel", 0)),
            input=d.get("input"),
            input_schema_id=str(d.get("input_schema_id", "")),
            output_schema_id=str(d.get("output_schema_id", "")),
            output_path=str(d.get("output_path", "")),
            meta=dict(d.get("meta") or {}),
            route_labels={str(k): str(v) for k, v in (d.get("route_labels") or {}).items()},
            retry=retry,
            timeout_sec=float(d.get("timeout_sec", 0.0)),
            delay_sec=float(d.get("delay_sec", 0.0)),
            delay_until=str(d.get("delay_until", "")),
            notify_message=str(d.get("notify_message", d.get("message", ""))),
            notify_severity=str(d.get("notify_severity", "info")),
            on_error=str(d.get("on_error", "")),
        )

    def to_dict(self) -> dict[str, Any]:
        d = {
            "type": self.type,
            "topic": self.topic,
            "depends_on": self.depends_on,
            "condition": self.condition,
            "for_each": self.for_each,
            "max_parallel": self.max_parallel,
            "input": self.input,
            "input_schema_id": self.input_schema_id,
            "output_schema_id": self.output_schema_id,
            "output_path": self.output_path,
            "meta": self.meta,
            "route_labels": self.route_labels,
            "timeout_sec": self.timeout_sec,
            "delay_sec": self.delay_sec,
            "delay_until": self.delay_until,
            "notify_message": self.notify_message,
            "notify_severity": self.notify_severity,
            "on_error": self.on_error,
        }
        if self.retry:
            d["retry"] = dict(self.retry.__dict__)
        return d


@dataclass
class Workflow:
    id: str = ""
    name: str = ""
    org_id: str = ""
    version: int = 1
    input_schema_id: str = ""
    # SLO class stamped on every dispatched JobRequest.priority ("" = BATCH);
    # a run label `cordum.slo_class` overrides it per run
    slo_class: str = ""
    steps: dict[str, Step] = field(default_factory=dict)
    labels: dict[str, str] = field(default_factory=dict)
    created_at_us: int = 0

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Workflow":
        wf = cls(
            id=str(d.get("id", "")),
            name=str(d.get("name", "")),
            org_id=str(d.get("org_id", "")),
            version=int(d.get("version", 1)),
            input_schema_id=str(d.get("input_schema_id", "")),
            slo_class=str(d.get("slo_class", "")).upper(),
            labels={str(k): str(v) for k, v in (d.get("labels") or {}).items()},
            created_at_us=int(d.get("created_at_us", 0) or now_us()),
        )
        for sid, sd in (d.get("steps") or {}).items():
            wf.steps[sid] = Step.from_dict(sid, sd or {})
        return wf

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "name": self.name,
            "org_id": self.org_id,
            "version": self.version,
            "input_schema_id": self.input_schema_id,
            "slo_class": self.slo_class,
            "labels": self.labels,
            "created_at_us": self.created_at_us,
            "steps": {sid: s.to_dict() for sid, s in self.steps.items()},
        }

    def validate(self) -> list[str]:
        errs = []
        if self.slo_class and self.slo_class not in SLO_CLASSES:
            errs.append(
                f"unknown slo_class {self.slo_class!r} (one of {', '.join(SLO_CLASSES)})"
            )
        for sid, step in self.steps.items():
            for dep in step.depends_on:
                if dep not in self.steps:
                    errs.append(f"step {sid}: unknown dependency {dep!r}")
            if step.type not in BUILTIN_STEP_TYPES and not step.topic:
                errs.append(f"step {sid}: dispatching step needs a topic")
        # cycle check (Kahn)
        indeg = {sid: len(s.depends_on) for sid, s in self.steps.items()}
        queue = [sid for sid, n in indeg.items() if n == 0]
        seen = 0
        while queue:
            sid = queue.pop()
            seen += 1
            for other, s in self.steps.items():
                if sid in s.depends_on:
                    indeg[other] -= 1
                    if indeg[other] == 0:
                        queue.append(other)
        if seen != len(self.steps):
            errs.append("dependency cycle detected")
        return errs


@dataclass
class StepRun:
    step_id: str = ""
    status: str = PENDING
    attempts: int = 0
    job_id: str = ""
    started_at_us: int = 0
    finished_at_us: int = 0
    error: str = ""
    next_retry_at_us: int = 0       # parked retry resume time
    wake_at_us: int = 0             # delay step resume time
    children: dict[str, "StepRun"] = field(default_factory=dict)  # for_each index → child
    processed_results: list[str] = field(default_factory=list)    # "jobid@attempt" dedupe

    def to_dict(self) -> dict[str, Any]:
        d = dict(self.__dict__)
        d["children"] = {k: c.to_dict() for k, c in self.children.items()}
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "StepRun":
        c = {k: StepRun.from_dict(v) for k, v in (d.get("children") or {}).items()}
        kw = {k: v for k, v in d.items() if k in cls.__dataclass_fields__ and k != "children"}
        sr = cls(**kw)
        sr.children = c
        return sr


@dataclass
class WorkflowRun:
    run_id: str = ""
    workflow_id: str = ""
    org_id: str = ""
    status: str = PENDING
    input: Any = None
    context: dict[str, Any] = field(default_factory=dict)  # {"input":…, "steps":{…}}
    steps: dict[str, StepRun] = field(default_factory=dict)
    created_at_us: int = 0
    updated_at_us: int = 0
    finished_at_us: int = 0
    error: str = ""
    dry_run: bool = False
    labels: dict[str, str] = field(default_factory=dict)
    # run-level trace: every step-dispatch span parents under one root span
    # so the whole agent loop renders as ONE waterfall with per-step blame
    trace_id: str = ""
    root_span_id: str = ""

    def to_dict(self) -> dict[str, Any]:
        d = dict(self.__dict__)
        d["steps"] = {k: s.to_dict() for k, s in self.steps.items()}
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "WorkflowRun":
        steps = {k: StepRun.from_dict(v) for k, v in (d.get("steps") or {}).items()}
        kw = {k: v for k, v in d.items() if k in cls.__dataclass_fields__ and k != "steps"}
        run = cls(**kw)
        run.steps = steps
        return run


@dataclass
class TimelineEvent:
    ts_us: int = 0
    run_id: str = ""
    step_id: str = ""
    event: str = ""
    detail: str = ""

    def to_dict(self) -> dict[str, Any]:
        return dict(self.__dict__)
