"""Workflow store: definitions, runs, timeline, idempotency
(reference ``core/workflow/store_redis.go:24-520``).

Keys: ``wf:def:<id>`` (+ org/all z-indexes), ``wf:run:<id>``
(+ per-workflow / all / status / org-active indexes), append-only timeline
list ``wf:run:timeline:<id>``, idempotency ``wf:run:idempotency:<key>``.
"""
from __future__ import annotations

import asyncio
import json
from typing import Optional

from ..infra.kv import KV
from ..utils.ids import now_us
from .models import RUN_TERMINAL, TimelineEvent, Workflow, WorkflowRun

TIMELINE_CAP = 500

RUN_LOCK_PREFIX = "lock:wfrun:"


def def_key(wf_id: str) -> str:
    return f"wf:def:{wf_id}"


def run_key(run_id: str) -> str:
    return f"wf:run:{run_id}"


def timeline_key(run_id: str) -> str:
    return f"wf:run:timeline:{run_id}"


class WorkflowStore:
    def __init__(self, kv: KV):
        self.kv = kv

    # -- definitions ------------------------------------------------------
    async def put_workflow(self, wf: Workflow) -> None:
        wf.created_at_us = wf.created_at_us or now_us()
        await self.kv.set(def_key(wf.id), json.dumps(wf.to_dict()).encode())
        await self.kv.zadd("wf:def:index", wf.id, float(wf.created_at_us))
        if wf.org_id:
            await self.kv.zadd(f"wf:def:org:{wf.org_id}", wf.id, float(wf.created_at_us))

    async def get_workflow(self, wf_id: str) -> Optional[Workflow]:
        b = await self.kv.get(def_key(wf_id))
        return Workflow.from_dict(json.loads(b)) if b else None

    async def delete_workflow(self, wf_id: str) -> bool:
        n = await self.kv.delete(def_key(wf_id))
        await self.kv.zrem("wf:def:index", wf_id)
        return n > 0

    async def list_workflows(self, limit: int = 100) -> list[str]:
        return await self.kv.zrange("wf:def:index", 0, limit - 1, desc=True)

    # -- runs --------------------------------------------------------------
    async def put_run(self, run: WorkflowRun) -> None:
        # one pipelined commit instead of ~11 serial KV round trips: put_run
        # sits on the result hot path (every applied step re-saves the run),
        # so the blob + index maintenance ship as a single PIPE frame
        run.updated_at_us = now_us()
        pipe = self.kv.pipeline()
        pipe.set(run_key(run.run_id), json.dumps(run.to_dict()).encode())
        pipe.zadd("wf:run:index", run.run_id, float(run.created_at_us or run.updated_at_us))
        pipe.zadd(f"wf:run:wf:{run.workflow_id}", run.run_id, float(run.created_at_us))
        # status indexes: remove from all, add to current
        for st in ("PENDING", "RUNNING", "WAITING", "WAITING_APPROVAL", "SUCCEEDED", "FAILED", "CANCELLED"):
            if st != run.status:
                pipe.zrem(f"wf:run:status:{st}", run.run_id)
        pipe.zadd(f"wf:run:status:{run.status}", run.run_id, float(run.updated_at_us))
        if run.org_id:
            if run.status in RUN_TERMINAL:
                pipe.zrem(f"wf:run:org_active:{run.org_id}", run.run_id)
            else:
                pipe.zadd(f"wf:run:org_active:{run.org_id}", run.run_id, float(run.updated_at_us))
        await pipe.execute()

    async def get_run(self, run_id: str) -> Optional[WorkflowRun]:
        b = await self.kv.get(run_key(run_id))
        return WorkflowRun.from_dict(json.loads(b)) if b else None

    async def list_runs(self, workflow_id: str = "", limit: int = 100) -> list[str]:
        key = f"wf:run:wf:{workflow_id}" if workflow_id else "wf:run:index"
        return await self.kv.zrange(key, 0, limit - 1, desc=True)

    async def list_run_ids_by_status(self, status: str, limit: int = 200) -> list[str]:
        return await self.kv.zrange(f"wf:run:status:{status}", 0, limit - 1)

    async def list_run_ids_by_statuses(
        self, statuses: tuple[str, ...], limit: int = 200
    ) -> list[tuple[str, str]]:
        """→ ``[(status, run_id), …]`` for several status indexes in ONE
        concurrent batch of zrange reads (the reconciler's per-pass scan
        used to pay one serial round trip per status)."""
        rows = await asyncio.gather(
            *(self.kv.zrange(f"wf:run:status:{st}", 0, limit - 1) for st in statuses)
        )
        return [(st, rid) for st, ids in zip(statuses, rows) for rid in ids]

    async def get_runs(self, run_ids: list[str]) -> list[Optional[WorkflowRun]]:
        """Batch run fetch (concurrent reads) for listings and reconciler
        sweeps; order matches ``run_ids``, misses come back ``None``."""
        blobs = await asyncio.gather(*(self.kv.get(run_key(r)) for r in run_ids))
        return [
            WorkflowRun.from_dict(json.loads(b)) if b else None for b in blobs
        ]

    async def count_active_runs(self, org_id: str) -> int:
        return await self.kv.zcard(f"wf:run:org_active:{org_id}")

    async def delete_run(self, run_id: str) -> bool:
        run = await self.get_run(run_id)
        n = await self.kv.delete(run_key(run_id), timeline_key(run_id))
        await self.kv.zrem("wf:run:index", run_id)
        if run:
            await self.kv.zrem(f"wf:run:wf:{run.workflow_id}", run_id)
            await self.kv.zrem(f"wf:run:status:{run.status}", run_id)
            if run.org_id:
                await self.kv.zrem(f"wf:run:org_active:{run.org_id}", run_id)
        return n > 0

    # -- timeline -----------------------------------------------------------
    async def append_timeline(self, ev: TimelineEvent) -> None:
        ev.ts_us = ev.ts_us or now_us()
        await self.kv.rpush(timeline_key(ev.run_id), json.dumps(ev.to_dict()).encode())
        await self.kv.ltrim(timeline_key(ev.run_id), -TIMELINE_CAP, -1)

    async def timeline(self, run_id: str) -> list[dict]:
        return [json.loads(b) for b in await self.kv.lrange(timeline_key(run_id))]

    # -- idempotency ---------------------------------------------------------
    async def try_set_run_idempotency(self, key: str, run_id: str, ttl_s: float = 24 * 3600) -> tuple[bool, str]:
        k = f"wf:run:idempotency:{key}"
        ok = await self.kv.setnx(k, run_id.encode(), ttl_s)
        if ok:
            return True, run_id
        cur = await self.kv.get(k)
        return False, cur.decode() if cur else ""

    # -- run locks ------------------------------------------------------------
    async def acquire_run_lock(self, run_id: str, owner: str, ttl_s: float = 30.0) -> bool:
        return await self.kv.setnx(RUN_LOCK_PREFIX + run_id, owner.encode(), ttl_s)

    async def release_run_lock(self, run_id: str, owner: str) -> None:
        # owner-checked compare-and-delete in one round trip (del_eq) instead
        # of the old read-then-delete pair
        await self.kv.del_eq(RUN_LOCK_PREFIX + run_id, owner.encode())

    async def held_run_locks(self) -> set[str]:
        """Run ids whose lock key currently exists — ONE prefix scan, so the
        reconciler can skip busy runs without a setnx round trip per run."""
        keys = await self.kv.keys(RUN_LOCK_PREFIX)
        return {k[len(RUN_LOCK_PREFIX):] for k in keys}
