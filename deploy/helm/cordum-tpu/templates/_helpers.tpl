{{- define "cordum.name" -}}
{{- .Chart.Name | trunc 63 | trimSuffix "-" -}}
{{- end -}}

{{- define "cordum.labels" -}}
app.kubernetes.io/name: {{ include "cordum.name" . }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion }}
{{- end -}}

{{- define "cordum.image" -}}
{{ .Values.image.repository }}:{{ .Values.image.tag }}
{{- end -}}

{{- define "cordum.statebusUrl" -}}
statebus://{{ .Release.Name }}-statebus:7420
{{- end -}}
