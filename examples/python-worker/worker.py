#!/usr/bin/env python
"""Minimal external worker (reference ``examples/hello-worker-go`` /
``python-worker``): connects to the statebus, consumes its pool topic,
fetches the context pointer, writes a result pointer, publishes JobResult —
using only the SDK worker runtime.

Run: CORDUM_STATEBUS_URL=statebus://127.0.0.1:7420 python worker.py
"""
import asyncio
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from cordum_tpu.infra import statebus
from cordum_tpu.infra.memstore import MemoryStore
from cordum_tpu.worker.runtime import JobContext, Worker


async def main() -> None:
    kv, bus, conn = await statebus.connect()
    worker = Worker(
        bus=bus,
        store=MemoryStore(kv),
        worker_id=os.environ.get("WORKER_ID", "hello-python-worker"),
        pool=os.environ.get("WORKER_POOL", "default"),
        topics=[os.environ.get("WORKER_TOPIC", "job.hello-pack.echo")],
        capabilities=["echo"],
    )

    async def echo(ctx: JobContext) -> dict:
        print(f"handling {ctx.request.job_id}: {ctx.payload}")
        return {"echo": ctx.payload, "worker": worker.worker_id}

    worker.register_default(echo)
    await worker.start()
    print(f"worker {worker.worker_id} consuming {worker.topics}; Ctrl-C to stop")
    try:
        await asyncio.Event().wait()
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        await worker.stop()
        await conn.close()


if __name__ == "__main__":
    asyncio.run(main())
