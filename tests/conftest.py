"""Test env: force JAX onto a virtual 8-device CPU mesh so sharding tests run
without TPU hardware (must be set before jax import anywhere)."""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import asyncio
import inspect

import pytest


def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests with asyncio.run (no pytest-asyncio in env)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None


@pytest.fixture
def kv():
    from cordum_tpu.infra.kv import MemoryKV

    return MemoryKV()


@pytest.fixture
def bus():
    from cordum_tpu.infra.bus import LoopbackBus

    return LoopbackBus()
