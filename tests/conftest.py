"""Test env: force JAX onto a virtual 8-device CPU mesh so sharding tests run
without TPU hardware.

The axon sitecustomize (PYTHONPATH=/root/.axon_site) registers the TPU-tunnel
PJRT plugin in every interpreter and sets jax_platforms="axon,cpu" via
jax.config — overriding the JAX_PLATFORMS env var.  The TPU grant is
exclusive, so a test process that initializes the axon backend blocks forever
behind any other claimant.  We must therefore (1) set the env vars, and
(2) re-override jax.config AFTER the sitecustomize hook ran, before any
backend initializes."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
# hermetic worker heartbeats: the suite saturates single-core CI hosts, and
# real loadavg-derived cpu_load would flip every worker to overloaded
os.environ["CORDUM_HOST_LOAD"] = "0"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import asyncio
import inspect

import pytest


def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests with asyncio.run (no pytest-asyncio in env)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None


@pytest.fixture(autouse=True)
def _syncsan_zero_reports():
    """CORDUM_SYNC_SANITIZER=1 runs: any interleave race the sanitizer
    diagnosed during a test fails that test (CI runs tier-1 under the
    sanitizer as its own step).  Free when the sanitizer is off."""
    from cordum_tpu.infra import syncsan

    if syncsan.enabled():
        syncsan.reset()
    yield
    if syncsan.enabled():
        reps = syncsan.reports()
        syncsan.reset()
        assert not reps, "sync sanitizer diagnosed interleave races:\n" + \
            "\n".join(str(r) for r in reps)


@pytest.fixture
def kv():
    from cordum_tpu.infra.kv import MemoryKV

    return MemoryKV()


@pytest.fixture
def bus():
    from cordum_tpu.infra.bus import LoopbackBus

    return LoopbackBus()
