"""Overload resilience (ISSUE 13): capacity-aware admission control,
priority load shedding, throughput-aware routing, and batch preemption.

Covers the AdmissionController's analytic/fallback/brownout decision paths
(incl. the cold/stale-matrix fallback and the never-divide-by-zero
guarantee), the ThroughputAwareStrategy's skewed-matrix routing and its
LeastLoaded degradation, the tenant-NAK exponential backoff, both gateway
429 paths' Retry-After headers + shed metrics, the SDK's jittered
Retry-After honor, the preemption loop end-to-end (pressure beacon →
governor → worker requeue → attempts-exempt re-dispatch → completion),
serving batch-prefill deprioritization, and the loadgen's traffic shaping.
"""
import asyncio
import json

import pytest

from cordum_tpu.controlplane.gateway.admission import (
    AdmissionController,
    render_admission_table,
)
from cordum_tpu.infra.bus import LoopbackBus, MAX_NAK_DELAY_S, RetryAfter
from cordum_tpu.infra.metrics import Metrics
from cordum_tpu.obs.fleet import FleetAggregator
from cordum_tpu.protocol import subjects as subj
from cordum_tpu.protocol.types import (
    AdmissionPressure,
    BusPacket,
    Heartbeat,
    JobRequest,
    LABEL_OP,
    LABEL_SESSION_KEY,
    TelemetrySnapshot,
)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def worker_beacon(instance: str, rows: dict, *, started: int = 1,
                  seq: int = 0) -> TelemetrySnapshot:
    """A worker telemetry snapshot carrying a capacity block (the shape
    Worker.telemetry_health → CapacityProfiler.snapshot produces)."""
    return TelemetrySnapshot(
        service="worker", instance=instance, seq=seq, started_at_us=started,
        interval_s=2.0,
        health={"role": "worker", "capacity": {
            "v": 1, "seq": seq, "full": True, "device_kind": "cpu",
            "rows": rows,
        }},
    )


def cap_row(op: str, items_per_s: float, *, bucket: str = "-",
            tokens_per_s: float = 0.0) -> dict:
    return {"op": op, "bucket": bucket, "n": 100, "items": 100,
            "items_per_s": items_per_s, "tokens_per_s": tokens_per_s}


class FakeSLO:
    """SLOTracker stand-in returning scripted burn states."""

    def __init__(self, burn_5m: float = 0.0, state: str = "ok"):
        self.burn_5m = burn_5m
        self.state = state

    def evaluate(self, aggregator) -> list[dict]:
        return [{
            "name": "interactive", "job_class": "INTERACTIVE",
            "state": self.state,
            "windows": {"5m": {"burn_rate": self.burn_5m},
                        "1h": {"burn_rate": self.burn_5m}},
        }]


def make_controller(*, config=None, slo=None, fleet=None, bus=None,
                    rng=None, metrics=None):
    clock_box = [0.0]
    ctrl = AdmissionController(
        fleet=fleet if fleet is not None else FleetAggregator(None),
        slo_tracker=slo, config=config if config is not None else {"enabled": True},
        metrics=metrics or Metrics(), bus=bus,
        clock=lambda: clock_box[0],
        rng=rng or (lambda: 0.0),  # 0.0 → shed whenever there is ANY excess
    )
    return ctrl, clock_box


def offer(ctrl, clock_box, op, klass, n, *, dt=1.0, tenant=""):
    """Record n arrivals then roll the EWMA over dt seconds: the offered
    rate for (op, klass) becomes exactly n/dt on the first roll."""
    for _ in range(n):
        ctrl._arrivals[(op, klass)] = ctrl._arrivals.get((op, klass), 0) + 1
    clock_box[0] += dt
    ctrl.refresh(clock_box[0])


# ---------------------------------------------------------------------------
# AdmissionController — analytic headroom
# ---------------------------------------------------------------------------


async def test_disabled_controller_admits_everything():
    ctrl, _ = make_controller(config={})
    assert not ctrl.enabled
    v = ctrl.admit(op="chat", job_class="BATCH", tenant="t")
    assert v.allowed and v.mode == "disabled"


async def test_analytic_batch_shed_first_interactive_protected():
    """Warm matrix: BATCH sheds as soon as total offered exceeds the
    capacity budget; INTERACTIVE rides until its OWN share is exhausted."""
    fleet = FleetAggregator(None)
    fleet.ingest(worker_beacon("w1", {"chat|-": cap_row("chat", 100.0)}))
    ctrl, clock = make_controller(fleet=fleet)
    # offered: 30/s interactive + 150/s batch = 180/s vs 90/s budget (0.9)
    offer(ctrl, clock, "chat", "INTERACTIVE", 30)
    offer(ctrl, clock, "chat", "BATCH", 150)
    vb = ctrl.admit(op="chat", job_class="BATCH", now=clock[0])
    assert not vb.allowed and vb.reason == "capacity"
    assert vb.retry_after_s >= ctrl.min_retry_after_s
    vi = ctrl.admit(op="chat", job_class="INTERACTIVE", now=clock[0])
    assert vi.allowed and vi.mode == "analytic"


async def test_interactive_sheds_past_its_own_capacity_share():
    fleet = FleetAggregator(None)
    fleet.ingest(worker_beacon("w1", {"chat|-": cap_row("chat", 100.0)}))
    ctrl, clock = make_controller(fleet=fleet)
    offer(ctrl, clock, "chat", "INTERACTIVE", 200)  # 200/s vs 90/s budget
    v = ctrl.admit(op="chat", job_class="INTERACTIVE", now=clock[0])
    assert not v.allowed and v.reason == "capacity_interactive"


async def test_proportional_shed_fraction():
    """rng near 1.0 admits even under excess (shed probability < 1), so
    shedding is proportional, not shed-everything."""
    fleet = FleetAggregator(None)
    fleet.ingest(worker_beacon("w1", {"chat|-": cap_row("chat", 100.0)}))
    # excess/batch_offered = (120-90)/120 = 0.25 → rng 0.9 admits
    ctrl, clock = make_controller(fleet=fleet, rng=lambda: 0.9)
    offer(ctrl, clock, "chat", "BATCH", 120)
    assert ctrl.admit(op="chat", job_class="BATCH", now=clock[0]).allowed
    # rng 0.1 < 0.25 sheds
    ctrl2, clock2 = make_controller(fleet=fleet, rng=lambda: 0.1)
    offer(ctrl2, clock2, "chat", "BATCH", 120)
    assert not ctrl2.admit(op="chat", job_class="BATCH", now=clock2[0]).allowed


async def test_retry_after_is_headroom_derived_and_bounded():
    fleet = FleetAggregator(None)
    fleet.ingest(worker_beacon("w1", {"chat|-": cap_row("chat", 100.0)}))
    ctrl, clock = make_controller(fleet=fleet)
    offer(ctrl, clock, "chat", "BATCH", 900)  # 10× the 90/s budget
    v = ctrl.admit(op="chat", job_class="BATCH", now=clock[0])
    assert not v.allowed
    # (offered − cap)/cap = (900−90)/90 = 9.0 s, clamped to max (15 s default)
    assert ctrl.min_retry_after_s <= v.retry_after_s <= ctrl.max_retry_after_s
    assert v.retry_after_s >= 5.0  # genuinely derived, not the floor


# ---------------------------------------------------------------------------
# AdmissionController — cold/stale matrix fallback (satellite)
# ---------------------------------------------------------------------------


async def test_cold_matrix_falls_back_to_queue_depth():
    """No capacity rows at all: the controller must not divide by zero and
    must use the scheduler-backlog heuristic — batch shed past the limit,
    interactive only past the (much larger) interactive bound."""
    fleet = FleetAggregator(None)
    # scheduler beacon carrying a deep backlog, but NO worker capacity rows
    fleet.ingest(TelemetrySnapshot(
        service="scheduler", instance="s0", started_at_us=1, interval_s=2.0,
        health={"role": "scheduler", "queue_depth": 500},
    ))
    ctrl, clock = make_controller(
        fleet=fleet,
        config={"enabled": True, "queue_depth_limit": 100,
                "interactive_queue_bound": 1000},
    )
    offer(ctrl, clock, "chat", "BATCH", 50)
    vb = ctrl.admit(op="chat", job_class="BATCH", now=clock[0])
    assert not vb.allowed and vb.reason == "queue_depth" and vb.mode == "fallback"
    vi = ctrl.admit(op="chat", job_class="INTERACTIVE", now=clock[0])
    assert vi.allowed and vi.mode == "fallback"


async def test_empty_fleet_no_zero_division():
    ctrl, clock = make_controller(fleet=FleetAggregator(None))
    for _ in range(50):
        v = ctrl.admit(op="anything", job_class="BATCH", now=clock[0])
    assert v.allowed and v.mode == "fallback"  # empty backlog → admit


async def test_stale_rows_excluded_then_reengage_analytic():
    """Rows from a worker whose beacon went stale leave the per-op totals
    (capacity_doc marks them stale); fresh rows re-engage analytic mode."""
    fleet = FleetAggregator(None, instance_evict_s=10_000.0)
    fleet.ingest(worker_beacon("w1", {"chat|-": cap_row("chat", 100.0)}))
    inst = fleet._instances[("worker", "w1")]
    inst.last_seen -= 1000.0  # beacon long overdue → stale
    ctrl, clock = make_controller(fleet=fleet)
    offer(ctrl, clock, "chat", "BATCH", 500)
    v = ctrl.admit(op="chat", job_class="BATCH", now=clock[0])
    assert v.mode == "fallback"  # stale row ⇒ no analytic capacity
    # fresh beacon lands → the next refresh goes analytic again
    fleet.ingest(worker_beacon("w1", {"chat|-": cap_row("chat", 100.0)}, seq=1))
    clock[0] += 1.0
    ctrl.refresh(clock[0])
    v2 = ctrl.admit(op="chat", job_class="BATCH", now=clock[0])
    assert not v2.allowed and v2.mode == "analytic"


# ---------------------------------------------------------------------------
# AdmissionController — brownout ladder + tenant quotas + pressure
# ---------------------------------------------------------------------------


async def test_brownout_tier1_sheds_all_batch():
    fleet = FleetAggregator(None)
    fleet.ingest(worker_beacon("w1", {"chat|-": cap_row("chat", 1000.0)}))
    ctrl, clock = make_controller(fleet=fleet, slo=FakeSLO(burn_5m=2.0))
    offer(ctrl, clock, "chat", "BATCH", 1)  # far under capacity
    assert ctrl.tier == 1
    v = ctrl.admit(op="chat", job_class="BATCH", now=clock[0])
    assert not v.allowed and v.reason == "brownout_batch"
    # interactive still rides
    assert ctrl.admit(op="chat", job_class="INTERACTIVE", now=clock[0]).allowed


async def test_brownout_tier2_sheds_best_effort_ops():
    fleet = FleetAggregator(None)
    fleet.ingest(worker_beacon("w1", {"embed|-": cap_row("embed", 1000.0)}))
    ctrl, clock = make_controller(
        fleet=fleet, slo=FakeSLO(burn_5m=20.0, state="page"),
        config={"enabled": True, "best_effort_ops": ["embed"]},
    )
    clock[0] += 1.0
    ctrl.refresh(clock[0])
    assert ctrl.tier == 2
    v = ctrl.admit(op="embed", job_class="INTERACTIVE", now=clock[0])
    assert not v.allowed and v.reason == "brownout_best_effort"


async def test_brownout_tier3_bounds_interactive():
    fleet = FleetAggregator(None)
    fleet.ingest(TelemetrySnapshot(
        service="scheduler", instance="s0", started_at_us=1, interval_s=2.0,
        health={"role": "scheduler", "queue_depth": 5000},
    ))
    ctrl, clock = make_controller(
        fleet=fleet, slo=FakeSLO(burn_5m=20.0, state="page"),
        config={"enabled": True, "queue_depth_limit": 10,
                "interactive_queue_bound": 100},
    )
    clock[0] += 1.0
    ctrl.refresh(clock[0])
    assert ctrl.tier == 3
    v = ctrl.admit(op="chat", job_class="INTERACTIVE", now=clock[0])
    assert not v.allowed and v.reason == "brownout_interactive"


async def test_tenant_token_bucket_quota():
    ctrl, clock = make_controller(config={
        "enabled": True,
        "tenants": {"acme": {"rate_rps": 1.0, "burst": 2}},
    })
    now = clock[0]
    assert ctrl.admit(op="x", job_class="BATCH", tenant="acme", now=now).allowed
    assert ctrl.admit(op="x", job_class="BATCH", tenant="acme", now=now).allowed
    v = ctrl.admit(op="x", job_class="BATCH", tenant="acme", now=now)
    assert not v.allowed and v.reason == "tenant_quota"
    assert v.retry_after_s > 0
    # unknown tenants fall to "default"; absent default = unlimited
    assert ctrl.admit(op="x", job_class="BATCH", tenant="other", now=now).allowed
    # a token accrues after 1/rate seconds
    clock[0] += 1.1
    assert ctrl.admit(op="x", job_class="BATCH", tenant="acme",
                      now=clock[0]).allowed


async def test_pressure_beacon_published_on_tier_change():
    bus = LoopbackBus(sync=True)
    got: list[AdmissionPressure] = []

    async def tap(subject, pkt):
        got.append(pkt.admission_pressure)

    await bus.subscribe(subj.ADMISSION_PRESSURE, tap)
    slo = FakeSLO(burn_5m=2.0)
    ctrl, clock = make_controller(bus=bus, slo=slo)
    clock[0] += 1.0
    ctrl.refresh(clock[0])
    assert await ctrl.publish_pressure(clock[0])
    assert got and got[-1].preempt_batch and got[-1].tier == 1
    # unchanged tier inside the beacon interval: no re-publish
    assert not await ctrl.publish_pressure(clock[0] + 0.1)
    # recovery publishes the all-clear once
    slo.burn_5m = 0.0
    clock[0] += 1.0
    ctrl.refresh(clock[0])
    assert await ctrl.publish_pressure(clock[0])
    assert not got[-1].preempt_batch and got[-1].tier == 0


async def test_admission_doc_and_render():
    fleet = FleetAggregator(None)
    fleet.ingest(worker_beacon("w1", {"chat|-": cap_row("chat", 100.0)}))
    ctrl, clock = make_controller(
        fleet=fleet,
        config={"enabled": True, "tenants": {"acme": {"rate_rps": 5, "burst": 5}}},
    )
    offer(ctrl, clock, "chat", "INTERACTIVE", 20)
    ctrl.admit(op="chat", job_class="INTERACTIVE", tenant="acme", now=clock[0])
    doc = ctrl.doc()
    assert doc["enabled"] and doc["tier"] == 0
    assert doc["ops"]["chat"]["capacity_per_s"] == 90.0
    assert doc["ops"]["chat"]["offered"]["INTERACTIVE"] == 20.0
    assert doc["tenants"]["acme"]["tokens"] is not None
    text = render_admission_table(doc)
    assert "brownout tier 0" in text and "chat" in text
    assert json.dumps(doc)  # JSON-serializable for GET /api/v1/admission


# ---------------------------------------------------------------------------
# CapacityView + ThroughputAwareStrategy
# ---------------------------------------------------------------------------


def make_strategy(rates: dict, *, clock=None):
    from cordum_tpu.controlplane.scheduler.strategy import (
        ThroughputAwareStrategy,
    )
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.registry import WorkerRegistry
    from cordum_tpu.obs.capacity import CapacityView

    reg = WorkerRegistry()
    pc = parse_pool_config({"topics": {"job.storm": "p"}, "pools": {"p": {}}})
    view = CapacityView(clock=clock or (lambda: 0.0))
    for wid, rate in rates.items():
        reg.update(Heartbeat(worker_id=wid, pool="p", max_parallel_jobs=1 << 30))
        if rate > 0:
            view.ingest(worker_beacon(wid, {"chat|-": cap_row("chat", rate)}))
    strat = ThroughputAwareStrategy(reg, pc, capacity=view, native=False)
    return strat, view, reg


def _route_counts(strat, n=120, labels=None):
    counts: dict[str, int] = {}
    for i in range(n):
        subject = strat.pick_subject(JobRequest(
            job_id=f"j{i}", topic="job.storm",
            labels=labels or {LABEL_OP: "chat"},
        ))
        counts[subject] = counts.get(subject, 0) + 1
    return counts


async def test_throughput_strategy_skews_to_fast_worker():
    """ISSUE 13 acceptance: a 3:1 synthetic matrix routes ≥2:1 fast:slow
    (the smooth WRR gives exactly the weight ratio)."""
    strat, _, _ = make_strategy({"w-fast": 300.0, "w-slow": 100.0})
    counts = _route_counts(strat)
    fast = counts.get("worker.w-fast.jobs", 0)
    slow = counts.get("worker.w-slow.jobs", 0)
    assert fast + slow == 120
    assert slow > 0  # proportional, not winner-take-all starvation
    assert fast >= 2 * slow
    assert strat.routed_measured == 120


async def test_throughput_strategy_empty_matrix_is_least_loaded():
    """No measured rows → behavior must equal LeastLoadedStrategy's."""
    from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.registry import WorkerRegistry

    strat, _, reg = make_strategy({"w-a": 0.0, "w-b": 0.0})
    pc = parse_pool_config({"topics": {"job.storm": "p"}, "pools": {"p": {}}})
    baseline = LeastLoadedStrategy(reg, pc, native=False)
    for i in range(20):
        req = JobRequest(job_id=f"j{i}", topic="job.storm",
                         labels={LABEL_OP: "chat"})
        assert strat.pick_subject(req) == baseline.pick_subject(req)
    assert strat.routed_fallback == 20 and strat.routed_measured == 0


async def test_throughput_strategy_unmeasured_worker_gets_median_weight():
    strat, _, _ = make_strategy({"w-m": 200.0, "w-new": 0.0})
    counts = _route_counts(strat, n=60)
    # the unmeasured worker receives traffic (so it becomes measured) at
    # roughly the median measured weight — i.e. an even split here
    assert counts.get("worker.w-new.jobs", 0) >= 20


async def test_throughput_strategy_session_affinity_delegates():
    strat, _, _ = make_strategy({"w-fast": 300.0, "w-slow": 100.0})
    counts = _route_counts(
        strat, n=30,
        labels={LABEL_OP: "chat", LABEL_SESSION_KEY: "conv-1"},
    )
    assert len(counts) == 1  # sticky: every turn rides to one worker


async def test_capacity_view_staleness_and_restart():
    clock_box = [0.0]
    strat, view, _ = make_strategy({"w1": 100.0}, clock=lambda: clock_box[0])
    assert view.rate("w1", "chat") == 100.0
    clock_box[0] += 100.0  # beacon silent past stale_after_s
    assert view.rate("w1", "chat") == 0.0
    # fresh beacon from a RESTARTED worker (new started_at_us) replaces rows
    view.ingest(worker_beacon("w1", {"embed|-": cap_row("embed", 50.0)},
                              started=999))
    assert view.rate("w1", "chat") == 0.0  # dead epoch's row cleared
    assert view.rate("w1", "embed") == 50.0


# ---------------------------------------------------------------------------
# tenant-concurrency NAK backoff (satellite)
# ---------------------------------------------------------------------------


def _engine_stack(**kw):
    from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
    from cordum_tpu.controlplane.scheduler.engine import Engine
    from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
    from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.jobstore import JobStore
    from cordum_tpu.infra.kv import MemoryKV
    from cordum_tpu.infra.registry import WorkerRegistry

    kv = MemoryKV()
    bus = LoopbackBus()
    js = JobStore(kv)
    kernel = SafetyKernel(policy_doc={
        "tenants": {"default": {"allow_topics": ["job.*", "job.>"]}}})
    reg = WorkerRegistry()
    pc = parse_pool_config({"topics": {"job.work": "p"}, "pools": {"p": {}}})
    eng = Engine(bus=bus, job_store=js, safety=SafetyClient(kernel.check),
                 strategy=LeastLoadedStrategy(reg, pc, native=False),
                 registry=reg, **kw)
    return kv, bus, js, reg, eng


async def test_tenant_nak_backoff_exponential_with_jitter():
    kv, bus, js, reg, eng = _engine_stack(tenant_concurrency_limit=1)
    # one active job pins the tenant at its limit
    await js.set_state("held", __import__(
        "cordum_tpu.protocol.types", fromlist=["JobState"]).JobState.PENDING,
        fields={"tenant_id": "default"})
    ops = js.tenant_active_add_ops("default", "held")
    await kv.pipe_execute({}, ops)
    assert await js.tenant_active_count("default") == 1

    async def delay_for(redeliveries: int) -> float:
        with pytest.raises(RetryAfter) as exc:
            await eng.handle_job_request(
                JobRequest(job_id=f"j-{redeliveries}", topic="job.work",
                           tenant_id="default"),
                redeliveries=redeliveries,
            )
        return exc.value.delay_s

    d0 = await delay_for(0)
    d3 = await delay_for(3)
    d20 = await delay_for(20)
    assert 0.25 * 0.75 <= d0 <= 0.25 * 1.25
    assert 2.0 * 0.75 <= d3 <= 2.0 * 1.25  # 0.25 × 2³, ±25%
    assert d20 <= MAX_NAK_DELAY_S * 1.25  # capped
    assert d3 > d0  # genuinely grows per redelivery


async def test_bus_stamps_redelivery_count():
    bus = LoopbackBus()
    seen: list[int] = []

    async def handler(subject, pkt):
        seen.append(pkt.redelivery_count)
        if len(seen) < 3:
            raise RetryAfter(0.0, "again")

    await bus.subscribe(subj.SUBMIT, handler, queue="q")
    await bus.publish(subj.SUBMIT, BusPacket.wrap(
        JobRequest(job_id="r1", topic="job.work"), sender_id="t"))
    await bus.drain()
    assert seen == [0, 1, 2]
    await bus.close()


# ---------------------------------------------------------------------------
# preemption end-to-end (acceptance: requeued, not FAILED/CANCELLED,
# attempts-exempt, completes after the burst)
# ---------------------------------------------------------------------------


async def test_preemption_end_to_end_requeues_and_completes():
    from cordum_tpu.infra.memstore import MemoryStore
    from cordum_tpu.worker.runtime import Worker

    kv, bus, js, reg, eng = _engine_stack()
    await eng.start()
    worker = Worker(bus=bus, store=MemoryStore(kv), worker_id="w1", pool="p",
                    topics=["job.work"], max_parallel_jobs=1,
                    heartbeat_interval_s=999)

    async def slow_handler(ctx):
        await asyncio.sleep(0.4)
        return {"ok": True}

    worker.register("job.work", slow_handler)
    await worker.start()
    await asyncio.sleep(0.02)

    # saturate: 3 BATCH jobs on a 1-slot worker — one runs, two queued.
    # NO bus.drain() here: drain would await the slow handlers themselves
    # and the burst would be over before pressure arrives.
    for i in range(3):
        await bus.publish(subj.SUBMIT, BusPacket.wrap(
            JobRequest(job_id=f"b{i}", topic="job.work", priority="BATCH",
                       tenant_id="default"),
            sender_id="t"))
    for _ in range(100):  # wait until all three are dispatched, not done
        await asyncio.sleep(0.005)
        states = [await js.get_state(f"b{i}") for i in range(3)]
        if all(s in ("DISPATCHED", "RUNNING") for s in states):
            break
    assert all(s in ("DISPATCHED", "RUNNING") for s in states), states

    # interactive pressure arrives: the governor preempts dispatched BATCH
    await bus.publish(subj.ADMISSION_PRESSURE, BusPacket.wrap(
        AdmissionPressure(tier=1, interactive_burn_5m=3.0,
                          preempt_batch=True, reason="slo_pressure"),
        sender_id="gw"))
    m = eng.metrics
    deadline = asyncio.get_running_loop().time() + 5.0
    while asyncio.get_running_loop().time() < deadline:
        if m.preemptions.value(reason="requeued") > 0:
            break
        await asyncio.sleep(0.02)
    assert m.preemptions.value(reason="requested") > 0
    assert m.preemptions.value(reason="requeued") > 0

    # preempted jobs complete after the burst (attempts-exempt hold-off ≈1s)
    deadline = asyncio.get_running_loop().time() + 10.0
    while asyncio.get_running_loop().time() < deadline:
        states = [await js.get_state(f"b{i}") for i in range(3)]
        if all(s == "SUCCEEDED" for s in states):
            break
        await bus.drain()
        await asyncio.sleep(0.05)
    states = [await js.get_state(f"b{i}") for i in range(3)]
    assert states == ["SUCCEEDED"] * 3  # requeued, never FAILED/CANCELLED
    for i in range(3):
        meta = await js.get_meta(f"b{i}")
        assert int(meta.get("attempts", "1")) == 1  # attempts-exempt

    await worker.stop()
    await eng.stop()
    await bus.close()


async def test_preempt_ignored_for_executing_job():
    """A job already holding its intake slot is NOT interrupted: preemption
    only reclaims queued slots and serving sessions."""
    from cordum_tpu.infra.memstore import MemoryStore
    from cordum_tpu.worker.runtime import Worker
    from cordum_tpu.protocol.types import JobPreempt

    kv, bus, js, reg, eng = _engine_stack()
    await eng.start()
    worker = Worker(bus=bus, store=MemoryStore(kv), worker_id="w1", pool="p",
                    topics=["job.work"], max_parallel_jobs=1,
                    heartbeat_interval_s=999)
    started = asyncio.Event()

    async def handler(ctx):
        started.set()
        await asyncio.sleep(0.2)
        return {"ok": True}

    worker.register("job.work", handler)
    await worker.start()
    await bus.publish(subj.SUBMIT, BusPacket.wrap(
        JobRequest(job_id="run1", topic="job.work", priority="BATCH",
                   tenant_id="default"), sender_id="t"))
    await bus.drain()
    await asyncio.wait_for(started.wait(), 5.0)
    await bus.publish(subj.PREEMPT, BusPacket.wrap(
        JobPreempt(job_id="run1", reason="slo_pressure"), sender_id="s"))
    deadline = asyncio.get_running_loop().time() + 5.0
    while asyncio.get_running_loop().time() < deadline:
        if await js.get_state("run1") == "SUCCEEDED":
            break
        await bus.drain()
        await asyncio.sleep(0.02)
    assert await js.get_state("run1") == "SUCCEEDED"
    await worker.stop()
    await eng.stop()
    await bus.close()


# ---------------------------------------------------------------------------
# serving: batch prefill deprioritization
# ---------------------------------------------------------------------------


async def test_serving_interactive_prefill_rides_before_batch():
    from cordum_tpu.serving.engine import GenRequest, ServingEngine, _Session

    class StubBackend:
        num_pages = 64
        page_size = 16
        max_context = 512
        max_seqs = 8
        max_batch_tokens = 8  # tight budget: one prefill chunk per step

    async def run_blocking(fn, *a):
        return fn(*a)

    eng = ServingEngine(StubBackend(), run_blocking=run_blocking,
                        max_concurrent_prefills=1)
    loop = asyncio.get_running_loop()
    # batch session admitted FIRST; both need prefill
    s_batch = _Session(job_id="b", req=GenRequest(
        prompt=list(range(20)), job_class="BATCH"), future=loop.create_future())
    s_int = _Session(job_id="i", req=GenRequest(
        prompt=list(range(20)), job_class="INTERACTIVE"),
        future=loop.create_future())
    eng._active = {"b": s_batch, "i": s_int}
    entries, rows = eng._assemble()
    # the single prefill chunk in the budget belongs to the INTERACTIVE one
    assert len(entries) == 1 and entries[0].key == "i"
    assert entries[0].phase == "prefill"
    # admission order still breaks ties within one class
    s_int2 = _Session(job_id="i2", req=GenRequest(
        prompt=list(range(20)), job_class="INTERACTIVE"),
        future=loop.create_future())
    eng._active = {"b": s_batch, "i": s_int, "i2": s_int2}
    entries, _ = eng._assemble()
    assert entries[0].key == "i"
    for f in (s_batch.future, s_int.future, s_int2.future):
        f.cancel()


# ---------------------------------------------------------------------------
# gateway 429 paths + SDK Retry-After honor (satellites)
# ---------------------------------------------------------------------------


class AdmStack:
    """Minimal gateway behind a live HTTP server with admission wired."""

    def __init__(self, *, admission_config=None, rate_rps=0.0):
        from aiohttp.test_utils import TestServer
        from cordum_tpu.controlplane.gateway.app import Gateway
        from cordum_tpu.controlplane.gateway.auth import BasicAuthProvider
        from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
        from cordum_tpu.infra.configsvc import ConfigService
        from cordum_tpu.infra.jobstore import JobStore
        from cordum_tpu.infra.kv import MemoryKV
        from cordum_tpu.infra.memstore import MemoryStore
        from cordum_tpu.infra.schemareg import SchemaRegistry
        from cordum_tpu.workflow.engine import Engine as WorkflowEngine
        from cordum_tpu.workflow.store import WorkflowStore

        self.kv = MemoryKV()
        self.bus = LoopbackBus()
        self.job_store = JobStore(self.kv)
        mem = MemoryStore(self.kv)
        schemas = SchemaRegistry(self.kv)
        configsvc = ConfigService(self.kv)
        kernel = SafetyKernel(policy_doc={
            "tenants": {"default": {"allow_topics": ["job.*", "job.>"]}}},
            configsvc=configsvc)
        wf_store = WorkflowStore(self.kv)
        self.gw = Gateway(
            kv=self.kv, bus=self.bus, job_store=self.job_store, mem=mem,
            kernel=kernel, wf_store=wf_store,
            wf_engine=WorkflowEngine(store=wf_store, bus=self.bus, mem=mem,
                                     schemas=schemas, configsvc=configsvc),
            schemas=schemas, configsvc=configsvc,
            auth=BasicAuthProvider(["user-key"]),
            admission_config=admission_config, rate_rps=rate_rps,
            telemetry=False,
        )
        self.server = TestServer(self.gw.app)

    async def __aenter__(self):
        await self.server.start_server()
        return self

    async def __aexit__(self, *exc):
        await self.server.close()
        await self.bus.close()

    def url(self) -> str:
        return str(self.server.make_url(""))


async def test_gateway_shed_429_retry_after_and_metric():
    import aiohttp

    async with AdmStack(admission_config={
        "enabled": True,
        "tenants": {"default": {"rate_rps": 0.5, "burst": 1}},
    }) as s:
        async with aiohttp.ClientSession(
            headers={"X-Api-Key": "user-key"}
        ) as http:
            r1 = await http.post(s.url() + "/api/v1/jobs",
                                 json={"topic": "job.work", "priority": "BATCH"})
            assert r1.status == 202
            r2 = await http.post(s.url() + "/api/v1/jobs",
                                 json={"topic": "job.work", "priority": "BATCH"})
            assert r2.status == 429
            assert float(r2.headers["Retry-After"]) > 0
            body = await r2.json()
            assert body["reason"] == "tenant_quota"
            assert s.gw.metrics.gateway_shed.value(
                reason="tenant_quota", job_class="BATCH") == 1
            # live controller state endpoint
            r3 = await http.get(s.url() + "/api/v1/admission")
            doc = await r3.json()
            assert doc["enabled"] and doc["shed"]
            # bulk path: per-entry verdicts + the header rides the response
            r4 = await http.post(
                s.url() + "/api/v1/jobs:batch",
                json={"jobs": [{"topic": "job.work"}]})
            assert r4.status == 400 and "Retry-After" in r4.headers


async def test_gateway_rate_limit_429_has_retry_after():
    import aiohttp

    async with AdmStack(rate_rps=0.001) as s:
        async with aiohttp.ClientSession(
            headers={"X-Api-Key": "user-key"}
        ) as http:
            last = None
            for _ in range(5):
                last = await http.get(s.url() + "/api/v1/jobs")
                if last.status == 429:
                    break
            assert last is not None and last.status == 429
            assert float(last.headers["Retry-After"]) > 0
            assert s.gw.metrics.gateway_shed.value(
                reason="rate_limit", job_class="unknown") >= 1


async def test_sdk_honors_retry_after_with_backoff():
    from cordum_tpu.sdk.client import ApiError, Client

    async with AdmStack(admission_config={
        "enabled": True,
        "tenants": {"default": {"rate_rps": 4.0, "burst": 1}},
    }) as s:
        async with Client(s.url(), api_key="user-key", retry_429=3) as c:
            t0 = asyncio.get_running_loop().time()
            await c.submit_job("job.work")  # takes the burst token
            doc = await c.submit_job("job.work")  # shed once, retried
            elapsed = asyncio.get_running_loop().time() - t0
            assert "job_id" in doc
            # the retry actually slept ≈ Retry-After (1/rate = 0.25 s),
            # not an immediate hammer
            assert elapsed >= 0.15
        async with Client(s.url(), api_key="user-key", retry_429=0) as c0:
            # retries disabled: the first empty-bucket hit surfaces as 429
            # (the bucket is drained from the block above, so a burst of
            # submits must trip it within a few calls)
            with pytest.raises(ApiError) as exc:
                for _ in range(5):
                    await c0.submit_job("job.work")
            assert exc.value.status == 429


async def test_gateway_stamps_op_label():
    async with AdmStack(admission_config={"enabled": True}) as s:
        import aiohttp

        async with aiohttp.ClientSession(
            headers={"X-Api-Key": "user-key"}
        ) as http:
            r = await http.post(
                s.url() + "/api/v1/jobs",
                json={"topic": "job.work", "payload": {"op": "embed"}})
            jid = (await r.json())["job_id"]
        req = await s.job_store.get_request(jid)
        assert req.labels[LABEL_OP] == "embed"


# ---------------------------------------------------------------------------
# loadgen traffic shaping
# ---------------------------------------------------------------------------


async def test_loadgen_shaping_and_sessions():
    from cordum_tpu.infra.loadgen import LoadGen, TenantSpec

    spec = TenantSpec(name="t", rate_rps=100.0, burst_factor=3.0,
                      burst_every_s=10.0, burst_len_s=1.0,
                      diurnal_period_s=40.0, diurnal_amp=0.5)
    assert spec.rate_at(0.5) == pytest.approx(
        100.0 * 3.0 * (1 + 0.5 * __import__("math").sin(
            2 * __import__("math").pi * 0.5 / 40.0)))
    assert spec.rate_at(5.0) < spec.rate_at(0.5)  # burst window closed

    turns: list[tuple[str, str, int]] = []

    async def submit(s, sid, turn):
        turns.append((s.name, sid, turn))

    gen = LoadGen(submit, [
        TenantSpec(name="chat", rate_rps=60.0, session_turns=3,
                   think_time_s=0.01),
        TenantSpec(name="flood", rate_rps=200.0),
    ], duration_s=0.5)
    counts = await gen.run()
    assert counts["sessions"]["flood"] > 20  # open loop actually drove
    assert counts["turns"]["chat"] == 3 * counts["sessions"]["chat"]
    chat_sessions = {sid for name, sid, _ in turns if name == "chat"}
    assert all(
        sorted(t for n, s, t in turns if s == sid) == [0, 1, 2]
        for sid in chat_sessions
    )
