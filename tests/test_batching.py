"""Micro-batching engine tests: window flush on size vs timeout, bucket
padding correctness (batched output == per-job output), partial-batch
failure isolation, cancel-while-queued, batch affinity, bulk-path context
re-indexing."""
import asyncio

import numpy as np
import pytest

from cordum_tpu.batching import (
    BatchCancelled,
    MicroBatcher,
    bucket_for,
    pow2_buckets,
)
from cordum_tpu.infra.metrics import Metrics


def make_recording_batcher(**kw):
    calls = []

    async def flush(op, bucket, items):
        calls.append((op, bucket, [it.job_id for it in items]))
        return [{"job": it.job_id, "rows": it.n_rows} for it in items]

    return MicroBatcher(flush, **kw), calls


# ---------------------------------------------------------------- engine


def test_bucket_ladder():
    assert pow2_buckets(16, 128) == (16, 32, 64, 128)
    assert bucket_for(1, (16, 32)) == 16
    assert bucket_for(17, (16, 32)) == 32
    assert bucket_for(999, (16, 32)) == 32  # clamp to the largest


async def test_flush_on_size():
    """Reaching max_batch_rows flushes immediately — no window wait."""
    b, calls = make_recording_batcher(max_batch_rows=4, max_wait_ms=10_000)
    out = await asyncio.gather(*[
        b.submit("embed", ["t"], job_id=f"j{i}", length=8) for i in range(4)
    ])
    assert [o["job"] for o in out] == ["j0", "j1", "j2", "j3"]
    assert len(calls) == 1 and calls[0][2] == ["j0", "j1", "j2", "j3"]
    await b.stop()


async def test_flush_on_timeout():
    """A partial batch flushes when the window expires."""
    b, calls = make_recording_batcher(max_batch_rows=100, max_wait_ms=30)
    t = [asyncio.ensure_future(b.submit("embed", ["t"], job_id=f"j{i}", length=8))
         for i in range(2)]
    out = await asyncio.wait_for(asyncio.gather(*t), timeout=5)
    assert len(calls) == 1 and len(out) == 2
    await b.stop()


async def test_buckets_separate_queues():
    """Different length buckets flush as different XLA programs."""
    b, calls = make_recording_batcher(
        max_batch_rows=100, max_wait_ms=20, len_buckets=(16, 64))
    await asyncio.gather(
        b.submit("embed", ["short"], job_id="s", length=8),
        b.submit("embed", ["long"], job_id="l", length=50),
    )
    assert sorted(c[1] for c in calls) == [16, 64]
    await b.stop()


async def test_multi_row_jobs_share_one_flush():
    """Row accounting: a 3-text job + a 1-text job = one 4-row flush."""
    b, calls = make_recording_batcher(max_batch_rows=4, max_wait_ms=10_000)
    out = await asyncio.gather(
        b.submit("embed", ["a", "b", "c"], job_id="j3", length=8, n_rows=3),
        b.submit("embed", ["d"], job_id="j1", length=8),
    )
    assert len(calls) == 1
    assert out[0]["rows"] == 3 and out[1]["rows"] == 1
    assert b.stats.flushed_rows == 4 and b.stats.flushes == 1
    await b.stop()


async def test_partial_batch_failure_isolates_failing_job():
    """A whole-batch failure re-runs members alone: only the poison job
    fails; its batch-mates still succeed."""
    async def flaky(op, bucket, items):
        ids = [it.job_id for it in items]
        if "bad" in ids and len(items) > 1:
            raise RuntimeError("poisoned batch")
        if ids == ["bad"]:
            raise ValueError("bad rows")
        return ["ok"] * len(items)

    b = MicroBatcher(flaky, max_batch_rows=3, max_wait_ms=10_000)
    out = await asyncio.gather(
        b.submit("embed", ["x"], job_id="g1", length=8),
        b.submit("embed", ["x"], job_id="bad", length=8),
        b.submit("embed", ["x"], job_id="g2", length=8),
        return_exceptions=True,
    )
    assert out[0] == "ok" and out[2] == "ok"
    assert isinstance(out[1], ValueError)
    assert b.stats.item_fallbacks == 3
    await b.stop()


async def test_cancel_while_queued():
    """A cancelled queued job is removed (never flushed) and its waiter
    raises BatchCancelled."""
    b, calls = make_recording_batcher(max_batch_rows=10, max_wait_ms=40)
    fut = asyncio.ensure_future(b.submit("embed", ["x"], job_id="c1", length=8))
    keep = asyncio.ensure_future(b.submit("embed", ["x"], job_id="k1", length=8))
    await asyncio.sleep(0)  # let both enqueue
    assert b.cancel("c1") is True
    assert b.cancel("nope") is False
    with pytest.raises(BatchCancelled):
        await fut
    assert (await keep)["job"] == "k1"
    # the flush that happened never contained the cancelled job
    assert all("c1" not in ids for _, _, ids in calls)
    assert b.stats.cancelled_in_queue == 1
    await b.stop()


async def test_adaptive_window_shrinks_with_slow_arrivals():
    """With a long observed inter-arrival gap the window collapses toward
    the gap (no point holding a batch the arrival rate will never fill);
    with no history it is the full max_wait."""
    b, _ = make_recording_batcher(max_batch_rows=32, max_wait_ms=100)
    key = ("embed", 16)
    assert b.window_s(key, 1) == pytest.approx(0.1)
    b._arrival_ewma[key] = 0.001  # 1ms gaps: wait ~the predicted fill time
    assert b.window_s(key, 1) == pytest.approx(0.001 * 31)
    b._arrival_ewma[key] = 10.0  # glacial arrivals → clamp to max_wait
    assert b.window_s(key, 1) == pytest.approx(0.1)
    b._arrival_ewma[key] = 1e-9  # near-simultaneous → floor at MIN_WAIT
    assert b.window_s(key, 31) == pytest.approx(0.0005)
    await b.stop()


async def test_batch_metrics_emitted():
    m = Metrics()
    b, _ = make_recording_batcher(max_batch_rows=2, max_wait_ms=10_000)
    b.metrics = m
    await asyncio.gather(
        b.submit("embed", ["x"], job_id="a", length=8),
        b.submit("embed", ["x"], job_id="b", length=8),
    )
    assert m.batch_flushes.value(op="embed", bucket="16") == 1
    assert m.batch_queue_depth.value(op="embed", bucket="16") == 0
    rendered = "\n".join(m.batch_size.render())
    assert "cordum_batch_size_count" in rendered
    await b.stop()


# ------------------------------------------------------- padding parity

@pytest.fixture(scope="module")
def compute():
    from cordum_tpu.models.embedder import EmbedderConfig
    from cordum_tpu.worker.handlers import TPUCompute

    return TPUCompute(tp=1, embedder_cfg=EmbedderConfig(n_layers=2, d_model=128, max_len=64))


def test_embed_batch_matches_per_job(compute):
    """Bucket padding correctness: rows embedded through the coalesced call
    equal the per-job embedder output (masked attention makes the pad rows
    and trimmed tail inert)."""
    texts = ["alpha beta gamma", "delta", "the quick brown fox jumps over it"]
    solo = np.asarray(compute.embedder.embed(texts))
    batched = np.asarray(compute.embed_batch(texts, seq_len=16))
    assert batched.shape == solo.shape
    np.testing.assert_allclose(batched, solo, atol=2e-2)


def test_infer_batch_matches_per_job(compute):
    """Each row's next token comes from its own last position, so the
    coalesced call agrees with per-job inference despite bucket padding."""
    rows = [[1, 2, 3], [4, 5], [7, 8, 9, 10, 11]]
    solo = compute.infer(rows)["next_tokens"]
    batched, t = compute.infer_batch(rows, seq_len=16)
    assert batched == solo
    assert t == 16


# ----------------------------------------------------- worker integration

async def settle(bus, rounds=6):
    for _ in range(rounds):
        await bus.drain()
        await asyncio.sleep(0.02)


def make_stack():
    from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
    from cordum_tpu.controlplane.scheduler.engine import Engine
    from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
    from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
    from cordum_tpu.infra.bus import LoopbackBus
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.jobstore import JobStore
    from cordum_tpu.infra.kv import MemoryKV
    from cordum_tpu.infra.memstore import MemoryStore
    from cordum_tpu.infra.registry import WorkerRegistry

    kv = MemoryKV()
    bus = LoopbackBus()
    js = JobStore(kv)
    ms = MemoryStore(kv)
    kernel = SafetyKernel(policy_doc={})
    reg = WorkerRegistry()
    pc = parse_pool_config({"topics": {"job.tpu.>": "tpu"},
                            "pools": {"tpu": {"requires": ["tpu"]}}})
    eng = Engine(bus=bus, job_store=js, safety=SafetyClient(kernel.check),
                 strategy=LeastLoadedStrategy(reg, pc), registry=reg)
    return kv, bus, js, ms, eng


def make_batched_worker(bus, ms, compute, **batcher_kw):
    from cordum_tpu.worker.handlers import make_micro_batcher, make_tpu_handlers
    from cordum_tpu.worker.runtime import Worker

    w = Worker(bus=bus, store=ms, worker_id="w-tpu", pool="tpu",
               topics=["job.tpu.>"], capabilities=["tpu"], heartbeat_interval_s=999)
    w.register_default(make_tpu_handlers(compute))
    w.attach_batcher(make_micro_batcher(compute, w, **batcher_kw))
    return w


async def test_worker_coalesces_embed_jobs(compute):
    """N embed jobs through the real pipeline coalesce into few flushes;
    results match the per-job shape and the flush span carries batch
    attributes."""
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import BusPacket, JobRequest

    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    w = make_batched_worker(bus, ms, compute, max_batch_rows=16, max_wait_ms=40)
    await w.start()
    await settle(bus)

    spans = []

    async def span_tap(subject, pkt):
        if pkt.span is not None:
            spans.append(pkt.span)

    await bus.subscribe(subj.TRACE_SPAN, span_tap)
    n = 10
    for i in range(n):
        jid = f"e{i}"
        ptr = await ms.put_context(jid, {"op": "embed", "texts": [f"doc number {i}"]})
        await bus.publish(subj.SUBMIT, BusPacket.wrap(
            JobRequest(job_id=jid, topic="job.tpu.ops", context_ptr=ptr)))
    for _ in range(150):
        await settle(bus, rounds=2)
        states = [await js.get_state(f"e{i}") for i in range(n)]
        if all(s == "SUCCEEDED" for s in states):
            break
    assert all(s == "SUCCEEDED" for s in states), states
    res = await ms.get_result("e0")
    assert res["dim"] == 128 and len(res["embeddings"]) == 1 and res["batched"]
    assert w.batcher.stats.flushes < n  # actually coalesced
    flush_spans = [s for s in spans if s.name == "batch-flush"]
    assert flush_spans, "no batch-flush span emitted"
    assert int(flush_spans[0].attrs["batch_size"]) >= 2
    assert "queue_wait_ms" in flush_spans[0].attrs
    execs = [s for s in spans if s.name == "execute" and s.attrs.get("batched") == "true"]
    assert execs and all("batch_size" in s.attrs for s in execs)
    await w.stop(); await eng.stop()


async def test_worker_cancel_while_batch_queued(compute):
    """A job cancelled while waiting in the batch queue is removed from the
    queue and publishes a CANCELLED result — it must not ride the flush."""
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import BusPacket, JobCancel, JobRequest

    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    # huge window so the queued job sits until we cancel it
    w = make_batched_worker(bus, ms, compute, max_batch_rows=64, max_wait_ms=30_000)
    await w.start()
    await settle(bus)
    ptr = await ms.put_context("jc", {"op": "embed", "texts": ["waiting room"]})
    await bus.publish(subj.SUBMIT, BusPacket.wrap(
        JobRequest(job_id="jc", topic="job.tpu.ops", context_ptr=ptr)))
    # NOTE: no bus.drain() here — the delivery task is parked awaiting the
    # batch flush, so drain would block until the (huge) window expires;
    # plain sleeps let the dispatch chain run while we watch the queue
    for _ in range(200):
        await asyncio.sleep(0.02)
        if w.batcher.queue_depth("embed") == 1:
            break
    assert w.batcher.queue_depth("embed") == 1, "job never reached the batch queue"
    await bus.publish(subj.CANCEL, BusPacket.wrap(JobCancel(job_id="jc", reason="test")))
    for _ in range(200):
        await asyncio.sleep(0.02)
        if await js.get_state("jc") == "CANCELLED":
            break
    assert await js.get_state("jc") == "CANCELLED"
    assert w.batcher.queue_depth("embed") == 0
    assert w.batcher.stats.flushes == 0  # nothing was flushed for it
    await w.stop(); await eng.stop()


async def test_worker_invalid_embed_payload_keeps_per_job_error(compute):
    """A malformed embed payload is not batchable: it takes the per-job
    handler path and fails with the op's own pointed error."""
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import BusPacket, JobRequest

    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    w = make_batched_worker(bus, ms, compute, max_batch_rows=8, max_wait_ms=20)
    await w.start()
    await settle(bus)
    ptr = await ms.put_context("jbad", {"op": "embed", "texts": "not-a-list"})
    await bus.publish(subj.SUBMIT, BusPacket.wrap(
        JobRequest(job_id="jbad", topic="job.tpu.ops", context_ptr=ptr)))
    for _ in range(60):
        await settle(bus)
        if await js.get_state("jbad") == "FAILED":
            break
    meta = await js.get_meta("jbad")
    assert meta["state"] == "FAILED" and "texts" in meta["error_message"]
    assert w.batcher.stats.flushes == 0
    await w.stop(); await eng.stop()


# ------------------------------------------------------- batch affinity

def test_strategy_batch_affinity_sticks_and_migrates():
    from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.registry import WorkerRegistry
    from cordum_tpu.protocol.types import Heartbeat, JobRequest, LABEL_BATCH_KEY

    reg = WorkerRegistry()
    pc = parse_pool_config({"topics": {"job.tpu.embed": "tpu"},
                            "pools": {"tpu": {"requires": []}}})
    strat = LeastLoadedStrategy(reg, pc, native=False)
    for wid, active in (("w-a", 0), ("w-b", 1)):
        reg.update(Heartbeat(worker_id=wid, pool="tpu", active_jobs=active,
                             max_parallel_jobs=16))
    req = JobRequest(job_id="j", topic="job.tpu.embed",
                     labels={LABEL_BATCH_KEY: "embed"})
    first = strat.pick_subject(req)
    assert first == "worker.w-a.jobs"  # least loaded wins the first pick
    # sticky even after the affinity worker becomes (mildly) busier
    reg.update(Heartbeat(worker_id="w-a", pool="tpu", active_jobs=5,
                         max_parallel_jobs=16))
    assert strat.pick_subject(req) == "worker.w-a.jobs"
    # a key-less job still routes by load
    plain = JobRequest(job_id="j2", topic="job.tpu.embed")
    assert strat.pick_subject(plain) == "worker.w-b.jobs"
    # overload evicts the sticky worker: the key migrates wholesale
    reg.update(Heartbeat(worker_id="w-a", pool="tpu", active_jobs=16,
                         max_parallel_jobs=16))
    assert strat.pick_subject(req) == "worker.w-b.jobs"
    assert strat._affinity["embed"][0] == "w-b"


def test_strategy_affinity_ttl_expires():
    from cordum_tpu.controlplane.scheduler.strategy import (
        BATCH_AFFINITY_TTL_S, LeastLoadedStrategy,
    )
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.registry import WorkerRegistry
    from cordum_tpu.protocol.types import Heartbeat, JobRequest, LABEL_BATCH_KEY

    reg = WorkerRegistry()
    pc = parse_pool_config({"topics": {"job.tpu.embed": "tpu"},
                            "pools": {"tpu": {}}})
    strat = LeastLoadedStrategy(reg, pc, native=False)
    reg.update(Heartbeat(worker_id="w-a", pool="tpu", max_parallel_jobs=16))
    req = JobRequest(job_id="j", topic="job.tpu.embed",
                     labels={LABEL_BATCH_KEY: "embed"})
    strat.pick_subject(req)
    # age the entry past the TTL: it must be dropped, not trusted
    wid, stamped = strat._affinity["embed"]
    strat._affinity["embed"] = (wid, stamped - BATCH_AFFINITY_TTL_S - 1)
    assert strat._affinity_worker("embed", pc.pools_for_topic("job.tpu.embed"), [], {}) == ""
    assert "embed" not in strat._affinity


# --------------------------------------------------- context bulk re-index

class RecordingEmbedder:
    """EmbedFn stub that records call sizes."""

    def __init__(self, dim=8):
        self.dim = dim
        self.calls: list[int] = []

    def embed(self, texts):
        self.calls.append(len(texts))
        rng = np.random.RandomState(len(texts))
        return rng.rand(len(texts), self.dim).astype(np.float32)


async def test_context_reindex_routes_through_bulk_slices(kv):
    from cordum_tpu.context.service import ContextService

    emb = RecordingEmbedder()
    svc = ContextService(kv, embedder=emb, embed_batch=2)
    chunks = [{"file_path": f"f{i}.py", "content": f"chunk body {i}"} for i in range(5)]
    n = await svc.put_chunks("m1", chunks)
    assert n == 5
    # 5 chunks through the bulk path in embed_batch=2 slices → 2,2,1
    assert emb.calls == [2, 2, 1]
    # re-index is incremental: nothing new → no embed calls
    emb.calls.clear()
    assert await svc.put_chunks("m1", chunks) == 0
    assert emb.calls == []
