"""Capacity observatory (ISSUE 10): the worker device profiler and its
delta-encoded beacon block, the fleet throughput matrix (fold, restart,
staleness, gauges), tail-latency attribution (histogram exemplars end to
end, tail-based trace retention, cross-trace critical-path blame), the
metric label-cardinality guard, and the gateway/CLI surfaces."""
import asyncio
import random

from cordum_tpu.infra.bus import LoopbackBus
from cordum_tpu.infra.kv import MemoryKV
from cordum_tpu.infra.memstore import MemoryStore
from cordum_tpu.infra.metrics import Counter, Histogram, Metrics
from cordum_tpu.obs import (
    CapacityProfiler,
    FleetAggregator,
    SpanCollector,
    TailSampler,
    TelemetryExporter,
    Tracer,
    aggregate_critical_paths,
    assemble,
    critical_path_blame,
    render_blame,
    render_capacity_table,
)
from cordum_tpu.obs.assembler import UNTRACKED_STAGE
from cordum_tpu.protocol import subjects as subj
from cordum_tpu.protocol.types import BusPacket, JobRequest, Span
from cordum_tpu.utils.ids import now_us
from cordum_tpu.worker.runtime import JobContext, Worker
from tests.test_fleet import _FleetStack, _parse_exposition
from tests.test_worker import make_stack, settle


# ---------------------------------------------------------------------------
# worker device profiler
# ---------------------------------------------------------------------------


def test_profiler_compile_steady_split_and_rates():
    p = CapacityProfiler("TPU v5p")
    p.observe("embed", device_s=0.5, bucket="64", items=8, compiled=True)
    for _ in range(4):
        p.observe("embed", device_s=0.01, bucket="64", items=8)
    rows = {f"{r['op']}|{r['bucket']}": r for r in p.rows()}
    r = rows["embed|64"]
    assert r["n"] == 5 and r["items"] == 40
    assert r["compile_n"] == 1 and r["compile_s"] == 0.5
    # steady items/s excludes the compile call: 32 items over 0.04 s
    assert abs(r["items_per_s"] - 800.0) < 1e-6
    # the one 500 ms compile is exactly the p99 outlier the histogram keeps
    assert r["p99_ms"] == 500.0 and r["p50_ms"] <= 25.0
    assert 0 < r["ewma_ms"] < 500.0
    assert r["last_us"] > 0


def test_profiler_tokens_per_sec_and_row_overflow():
    p = CapacityProfiler("cpu", max_rows=3)
    p.observe("llm.generate", device_s=0.1, bucket="4", items=4, tokens=4)
    p.observe("llm.generate", device_s=0.1, bucket="4", items=4, tokens=4)
    rows = {r["op"]: r for r in p.rows()}
    assert abs(rows["llm.generate"]["tokens_per_s"] - 40.0) < 1e-6
    # row-count guard: unbounded (op, bucket) pairs fold into one overflow row
    for i in range(10):
        p.observe(f"op-{i}", device_s=0.001, bucket=str(i))
    rows = {f"{r['op']}|{r['bucket']}": r for r in p.rows()}
    assert len(rows) <= 4 and "overflow|-" in rows
    assert rows["overflow|-"]["n"] >= 8


def test_profiler_snapshot_delta_encoding():
    p = CapacityProfiler("cpu", full_every=4)
    p.observe("echo", device_s=0.001)
    first = p.snapshot()  # seq 0 → full
    assert first["full"] and "echo|-" in first["rows"]
    assert first["device_kind"] == "cpu" and first["ts_us"] > 0

    quiet = p.snapshot()  # nothing moved → no rows ride
    assert not quiet["full"] and quiet["rows"] == {}

    p.observe("echo", device_s=0.003)
    changed = p.snapshot()
    assert not changed["full"]
    # delta decides WHICH rows ride; the row itself is cumulative
    assert changed["rows"]["echo|-"]["n"] == 2

    p.snapshot()  # seq 3
    full_again = p.snapshot()  # seq 4 → periodic full
    assert full_again["full"] and full_again["rows"]["echo|-"]["n"] == 2


def test_profiler_gauge_callbacks_ride_snapshot():
    p = CapacityProfiler("cpu")
    p.set_kv_headroom(lambda: {"pages_total": 127, "pages_free": 100})
    p.set_occupancy(lambda: {"decode_mean": 5.5})
    blk = p.snapshot()
    assert blk["kv_pages"]["pages_free"] == 100
    assert blk["occupancy"]["decode_mean"] == 5.5


# ---------------------------------------------------------------------------
# fleet throughput matrix (fold, restart, staleness, gauges)
# ---------------------------------------------------------------------------


def _worker_beacon(agg, instance, profiler, *, started_shift=0, full=True):
    m = Metrics()
    exp = TelemetryExporter("worker", None, m, instance_id=instance)
    exp.started_at_us += started_shift
    exp.health_fn = lambda: {"role": "worker",
                             "capacity": profiler.snapshot(full=full)}
    snap = exp.build_snapshot()
    # a real beacon crosses the wire: prove msgpack round-trips the block
    decoded = BusPacket.from_wire(BusPacket.wrap(snap, sender_id=instance).to_wire())
    agg.ingest(decoded.telemetry)
    return exp


def test_capacity_matrix_folds_worker_beacons():
    agg = FleetAggregator(None)
    p1, p2 = CapacityProfiler("TPU v5p"), CapacityProfiler("cpu")
    p1.observe("embed", device_s=0.01, bucket="64", items=16)
    p1.observe("llm.generate", device_s=0.02, bucket="8", items=8, tokens=8)
    p2.observe("embed", device_s=0.1, bucket="64", items=16)
    _worker_beacon(agg, "w-tpu", p1)
    _worker_beacon(agg, "w-cpu", p2)
    doc = agg.capacity_doc()
    assert set(doc["workers"]) == {"w-tpu", "w-cpu"}
    assert doc["workers"]["w-tpu"]["device_kind"] == "TPU v5p"
    by = {(r["op"], r["worker"]): r for r in doc["matrix"]}
    # the heterogeneity signal: same op, 10x throughput gap across workers
    assert by[("embed", "w-tpu")]["items_per_s"] == 1600.0
    assert by[("embed", "w-cpu")]["items_per_s"] == 160.0
    assert by[("llm.generate", "w-tpu")]["tokens_per_s"] == 400.0
    assert doc["ops"]["embed"] == 1760.0
    # fleet exposition carries the matrix as gauges
    parsed = _parse_exposition(agg.render())
    series = parsed["cordum_capacity_items_per_sec"]
    assert series[frozenset({("op", "embed"), ("bucket", "64"),
                             ("worker", "w-tpu")})] == 1600.0
    assert parsed["cordum_capacity_tokens_per_sec"][
        frozenset({("op", "llm.generate"), ("bucket", "8"),
                   ("worker", "w-tpu")})] == 400.0
    table = render_capacity_table(doc)
    assert "embed" in table and "w-tpu" in table and "1600.0" in table


def test_capacity_rows_reset_across_worker_restart():
    """The satellite contract: a restarted worker's fresh capacity block
    replaces the dead epoch's rows instead of merging with them (counters
    fold-and-climb; capacity profiles are per-epoch rate views)."""
    agg = FleetAggregator(None)
    p = CapacityProfiler("cpu")
    for _ in range(10):
        p.observe("embed", device_s=0.01, bucket="64", items=8)
    p.observe("matmul", device_s=0.02, bucket="512x512x512", items=1)
    _worker_beacon(agg, "w0", p)
    doc = agg.capacity_doc()
    assert {r["op"] for r in doc["matrix"]} == {"embed", "matmul"}
    assert [r for r in doc["matrix"] if r["op"] == "embed"][0]["n"] == 10

    # restart: new process epoch, fresh profiler that has only seen 2 jobs
    p2 = CapacityProfiler("cpu")
    p2.observe("embed", device_s=0.01, bucket="64", items=8)
    p2.observe("embed", device_s=0.01, bucket="64", items=8)
    _worker_beacon(agg, "w0", p2, started_shift=1)
    doc = agg.capacity_doc()
    assert {r["op"] for r in doc["matrix"]} == {"embed"}  # matmul row gone
    row = doc["matrix"][0]
    assert row["n"] == 2 and row["worker"] == "w0"


def test_capacity_staleness_marks_rows_and_drops_from_totals():
    agg = FleetAggregator(None)
    p = CapacityProfiler("cpu")
    p.observe("embed", device_s=0.01, items=8)
    _worker_beacon(agg, "w-stale", p)
    inst = agg._instances[("worker", "w-stale")]
    inst.last_seen -= 3600.0  # beacon long overdue
    doc = agg.capacity_doc()
    assert doc["matrix"][0]["stale"] is True
    assert doc["ops"] == {}  # stale rows don't count toward fleet capacity
    # ... and stale rows don't become fleet gauges either
    assert "cordum_capacity_items_per_sec" not in agg.render()


# ---------------------------------------------------------------------------
# histogram exemplars (observe → exposition → telemetry → fleet)
# ---------------------------------------------------------------------------


def test_exemplar_round_trips_through_exposition_parsing():
    h = Histogram("h_ex", buckets=(0.25, 1.0))
    h.observe(0.2, exemplar="tr-fast", job_class="BATCH")
    h.observe(5.0, exemplar="tr-slow", job_class="BATCH")
    exs = {}
    parsed = _parse_exposition("\n".join(h.render()), exemplars=exs)
    assert parsed["h_ex_count"][frozenset({("job_class", "BATCH")})] == 2.0
    assert exs[("h_ex_bucket",
                frozenset({("job_class", "BATCH"), ("le", "0.25")}))] == "tr-fast"
    assert exs[("h_ex_bucket",
                frozenset({("job_class", "BATCH"), ("le", "+Inf")}))] == "tr-slow"


def test_exemplar_reaches_fleet_scope_through_telemetry():
    m = Metrics()
    m.e2e_latency.observe(0.2, exemplar="tr-e2e", job_class="BATCH")
    exp = TelemetryExporter("scheduler", None, m, instance_id="s0")
    snap = exp.build_snapshot()
    assert "exemplars" in snap.metrics["histograms"]["cordum_job_e2e_seconds"]
    agg = FleetAggregator(None)
    decoded = BusPacket.from_wire(BusPacket.wrap(snap, sender_id="s0").to_wire())
    agg.ingest(decoded.telemetry)
    exs = {}
    parsed = _parse_exposition(agg.render(), exemplars=exs)
    assert parsed["cordum_job_e2e_seconds_count"][
        frozenset({("job_class", "BATCH")})] == 1.0
    got = [tid for (name, _), tid in exs.items()
           if name == "cordum_job_e2e_seconds_bucket"]
    assert got == ["tr-e2e"]


async def test_exemplar_auto_captured_from_active_span():
    """Without an explicit exemplar, observe() picks up the active span's
    trace id via the provider cordum_tpu.obs registers at import."""
    tracer = Tracer("test", None)
    h = Histogram("h_auto", buckets=(1.0,))
    async with tracer.span("work", trace_id="tr-ambient"):
        h.observe(0.5)
    h.observe(0.5)  # outside any span: no exemplar attached
    exs = {}
    _parse_exposition("\n".join(h.render()), exemplars=exs)
    assert set(exs.values()) == {"tr-ambient"}


# ---------------------------------------------------------------------------
# label-cardinality guard
# ---------------------------------------------------------------------------


def test_counter_cardinality_guard_folds_into_overflow():
    c = Counter("c_guard", max_label_sets=10)
    for i in range(25):
        c.inc(job_id=f"job-{i}")  # the job-id-label mistake
    assert len(c._values) == 11  # 10 real series + the overflow series
    assert c.value(overflow="true") == 15.0
    assert c.total() == 25.0  # nothing lost, just folded
    # existing series keep incrementing normally after overflow
    c.inc(job_id="job-0")
    assert c.value(job_id="job-0") == 2.0
    _parse_exposition("\n".join(c.render()))  # still conformant


def test_histogram_cardinality_guard_folds_into_overflow():
    h = Histogram("h_guard", buckets=(1.0,), max_label_sets=5)
    for i in range(20):
        h.observe(0.5, key=f"k-{i}")
    assert len(h._totals) == 6
    snap = {k: total for k, _, _, total in h._snapshot()}
    assert snap[(("overflow", "true"),)] == 15
    assert sum(snap.values()) == 20
    _parse_exposition("\n".join(h.render()))


# ---------------------------------------------------------------------------
# tail-based trace retention
# ---------------------------------------------------------------------------


def test_tail_sampler_keeps_all_slow_samples_fast():
    """Steady-state 95/5 fast/slow mix: every slow trace is kept, the fast
    are sampled at ~keep_fraction, and verdicts are deterministic."""

    def run():
        s = TailSampler(0.2, window=100, min_samples=20)
        rng = random.Random(7)
        fast_verdicts, slow_verdicts = [], []
        for i in range(1200):
            # 10% slow keeps the rolling p95 firmly inside the slow band
            # (at 5% the window's 95th entry flaps across the boundary)
            slow = rng.random() < 0.10
            dur = 500_000 if slow else rng.randrange(1_000, 2_000)
            verdict = s.admit(f"t-{i}", dur)
            if i >= 200:  # let the rolling window reach steady state
                (slow_verdicts if slow else fast_verdicts).append(verdict)
        return fast_verdicts, slow_verdicts

    fast, slow = run()
    assert slow and all(slow)  # keeps-all-slow invariant
    assert 0.10 < sum(fast) / len(fast) < 0.35  # ~keep_fraction of the fast
    # deterministic: the same trace ids get the same verdicts
    fast2, slow2 = run()
    assert fast2 == fast and slow2 == slow


def test_tail_sampler_inactive_at_keep_fraction_one():
    s = TailSampler(1.0, min_samples=2)
    assert not s.active
    for i in range(100):
        assert s.admit(f"t-{i}", 1)  # everything kept: the default behavior


async def test_collector_tail_retention_drops_fast_keeps_slow():
    kv, bus, m = MemoryKV(), LoopbackBus(), Metrics()
    col = SpanCollector(kv, bus, metrics=m,
                        tail_keep_fraction=0.0, tail_min_samples=5)
    t0 = now_us()

    async def feed(tid, dur_us):
        await col.add(Span(span_id=f"{tid}-x", parent_span_id=f"{tid}-r",
                           trace_id=tid, name="execute", service="worker",
                           start_us=t0, end_us=t0 + dur_us // 2))
        await col.add(Span(span_id=f"{tid}-r", trace_id=tid, name="submit",
                           service="gateway", start_us=t0, end_us=t0 + dur_us))

    for i in range(8):  # warm the window (all kept while it warms)
        await feed(f"warm-{i}", 1000 + i)
    thr = col.tail_sampler.threshold_us()
    await feed("t-fast", 10)       # far under p95 → dropped (fraction 0.0)
    await feed("t-slow", thr * 50)  # tail → always kept
    assert await col.spans("t-fast") == []
    slow = await col.spans("t-slow")
    assert len(slow) == 2
    # a late span of the dropped trace must not resurrect it
    await col.add(Span(span_id="late", parent_span_id="t-fast-r",
                       trace_id="t-fast", name="result", service="scheduler",
                       start_us=t0, end_us=t0 + 5))
    assert await col.spans("t-fast") == []
    # accounting: 2 spans at drop time + 1 late skip
    assert m.spans_dropped.value(reason="tail_sampled") == 3.0
    # measurement is unsampled: the stage histograms saw every span
    assert m.stage_seconds.quantile(0.5, stage="submit",
                                    service="gateway") is not None
    counts = {k: t for k, _, _, t in m.stage_seconds._snapshot()}
    assert sum(counts.values()) == 21  # 16 warm + 2 fast(+late) + 2 slow


# ---------------------------------------------------------------------------
# cross-trace critical-path blame
# ---------------------------------------------------------------------------


def _chain_trace(rng, tid):
    """A random nested stage chain (occasionally an async child outliving
    its parent) → list[Span]."""
    names = ["submit", "schedule", "dispatch", "execute", "device"]
    depth = rng.randrange(2, len(names) + 1)
    t0 = rng.randrange(0, 10_000)
    total = rng.randrange(5_000, 200_000)
    spans = [Span(span_id=f"{tid}-0", trace_id=tid, name=names[0],
                  service="gateway", start_us=t0, end_us=t0 + total)]
    start, end = t0, t0 + total
    for d in range(1, depth):
        start = rng.randrange(start, end)
        if rng.random() < 0.2:
            end = end + rng.randrange(0, 5_000)  # child outlives parent
        else:
            end = rng.randrange(start + 1, end + 1)
        spans.append(Span(span_id=f"{tid}-{d}", parent_span_id=f"{tid}-{d-1}",
                          trace_id=tid, name=names[d], service="svc",
                          start_us=start, end_us=end))
    return spans


def test_blame_shares_sum_to_one_property():
    rng = random.Random(42)
    docs = [assemble(f"t{i}", _chain_trace(rng, f"t{i}")) for i in range(40)]
    agg = aggregate_critical_paths(docs)
    assert agg["traces"] == 40
    # the exact invariant: blame µs partition the critical-path time; the
    # published shares only carry 4-decimal rounding noise on top
    total = sum(s["total_us"] for s in agg["stages"].values())
    assert total == agg["critical_path_us_total"]
    share_sum = sum(s["blame_share"] for s in agg["stages"].values())
    assert abs(share_sum - 1.0) < 1e-3, agg["stages"]
    for st in agg["stages"].values():
        assert 0 <= st["p50_ms"] <= st["p99_ms"]


def test_blame_agrees_with_single_trace_assemble():
    """1-trace input: blame µs equal the trace's own critical-path exclusive
    times and sum exactly to assemble()'s critical_path_us."""
    spans = [
        Span(span_id="a", trace_id="t1", name="submit", service="gw",
             start_us=0, end_us=10_000),
        Span(span_id="b", parent_span_id="a", trace_id="t1", name="schedule",
             service="sch", start_us=1_000, end_us=4_000),
        Span(span_id="c", parent_span_id="b", trace_id="t1", name="execute",
             service="w", start_us=1_500, end_us=9_000),
    ]
    doc = assemble("t1", spans)
    assert doc["critical_path"] == ["a", "b", "c"]
    blame = critical_path_blame(doc)
    # execute owns 1500..9000; schedule owns 1000..1500; submit the rest
    assert blame == {"submit": 2_000, "schedule": 500, "execute": 7_500}
    assert sum(blame.values()) == doc["critical_path_us"]
    agg = aggregate_critical_paths([doc])
    assert {k: v["total_us"] for k, v in agg["stages"].items()} == blame
    assert agg["slowest"][0]["trace_id"] == "t1"
    out = render_blame(agg)
    assert "execute" in out and "75.0%" in out


def test_blame_untracked_gap_accounted():
    # root 0..10000 but its only child covers 1000..2000: the 8000 µs of
    # wall the root alone covers is the root's; a path GAP shows as the
    # child ending early with nothing after it
    spans = [
        Span(span_id="a", trace_id="t", name="submit", service="gw",
             start_us=0, end_us=2_000),
        Span(span_id="b", parent_span_id="a", trace_id="t", name="execute",
             service="w", start_us=500, end_us=10_000),
    ]
    doc = assemble("t", spans)
    blame = critical_path_blame(doc)
    assert blame["submit"] == 500 and blame["execute"] == 9_500
    assert UNTRACKED_STAGE not in blame
    # now a genuinely uncovered window: child detached in time
    spans[1].start_us, spans[1].end_us = 8_000, 10_000
    doc = assemble("t", spans)
    blame = critical_path_blame(doc)
    assert blame[UNTRACKED_STAGE] == 6_000  # 2000..8000 nobody measured
    assert sum(blame.values()) == doc["critical_path_us"]


def test_blame_empty_input():
    agg = aggregate_critical_paths([])
    assert agg["traces"] == 0 and agg["stages"] == {}
    assert "no traces" in render_blame(agg)


# ---------------------------------------------------------------------------
# worker runtime feeds the profiler
# ---------------------------------------------------------------------------


async def test_worker_jobs_feed_capacity_profiler():
    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    w = Worker(bus=bus, store=ms, worker_id="w1", pool="default",
               topics=["job.default"], capabilities=["echo"],
               heartbeat_interval_s=999)

    async def handler(ctx: JobContext):
        op = (ctx.payload or {}).get("op")
        if op == "timed":
            with ctx.device_timer("device", op="timed", items="4",
                                  bucket="64", compile_cached="false"):
                pass
            return {"ok": True}
        return {"echo": ctx.payload}

    w.register("job.default", handler)
    await w.start()
    await settle(bus)
    for i, payload in enumerate(({"op": "echo"}, {"op": "echo"},
                                 {"op": "timed"})):
        ptr = await ms.put_context(f"j{i}", payload)
        await bus.publish(subj.SUBMIT, BusPacket.wrap(
            JobRequest(job_id=f"j{i}", topic="job.default", context_ptr=ptr)))
    await settle(bus)
    rows = {f"{r['op']}|{r['bucket']}": r for r in w.capacity.rows()}
    # host op without a device timer: execute wall feeds the matrix
    assert rows["echo|-"]["n"] == 2 and rows["echo|-"]["device_s"] > 0
    # device-timer records carry op/items/bucket + the compile split
    timed = rows["timed|64"]
    assert timed["items"] == 4 and timed["compile_n"] == 1
    # ... and the telemetry beacon carries the block
    health = w.telemetry_health()
    assert "echo|-" in health["capacity"]["rows"]
    await w.stop()
    await eng.stop()


async def test_worker_failed_jobs_do_not_pollute_capacity():
    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    w = Worker(bus=bus, store=ms, worker_id="w1", pool="default",
               topics=["job.default"], heartbeat_interval_s=999)

    async def boom(ctx: JobContext):
        raise RuntimeError("nope")

    w.register("job.default", boom)
    await w.start()
    await settle(bus)
    ptr = await ms.put_context("jf", {"op": "boom"})
    await bus.publish(subj.SUBMIT, BusPacket.wrap(
        JobRequest(job_id="jf", topic="job.default", context_ptr=ptr)))
    await settle(bus)
    assert await js.get_state("jf") == "FAILED"
    assert w.capacity.rows() == []
    await w.stop()
    await eng.stop()


async def test_serving_steps_feed_capacity_profiler():
    """Every ragged mixed step reports its delivered tokens at the static
    flat-buffer bucket — ONE row per worker, not a pow2 ladder — with the
    warmup compile flagged so steady-state tokens/s excludes it."""
    from cordum_tpu.serving.engine import GenRequest, ServingEngine
    from tests.test_serving import FakeBackend, run_blocking

    cap = CapacityProfiler("cpu")
    be = FakeBackend(num_pages=64)
    eng = ServingEngine(be, run_blocking=run_blocking,
                        max_sessions=4, capacity=cap)
    await asyncio.gather(*(
        eng.submit(GenRequest(prompt=[1, 2, 3], max_new_tokens=5,
                              stream=False), job_id=f"j{i}")
        for i in range(3)
    ))
    await eng.stop()
    rows = [r for r in cap.rows() if r["op"] == "llm.generate"]
    # one static shape -> one (op, bucket) row at the flat-buffer width
    assert [r["bucket"] for r in rows] == [str(be.max_batch_tokens)]
    row = rows[0]
    # 3 sessions x 5 generated tokens (the first token of each comes from
    # its prefill-completing chunk, which now rides the same mixed step)
    assert row["tokens"] == 15 and row["items"] == row["tokens"]
    assert row["tokens_per_s"] > 0
    # the fake's first step is its "compile"; the split keeps it out of
    # the steady-state rate the fleet matrix reports
    assert row["compile_n"] == 1 and row["n"] > row["compile_n"]


# ---------------------------------------------------------------------------
# gateway surfaces
# ---------------------------------------------------------------------------


async def test_gateway_capacity_endpoint():
    async with _FleetStack() as s:
        p = CapacityProfiler("cpu")
        p.observe("embed", device_s=0.01, bucket="64", items=16)
        exp = TelemetryExporter("worker", s.bus, Metrics(), instance_id="w9")
        exp.health_fn = lambda: {"role": "worker",
                                 "capacity": p.snapshot(full=True)}
        await exp.publish_once()
        await s.bus.drain()
        r = await s.client.get("/api/v1/capacity", headers=s.h())
        assert r.status == 200
        doc = await r.json()
        assert doc["workers"]["w9"]["device_kind"] == "cpu"
        assert doc["matrix"][0]["op"] == "embed"
        assert doc["matrix"][0]["items_per_s"] == 1600.0
        assert doc["ops"] == {"embed": 1600.0}
        # fleet metrics scope exposes the matrix gauges
        r = await s.client.get("/metrics?scope=fleet", headers=s.h())
        assert "cordum_capacity_items_per_sec" in await r.text()


async def test_gateway_traces_analysis_endpoint():
    async with _FleetStack() as s:
        t0 = now_us()
        for i, tid in enumerate(("tr-a", "tr-b")):
            await s.gw.span_collector.add(Span(
                span_id=f"{tid}-r", trace_id=tid, name="submit",
                service="gateway", start_us=t0, end_us=t0 + 10_000 * (i + 1)))
            await s.gw.span_collector.add(Span(
                span_id=f"{tid}-e", parent_span_id=f"{tid}-r", trace_id=tid,
                name="execute", service="worker", start_us=t0 + 1_000,
                end_us=t0 + 8_000))
        r = await s.client.get("/api/v1/traces/analysis?last=10",
                               headers=s.h())
        assert r.status == 200
        doc = await r.json()
        assert doc["traces"] == 2
        assert {"submit", "execute"} <= set(doc["stages"])
        share_sum = sum(st["blame_share"] for st in doc["stages"].values())
        assert abs(share_sum - 1.0) < 1e-6
        # the slowest trace is the exemplar entry point
        assert doc["slowest"][0]["trace_id"] == "tr-b"
        assert render_blame(doc)  # renders without error
        # the literal route must not shadow real trace ids
        r = await s.client.get("/api/v1/traces/tr-a", headers=s.h())
        assert (await r.json())["span_count"] == 2


# ---------------------------------------------------------------------------
# CapacityView decode-side fields (ISSUE 14, docs/SERVING.md §Disaggregation)
# ---------------------------------------------------------------------------


def _decode_beacon(instance, *, started=1, seq=0, rows=None, kv=None,
                   occ=None, role=None, draining=False):
    """A worker telemetry snapshot whose capacity block carries the
    decode-side serving state (the Worker.telemetry_health shape)."""
    from cordum_tpu.protocol.types import TelemetrySnapshot

    block = {"v": 1, "seq": seq, "full": True, "device_kind": "cpu",
             "rows": rows or {}}
    if kv is not None:
        block["kv_pages"] = kv
    if occ is not None:
        block["occupancy"] = occ
    if role is not None:
        block["serving_role"] = role
    if draining:
        block["draining"] = True
    return TelemetrySnapshot(service="worker", instance=instance, seq=seq,
                             started_at_us=started, interval_s=2.0,
                             health={"role": "worker", "capacity": block})


def _mk_view(clock_box):
    from cordum_tpu.obs.capacity import CapacityView

    return CapacityView(clock=lambda: clock_box[0])


def test_capacity_view_folds_decode_side_fields():
    """Occupancy, kv_pages_free, serving role and the drain flag fold from
    worker beacons next to the throughput rows (PR 13 only tested the
    items/s path) — the ServingPlacer/DecodeRebalancer read side."""
    clock = [0.0]
    view = _mk_view(clock)
    view.ingest(_decode_beacon(
        "w1",
        rows={"llm.generate|28": {"op": "llm.generate", "bucket": "28",
                                  "items_per_s": 90.0, "tokens_per_s": 90.0},
              "llm.prefill|28": {"op": "llm.prefill", "bucket": "28",
                                 "items_per_s": 400.0,
                                 "tokens_per_s": 400.0}},
        kv={"pages_total": 127, "pages_free": 40, "pages_in_use": 87},
        occ={"active_sessions": 6, "decode_mean": 5.5, "decode_max": 8},
        role="decode"))
    assert view.token_rate("w1", "llm.generate") == 90.0
    assert view.token_rate("w1", "llm.prefill") == 400.0
    assert view.kv_pages("w1") == {"pages_total": 127, "pages_free": 40,
                                   "pages_in_use": 87}
    assert view.decode_occupancy("w1")["active_sessions"] == 6
    assert view.serving_role("w1") == "decode"
    assert view.draining("w1") is False
    assert view.serving_workers() == ["w1"]
    # a later beacon flips the drain flag
    view.ingest(_decode_beacon("w1", seq=1, draining=True,
                               kv={"pages_total": 127, "pages_free": 40}))
    assert view.draining("w1") is True


def test_capacity_view_decode_fields_staleness_expiry():
    """A silent worker's decode-side state reads as unmeasured past
    stale_after_s — the rebalancer must never act on a dead beacon."""
    clock = [0.0]
    view = _mk_view(clock)
    view.ingest(_decode_beacon(
        "w1", kv={"pages_total": 127, "pages_free": 3},
        occ={"active_sessions": 9}, role="decode"))
    assert view.kv_pages("w1")["pages_free"] == 3
    clock[0] += 100.0  # beacon silent past stale_after_s (15s)
    assert view.kv_pages("w1") == {}
    assert view.decode_occupancy("w1") == {}
    assert view.serving_role("w1") == ""
    assert view.draining("w1") is False
    assert view.serving_workers() == []


def test_capacity_view_decode_fields_restart_epoch_clear():
    """A restarted worker (new started_at_us) starts a fresh fold: the dead
    epoch's occupancy/pages must not linger under the new epoch."""
    clock = [0.0]
    view = _mk_view(clock)
    view.ingest(_decode_beacon(
        "w1", started=1, kv={"pages_total": 127, "pages_free": 2},
        occ={"active_sessions": 9}, role="prefill"))
    assert view.decode_occupancy("w1")["active_sessions"] == 9
    # restart: fresh epoch, no serving state beaconed yet
    view.ingest(_decode_beacon("w1", started=999, seq=0))
    assert view.kv_pages("w1") == {}
    assert view.decode_occupancy("w1") == {}
    assert view.serving_role("w1") == ""
    # the fresh epoch's own state folds normally
    view.ingest(_decode_beacon("w1", started=999, seq=1,
                               kv={"pages_total": 127, "pages_free": 120},
                               role="mixed"))
    assert view.kv_pages("w1")["pages_free"] == 120
    assert view.serving_role("w1") == "mixed"


def test_capacity_table_renders_worker_serving_columns():
    """`cordumctl capacity` surfaces per-worker kv_pages_free, decode
    occupancy and the draining flag (the renderer used to drop them)."""
    doc = {
        "workers": {
            "w-dec": {"service": "worker", "fresh": True, "rows": 1,
                      "serving_role": "decode", "draining": True,
                      "kv_pages": {"pages_total": 127, "pages_free": 40,
                                   "pages_in_use": 87, "prefix_pages": 12},
                      "occupancy": {"active_sessions": 6,
                                    "decode_mean": 5.5,
                                    "prefix_hit_rate": 0.86,
                                    "resident_warm": 6, "resident_cold": 18,
                                    "hibernated_sessions": 18}},
            "w-plain": {"service": "worker", "fresh": True, "rows": 1},
        },
        "matrix": [{"op": "llm.generate", "bucket": "28", "worker": "w-dec",
                    "items_per_s": 90.0, "tokens_per_s": 90.0}],
        "ops": {"llm.generate": 90.0},
    }
    table = render_capacity_table(doc)
    lines = table.splitlines()
    header = next(line for line in lines if "kv_free" in line)
    assert "sessions" in header and "draining" in header and "role" in header
    assert "pfx_pages" in header and "resident" in header and "hib" in header
    row = next(line for line in lines if line.startswith("w-dec"))
    assert "decode" in row and "40" in row and "87" in row
    assert "6" in row and "yes" in row  # sessions + draining flag
    # prefix cache + tiering columns (docs/SERVING.md §Prefix cache and
    # tiering): cached-page count, hit rate, warm/cold census, hibernated
    assert "12" in row and "86%" in row and "6w/18c" in row
    # a worker that doesn't beacon the fields degrades to "-" (not a crash)
    plain_doc = {"workers": {"w-old": {
        "service": "worker", "fresh": True, "rows": 1,
        "serving_role": "mixed",
        "kv_pages": {"pages_total": 64, "pages_free": 60}}},
        "matrix": [], "ops": {}}
    old_row = next(line for line in render_capacity_table(plain_doc)
                   .splitlines() if line.startswith("w-old"))
    assert old_row.count("-") >= 3  # pfx_pages, pfx_hit, resident, hib
    # a worker with no serving state stays out of the serving section but
    # the matrix still renders
    assert not any(line.startswith("w-plain") and "yes" in line
                   for line in lines if "kv_free" not in line)
    assert any("llm.generate" in line for line in lines)
