"""Fault-injection harness (cordum_tpu/infra/chaos.py) + the kill-primary
chaos suite (ISSUE 8 headline).

The `chaos` marker tags tests that kill/partition live statebus processes;
CI runs them as a dedicated step (test.yml) and they also ride tier-1.

The headline test runs the miniature full platform — 2 scheduler shards ×
2 replicated statebus partitions (4 real ``cmd.statebus`` subprocesses,
sync-ack mode) — SIGKILLs one partition's primary mid-submit-burst, and
proves zero job loss: the replica promotes, clients fail over, the pending
replayer resurfaces anything dropped between failover and resubscription,
and every submitted job reaches SUCCEEDED with an intact event log.
"""
from __future__ import annotations

import asyncio
import time
from pathlib import Path

import pytest

from cordum_tpu.controlplane.scheduler.reconciler import (
    PendingReplayer,
    WorkerFailover,
)
from cordum_tpu.infra.chaos import ChaosProxy, ServerProc, WorkerProc, free_port
from cordum_tpu.infra.config import Timeouts
from cordum_tpu.infra.jobstore import JobStore
from cordum_tpu.infra.replication import probe_role
from cordum_tpu.infra.statebus import StateBusServer, connect, connect_partitioned
from cordum_tpu.protocol import subjects as subj
from cordum_tpu.protocol.types import BusPacket, JobRequest, JobState

from .test_sharding import _attach_worker, _mk_engine

REPO_ROOT = str(Path(__file__).resolve().parents[1])

#: the canonical lifecycle of a successful job; chaos runs may interleave
#: extra events (replays are at-least-once) but must preserve this order
CANONICAL_EVENTS = ["submit", "scheduled", "dispatched", "running", "result"]


def _is_subsequence(needle: list, hay: list) -> bool:
    it = iter(hay)
    return all(x in it for x in needle)


async def wait_for(cond, timeout_s: float = 10.0, msg: str = "condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        v = cond()
        if asyncio.iscoroutine(v):
            v = await v
        if v:
            return
        await asyncio.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# ChaosProxy
# ---------------------------------------------------------------------------


async def test_proxy_passthrough_and_delay():
    srv = StateBusServer(port=0)
    await srv.start()
    proxy = ChaosProxy("127.0.0.1", srv.port)
    await proxy.start()
    kv, _, conn = await connect(proxy.url)
    try:
        await kv.set("through-proxy", b"1")
        assert await kv.get("through-proxy") == b"1"
        assert proxy.connections_total == 1
        proxy.set_delay(0.15)
        t0 = time.monotonic()
        assert await kv.get("through-proxy") == b"1"
        assert time.monotonic() - t0 >= 0.15  # request + reply each delayed
        proxy.restore()
        t0 = time.monotonic()
        await kv.get("through-proxy")
        assert time.monotonic() - t0 < 0.15
    finally:
        await conn.close()
        await proxy.stop()
        await srv.stop()


async def test_proxy_sever_client_reconnects():
    srv = StateBusServer(port=0)
    await srv.start()
    proxy = ChaosProxy("127.0.0.1", srv.port)
    await proxy.start()
    kv, _, conn = await connect(proxy.url)
    try:
        await kv.set("pre", b"1")
        proxy.sever()
        # the RST kicks the client into its reconnect loop; the proxy still
        # accepts, so the next call rides a fresh proxied connection
        assert await kv.get("pre") == b"1"
        await wait_for(lambda: conn.reconnect_count >= 1, msg="reconnect count")
        assert proxy.connections_total >= 2
    finally:
        await conn.close()
        await proxy.stop()
        await srv.stop()


async def test_proxy_per_direction_blackhole_is_asymmetric():
    """blackhole("s2c") models the asymmetric partition: requests still
    REACH the server (state changes) while replies vanish (the client's
    call stays parked) — restore releases the parked reply."""
    srv = StateBusServer(port=0)
    await srv.start()
    proxy = ChaosProxy("127.0.0.1", srv.port)
    await proxy.start()
    kv, _, conn = await connect(proxy.url)
    try:
        await kv.set("pre", b"0")
        proxy.blackhole("s2c")
        task = asyncio.ensure_future(kv.set("one-way", b"1"))
        # the request crossed: the server applied the write...
        await wait_for(lambda: srv.kv.get("one-way"), msg="server got the write")
        await asyncio.sleep(0.1)
        assert not task.done(), "reply crossed a blackholed s2c direction"
        proxy.restore()
        await asyncio.wait_for(task, timeout=10)  # parked reply released
        # the opposite asymmetry: c2s blackholed = requests vanish
        proxy.blackhole("c2s")
        t2 = asyncio.ensure_future(kv.set("other-way", b"2"))
        await asyncio.sleep(0.2)
        assert await srv.kv.get("other-way") is None, "write crossed c2s hole"
        assert not t2.done()
        proxy.restore()
        await asyncio.wait_for(t2, timeout=10)
    finally:
        await conn.close()
        await proxy.stop()
        await srv.stop()


async def test_proxy_per_direction_delay_composes():
    """Per-direction delays add up: delaying only c2s costs one delay per
    round trip, delaying both costs two."""
    srv = StateBusServer(port=0)
    await srv.start()
    proxy = ChaosProxy("127.0.0.1", srv.port)
    await proxy.start()
    kv, _, conn = await connect(proxy.url)
    try:
        await kv.set("k", b"1")
        proxy.set_delay(0.15, "c2s")
        t0 = time.monotonic()
        await kv.get("k")
        one_way = time.monotonic() - t0
        assert 0.15 <= one_way < 0.45, one_way
        proxy.set_delay(0.15, "s2c")  # now both directions pay
        t0 = time.monotonic()
        await kv.get("k")
        assert time.monotonic() - t0 >= 0.3
        proxy.restore()
        t0 = time.monotonic()
        await kv.get("k")
        assert time.monotonic() - t0 < 0.15
    finally:
        await conn.close()
        await proxy.stop()
        await srv.stop()


async def test_proxy_blackhole_detected_by_ping_and_failed_over():
    """A black-holed connection (host died behind a switch: no FIN/RST)
    never EOFs — only the liveness ping turns it into a failover, and the
    replica-set walk lands on the healthy standby."""
    primary = StateBusServer(port=0)
    await primary.start()
    standby = StateBusServer(port=0)  # independent primary = promoted twin
    await standby.start()
    proxy = ChaosProxy("127.0.0.1", primary.port)
    await proxy.start()
    url = f"{proxy.url}|statebus://127.0.0.1:{standby.port}"
    kv, _, conn = await connect(url, ping_interval_s=0.2)
    try:
        await kv.set("alive", b"1")
        proxy.blackhole()
        # ping times out -> forced close -> walk: proxy dial hangs on the
        # role check, standby answers -> failover completes
        await wait_for(lambda: (conn.host, conn.port) == ("127.0.0.1", standby.port),
                       20.0, "failover to standby")
        await kv.set("after-blackhole", b"2")
        assert await standby.kv.get("after-blackhole") == b"2"
    finally:
        await conn.close()
        await proxy.stop()
        await standby.stop()
        await primary.stop()


# ---------------------------------------------------------------------------
# ServerProc: real cmd.statebus subprocesses
# ---------------------------------------------------------------------------


@pytest.mark.chaos
async def test_server_proc_kill_and_restart_replays_aof(tmp_path):
    port = free_port()
    proc = ServerProc(port, env={"STATEBUS_AOF": str(tmp_path / "p.aof")},
                      cwd=REPO_ROOT)
    await proc.start()
    try:
        kv, _, conn = await connect(f"statebus://127.0.0.1:{port}")
        await kv.set("durable", b"1")
        await conn.close()
        proc.kill()  # SIGKILL: no GOAWAY, no graceful drain
        assert not proc.alive
        await proc.start()
        kv, _, conn = await connect(f"statebus://127.0.0.1:{port}")
        assert await kv.get("durable") == b"1"
        await conn.close()
    finally:
        proc.kill()


@pytest.mark.chaos
async def test_sigterm_goaway_fails_over_without_heartbeat_wait(tmp_path):
    """Graceful shutdown (SIGTERM): the GOAWAY broadcast promotes the
    replica and fails clients over immediately — the 30s heartbeat timeout
    configured here would fail this test if the GOAWAY path were broken."""
    p_port, r_port = free_port(), free_port()
    peers = f"statebus://127.0.0.1:{p_port},statebus://127.0.0.1:{r_port}"
    primary = ServerProc(p_port, env={
        "STATEBUS_AOF": str(tmp_path / "p.aof"), "STATEBUS_PEERS": peers,
        "STATEBUS_HEARTBEAT_TIMEOUT": "30.0"}, cwd=REPO_ROOT)
    replica = ServerProc(r_port, env={
        "STATEBUS_AOF": str(tmp_path / "r.aof"), "STATEBUS_PEERS": peers,
        "STATEBUS_REPLICA_OF": f"statebus://127.0.0.1:{p_port}",
        "STATEBUS_HEARTBEAT_TIMEOUT": "30.0"}, cwd=REPO_ROOT)
    await primary.start()
    await replica.start()
    kv, _, conn = await connect(
        f"statebus://127.0.0.1:{p_port}|statebus://127.0.0.1:{r_port}")
    try:
        await kv.set("pre-term", b"1")

        async def replicated():
            doc = await probe_role("127.0.0.1", r_port)
            return doc is not None and doc.get("offset", 0) >= 1

        await wait_for(replicated, msg="replica caught up")
        t0 = time.monotonic()
        await asyncio.to_thread(primary.terminate)  # SIGTERM -> GOAWAY
        await kv.set("post-term", b"2")  # parked, retransmitted on failover
        elapsed = time.monotonic() - t0
        assert elapsed < 15.0, f"failover took {elapsed:.1f}s (GOAWAY broken?)"
        doc = await probe_role("127.0.0.1", r_port)
        assert doc["role"] == "primary"
        assert await kv.get("pre-term") == b"1"  # replicated before the term
    finally:
        await conn.close()
        primary.kill()
        replica.kill()


# ---------------------------------------------------------------------------
# result-replay nudge (PendingReplayer third leg)
# ---------------------------------------------------------------------------


async def test_replayer_nudges_lost_result_to_completion():
    """A job wedged in RUNNING because its result packet was lost (the
    pub/sub at-most-once window a failover opens) is re-delivered to its
    worker by the replayer; the worker republishes and the job completes —
    no TIMEOUT, no re-execution required of an idempotent worker."""
    from cordum_tpu.infra.bus import LoopbackBus
    from cordum_tpu.infra.kv import MemoryKV
    from cordum_tpu.protocol.types import JobResult, LABEL_PARTITION

    kv = MemoryKV()
    bus = LoopbackBus()
    eng = _mk_engine(bus, kv, index=0, count=1)
    await eng.start()
    deliveries = []

    async def flaky_worker(subject, pkt):
        req = pkt.job_request
        deliveries.append(req.job_id)
        if len(deliveries) == 1:
            return  # drop the first result: simulates the failover window
        await bus.publish(
            subj.stamped_result_subject((req.labels or {}).get(LABEL_PARTITION, "")),
            BusPacket.wrap(JobResult(job_id=req.job_id, status="SUCCEEDED",
                                     worker_id="w1"), sender_id="w1"),
        )

    await bus.subscribe(subj.direct_subject("w1"), flaky_worker, queue="w")
    js = JobStore(kv)
    rep = PendingReplayer(eng, js, Timeouts(scan_interval_s=0.1,
                                            pending_replay_s=30.0,
                                            result_replay_s=0.2))
    await rep.start()
    try:
        await bus.publish(
            subj.SUBMIT,
            BusPacket.wrap(JobRequest(job_id="lost-result", topic="job.bench",
                                      tenant_id="default"), sender_id="t"),
        )
        await wait_for(lambda: js.get_state("lost-result"), msg="job created")
        await wait_for(
            lambda: _get_state_eq(js, "lost-result", "SUCCEEDED"),
            10.0, "nudge-driven completion")
        assert len(deliveries) >= 2  # original dispatch + >=1 nudge
        events = [e["event"] for e in await js.events("lost-result")]
        assert _is_subsequence(CANONICAL_EVENTS, events), events
        assert eng.metrics.inflight_nudges.total() >= 1
    finally:
        await rep.stop()
        await eng.stop()
        await bus.close()


async def _get_state_eq(js: JobStore, jid: str, want: str) -> bool:
    return await js.get_state(jid) == want


# ---------------------------------------------------------------------------
# serving chaos: SIGKILL a serving worker mid-decode, every session resumes
# (ISSUE 12 acceptance — docs/SERVING.md §Migration, drain, and failover)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.slow  # two jax worker subprocesses: its own dedicated CI step
async def test_sigkill_serving_worker_mid_decode_sessions_resume(tmp_path):
    """SIGKILL a real ``cmd.worker`` subprocess mid-decode with 3 active
    llm.generate sessions: the scheduler's WorkerFailover detects the
    silence, re-dispatches each session to the surviving worker with the
    already-streamed tokens as a forced-decode prefix, and every client's
    offset-assembled token stream is EXACTLY the fp32 sequential-oracle
    output — no duplicated, missing, or divergent tokens."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
    from cordum_tpu.controlplane.scheduler.engine import Engine
    from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
    from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.memstore import MemoryStore
    from cordum_tpu.infra.registry import WorkerRegistry
    from cordum_tpu.infra.statebus import connect
    from cordum_tpu.models import llama
    from cordum_tpu.protocol.types import LABEL_SESSION_KEY, STATUS_HINT_STREAM

    from .test_serving import ref_greedy

    port = free_port()
    sb = ServerProc(port, env={"STATEBUS_AOF": str(tmp_path / "s.aof")},
                    cwd=REPO_ROOT)
    await sb.start()
    url = f"statebus://127.0.0.1:{port}"
    kv, bus, conn = await connect(url)
    js, ms = JobStore(kv), MemoryStore(kv)
    kernel = SafetyKernel(policy_doc={
        "tenants": {"default": {"allow_topics": ["job.*", "job.>"]}}})
    reg = WorkerRegistry(ttl_s=3.0)
    pc = parse_pool_config({"topics": {"job.tpu.generate": "tpu"},
                            "pools": {"tpu": {"requires": []}}})
    eng = Engine(bus=bus, job_store=js, safety=SafetyClient(kernel.check),
                 strategy=LeastLoadedStrategy(reg, pc), registry=reg)
    await eng.start()
    fo = WorkerFailover(eng, js, reg, Timeouts(scan_interval_s=0.5))
    await fo.start()
    # assemble each job's client-visible stream by offset, asserting any
    # replayed prefix agrees token-for-token with what already streamed
    streams: dict[str, list[int]] = {}

    async def tap(subject, pkt):
        pr = pkt.job_progress
        if pr is None or pr.status_hint != STATUS_HINT_STREAM:
            return
        buf = streams.setdefault(pr.job_id, [])
        off = pr.offset if pr.offset >= 0 else len(buf)
        for i, t in enumerate(pr.tokens):
            idx = off + i
            if idx == len(buf):
                buf.append(int(t))
            elif idx < len(buf):
                assert buf[idx] == int(t), (pr.job_id, idx, buf[idx], t)

    await bus.subscribe(subj.PROGRESS, tap)

    wenv = {
        "CORDUM_STATEBUS_URL": url,
        "WORKER_POOL": "tpu",
        "WORKER_TOPICS": "job.tpu.>",
        "WORKER_CAPABILITIES": "tpu",
        "WORKER_HEARTBEAT_INTERVAL": "0.5",
        # fp32 tiny model: resumed streams compare EXACTLY against the
        # fp32 oracle computed in this process (same seed, same config)
        "WORKER_LLAMA_DTYPE": "float32",
        "WORKER_SERVING_PAGE_SIZE": "8",
        "WORKER_SERVING_CACHE_PAGES": "128",
        "WORKER_SERVING_MAX_SESSIONS": "8",
        "WORKER_SERVING_MAX_NEW_TOKENS": "256",
        "WORKER_BATCHING": "0",
    }
    w1 = WorkerProc("chaos-w1", env=wenv, cwd=REPO_ROOT,
                    log_path=str(tmp_path / "w1.log"))
    w2 = WorkerProc("chaos-w2", env=wenv, cwd=REPO_ROOT,
                    log_path=str(tmp_path / "w2.log"))
    w1.start()
    w2.start()
    jobs: dict[str, list[int]] = {}
    try:
        await wait_for(lambda: len(reg.snapshot()) >= 2, 120.0,
                       "both workers heartbeating")
        n_new = 96
        for i, plen in enumerate((3, 9, 14)):
            jid = f"chaos-gen-{i}"
            prompt = [(7 * i + j + 1) % 256 for j in range(plen)]
            jobs[jid] = prompt
            ptr = await ms.put_context(jid, {
                "op": "llm.generate", "tokens": prompt,
                "max_new_tokens": n_new, "session_id": f"conv-chaos-{i}",
            })
            await js.set_state(jid, JobState.PENDING, fields={
                "topic": "job.tpu.generate", "tenant_id": "default",
            }, event="submit")
            await js.put_request(JobRequest(
                job_id=jid, topic="job.tpu.generate", context_ptr=ptr,
                tenant_id="default",
                labels={"preferred_worker_id": "chaos-w1",
                        LABEL_SESSION_KEY: f"conv-chaos-{i}"}))
            await bus.publish(subj.SUBMIT, BusPacket.wrap(JobRequest(
                job_id=jid, topic="job.tpu.generate", context_ptr=ptr,
                tenant_id="default",
                labels={"preferred_worker_id": "chaos-w1",
                        LABEL_SESSION_KEY: f"conv-chaos-{i}"}), sender_id="t"))

        # mid-decode: every session has streamed some tokens but none is
        # close to done — then the worker dies with no warning
        await wait_for(
            lambda: all(4 <= len(streams.get(j, [])) for j in jobs)
            and all(len(streams.get(j, [])) < n_new - 20 for j in jobs),
            180.0, "all 3 sessions streaming mid-decode")
        w1.kill()
        assert not w1.alive

        async def all_succeeded():
            for jid in jobs:
                if await js.get_state(jid) != "SUCCEEDED":
                    return False
            return True

        try:
            await wait_for(all_succeeded, 180.0, "sessions resumed on w2")
        except AssertionError:
            states = {j: await js.get_state(j) for j in jobs}
            raise AssertionError(f"sessions stuck after SIGKILL: {states}")

        cfg = dataclasses.replace(llama.LlamaConfig.tiny(), dtype=jnp.float32)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        for jid, prompt in jobs.items():
            oracle = ref_greedy(cfg, params, prompt, n_new)
            res = await ms.get_result(jid)
            assert res["tokens"] == oracle, (
                f"{jid}: resumed output diverges from the oracle")
            assert streams[jid] == oracle, (
                f"{jid}: assembled client stream has dup/missing tokens")
            events = [e["event"] for e in await js.events(jid)]
            assert "failover" in events, (jid, events)
            assert "cancelled" not in events
        assert eng.metrics.session_failovers.value(reason="worker_dead") >= 3
    finally:
        w1.kill()
        w2.kill()
        await fo.stop()
        await eng.stop()
        await conn.close()
        sb.kill()


# ---------------------------------------------------------------------------
# the headline: kill a statebus primary mid-burst, lose zero jobs
# ---------------------------------------------------------------------------


async def _gateway_submit(js: JobStore, bus, jid: str) -> None:
    """The gateway submit contract in miniature (gateway/app.py
    _submit_one): persist PENDING + the request, THEN publish — so a submit
    packet lost to a failover window is replayed from state, not gone."""
    from cordum_tpu.utils.ids import now_us

    req = JobRequest(job_id=jid, topic="job.bench", tenant_id="default")
    await js.set_state(jid, JobState.PENDING, fields={
        "topic": "job.bench", "tenant_id": "default",
        "submitted_at_us": str(now_us()),
    }, event="submit")
    await js.put_request(req)
    await bus.publish(subj.submit_subject_for(jid, 2),
                      BusPacket.wrap(req, sender_id="gw"))


@pytest.mark.chaos
@pytest.mark.statebus
async def test_kill_primary_mid_burst_zero_job_loss(tmp_path):
    """ISSUE 8 acceptance: 2 scheduler shards × 2 replicated statebus
    partitions (sync-ack), SIGKILL partition 0's primary mid-burst →
    replica promotes, every submitted job reaches a terminal state with an
    intact event log, and the returning old primary demotes (no
    split-brain)."""
    ports = {f"p{i}": free_port() for i in range(2)}
    ports.update({f"r{i}": free_port() for i in range(2)})
    procs: dict[str, ServerProc] = {}
    for i in range(2):
        peers = (f"statebus://127.0.0.1:{ports[f'p{i}']},"
                 f"statebus://127.0.0.1:{ports[f'r{i}']}")
        common = {"STATEBUS_PEERS": peers, "STATEBUS_SYNC_REPLICATION": "1",
                  "STATEBUS_HEARTBEAT_TIMEOUT": "1.0"}
        procs[f"p{i}"] = ServerProc(ports[f"p{i}"], env={
            **common, "STATEBUS_AOF": str(tmp_path / f"p{i}.aof")}, cwd=REPO_ROOT)
        procs[f"r{i}"] = ServerProc(ports[f"r{i}"], env={
            **common, "STATEBUS_AOF": str(tmp_path / f"r{i}.aof"),
            "STATEBUS_REPLICA_OF": f"statebus://127.0.0.1:{ports[f'p{i}']}",
        }, cwd=REPO_ROOT)
    await asyncio.gather(*(p.start() for p in procs.values()))
    url = ",".join(
        f"statebus://127.0.0.1:{ports[f'p{i}']}|statebus://127.0.0.1:{ports[f'r{i}']}"
        for i in range(2))

    async def replicas_attached():
        docs = await asyncio.gather(
            *(probe_role("127.0.0.1", ports[f"p{i}"]) for i in range(2)))
        return all(d and d.get("replicas") for d in docs)

    conns, engines, replayers = [], [], []
    jobs = [f"chaos-{i}" for i in range(40)]
    try:
        await wait_for(replicas_attached, 20.0, "both replicas attached")
        timeouts = Timeouts(dispatch_timeout_s=5.0, running_timeout_s=60.0,
                            scan_interval_s=0.5, pending_replay_s=1.5,
                            result_replay_s=1.5)
        for i in range(2):
            kv, bus, grp = await connect_partitioned(url)
            conns.append(grp)
            eng = _mk_engine(bus, kv, index=i, count=2)
            engines.append(eng)
            await eng.start()
            rep = PendingReplayer(eng, JobStore(kv), timeouts)
            replayers.append(rep)
            await rep.start()
        wkv, wbus, wgrp = await connect_partitioned(url)
        conns.append(wgrp)
        await _attach_worker(wbus)
        js = JobStore(wkv)

        # burst: 15 in, SIGKILL partition 0's primary, 25 more mid-failover
        for jid in jobs[:15]:
            await _gateway_submit(js, wbus, jid)
        procs["p0"].kill()
        for jid in jobs[15:]:
            await _gateway_submit(js, wbus, jid)

        async def all_succeeded():
            for jid in jobs:
                if await js.get_state(jid) != "SUCCEEDED":
                    return False
            return True

        try:
            await wait_for(all_succeeded, 90.0, "all 40 jobs SUCCEEDED")
        except AssertionError:
            states = {jid: await js.get_state(jid) for jid in jobs}
            stuck = {j: s for j, s in states.items() if s != "SUCCEEDED"}
            raise AssertionError(f"jobs stuck after failover: {stuck}")

        # intact event logs: the canonical lifecycle survives the failover
        # in order (at-least-once replays may add extras, never reorder)
        for jid in jobs:
            events = [e["event"] for e in await js.events(jid)]
            assert _is_subsequence(CANONICAL_EVENTS, events), (jid, events)

        # the replica took over partition 0 with a bumped epoch
        doc = await probe_role("127.0.0.1", ports["r0"])
        assert doc["role"] == "primary" and doc["epoch"] >= 1

        # the returning old primary demotes itself and re-syncs: exclusive
        # promotion, no dual-accept
        await procs["p0"].start()
        async def demoted():
            d = await probe_role("127.0.0.1", ports["p0"])
            return d is not None and d.get("role") == "replica"
        await wait_for(demoted, 20.0, "old primary demoted")

        async def caught_up():
            new_p = await probe_role("127.0.0.1", ports["r0"])
            old_p = await probe_role("127.0.0.1", ports["p0"])
            return (new_p and old_p and new_p["epoch"] == old_p["epoch"]
                    and old_p["offset"] >= new_p["offset"])
        await wait_for(caught_up, 20.0, "old primary re-synced")
    finally:
        for rep in replayers:
            await rep.stop()
        for eng in engines:
            await eng.stop()
        for grp in conns:
            await grp.close()
        for p in procs.values():
            p.kill()
