"""Config schema validation: malformed pools/timeouts/safety files fail at
parse with pointed errors; shipped config files validate; taxonomy doc
stays generated (reference ``core/infra/config/validation.go:11`` +
``categories.go:6-160``)."""
import os

import pytest
import yaml

from cordum_tpu.infra.config import (
    load_pool_config, load_timeouts, parse_pool_config, parse_timeouts,
)
from cordum_tpu.infra.configschema import (
    ConfigError, SAFETY_SCHEMA, effective_schema, taxonomy_markdown, validate,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_pool_typo_fails_with_pointed_error(tmp_path):
    p = tmp_path / "pools.yaml"
    p.write_text("pools:\n  tpu:\n    min_chip: 4\n")  # typo: min_chip
    with pytest.raises(ConfigError, match="min_chip"):
        load_pool_config(str(p))
    p.write_text("pools:\n  tpu:\n    topology: not-a-topology\n")
    with pytest.raises(ConfigError, match="topology"):
        load_pool_config(str(p))
    p.write_text("pools:\n  tpu:\n    min_chips: -1\n")
    with pytest.raises(ConfigError):
        load_pool_config(str(p))


def test_timeouts_typo_fails(tmp_path):
    p = tmp_path / "timeouts.yaml"
    p.write_text("reconciler:\n  dispatch_timeout_secs: 10\n")  # typo
    with pytest.raises(ConfigError, match="dispatch_timeout_secs"):
        load_timeouts(str(p))
    p.write_text("reconciler:\n  scan_interval_seconds: fast\n")
    with pytest.raises(ConfigError, match="scan_interval_seconds"):
        load_timeouts(str(p))


def test_safety_policy_validation():
    validate(yaml.safe_load(open(f"{REPO}/config/safety.yaml")), SAFETY_SCHEMA)
    with pytest.raises(ConfigError, match="decision"):
        validate({"rules": [{"decision": "alow"}]}, SAFETY_SCHEMA)  # typo enum
    with pytest.raises(ConfigError, match="topic"):
        validate({"rules": [{"decision": "deny", "match": {"topic": ["x"]}}]},
                 SAFETY_SCHEMA)  # topic vs topics


async def test_kernel_rejects_malformed_policy_at_startup(tmp_path):
    from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel

    p = tmp_path / "safety.yaml"
    p.write_text("rules:\n  - decision: alow\n")
    with pytest.raises(ConfigError):
        await SafetyKernel(policy_path=str(p)).reload()
    # hot reload keeps the previous good policy instead of raising
    p.write_text("rules:\n  - {id: r, decision: deny, match: {topics: ['x.*']}}\n")
    k = SafetyKernel(policy_path=str(p))
    snap = await k.reload()
    p.write_text("rules:\n  - decision: alow\n")
    assert await k.reload() == snap


def test_shipped_configs_validate():
    assert load_pool_config(f"{REPO}/config/pools.yaml").pools["tpu"].requires == ["tpu"]
    assert load_timeouts(f"{REPO}/config/timeouts.yaml").dispatch_timeout_s == 300


def test_effective_schema_and_taxonomy_doc():
    es = effective_schema()
    validate({"rate_limits": {"concurrent_jobs": 8}, "custom_pack_ns": {"x": 1}}, es)
    with pytest.raises(ConfigError, match="concurrent_jobs"):
        validate({"rate_limits": {"concurrent_jobs": "many"}}, es)
    with pytest.raises(ConfigError):
        validate({"rate_limits": {"concurent_jobs": 8}}, es)  # typo field
    # the committed doc is the generated doc (keeps docs/CONFIG.md honest)
    with open(f"{REPO}/docs/CONFIG.md") as f:
        assert f.read() == taxonomy_markdown()
