"""Context engine: window modes, memory updates, semantic RAG ranking,
token budget trimming."""
import numpy as np
import pytest

from cordum_tpu.context.service import (
    ContextService,
    ModelMessage,
    estimate_tokens,
    trim_to_budget,
)
from cordum_tpu.infra.kv import MemoryKV


class FakeEmbedder:
    """Deterministic bag-of-words embedder for tests."""

    VOCAB = ["scheduler", "jobs", "tpu", "cooking", "recipe", "pasta"]

    def embed(self, texts):
        out = np.zeros((len(texts), len(self.VOCAB)), np.float32)
        for i, t in enumerate(texts):
            for j, w in enumerate(self.VOCAB):
                out[i, j] = t.lower().count(w)
            n = np.linalg.norm(out[i]) or 1.0
            out[i] /= n
        return out


async def test_raw_mode(kv):
    svc = ContextService(kv)
    msgs = await svc.build_window("m1", mode="RAW", payload={"q": "hello"})
    assert len(msgs) == 1 and msgs[0].source == "payload"


async def test_chat_mode_history_window(kv):
    svc = ContextService(kv)
    for i in range(30):
        await svc.update_memory("m1", user_payload=f"q{i}", model_response=f"a{i}")
    msgs = await svc.build_window("m1", mode="CHAT", payload="latest")
    history = [m for m in msgs if m.source == "history"]
    assert len(history) == 20  # last-20 window
    assert history[-1].content == "a29"
    assert msgs[-1].content == "latest"


async def test_rag_semantic_ranking(kv):
    svc = ContextService(kv, embedder=FakeEmbedder(), max_chunks=2)
    await svc.put_chunks("m1", [
        {"file_path": "cook.md", "content": "cooking pasta recipe"},
        {"file_path": "sched.md", "content": "the scheduler dispatches jobs to tpu"},
        {"file_path": "other.md", "content": "unrelated things entirely"},
    ])
    msgs = await svc.build_window("m1", mode="RAG", payload="how does the scheduler assign jobs?")
    rag = [m for m in msgs if m.source.startswith("rag:")]
    assert rag and "sched.md" in rag[0].content  # semantic top hit


async def test_rag_embedding_cache_incremental(kv):
    emb = FakeEmbedder()
    calls = []
    orig = emb.embed

    def counting(texts):
        calls.append(len(texts))
        return orig(texts)

    emb.embed = counting
    svc = ContextService(kv, embedder=emb)
    n1 = await svc.put_chunks("m1", [{"file_path": "a", "content": "tpu jobs"}])
    assert n1 == 1
    n2 = await svc.put_chunks("m1", [{"file_path": "a", "content": "tpu jobs"},
                                     {"file_path": "b", "content": "pasta"}])
    assert n2 == 1  # only the new chunk embedded


async def test_rag_summary_fallback(kv):
    svc = ContextService(kv)
    await svc.set_summary("m1", "summary of past events")
    msgs = await svc.build_window("m1", mode="RAG", payload="q")
    assert msgs[0].source == "summary"


async def test_rag_lexical_fallback_without_embedder(kv):
    svc = ContextService(kv)
    await svc.put_chunks("m1", [
        {"file_path": "a.md", "content": "scheduler dispatch logic"},
        {"file_path": "b.md", "content": "zebra giraffe"},
    ])
    msgs = await svc.build_window("m1", mode="RAG", payload="scheduler dispatch details")
    rag = [m for m in msgs if m.source.startswith("rag:")]
    assert len(rag) == 1 and "a.md" in rag[0].content


def test_token_estimate_and_trim():
    assert estimate_tokens("abcd" * 10) == 10
    msgs = [
        ModelMessage(role="system", content="x" * 400, source="history"),
        ModelMessage(role="system", content="y" * 400, source="history"),
        ModelMessage(role="user", content="z" * 40, source="payload"),
    ]
    out = trim_to_budget(msgs, 120)
    # oldest history dropped first; payload survives
    assert [m.source for m in out] == ["history", "payload"]
    # extreme budget truncates the payload itself
    out2 = trim_to_budget(list(msgs), 5)
    assert len(out2) == 1 and len(out2[0].content) <= 20


async def test_update_memory_caps_history(kv):
    svc = ContextService(kv)
    for i in range(600):
        await svc.update_memory("m1", user_payload=f"u{i}")
    from cordum_tpu.context.service import _events_key

    assert await kv.llen(_events_key("m1")) == 500
