"""cordumlint: each rule fires exactly where expected (bad fixture), stays
quiet on the idiomatic fix (good fixture); suppression + baseline mechanics."""
from __future__ import annotations

import json
from pathlib import Path

import pytest

from tools.cordumlint import baseline as baseline_mod
from tools.cordumlint.cli import main as cli_main
from tools.cordumlint.core import lint_paths


def run_lint(tmp_path: Path, name: str, source: str, **kw):
    f = tmp_path / name
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(source)
    result = lint_paths([name], root=tmp_path, **kw)
    return result.findings


def rule_ids(findings):
    return [f.rule_id for f in findings]


# ---------------------------------------------------------------- CL001

CL001_BAD = """\
import time

def expire(ttl_s):
    deadline = time.time() + ttl_s
    return deadline
"""

CL001_GOOD = """\
import time

def expire(ttl_s):
    deadline = time.monotonic() + ttl_s
    return deadline
"""


def test_cl001_fires_on_wall_clock_deadline(tmp_path):
    findings = run_lint(tmp_path, "a.py", CL001_BAD, select={"CL001"})
    assert rule_ids(findings) == ["CL001"]
    assert findings[0].line == 4


def test_cl001_quiet_on_monotonic(tmp_path):
    assert run_lint(tmp_path, "a.py", CL001_GOOD, select={"CL001"}) == []


def test_cl001_quiet_without_deadline_context(tmp_path):
    src = "import time\nstamp = time.time()\n"
    assert run_lint(tmp_path, "a.py", src, select={"CL001"}) == []


def test_cl001_strict_path_needs_no_keyword(tmp_path):
    src = "import time\nx = time.time()\n"
    findings = run_lint(
        tmp_path, "cordum_tpu/infra/locks.py", src, select={"CL001"}
    )
    assert rule_ids(findings) == ["CL001"]


def test_cl001_allows_blessed_clock_module(tmp_path):
    src = "import time\n\ndef now_with_ttl(ttl):\n    return time.time() + ttl\n"
    assert run_lint(tmp_path, "cordum_tpu/utils/ids.py", src, select={"CL001"}) == []


# ---------------------------------------------------------------- CL002

CL002_BAD = """\
def f():
    try:
        risky()
    except Exception:
        pass
"""

CL002_BAD_TUPLE = """\
async def stop(task):
    try:
        await task
    except (CancelledError, Exception):
        pass
"""

CL002_GOOD = """\
import logging

def f():
    try:
        risky()
    except Exception as e:
        logging.getLogger("x").error("risky failed: %s", e)
"""

CL002_GOOD_FALLBACK = """\
def f():
    try:
        return risky()
    except Exception:
        return 0.0, 0.0
"""


def test_cl002_fires_on_silent_pass(tmp_path):
    findings = run_lint(tmp_path, "a.py", CL002_BAD, select={"CL002"})
    assert rule_ids(findings) == ["CL002"]


def test_cl002_fires_on_tuple_with_exception(tmp_path):
    findings = run_lint(tmp_path, "a.py", CL002_BAD_TUPLE, select={"CL002"})
    assert rule_ids(findings) == ["CL002"]


def test_cl002_fires_on_bare_except(tmp_path):
    src = "try:\n    x()\nexcept:\n    pass\n"
    assert rule_ids(run_lint(tmp_path, "a.py", src, select={"CL002"})) == ["CL002"]


def test_cl002_quiet_when_logged_or_fallback(tmp_path):
    assert run_lint(tmp_path, "a.py", CL002_GOOD, select={"CL002"}) == []
    assert run_lint(tmp_path, "b.py", CL002_GOOD_FALLBACK, select={"CL002"}) == []


def test_cl002_quiet_on_narrow_except(tmp_path):
    src = "try:\n    x()\nexcept KeyError:\n    pass\n"
    assert run_lint(tmp_path, "a.py", src, select={"CL002"}) == []


# ---------------------------------------------------------------- CL003

CL003_BAD = """\
import time

async def handler():
    time.sleep(1.0)
"""

CL003_BAD_OPEN = """\
async def load(path):
    with open(path) as f:
        return f.read()
"""

CL003_GOOD = """\
import asyncio

async def handler():
    await asyncio.sleep(1.0)

async def load(path):
    return await asyncio.to_thread(_read, path)

def _read(path):
    with open(path) as f:
        return f.read()
"""


def test_cl003_fires_on_sleep_and_open(tmp_path):
    assert rule_ids(run_lint(tmp_path, "a.py", CL003_BAD, select={"CL003"})) == ["CL003"]
    assert rule_ids(run_lint(tmp_path, "b.py", CL003_BAD_OPEN, select={"CL003"})) == ["CL003"]


def test_cl003_quiet_on_async_idioms(tmp_path):
    assert run_lint(tmp_path, "a.py", CL003_GOOD, select={"CL003"}) == []


def test_cl003_ignores_nested_sync_helper(tmp_path):
    src = """\
async def outer():
    def helper(path):
        with open(path) as f:
            return f.read()
    return helper
"""
    assert run_lint(tmp_path, "a.py", src, select={"CL003"}) == []


# ---------------------------------------------------------------- CL004

CL004_BAD = """\
def resurrect(job):
    job.state = "RUNNING"
"""

CL004_BAD_DICT = """\
def payload(job_id):
    return {"job_id": job_id, "state": "PENDING"}
"""

CL004_GOOD = """\
from cordum_tpu.protocol.types import JobState

def payload(job_id):
    return {"job_id": job_id, "state": JobState.PENDING.value}

async def advance(store, job_id):
    await store.set_state(job_id, JobState.RUNNING)
"""


def test_cl004_fires_on_raw_state_writes(tmp_path):
    assert rule_ids(run_lint(tmp_path, "a.py", CL004_BAD, select={"CL004"})) == ["CL004"]
    assert rule_ids(run_lint(tmp_path, "b.py", CL004_BAD_DICT, select={"CL004"})) == ["CL004"]


def test_cl004_quiet_on_enum_usage(tmp_path):
    assert run_lint(tmp_path, "a.py", CL004_GOOD, select={"CL004"}) == []


def test_cl004_allows_transition_table_home(tmp_path):
    findings = run_lint(
        tmp_path, "cordum_tpu/infra/jobstore.py", CL004_BAD, select={"CL004"}
    )
    assert findings == []


def test_cl004_ignores_non_state_strings(tmp_path):
    src = 'def f(x):\n    x.state = "closed"\n'  # circuit breaker, not a JobState
    assert run_lint(tmp_path, "a.py", src, select={"CL004"}) == []


# ---------------------------------------------------------------- CL005

CL005_BAD = """\
async def tap(bus, handler):
    await bus.subscribe("sys.job.result", handler)
"""

CL005_BAD_FSTRING = """\
def subject_for(worker_id):
    return f"worker.{worker_id}.jobs"
"""

CL005_GOOD = """\
from cordum_tpu.protocol import subjects as subj

async def tap(bus, handler):
    await bus.subscribe(subj.RESULT, handler)

def subject_for(worker_id):
    return subj.direct_subject(worker_id)
"""


def test_cl005_fires_on_subject_literals(tmp_path):
    assert rule_ids(run_lint(tmp_path, "a.py", CL005_BAD, select={"CL005"})) == ["CL005"]
    assert rule_ids(run_lint(tmp_path, "b.py", CL005_BAD_FSTRING, select={"CL005"})) == ["CL005"]


def test_cl005_quiet_on_constants(tmp_path):
    assert run_lint(tmp_path, "a.py", CL005_GOOD, select={"CL005"}) == []


def test_cl005_allows_subjects_module(tmp_path):
    src = 'SUBMIT = "sys.job.submit"\n\ndef direct_subject(w):\n    return f"worker.{w}.jobs"\n'
    assert run_lint(
        tmp_path, "cordum_tpu/protocol/subjects.py", src, select={"CL005"}
    ) == []


# ---------------------------------------------------------------- CL006

CL006_BAD = """\
from jax.experimental.shard_map import shard_map

def build(f, mesh, spec):
    return shard_map(f, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False)
"""

CL006_GOOD = """\
from cordum_tpu.parallel.compat import shard_map_compat

def build(f, mesh, spec):
    return shard_map_compat(f, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False)
"""


def test_cl006_fires_on_gated_kwarg(tmp_path):
    findings = run_lint(tmp_path, "a.py", CL006_BAD, select={"CL006"})
    assert rule_ids(findings) == ["CL006"]
    assert "check_vma" in findings[0].message


def test_cl006_quiet_via_compat_shim(tmp_path):
    assert run_lint(tmp_path, "a.py", CL006_GOOD, select={"CL006"}) == []


def test_cl006_allows_compat_module(tmp_path):
    assert run_lint(
        tmp_path, "cordum_tpu/parallel/compat.py", CL006_BAD, select={"CL006"}
    ) == []


# ---------------------------------------------------------------- CL007

CL007_BAD = """\
import json

def put(kv, rec):
    return json.dumps(rec).encode()

def get(b):
    return json.loads(b)
"""

CL007_GOOD = """\
from cordum_tpu.infra.codec import pack_record, unpack_record

def put(kv, rec):
    return pack_record(rec)

def get(b):
    return unpack_record(b)
"""


def test_cl007_fires_in_hot_path_module(tmp_path):
    findings = run_lint(
        tmp_path, "cordum_tpu/infra/jobstore.py", CL007_BAD, select={"CL007"}
    )
    assert rule_ids(findings) == ["CL007", "CL007"]
    assert "msgpack codec" in findings[0].message


def test_cl007_fires_in_every_declared_hot_module(tmp_path):
    for mod in (
        "cordum_tpu/infra/kv.py",
        "cordum_tpu/infra/statebus.py",
        "cordum_tpu/controlplane/scheduler/engine.py",
    ):
        findings = run_lint(tmp_path, mod, CL007_BAD, select={"CL007"})
        assert rule_ids(findings) == ["CL007", "CL007"], mod


def test_cl007_quiet_on_msgpack_codec(tmp_path):
    assert run_lint(
        tmp_path, "cordum_tpu/infra/jobstore.py", CL007_GOOD, select={"CL007"}
    ) == []


def test_cl007_quiet_outside_hot_paths(tmp_path):
    # codec.py (the legacy-JSON fallback home) and arbitrary modules may
    # use json freely — the rule is scoped to the declared hot modules
    assert run_lint(
        tmp_path, "cordum_tpu/infra/codec.py", CL007_BAD, select={"CL007"}
    ) == []
    assert run_lint(tmp_path, "cordum_tpu/cli.py", CL007_BAD, select={"CL007"}) == []


def test_cl007_suppressible_inline(tmp_path):
    src = (
        "import json\n"
        "def put(rec):\n"
        "    return json.dumps(rec)  "
        "# cordumlint: disable=CL007 -- legacy export path\n"
    )
    assert run_lint(
        tmp_path, "cordum_tpu/infra/jobstore.py", src, select={"CL007"}
    ) == []


# ---------------------------------------------------------------- engine

def test_inline_suppression(tmp_path):
    src = """\
def f():
    try:
        risky()
    except Exception:  # cordumlint: disable=CL002 -- crash loop guard, metrics count it
        pass
"""
    assert run_lint(tmp_path, "a.py", src, select={"CL002"}) == []


def test_inline_suppression_standalone_line(tmp_path):
    src = """\
import time

def lease(ttl):
    # cordumlint: disable=CL001 -- cross-host lease, wall clock is the contract
    return time.time() + ttl
"""
    assert run_lint(tmp_path, "a.py", src, select={"CL001"}) == []


def test_suppression_is_per_rule(tmp_path):
    src = """\
import time

async def f(ttl):
    time.sleep(ttl)  # cordumlint: disable=CL001
"""
    # CL001 disabled but CL003 still fires on the same line
    findings = run_lint(tmp_path, "a.py", src)
    assert rule_ids(findings) == ["CL003"]


def test_rule_disable_via_config(tmp_path):
    config = {"rules": {"CL002": {"enabled": False}}}
    assert run_lint(tmp_path, "a.py", CL002_BAD, config=config) == []


def test_multiple_rules_one_file(tmp_path):
    src = CL001_BAD + "\n" + CL002_BAD
    findings = run_lint(tmp_path, "a.py", src)
    assert sorted(set(rule_ids(findings))) == ["CL001", "CL002"]


# ---------------------------------------------------------------- baseline

def test_baseline_suppresses_grandfathered_only(tmp_path):
    f = tmp_path / "a.py"
    f.write_text(CL002_BAD)
    result = lint_paths(["a.py"], root=tmp_path)
    bl = tmp_path / "baseline.json"
    n = baseline_mod.write(bl, result.findings, "legacy handler, tracked in #42")
    assert n == 1

    # same finding → baselined
    doc = baseline_mod.load(bl)
    marked = baseline_mod.apply(result.findings, doc)
    assert all(fi.baselined for fi in marked)

    # a NEW violation elsewhere is not covered
    f.write_text(CL002_BAD + "\n\n" + CL002_BAD.replace("risky()", "other()"))
    result2 = lint_paths(["a.py"], root=tmp_path)
    marked2 = baseline_mod.apply(result2.findings, doc)
    assert [m.baselined for m in marked2] == [True, False]


def test_baseline_survives_line_shift(tmp_path):
    f = tmp_path / "a.py"
    f.write_text(CL002_BAD)
    result = lint_paths(["a.py"], root=tmp_path)
    bl = tmp_path / "baseline.json"
    baseline_mod.write(bl, result.findings, "grandfathered")
    # unrelated code above shifts the finding down 3 lines
    f.write_text("X = 1\nY = 2\nZ = 3\n" + CL002_BAD)
    shifted = lint_paths(["a.py"], root=tmp_path)
    marked = baseline_mod.apply(shifted.findings, baseline_mod.load(bl))
    assert [m.baselined for m in marked] == [True]


def test_baseline_invalidates_when_line_changes(tmp_path):
    f = tmp_path / "a.py"
    f.write_text(CL002_BAD)
    result = lint_paths(["a.py"], root=tmp_path)
    bl = tmp_path / "baseline.json"
    baseline_mod.write(bl, result.findings, "grandfathered")
    # the offending handler itself changes → must be re-decided
    f.write_text(CL002_BAD.replace("except Exception:", "except (ValueError, Exception):"))
    changed = lint_paths(["a.py"], root=tmp_path)
    marked = baseline_mod.apply(changed.findings, baseline_mod.load(bl))
    assert [m.baselined for m in marked] == [False]


# ---------------------------------------------------------------- CLI

def test_cli_exit_codes_and_json(tmp_path, capsys):
    (tmp_path / "clean.py").write_text("x = 1\n")
    assert cli_main(["clean.py", "--root", str(tmp_path)]) == 0

    (tmp_path / "dirty.py").write_text(CL002_BAD)
    assert cli_main(["dirty.py", "--root", str(tmp_path)]) == 1

    capsys.readouterr()
    rc = cli_main(["dirty.py", "--root", str(tmp_path), "--format", "json"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["summary"] == {"CL002": 1}
    assert doc["findings"][0]["rule_id"] == "CL002"


def test_cli_write_baseline_requires_justification(tmp_path, capsys):
    (tmp_path / "dirty.py").write_text(CL002_BAD)
    assert cli_main(["dirty.py", "--root", str(tmp_path), "--write-baseline"]) == 2

    rc = cli_main([
        "dirty.py", "--root", str(tmp_path), "--write-baseline",
        "--justification", "legacy, tracked",
    ])
    assert rc == 0
    capsys.readouterr()
    # baselined finding no longer fails the gate
    assert cli_main(["dirty.py", "--root", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "(1 baselined)" in out


def test_cli_select_and_list_rules(tmp_path, capsys):
    (tmp_path / "a.py").write_text(CL001_BAD + "\n" + CL002_BAD)
    rc = cli_main(["a.py", "--root", str(tmp_path), "--select", "CL001"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "CL001" in out and "CL002" not in out

    assert cli_main(["--list-rules", "--root", str(tmp_path)]) == 0
    listing = capsys.readouterr().out
    for rid in ("CL001", "CL002", "CL003", "CL004", "CL005", "CL006"):
        assert rid in listing


def test_repo_tree_is_clean():
    """The gate the CI enforces: the shipped tree has zero active findings."""
    repo = Path(__file__).resolve().parents[1]
    rc = cli_main(["cordum_tpu", "bench.py", "--root", str(repo)])
    assert rc == 0
