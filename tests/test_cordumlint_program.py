"""Whole-program cordumlint rules (CL008-CL011): each fires on a bad
multi-file fixture tree, stays quiet on the fixed tree, and verifies —
rather than trusts — its annotations."""
from __future__ import annotations

from pathlib import Path

from tools.cordumlint.cli import main as cli_main
from tools.cordumlint.core import lint_paths


def run_tree(tmp_path: Path, files: dict[str, str], select=None):
    """Write a fixture tree (py sources + docs) and lint the py files."""
    for name, src in files.items():
        f = tmp_path / name
        f.parent.mkdir(parents=True, exist_ok=True)
        f.write_text(src)
    paths = [n for n in files if n.endswith(".py")]
    return lint_paths(paths, root=tmp_path, select=select).findings


def messages(findings):
    return [f.message for f in findings]


# ---------------------------------------------------------------- CL008

CL008_RMW = """\
import asyncio

class Cache:
    def __init__(self):
        self.items = []

    async def add(self, fetch, x):
        cur = self.items
        data = await fetch(x)
        self.items = cur + [data]
"""

CL008_RMW_LOCKED = """\
import asyncio

class Cache:
    def __init__(self):
        self._lock = asyncio.Lock()
        self.items = []

    async def add(self, fetch, x):
        async with self._lock:
            cur = self.items
            data = await fetch(x)
            self.items = cur + [data]
"""

CL008_CHECK_THEN_ACT = """\
import asyncio

class Runner:
    def __init__(self):
        self._task = None

    async def stop(self):
        if self._task is not None:
            self._task.cancel()
            await asyncio.sleep(0)
            self._task = None
"""

CL008_SINGLE_FLIGHT = """\
import asyncio

class Runner:
    def __init__(self):
        self._task = None

    # cordum: single-flight -- one shutdown caller by construction
    async def stop(self):
        if self._task is not None:
            self._task.cancel()
            await asyncio.sleep(0)
            self._task = None
"""

CL008_GUARDED_OK = """\
import asyncio

class Counter:
    def __init__(self):
        self._mu = asyncio.Lock()
        self.n = 0

    # cordum: guarded-by(_mu) -- caller serializes via self._mu
    async def bump(self, fetch):
        cur = self.n
        await fetch()
        self.n = cur + 1
"""

CL008_GUARDED_BOGUS = """\
import asyncio

class Counter:
    def __init__(self):
        self.n = 0

    # cordum: guarded-by(_no_such_lock)
    async def bump(self, fetch):
        cur = self.n
        await fetch()
        self.n = cur + 1
"""


def test_cl008_fires_on_read_modify_write_across_await(tmp_path):
    findings = run_tree(tmp_path, {"a.py": CL008_RMW}, select={"CL008"})
    assert len(findings) == 1
    assert "read-modify-write race: self.items" in findings[0].message
    assert findings[0].line == 10  # the write-back line


def test_cl008_quiet_when_lock_held_across_rmw(tmp_path):
    assert run_tree(tmp_path, {"a.py": CL008_RMW_LOCKED}, select={"CL008"}) == []


def test_cl008_fires_on_check_then_act(tmp_path):
    findings = run_tree(tmp_path, {"a.py": CL008_CHECK_THEN_ACT}, select={"CL008"})
    assert len(findings) == 1
    assert "check-then-act race: self._task" in findings[0].message


def test_cl008_single_flight_annotation_waives(tmp_path):
    assert run_tree(tmp_path, {"a.py": CL008_SINGLE_FLIGHT}, select={"CL008"}) == []


def test_cl008_guarded_by_verified_against_class_locks(tmp_path):
    # a real lock attribute: waived, and the annotation itself is accepted
    assert run_tree(tmp_path, {"a.py": CL008_GUARDED_OK}, select={"CL008"}) == []


def test_cl008_guarded_by_bogus_lock_is_itself_a_finding(tmp_path):
    findings = run_tree(tmp_path, {"a.py": CL008_GUARDED_BOGUS}, select={"CL008"})
    assert len(findings) == 1
    assert "annotation error" in findings[0].message
    assert "_no_such_lock" in findings[0].message


def test_cl008_inline_suppression_still_works(tmp_path):
    src = CL008_RMW.replace(
        "        self.items = cur + [data]",
        "        self.items = cur + [data]  # cordumlint: disable=CL008 -- test",
    )
    assert run_tree(tmp_path, {"a.py": src}, select={"CL008"}) == []


# ---------------------------------------------------------------- CL009

SUBJECTS_PY = """\
SUBMIT = "sys.job.submit"
RESULT = "sys.job.result"
EVENTS = "sys.events"
"""

DOC_OK = """\
# Protocol

## Subjects

| Subject | Delivery | Purpose |
|---|---|---|
| `sys.events` | best-effort | fan-out |
"""

PUB_PY = """\
from proto import subjects as subj

async def run(bus, pkt):
    await bus.publish(subj.EVENTS, pkt)
"""

SUB_PY = """\
from proto import subjects as subj

async def attach(bus, handler):
    await bus.subscribe(subj.EVENTS, handler)
"""


def test_cl009_orphan_publish(tmp_path):
    findings = run_tree(tmp_path, {
        "proto/protocol/subjects.py": SUBJECTS_PY,
        "pub.py": PUB_PY,
        "docs/PROTOCOL.md": DOC_OK,
    }, select={"CL009"})
    assert len(findings) == 1
    assert "orphan publish" in findings[0].message
    assert "sys.events" in findings[0].message
    assert findings[0].path == "pub.py"


def test_cl009_quiet_when_graph_closes(tmp_path):
    assert run_tree(tmp_path, {
        "proto/protocol/subjects.py": SUBJECTS_PY,
        "pub.py": PUB_PY,
        "sub.py": SUB_PY,
        "docs/PROTOCOL.md": DOC_OK,
    }, select={"CL009"}) == []


def test_cl009_external_doc_row_exempts_publish(tmp_path):
    doc = DOC_OK.replace("fan-out", "external dashboards consume this")
    assert run_tree(tmp_path, {
        "proto/protocol/subjects.py": SUBJECTS_PY,
        "pub.py": PUB_PY,
        "docs/PROTOCOL.md": doc,
    }, select={"CL009"}) == []


def test_cl009_orphan_subscription(tmp_path):
    findings = run_tree(tmp_path, {
        "proto/protocol/subjects.py": SUBJECTS_PY,
        "sub.py": SUB_PY,
        "docs/PROTOCOL.md": DOC_OK,
    }, select={"CL009"})
    assert len(findings) == 1
    assert "orphan subscription" in findings[0].message


def test_cl009_doc_drift_missing_row(tmp_path):
    doc = "# Protocol\n\n## Subjects\n\n| Subject | Delivery | Purpose |\n|---|---|---|\n"
    findings = run_tree(tmp_path, {
        "proto/protocol/subjects.py": SUBJECTS_PY,
        "pub.py": PUB_PY,
        "sub.py": SUB_PY,
        "docs/PROTOCOL.md": doc,
    }, select={"CL009"})
    assert len(findings) == 1
    assert "doc drift" in findings[0].message
    assert "no row" in findings[0].message


def test_cl009_durability_drift_against_mirror(tmp_path):
    doc = DOC_OK.replace("best-effort", "durable")
    findings = run_tree(tmp_path, {
        "proto/protocol/subjects.py": SUBJECTS_PY,
        "pub.py": PUB_PY,
        "sub.py": SUB_PY,
        "docs/PROTOCOL.md": doc,
    }, select={"CL009"})
    assert len(findings) == 1
    assert "durability drift" in findings[0].message
    assert findings[0].path == "docs/PROTOCOL.md"


# ---------------------------------------------------------------- CL010

TYPES_PY = """\
from dataclasses import dataclass

@dataclass
class Thing:
    used: str = ""
    dead: str = ""
"""

TYPES_COMPAT_PY = """\
from dataclasses import dataclass

@dataclass
class Thing:
    used: str = ""
    dead: str = ""  # cordum: wire-compat -- legacy peers still decode it
"""

USAGE_PY = """\
from proto.protocol.types import Thing

def read(t):
    return t.used

def make():
    return Thing(used="x")
"""


def test_cl010_dead_field_fires(tmp_path):
    findings = run_tree(tmp_path, {
        "proto/protocol/types.py": TYPES_PY,
        "usage.py": USAGE_PY,
    }, select={"CL010"})
    assert len(findings) == 1
    assert "dead wire field: Thing.dead" in findings[0].message
    assert findings[0].path == "proto/protocol/types.py"


def test_cl010_wire_compat_annotation_exempts(tmp_path):
    assert run_tree(tmp_path, {
        "proto/protocol/types.py": TYPES_COMPAT_PY,
        "usage.py": USAGE_PY,
    }, select={"CL010"}) == []


def test_cl010_never_set_field_fires(tmp_path):
    usage = USAGE_PY + "\ndef read2(t):\n    return t.dead\n"
    # `dead` is now read but still never stored anywhere
    findings = run_tree(tmp_path, {
        "proto/protocol/types.py": TYPES_PY,
        "usage.py": usage,
    }, select={"CL010"})
    assert len(findings) == 1
    assert "never-set wire field: Thing.dead" in findings[0].message


def test_cl010_positional_ctor_counts_as_store(tmp_path):
    usage = """\
from proto.protocol.types import Thing

def read(t):
    return (t.used, t.dead)

def make():
    return Thing("x", "y")
"""
    assert run_tree(tmp_path, {
        "proto/protocol/types.py": TYPES_PY,
        "usage.py": usage,
    }, select={"CL010"}) == []


def test_cl010_record_key_drift(tmp_path):
    src = """\
from codec import pack_record, unpack_record

def write(stream):
    stream.append(pack_record({"offset": 1, "op": "set"}))

def read(blob):
    rec = unpack_record(blob)
    return rec["epoch"]
"""
    findings = run_tree(tmp_path, {"repl.py": src}, select={"CL010"})
    assert len(findings) == 1
    assert "record-key drift" in findings[0].message
    assert "'epoch'" in findings[0].message


def test_cl010_opaque_pack_disables_record_check(tmp_path):
    src = """\
from codec import pack_record, unpack_record

def write(stream, payload):
    stream.append(pack_record(payload))

def read(blob):
    rec = unpack_record(blob)
    return rec["epoch"]
"""
    assert run_tree(tmp_path, {"repl.py": src}, select={"CL010"}) == []


# ---------------------------------------------------------------- CL011

METRICS_DRIFT_PY = """\
from metrics import Counter

jobs = Counter("cordum_jobs_total", "jobs processed")

def f():
    jobs.inc(tenant="a")

def g():
    jobs.inc(pool="b")
"""

METRICS_OK_PY = """\
from metrics import Counter

jobs = Counter("cordum_jobs_total", "jobs processed")

def f():
    jobs.inc(tenant="a")

def g():
    jobs.inc(tenant="b")
"""

OBS_DOC = "# Observability\n\n`cordum_jobs_total` counts jobs.\n"


def test_cl011_label_schema_drift(tmp_path):
    findings = run_tree(tmp_path, {
        "m.py": METRICS_DRIFT_PY,
        "docs/OBSERVABILITY.md": OBS_DOC,
    }, select={"CL011"})
    assert len(findings) == 1
    assert "label-schema drift: cordum_jobs_total" in findings[0].message


def test_cl011_quiet_on_consistent_schema(tmp_path):
    assert run_tree(tmp_path, {
        "m.py": METRICS_OK_PY,
        "docs/OBSERVABILITY.md": OBS_DOC,
    }, select={"CL011"}) == []


def test_cl011_undocumented_metric(tmp_path):
    findings = run_tree(tmp_path, {
        "m.py": METRICS_OK_PY,
        "docs/OBSERVABILITY.md": "# Observability\n\nnothing here\n",
    }, select={"CL011"})
    assert len(findings) == 1
    assert "undocumented metric: cordum_jobs_total" in findings[0].message


def test_cl011_inventory_label_drift(tmp_path):
    doc = (
        "# Observability\n\n"
        "<!-- cordumlint: metrics-inventory begin -->\n"
        "| Metric | Type | Labels | Help |\n"
        "|---|---|---|---|\n"
        "| `cordum_jobs_total` | counter | pool | jobs processed |\n"
        "<!-- cordumlint: metrics-inventory end -->\n"
    )
    findings = run_tree(tmp_path, {
        "m.py": METRICS_OK_PY,
        "docs/OBSERVABILITY.md": doc,
    }, select={"CL011"})
    assert len(findings) == 1
    assert "inventory drift" in findings[0].message
    assert "tenant" in findings[0].message


def test_cl011_stale_inventory_row(tmp_path):
    doc = (
        "# Observability\n\n`cordum_jobs_total` counts jobs.\n\n"
        "<!-- cordumlint: metrics-inventory begin -->\n"
        "| Metric | Type | Labels | Help |\n"
        "|---|---|---|---|\n"
        "| `cordum_jobs_total` | counter | tenant | jobs processed |\n"
        "| `cordum_gone_total` | counter | — | removed long ago |\n"
        "<!-- cordumlint: metrics-inventory end -->\n"
    )
    findings = run_tree(tmp_path, {
        "m.py": METRICS_OK_PY,
        "docs/OBSERVABILITY.md": doc,
    }, select={"CL011"})
    assert len(findings) == 1
    assert "no longer defines" in findings[0].message
    assert "cordum_gone_total" in findings[0].message


# ------------------------------------------------------- CLI integration

def test_cli_exits_one_on_injected_violation(tmp_path):
    (tmp_path / "bad.py").write_text(CL008_RMW)
    rc = cli_main(["bad.py", "--root", str(tmp_path), "--no-baseline"])
    assert rc == 1


def test_cli_exits_zero_on_clean_fixture(tmp_path):
    (tmp_path / "ok.py").write_text(CL008_RMW_LOCKED)
    rc = cli_main(["ok.py", "--root", str(tmp_path), "--no-baseline"])
    assert rc == 0
