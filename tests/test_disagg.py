"""Disaggregated prefill/decode serving (ISSUE 14, docs/SERVING.md
§Disaggregation): role-aware placement (ServingPlacer + strategy
integration + affinity retargeting), the post-prefill page hand-off
(engine hook, worker peer ranking, token-exactness of policy-triggered
migrations including mid-prefill threshold moves, jittered next-best
retry, failure-reason accounting), and the decode rebalancer (skew/
hysteresis/cooldown planning, worker-side cheapest-session moves, the
anti-ping-pong immunity window, cancel-after-hand-off ownership)."""
import asyncio
import random

import pytest

from cordum_tpu.controlplane.scheduler.placer import (
    DecodeRebalancer,
    ServingPlacer,
)
from cordum_tpu.controlplane.scheduler.strategy import ThroughputAwareStrategy
from cordum_tpu.infra.bus import LoopbackBus
from cordum_tpu.infra.config import parse_pool_config
from cordum_tpu.infra.kv import MemoryKV
from cordum_tpu.infra.memstore import MemoryStore
from cordum_tpu.infra.metrics import Metrics
from cordum_tpu.infra.registry import WorkerRegistry
from cordum_tpu.protocol import subjects as subj
from cordum_tpu.protocol.types import (
    BusPacket,
    Heartbeat,
    JobCancel,
    JobRequest,
    LABEL_BATCH_KEY,
    LABEL_MIGRATE_ADDR,
    LABEL_OP,
    LABEL_SESSION_KEY,
    SessionRebalance,
)
from cordum_tpu.serving.engine import GenRequest, ServingEngine
from cordum_tpu.serving.migration import MigrationServer, migrate_session

from .test_serving import FakeBackend, fake_ref, run_blocking
from .test_serving_failover import (
    MigFakeBackend,
    install_into,
    make_serving_worker,
    wait_until,
)


# ---------------------------------------------------------------------------
# a scripted CapacityView stand-in (the placer/rebalancer read interface)
# ---------------------------------------------------------------------------


class StubView:
    def __init__(self):
        self.rates: dict[tuple, float] = {}  # (wid, op) -> tokens/s
        self.kv: dict[str, dict] = {}
        self.occ: dict[str, dict] = {}
        self.roles: dict[str, str] = {}
        self.drain: dict[str, bool] = {}

    def token_rate(self, wid, op):
        return self.rates.get((wid, op), 0.0)

    def rate(self, wid, op):
        return self.rates.get((wid, op), 0.0)

    def kv_pages(self, wid):
        return dict(self.kv.get(wid, {}))

    def decode_occupancy(self, wid):
        return dict(self.occ.get(wid, {}))

    def serving_role(self, wid):
        return self.roles.get(wid, "")

    def draining(self, wid):
        return self.drain.get(wid, False)

    def serving_workers(self):
        return [w for w in self.kv if self.kv[w]]


def hb(wid, **kw):
    kw.setdefault("pool", "tpu")
    kw.setdefault("max_parallel_jobs", 1 << 30)
    return Heartbeat(worker_id=wid, **kw)


# ---------------------------------------------------------------------------
# ServingPlacer
# ---------------------------------------------------------------------------


def test_placer_routes_by_prefill_rate_and_excludes_decode_role():
    """New sessions go to prefill-capable workers in proportion to
    measured prefill tokens/s × page headroom; decode-roled workers are
    excluded while any prefill-capable worker exists."""
    view = StubView()
    view.rates[("w-pre", "llm.prefill")] = 300.0
    view.rates[("w-mix", "llm.prefill")] = 100.0
    view.rates[("w-dec", "llm.prefill")] = 900.0  # fastest — but decode-roled
    view.roles.update({"w-pre": "prefill", "w-mix": "mixed",
                       "w-dec": "decode"})
    for w in ("w-pre", "w-mix", "w-dec"):
        view.kv[w] = {"pages_total": 100, "pages_free": 100}
    placer = ServingPlacer(view)
    cands = [hb("w-pre"), hb("w-mix"), hb("w-dec")]
    picks = {w: 0 for w in ("w-pre", "w-mix", "w-dec")}
    for _ in range(120):
        picks[placer.pick(cands)] += 1
    assert picks["w-dec"] == 0
    assert picks["w-pre"] + picks["w-mix"] == 120
    # smooth WRR converges to the 3:1 rate ratio
    assert picks["w-pre"] >= 2 * picks["w-mix"] > 0


def test_placer_headroom_scales_weight_and_full_arena_excluded():
    view = StubView()
    view.rates[("w-a", "llm.prefill")] = 100.0
    view.rates[("w-b", "llm.prefill")] = 100.0
    view.kv["w-a"] = {"pages_total": 100, "pages_free": 90}
    view.kv["w-b"] = {"pages_total": 100, "pages_free": 10}
    placer = ServingPlacer(view)
    cands = [hb("w-a"), hb("w-b")]
    picks = {"w-a": 0, "w-b": 0}
    for _ in range(100):
        picks[placer.pick(cands)] += 1
    assert picks["w-a"] >= 5 * picks["w-b"] > 0  # 9:1 headroom skew
    # a full arena gets nothing
    view.kv["w-b"]["pages_free"] = 0
    placer2 = ServingPlacer(view)
    assert all(placer2.pick(cands) == "w-a" for _ in range(10))


def test_placer_degrades_without_measurement_or_candidates():
    view = StubView()
    placer = ServingPlacer(view)
    assert placer.pick([hb("w-a")]) == ""  # nothing measured anywhere
    assert placer.fallbacks == 1
    view.drain["w-a"] = True
    view.rates[("w-a", "llm.prefill")] = 100.0
    assert placer.pick([hb("w-a")]) == ""  # only candidate is draining


# ---------------------------------------------------------------------------
# strategy integration + affinity retargeting
# ---------------------------------------------------------------------------


def _mk_strategy(view):
    reg = WorkerRegistry()
    pc = parse_pool_config({"topics": {"job.tpu.generate": "tpu"},
                            "pools": {"tpu": {}}})
    strat = ThroughputAwareStrategy(reg, pc, capacity=view,
                                    placer=ServingPlacer(view), native=False)
    return strat, reg


def test_strategy_serving_jobs_route_via_placer_then_stick():
    view = StubView()
    view.rates[("w-pre", "llm.prefill")] = 500.0
    view.rates[("w-dec", "llm.prefill")] = 500.0
    view.roles.update({"w-pre": "prefill", "w-dec": "decode"})
    view.kv["w-pre"] = {"pages_total": 100, "pages_free": 100}
    view.kv["w-dec"] = {"pages_total": 100, "pages_free": 100}
    strat, reg = _mk_strategy(view)
    reg.update(hb("w-pre"))
    reg.update(hb("w-dec"))
    req = JobRequest(job_id="j1", topic="job.tpu.generate",
                     labels={LABEL_OP: "llm.generate",
                             LABEL_SESSION_KEY: "conv-1"})
    assert strat.pick_subject(req) == "worker.w-pre.jobs"
    assert strat.routed_placed == 1
    # the follow-up turn rides session affinity, not a fresh placement
    req2 = JobRequest(job_id="j2", topic="job.tpu.generate",
                      labels={LABEL_OP: "llm.generate",
                              LABEL_SESSION_KEY: "conv-1"})
    assert strat.pick_subject(req2) == "worker.w-pre.jobs"
    assert strat.session_affinity_hits == 1 and strat.routed_placed == 1


def test_strategy_placer_fallback_is_generic_routing():
    """An empty prefill matrix must not break serving jobs: the placer
    returns "" and the generic measured-items/s (→ LeastLoaded) path
    routes as before."""
    view = StubView()
    strat, reg = _mk_strategy(view)
    reg.update(hb("w-a"))
    req = JobRequest(job_id="j1", topic="job.tpu.generate",
                     labels={LABEL_OP: "llm.generate"})
    assert strat.pick_subject(req) == "worker.w-a.jobs"
    assert strat.routed_placed == 0


def test_retarget_session_follows_ownership():
    """A SessionMoved announcement repoints the session's affinity: the
    next turn routes to the adopting worker, not the original placement."""
    view = StubView()
    view.rates[("w-pre", "llm.prefill")] = 500.0
    view.roles["w-pre"] = "prefill"
    view.roles["w-dec"] = "decode"  # excluded from new-session placement
    view.kv["w-pre"] = {"pages_total": 100, "pages_free": 100}
    strat, reg = _mk_strategy(view)
    reg.update(hb("w-pre"))
    reg.update(hb("w-dec"))
    first = strat.pick_subject(JobRequest(
        job_id="j1", topic="job.tpu.generate",
        labels={LABEL_OP: "llm.generate", LABEL_SESSION_KEY: "conv-9"}))
    assert first == "worker.w-pre.jobs"
    strat.retarget_session("conv-9", "w-dec")
    assert strat.session_affinity_retargeted == 1
    nxt = strat.pick_subject(JobRequest(
        job_id="j2", topic="job.tpu.generate",
        labels={LABEL_OP: "llm.generate", LABEL_SESSION_KEY: "conv-9"}))
    assert nxt == "worker.w-dec.jobs"


def test_batch_sticky_win_still_elects_session_affinity():
    """A session-carrying job routed by its batch key (a workflow turn
    riding wf-tpl template co-location, docs/SERVING.md §Prefix cache and
    tiering) must still record its session entry: the batch-sticky early
    return used to skip the election, so every later turn of the run
    counted "new" and could never hit."""
    view = StubView()
    strat, reg = _mk_strategy(view)
    reg.update(hb("w-a"))
    reg.update(hb("w-b"))
    # establish the template's batch entry (turn 1 of some sibling run)
    first = strat.pick_subject(JobRequest(
        job_id="r1:plan@1", topic="job.tpu.generate",
        labels={LABEL_OP: "llm.generate", LABEL_BATCH_KEY: "wf-tpl:agent"}))
    # a session whose affinity entry is absent rides the batch key ...
    second = strat.pick_subject(JobRequest(
        job_id="r2:plan@1", topic="job.tpu.generate",
        labels={LABEL_OP: "llm.generate", LABEL_BATCH_KEY: "wf-tpl:agent",
                LABEL_SESSION_KEY: "run-7"}))
    assert second == first
    # ... and that ride must have elected the session entry: the follow-up
    # turn (no batch key — e.g. a direct cancel/turn on the session) hits
    third = strat.pick_subject(JobRequest(
        job_id="r2:act@1", topic="job.tpu.generate",
        labels={LABEL_OP: "llm.generate", LABEL_SESSION_KEY: "run-7"}))
    assert third == first
    assert strat.session_affinity_hits == 1, (
        strat.session_affinity_hits, strat.session_affinity_new)


# ---------------------------------------------------------------------------
# engine: hand-off hook + rebalance picking
# ---------------------------------------------------------------------------


async def test_handoff_hook_fires_once_on_prefill_completion():
    be = FakeBackend(num_pages=32, step_delay=0.002)
    eng = ServingEngine(be, run_blocking=run_blocking, max_new_tokens_cap=64)
    fired = []
    eng.on_prefill_done = fired.append
    out = await eng.submit(GenRequest(prompt=[1, 2, 3], max_new_tokens=10,
                                      stream=False), job_id="h1")
    assert out["tokens"] == fake_ref([1, 2, 3], 10)
    assert fired == ["h1"]  # once, not per step
    await eng.stop()


async def test_handoff_hook_threshold_fires_mid_prefill():
    """serving_handoff_tokens > 0: the hook fires while the prompt is
    still prefilling, so long prompts start moving before ingestion
    finishes."""
    be = FakeBackend(num_pages=64, max_context=512, step_delay=0.002,
                     max_batch_tokens=8)
    eng = ServingEngine(be, run_blocking=run_blocking, max_new_tokens_cap=64,
                        handoff_threshold_tokens=8)
    state_at_fire = {}

    def hook(job_id):
        state_at_fire[job_id] = dict(eng.export_state(job_id))

    eng.on_prefill_done = hook
    prompt = list(range(1, 31))  # 30 tokens, chunked at <=8/step
    out = await eng.submit(GenRequest(prompt=prompt, max_new_tokens=5,
                                      stream=False), job_id="t1")
    assert out["tokens"] == fake_ref(prompt, 5)
    assert "t1" in state_at_fire
    assert 8 <= state_at_fire["t1"]["prefill_pos"] < len(prompt)
    await eng.stop()


async def test_policy_handoff_token_exact_property():
    """Acceptance: policy-triggered migrations are token-exact — the
    engine hook (completion AND mid-prefill threshold variants, random
    prompts) drives migrate_session to a peer and the relocated stream
    equals the sequential oracle."""
    rng = random.Random(23)
    for trial in range(4):
        threshold = rng.choice([0, 4, 9])
        a = ServingEngine(
            MigFakeBackend(num_pages=64, max_context=512, step_delay=0.002,
                           max_batch_tokens=8),
            run_blocking=run_blocking, max_new_tokens_cap=600,
            handoff_threshold_tokens=threshold)
        b = ServingEngine(MigFakeBackend(num_pages=64, max_context=512,
                                         step_delay=0.002),
                          run_blocking=run_blocking, max_new_tokens_cap=600)
        results: dict = {}
        srv = MigrationServer(install_into(b, results))
        await srv.start()
        moves: list = []

        def hook(job_id):
            moves.append(asyncio.ensure_future(
                migrate_session(a, job_id, srv.host, srv.port)))

        a.on_prefill_done = hook
        plen = rng.randint(1, 24)
        prompt = [rng.randrange(1, 200) for _ in range(plen)]
        n_new = rng.randint(2, 40)
        jid = f"ph{trial}"
        src = asyncio.ensure_future(a.submit(
            GenRequest(prompt=prompt, max_new_tokens=n_new, stream=False),
            job_id=jid))
        await wait_until(lambda: moves, msg="hand-off fired")
        moved = await moves[0]
        if moved:
            with pytest.raises(Exception):
                await asyncio.wait_for(src, timeout=10)
            await wait_until(lambda: jid in results, msg="target finished")
            got = results[jid]
            assert b.stats.migrated_in == 1
        else:  # racy finish before freeze: local completion is also exact
            got = (await asyncio.wait_for(src, timeout=10))["tokens"]
        assert got == fake_ref(prompt, n_new), (trial, threshold, prompt)
        await a.stop(), await b.stop(), await srv.stop()


async def test_mid_prefill_handoff_matches_oracle_real_backend():
    """The fp32 oracle check for a threshold hand-off that fires while the
    prompt is mid-prefill on the REAL paged backend: partially filled
    pages + prefill progress move worker→worker and the finished stream is
    token-identical to the uninterrupted run."""
    import jax
    import jax.numpy as jnp

    from cordum_tpu.models import llama
    from cordum_tpu.serving.backend import LlamaServingBackend

    from .test_serving import ref_greedy

    cfg = llama.LlamaConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                            n_kv_heads=2, d_ff=128, max_seq_len=128,
                            dtype=jnp.float32)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    bea = LlamaServingBackend(cfg, num_pages=64, page_size=8,
                              max_seqs=4, max_batch_tokens=12,
                              params_provider=lambda: params)
    beb = LlamaServingBackend(cfg, num_pages=64, page_size=8,
                              params_provider=lambda: params)
    a = ServingEngine(bea, run_blocking=run_blocking, max_new_tokens_cap=64,
                      handoff_threshold_tokens=9)
    b = ServingEngine(beb, run_blocking=run_blocking, max_new_tokens_cap=64)
    results: dict = {}
    srv = MigrationServer(install_into(b, results))
    await srv.start()
    fired = asyncio.Event()
    prefill_pos_at_fire = []

    def hook(job_id):
        prefill_pos_at_fire.append(a.export_state(job_id)["prefill_pos"])
        fired.set()
        asyncio.ensure_future(migrate_session(a, job_id, srv.host, srv.port))

    a.on_prefill_done = hook
    prompt = [7, 3, 11, 19, 2, 5, 23, 1, 13, 40, 9, 4, 17, 31, 2, 8, 5, 90,
              33, 12]  # 20 tokens: several chunks at <=12/step
    src = asyncio.ensure_future(a.submit(
        GenRequest(prompt=prompt, max_new_tokens=12, stream=False),
        job_id="mp1"))
    await asyncio.wait_for(fired.wait(), timeout=120)
    assert prefill_pos_at_fire[0] < len(prompt)  # genuinely mid-prefill
    try:
        out = (await asyncio.wait_for(src, timeout=120))["tokens"]
    except Exception:  # SessionMigrated: the target owns the result
        await wait_until(lambda: "mp1" in results, timeout_s=120,
                         msg="target finished")
        out = results["mp1"]
        assert b.stats.migrated_in == 1
    assert out == ref_greedy(cfg, params, prompt, 12)
    await a.stop(), await b.stop(), await srv.stop()


async def test_pick_rebalance_sessions_cheapest_and_immunity():
    """Cheapest = fewest live pages then oldest decode position; a
    migrated-in session is immune until its cooldown passes; drain's
    session_ids ignores immunity."""
    be = MigFakeBackend(num_pages=64, max_context=512, step_delay=0.01)
    eng = ServingEngine(be, run_blocking=run_blocking, max_new_tokens_cap=600,
                        migrate_in_cooldown_s=0.3)
    waiters = []
    for i, plen in enumerate((14, 2, 8)):  # page footprints 9,6,7 (ps=4)
        waiters.append(asyncio.ensure_future(eng.submit(
            GenRequest(prompt=list(range(1, plen + 1)), max_new_tokens=20,
                       stream=False), job_id=f"s{i}")))
    await wait_until(
        lambda: all((eng.export_state(f"s{i}") or {}).get("pos", 0)
                    > 0 for i in range(3)),
        msg="all sessions decoding")
    order = eng.pick_rebalance_sessions(3)
    assert order[0] == "s1" and set(order) == {"s0", "s1", "s2"}
    # adopt a migrated-in session: immune, so not pickable yet
    fut = await eng.install_session(
        GenRequest(prompt=[5], max_new_tokens=60, stream=False),
        job_id="adopted",
        state={"pos": 1, "prefill_pos": 1, "out_tokens": [9],
               "last_token": 9},
        records=[])
    assert "adopted" not in eng.pick_rebalance_sessions(4)
    assert "adopted" in eng.session_ids()  # drain still moves it
    await asyncio.sleep(0.35)  # cooldown passes → movable again
    assert "adopted" in eng.pick_rebalance_sessions(4)
    for w in waiters:
        w.cancel()
    fut.cancel()
    await eng.stop()


# ---------------------------------------------------------------------------
# DecodeRebalancer planning
# ---------------------------------------------------------------------------


def _mk_rebalancer(view, reg, **kw):
    kw.setdefault("hysteresis_ticks", 2)
    kw.setdefault("cooldown_s", 30.0)
    clock = [0.0]
    rb = DecodeRebalancer(None, view, reg, clock=lambda: clock[0], **kw)
    return rb, clock


def _serving_fleet_view(hot_sessions=8, hot_in_use=90):
    view = StubView()
    view.kv["w-hot"] = {"pages_total": 100,
                        "pages_free": 100 - hot_in_use,
                        "pages_in_use": hot_in_use}
    view.occ["w-hot"] = {"active_sessions": hot_sessions}
    view.kv["w-cold"] = {"pages_total": 100, "pages_free": 90,
                         "pages_in_use": 10}
    view.occ["w-cold"] = {"active_sessions": 2}
    view.rates[("w-cold", "llm.generate")] = 100.0
    return view


def test_rebalancer_skew_needs_hysteresis_then_cooldown_limits():
    view = _serving_fleet_view()
    reg = WorkerRegistry()
    reg.update(hb("w-hot", labels={LABEL_MIGRATE_ADDR: "127.0.0.1:1"}))
    reg.update(hb("w-cold", labels={LABEL_MIGRATE_ADDR: "127.0.0.1:2"}))
    rb, clock = _mk_rebalancer(view, reg, max_moves=2)
    assert rb.plan() == []  # tick 1: hot, but hysteresis holds fire
    cmds = rb.plan()  # tick 2: consecutive → command
    assert len(cmds) == 1
    cmd = cmds[0]
    assert cmd.worker_id == "w-hot" and cmd.target_worker == "w-cold"
    assert cmd.target_addr == "127.0.0.1:2"
    assert 1 <= cmd.max_sessions <= 2
    # still hot: the per-worker cooldown rate-limits further commands
    assert rb.plan() == [] and rb.plan() == []
    clock[0] += 31.0
    # continuously hot through the cooldown: fires again on expiry
    assert len(rb.plan()) == 1


def test_rebalancer_ignores_balanced_draining_and_single_worker():
    # 3 vs 2 sessions and 12 vs 10 pages in use: within skew ratio
    view = _serving_fleet_view(hot_sessions=3, hot_in_use=12)
    reg = WorkerRegistry()
    reg.update(hb("w-hot", labels={LABEL_MIGRATE_ADDR: "127.0.0.1:1"}))
    reg.update(hb("w-cold", labels={LABEL_MIGRATE_ADDR: "127.0.0.1:2"}))
    rb, _ = _mk_rebalancer(view, reg, skew_ratio=2.0)
    assert rb.plan() == [] and rb.plan() == []
    # a draining target never receives moves; with it gone there is only
    # one worker left → no plan either
    view.occ["w-hot"]["active_sessions"] = 8
    view.drain["w-cold"] = True
    assert rb.plan() == [] and rb.plan() == []


def test_rebalancer_page_pressure_alone_can_mark_hot():
    view = StubView()
    view.kv["w-hot"] = {"pages_total": 100, "pages_free": 5,
                        "pages_in_use": 95}
    view.occ["w-hot"] = {"active_sessions": 3}
    view.kv["w-cold"] = {"pages_total": 100, "pages_free": 80,
                         "pages_in_use": 20}
    view.occ["w-cold"] = {"active_sessions": 3}  # occupancy balanced
    reg = WorkerRegistry()
    reg.update(hb("w-hot", labels={LABEL_MIGRATE_ADDR: "127.0.0.1:1"}))
    reg.update(hb("w-cold", labels={LABEL_MIGRATE_ADDR: "127.0.0.1:2"}))
    rb, _ = _mk_rebalancer(view, reg)
    rb.plan()
    cmds = rb.plan()
    assert len(cmds) == 1 and "pressure" in cmds[0].reason


# ---------------------------------------------------------------------------
# worker e2e: hand-off, rebalance command, ping-pong immunity, cancel
# ---------------------------------------------------------------------------


def make_role_worker(bus, ms, wid, role, *, step_delay=0.01, metrics=None,
                     **eng_kw):
    w = make_serving_worker(bus, ms, wid, step_delay=step_delay,
                            metrics=metrics, **eng_kw)
    w.serving_role = role
    if role == "prefill":
        w.serving.on_prefill_done = w._on_prefill_done
    return w


async def submit_gen(bus, ms, wid, jid, prompt, n_new, *, session=None):
    ptr = await ms.put_context(jid, {
        "op": "llm.generate", "tokens": prompt, "max_new_tokens": n_new,
        "session_id": session or f"conv-{jid}",
    })
    await bus.publish(subj.direct_subject(wid), BusPacket.wrap(JobRequest(
        job_id=jid, topic="job.tpu.generate", context_ptr=ptr)))


class ResultTap:
    def __init__(self):
        self.results: dict[str, object] = {}

    async def __call__(self, subject, pkt):
        res = pkt.job_result
        if res is not None and res.status in ("SUCCEEDED", "CANCELLED",
                                              "FAILED"):
            self.results[res.job_id] = res


async def test_prefill_worker_hands_off_to_decode_peer_e2e():
    """The tentpole path end to end: a session submitted to a
    prefill-roled worker prefills there, live-migrates to the decode peer
    once the prompt completes, finishes token-exact from the NEW owner,
    and the adopting worker announces ownership (SessionMoved)."""
    bus = LoopbackBus()
    ms = MemoryStore(MemoryKV())
    metrics = Metrics()
    w1 = make_role_worker(bus, ms, "w-pre", "prefill", metrics=metrics)
    w2 = make_role_worker(bus, ms, "w-dec", "decode", metrics=metrics)
    await w1.start()
    await w2.start()
    moved = []

    async def tap_moved(subject, pkt):
        if pkt.session_moved is not None:
            moved.append(pkt.session_moved)

    await bus.subscribe(subj.SERVING_MOVED, tap_moved)
    tap = ResultTap()
    await bus.subscribe(subj.RESULT, tap)
    await w1.send_heartbeat()
    await w2.send_heartbeat()
    await bus.drain()
    assert "w-dec" in w1._peers and w1._peers["w-dec"]["role"] == "decode"
    prompt = [4, 9, 2]
    await submit_gen(bus, ms, "w-pre", "ho1", prompt, 40, session="conv-ho")
    await wait_until(lambda: "ho1" in tap.results, msg="job finished")
    res = tap.results["ho1"]
    assert res.status == "SUCCEEDED" and res.worker_id == "w-dec"
    assert (await ms.get_result("ho1"))["tokens"] == fake_ref(prompt, 40)
    assert w1.serving.stats.migrated_out == 1
    assert w2.serving.stats.migrated_in == 1
    assert metrics.serving_handoffs.total() >= 1
    assert moved and moved[0].to_worker == "w-dec"
    assert moved[0].session_key == "conv-ho"
    assert moved[0].reason == "handoff"
    # both arenas end clean
    await wait_until(lambda: w2.serving.allocator.used_pages == 0,
                     msg="target freed")
    assert w1.serving.allocator.used_pages == 0
    await w1.stop(), await w2.stop(), await bus.close()


async def test_cancel_after_handoff_reaches_new_owner():
    """Acceptance: session affinity follows ownership — a cancel issued
    after the hand-off lands on the adopting worker, which retires the
    session (pages freed) and publishes the CANCELLED result."""
    bus = LoopbackBus()
    ms = MemoryStore(MemoryKV())
    w1 = make_role_worker(bus, ms, "w-pre", "prefill", step_delay=0.02)
    w2 = make_role_worker(bus, ms, "w-dec", "decode", step_delay=0.02)
    await w1.start()
    await w2.start()
    tap = ResultTap()
    await bus.subscribe(subj.RESULT, tap)
    await w1.send_heartbeat()
    await w2.send_heartbeat()
    await bus.drain()
    await submit_gen(bus, ms, "w-pre", "ca1", [3, 1, 4], 100,
                     session="conv-ca")
    await wait_until(lambda: w2.serving.stats.migrated_in == 1,
                     msg="hand-off committed")
    await bus.publish(subj.CANCEL, BusPacket.wrap(JobCancel(job_id="ca1")))
    await wait_until(lambda: "ca1" in tap.results, msg="cancel published")
    res = tap.results["ca1"]
    assert res.status == "CANCELLED" and res.worker_id == "w-dec"
    assert w2.serving.stats.cancelled == 1
    await wait_until(lambda: w2.serving.allocator.used_pages == 0,
                     msg="pages freed on new owner")
    await w1.stop(), await w2.stop(), await bus.close()


async def test_rebalance_command_moves_cheapest_then_immunity_blocks_pingpong():
    """Acceptance: the governor's move lands the cheapest session on the
    target, where it is cooldown-immune — an immediate reverse command
    (oscillating skew) moves NOTHING back."""
    bus = LoopbackBus()
    ms = MemoryStore(MemoryKV())
    metrics = Metrics()
    w1 = make_role_worker(bus, ms, "w-a", "decode", step_delay=0.02,
                          metrics=metrics)
    w2 = make_role_worker(bus, ms, "w-b", "decode", step_delay=0.02,
                          metrics=metrics)
    await w1.start()
    await w2.start()
    await w1.send_heartbeat()
    await w2.send_heartbeat()
    await bus.drain()
    for i, plen in enumerate((9, 2)):  # rb1 is the cheaper session
        await submit_gen(bus, ms, "w-a", f"rb{i}",
                         list(range(1, plen + 1)), 80)
    await wait_until(lambda: w1.serving.active_sessions() == 2,
                     msg="sessions on w-a")
    await wait_until(
        lambda: all((w1.serving.export_state(f"rb{i}") or {}).get("pos", 0)
                    > 0 for i in range(2)),
        msg="decoding")
    await bus.publish(subj.SERVING_REBALANCE, BusPacket.wrap(
        SessionRebalance(worker_id="w-a", target_worker="w-b",
                         target_addr=w2._migration.addr, max_sessions=1)))
    await wait_until(lambda: w2.serving.stats.migrated_in == 1,
                     msg="rebalance move landed")
    assert w2.serving.describe_session("rb1") is not None  # the cheap one
    moved_before = w1.serving.stats.migrated_in
    # oscillation: the governor immediately asks w-b to shed — the
    # migrated-in session is immune, so nothing moves back
    await bus.publish(subj.SERVING_REBALANCE, BusPacket.wrap(
        SessionRebalance(worker_id="w-b", target_worker="w-a",
                         target_addr=w1._migration.addr, max_sessions=1)))
    await bus.drain()
    await asyncio.sleep(0.1)
    assert w1.serving.stats.migrated_in == moved_before  # no ping-pong
    assert metrics.serving_rebalances.value(stage="no_sessions") >= 1
    assert metrics.serving_rebalances.value(stage="moved") >= 1
    await w1.stop(), await w2.stop(), await bus.close()


async def test_handoff_retries_next_best_target_and_labels_failure():
    """Satellite: a failed handshake retries once (jittered) against the
    next-best peer instead of silently abandoning the hand-off, and the
    failure counter carries a {reason} label."""
    bus = LoopbackBus()
    ms = MemoryStore(MemoryKV())
    metrics = Metrics()
    w1 = make_role_worker(bus, ms, "w-pre", "prefill", step_delay=0.02,
                          metrics=metrics)
    w2 = make_role_worker(bus, ms, "w-dec", "decode", step_delay=0.02,
                          metrics=metrics)
    await w1.start()
    await w2.start()
    tap = ResultTap()
    await bus.subscribe(subj.RESULT, tap)
    await w2.send_heartbeat()
    await bus.drain()
    import time as _t

    # a dead peer that outranks the live one (more free pages)
    w1._peers["w-ghost"] = {
        "addr": "127.0.0.1:1", "pages_free": 10_000, "decode_tps": 999.0,
        "role": "decode", "draining": False, "seen": _t.monotonic(),
    }
    ranked = w1._ranked_handoff_peers()
    assert ranked[0][0] == "w-ghost" and ranked[1][0] == "w-dec"
    prompt = [8, 8, 1]
    await submit_gen(bus, ms, "w-pre", "rt1", prompt, 40)
    await wait_until(lambda: "rt1" in tap.results, msg="job finished")
    assert tap.results["rt1"].status == "SUCCEEDED"
    assert tap.results["rt1"].worker_id == "w-dec"  # landed on the retry
    assert (await ms.get_result("rt1"))["tokens"] == fake_ref(prompt, 40)
    assert metrics.serving_handoffs.value(outcome="retried_ok") == 1
    # the dead target's handshake failure is reason-labeled
    assert metrics.serving_migration_failures.value(reason="io") >= 1
    await w1.stop(), await w2.stop(), await bus.close()
