"""1×1 hot-path specialization (ISSUE 6): identity-dispatch collapse,
batched-tick == per-job equivalence (states, event logs, trace spans),
msgpack↔legacy-JSON stored-record compatibility, the CI perf-floor
checker, and the bench backend-probe watchdog contract."""
from __future__ import annotations

import asyncio
import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
from cordum_tpu.controlplane.scheduler.engine import Engine
from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
from cordum_tpu.infra.bus import LoopbackBus
from cordum_tpu.infra.codec import pack_record, unpack_record
from cordum_tpu.infra.config import parse_pool_config
from cordum_tpu.infra.jobstore import JobStore, SafetyDecisionRecord, events_key
from cordum_tpu.infra.kv import MemoryKV
from cordum_tpu.infra.statebus import PartitionedBus, PartitionedKV
from cordum_tpu.protocol import subjects as subj
from cordum_tpu.protocol.types import (
    BusPacket,
    Heartbeat,
    JobRequest,
    JobResult,
    LABEL_PARTITION,
)

REPO = Path(__file__).resolve().parents[1]


# ---------------------------------------------------------------------------
# identity-dispatch collapse (routing chosen at construction, not per op)
# ---------------------------------------------------------------------------


def test_partitioned_kv_single_part_collapses_to_backend():
    """An unsharded store IS its single backend: no routing wrapper object,
    so the 1×1 hot path pays zero per-op partition dispatch."""
    kv = MemoryKV()
    assert PartitionedKV([kv]) is kv
    multi = PartitionedKV([MemoryKV(), MemoryKV()])
    assert type(multi) is PartitionedKV and multi.n == 2


def test_partitioned_bus_single_collapses_to_backend():
    bus = LoopbackBus()
    assert PartitionedBus([bus]) is bus
    multi = PartitionedBus([LoopbackBus(), LoopbackBus()])
    assert type(multi) is PartitionedBus and multi.n == 2


def test_unsharded_engine_identity_ownership_and_no_stamp():
    """shard_count == 1 binds identity ownership and a no-op partition
    stamp at construction — no crc32, no label mutation on dispatch."""
    eng = _mk_engine(LoopbackBus(), MemoryKV(), batch_ticks=False)
    assert eng.owns("any-job-id") and eng.owns("another")
    req = JobRequest(job_id="j1", topic="job.bench")
    eng._stamp_partition(req)
    assert not (req.labels or {}).get(LABEL_PARTITION)
    sharded = _mk_engine(LoopbackBus(), MemoryKV(), batch_ticks=False,
                         shard_index=1, shard_count=2)
    req2 = JobRequest(job_id="j1", topic="job.bench")
    sharded._stamp_partition(req2)
    assert req2.labels[LABEL_PARTITION] == "1"


# ---------------------------------------------------------------------------
# batched tick path == per-job path (states, event logs, trace spans)
# ---------------------------------------------------------------------------


def _mk_engine(bus, kv, *, batch_ticks: bool, shard_index: int = 0,
               shard_count: int = 1) -> Engine:
    kernel = SafetyKernel(
        policy_doc={"tenants": {"default": {"allow_topics": ["job.*", "job.>"]}}}
    )
    from cordum_tpu.infra.registry import WorkerRegistry

    reg = WorkerRegistry()
    pc = parse_pool_config(
        {"topics": {"job.bench": "bench"}, "pools": {"bench": {"requires": []}}}
    )
    eng = Engine(
        bus=bus, job_store=JobStore(kv), safety=SafetyClient(kernel.check),
        strategy=LeastLoadedStrategy(reg, pc), registry=reg,
        instance_id=f"eng-{shard_index}", shard_index=shard_index,
        shard_count=shard_count, batch_ticks=batch_ticks,
    )
    reg.update(Heartbeat(worker_id="w1", pool="bench", max_parallel_jobs=1 << 30))
    return eng


async def _run_burst(job_ids: list[str], *, batch_ticks: bool):
    """Submit a burst, run to completion, return per-job
    (state, [event names], {span name: count}, schedule-parented names)."""
    kv = MemoryKV()
    bus = LoopbackBus()
    spans: list = []

    async def collect_span(subject, pkt):
        spans.append(pkt.payload)

    await bus.subscribe(subj.TRACE_SPAN, collect_span)
    eng = _mk_engine(bus, kv, batch_ticks=batch_ticks)
    await eng.start()

    async def worker_handler(subject, pkt):
        req = pkt.job_request
        await bus.publish(
            subj.RESULT,
            BusPacket.wrap(
                JobResult(job_id=req.job_id, status="SUCCEEDED", worker_id="w1"),
                sender_id="w1",
            ),
        )

    await bus.subscribe(subj.direct_subject("w1"), worker_handler, queue="w")
    for jid in job_ids:
        await bus.publish(
            subj.SUBMIT,
            BusPacket.wrap(
                JobRequest(job_id=jid, topic="job.bench", tenant_id="default"),
                sender_id="t",
            ),
        )
    js = JobStore(kv)
    for _ in range(2000):
        await bus.drain()
        states = [await js.get_state(j) for j in job_ids]
        if all(s == "SUCCEEDED" for s in states):
            break
        await asyncio.sleep(0.005)
    # let the trailing result spans flush
    for _ in range(10):
        await bus.drain()
        await asyncio.sleep(0.002)
    out = {}
    by_job: dict[str, list] = {}
    for sp in spans:
        jid = (sp.attrs or {}).get("job_id", "")
        if jid:
            by_job.setdefault(jid, []).append(sp)
    for jid in job_ids:
        ev = [e["event"] for e in await js.events(jid)]
        job_spans = by_job.get(jid, [])
        names: dict[str, int] = {}
        for sp in job_spans:
            names[sp.name] = names.get(sp.name, 0) + 1
        sched_ids = {sp.span_id for sp in job_spans if sp.name == "schedule"}
        under_schedule = sorted(
            sp.name for sp in job_spans if sp.parent_span_id in sched_ids
        )
        out[jid] = (await js.get_state(jid), ev, names, under_schedule)
    await eng.stop()
    await bus.close()
    return out


async def test_batched_tick_path_matches_per_job_path():
    """Tentpole equivalence: an identical job burst through the batched
    tick fast path lands the same final states, the same event logs, and
    the same trace-span structure as the per-job path."""
    jobs = [f"fp-{i}" for i in range(24)]
    batched = await _run_burst(jobs, batch_ticks=True)
    per_job = await _run_burst(jobs, batch_ticks=False)
    for jid in jobs:
        b_state, b_events, b_spans, b_under = batched[jid]
        p_state, p_events, p_spans, p_under = per_job[jid]
        assert b_state == p_state == "SUCCEEDED"
        assert b_events == p_events, f"{jid}: {b_events} != {p_events}"
        assert b_spans == p_spans, f"{jid}: {b_spans} != {p_spans}"
        # policy-check/strategy/dispatch parent under the schedule span in
        # both paths (the batched path takes explicit parents, not ambient
        # context — structure must not drift)
        assert b_under == p_under == ["dispatch", "policy-check", "strategy"]


async def test_batched_engine_observes_tick_metrics():
    """The fast path reports its batch sizes (cordum_sched_tick_batch_size)."""
    jobs = [f"tm-{i}" for i in range(8)]
    kv = MemoryKV()
    bus = LoopbackBus()
    eng = _mk_engine(bus, kv, batch_ticks=True)
    await eng.start()

    async def worker_handler(subject, pkt):
        req = pkt.job_request
        await bus.publish(
            subj.RESULT,
            BusPacket.wrap(
                JobResult(job_id=req.job_id, status="SUCCEEDED", worker_id="w1"),
                sender_id="w1",
            ),
        )

    await bus.subscribe(subj.direct_subject("w1"), worker_handler, queue="w")
    for jid in jobs:
        await bus.publish(
            subj.SUBMIT,
            BusPacket.wrap(
                JobRequest(job_id=jid, topic="job.bench", tenant_id="default"),
                sender_id="t",
            ),
        )
    js = JobStore(kv)
    for _ in range(2000):
        await bus.drain()
        states = [await js.get_state(j) for j in jobs]
        if all(s == "SUCCEEDED" for s in states):
            break
        await asyncio.sleep(0.005)
    assert all(s == "SUCCEEDED" for s in states)
    rendered = eng.metrics.render()
    assert "cordum_sched_tick_batch_size" in rendered
    count_lines = [ln for ln in rendered.splitlines()
                   if ln.startswith("cordum_sched_tick_batch_size_count")]
    assert count_lines and float(count_lines[0].rsplit(" ", 1)[1]) > 0
    await eng.stop()
    await bus.close()


# ---------------------------------------------------------------------------
# msgpack ↔ legacy-JSON stored-record compatibility
# ---------------------------------------------------------------------------


def test_unpack_record_reads_both_encodings():
    rec = {"ts_us": 7, "event": "submit", "n": 3}
    assert unpack_record(pack_record(rec)) == rec
    assert unpack_record(json.dumps(rec).encode()) == rec
    # tolerate the pretty-printed / whitespace-prefixed JSON some legacy
    # tooling wrote
    assert unpack_record(b"  \n" + json.dumps(rec, indent=1).encode()) == rec
    assert unpack_record(json.dumps([1, "a"]).encode()) == [1, "a"]


async def test_event_log_mixes_legacy_json_and_msgpack():
    """Old AOF/KV data keeps loading: an event log with pre-ISSUE-6 JSON
    entries still reads after this build appends msgpack entries."""
    kv = MemoryKV()
    js = JobStore(kv)
    legacy = {"ts_us": 1, "state": "PENDING", "prev": "", "event": "submit"}
    await kv.rpush(events_key("old-job"), json.dumps(legacy).encode())
    await js.append_event("old-job", "redelivered", attempt=2)
    ev = await js.events("old-job")
    assert ev[0] == legacy
    assert ev[1]["event"] == "redelivered" and ev[1]["attempt"] == 2


async def test_safety_decision_reads_legacy_json_record():
    kv = MemoryKV()
    js = JobStore(kv)
    rec = SafetyDecisionRecord(
        job_id="old-job", decision="ALLOW", policy_snapshot="h", decided_at_us=5
    )
    await kv.set("job:safety:old-job", json.dumps(rec.__dict__).encode())
    got = await js.get_safety_decision("old-job")
    assert got is not None and got.decision == "ALLOW" and got.decided_at_us == 5
    # and the msgpack write path round-trips through the same reader
    await js.put_safety_decision(
        SafetyDecisionRecord(job_id="new-job", decision="DENY", policy_snapshot="h2")
    )
    got2 = await js.get_safety_decision("new-job")
    assert got2 is not None and got2.decision == "DENY"


# ---------------------------------------------------------------------------
# CI perf floor checker (tools/check_bench_floor.py + bench_floor.json)
# ---------------------------------------------------------------------------


def _floor_mod():
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import check_bench_floor
    finally:
        sys.path.pop(0)
    return check_bench_floor


_HEALTHY_STORM = {
    "storm_interactive_p99_ms": 900.0, "storm_interactive_shed_rate": 0.0,
    "storm_batch_goodput": 35.0, "storm_control_vs_admitted_p99": 5.0,
}

# disaggregated serving keys (ISSUE 14): migrations happened, the
# steady-state decode-worker stream p99 held, prefill rate attributable
_HEALTHY_DISAGG = {
    "prefill_tokens_per_sec": 850.0, "disagg_migrations_done": 9,
    "disagg_inter_token_p99_ms": 23.0,
}

# gang scheduling keys (ISSUE 15): the control-plane gang pipeline ran,
# the three MULTICHIP flows completed, and the all-or-nothing invariant
# counter stayed at exactly zero
_HEALTHY_GANG = {
    "gang_jobs_per_sec": 4.0, "gang_flows_ok": 1.0,
    "gang_partial_reservations": 0.0,
}

# the agent-loop storm: multi-turn DAG runs rode session affinity end-to-end
# (hit rate 1.0, zero re-prefills) with context embeds batched on the pool
_HEALTHY_AGENTS = {
    "agents_workflow_steps_per_sec": 170.0, "agents_affinity_hit_rate": 1.0,
    "agents_context_embeds_per_sec": 80.0,
    "agents_reprefills": 0.0, "agents_step_p99_ms": 20.0,
}

# prefix cache + session tiering (ISSUE 18): the hit pass beat the cold
# pass token-identically, hibernation held residency above the device
# arena, and the cold->warm restore actually ran (fast)
_HEALTHY_CHAT = {
    "chat_prefix_ttft_speedup": 2.4, "chat_token_identical": 1,
    "chat_prefix_hit_rate": 0.857, "chat_resident_over_capacity": 1.6,
    "chat_restored_pages": 8, "chat_restore_pause_p50_ms": 1.0,
}

_HEALTHY_SPEC = {
    "spec_decode_speedup": 1.96, "spec_token_identity": 1,
    "spec_compile_count": 1,
}

# sharded serving gang (TP=2 over the in-process gang group): identity is
# binary, the compile ceiling is exactly one program per rank, and the
# speedup floor is a collapse guard only (both ranks time-share the core
# on 1-2 core CI hosts — see the bench_floor.json commentary)
_HEALTHY_TP = {
    "tp_token_identity": 1, "tp_speedup": 0.51,
    "tp_tokens_per_sec": 15.5, "tp_compile_per_rank": 1,
}


def test_floor_checker_passes_healthy_doc():
    mod = _floor_mod()
    doc = {"value": 2600.0, "selections_per_sec": 90000.0,
           "kv_roundtrips_per_job": 3.0, "statebus_kv_roundtrips_per_job": 8.0,
           "statebus_pipeline_speedup": 1.9,
           "sharded_jobs_per_sec": 300.0, "sharded_single_jobs_per_sec": 320.0,
           "serving_speedup": 4.5, "serving_affinity_hit_rate": 1.0,
           "decode_tokens_per_sec": 2900.0, "serving_compile_count": 1,
           "inter_token_p99_ms": 4.0, "migration_pause_p50_ms": 10.0,
           "statebus_replication_overhead_pct": 8.0,
           "fleet_snapshot_ok": 1.0, "telemetry_overhead_pct": 0.5,
           "capacity_matrix_ok": 1.0, "profiling_overhead_pct": 0.4,
           **_HEALTHY_STORM, **_HEALTHY_DISAGG, **_HEALTHY_GANG,
           **_HEALTHY_AGENTS, **_HEALTHY_CHAT, **_HEALTHY_SPEC,
           **_HEALTHY_TP}
    floors = json.loads((REPO / "bench_floor.json").read_text())
    assert mod.check(doc, floors) == []


def test_floor_checker_fails_regressed_metric(tmp_path):
    """The gate actually gates: a metric below its floor exits 1 (the
    deliberately-regressed-value demonstration from the acceptance bar)."""
    mod = _floor_mod()
    floors = json.loads((REPO / "bench_floor.json").read_text())
    doc = {"value": 100.0, "selections_per_sec": 90000.0,
           "kv_roundtrips_per_job": 3.0, "statebus_kv_roundtrips_per_job": 8.0,
           "statebus_pipeline_speedup": 1.9,
           "sharded_jobs_per_sec": 300.0, "sharded_single_jobs_per_sec": 320.0,
           "serving_speedup": 4.5, "serving_affinity_hit_rate": 1.0,
           "decode_tokens_per_sec": 2900.0, "serving_compile_count": 1,
           "inter_token_p99_ms": 4.0, "migration_pause_p50_ms": 10.0,
           "statebus_replication_overhead_pct": 8.0,
           "fleet_snapshot_ok": 1.0, "telemetry_overhead_pct": 0.5,
           "capacity_matrix_ok": 1.0, "profiling_overhead_pct": 0.4,
           **_HEALTHY_STORM, **_HEALTHY_DISAGG, **_HEALTHY_GANG,
           **_HEALTHY_AGENTS, **_HEALTHY_CHAT, **_HEALTHY_SPEC,
           **_HEALTHY_TP}
    violations = mod.check(doc, floors)
    assert violations and "value" in violations[0]
    # ceilings guard the other direction (round-trip budget regression)
    doc["value"] = 2600.0
    doc["kv_roundtrips_per_job"] = 49.0
    assert any("kv_roundtrips_per_job" in v for v in mod.check(doc, floors))
    # ... and the bucket-recompile cliff coming back is a gated failure
    doc["kv_roundtrips_per_job"] = 3.0
    doc["serving_compile_count"] = 6  # the old bucketed backend's count
    assert any("serving_compile_count" in v for v in mod.check(doc, floors))
    doc["serving_compile_count"] = 1
    # storm overload gates (ISSUE 13): interactive collapse, interactive
    # shed creep, shed-everything batch starvation, and a controller that
    # stopped doing anything (control run no longer degrades) all fail
    doc["storm_interactive_p99_ms"] = 9000.0
    assert any("storm_interactive_p99_ms" in v for v in mod.check(doc, floors))
    doc["storm_interactive_p99_ms"] = 900.0
    doc["storm_interactive_shed_rate"] = 0.2
    assert any("storm_interactive_shed_rate" in v for v in mod.check(doc, floors))
    doc["storm_interactive_shed_rate"] = 0.0
    doc["storm_batch_goodput"] = 0.0
    assert any("storm_batch_goodput" in v for v in mod.check(doc, floors))
    doc["storm_batch_goodput"] = 35.0
    doc["storm_control_vs_admitted_p99"] = 1.0
    assert any("storm_control_vs_admitted_p99" in v
               for v in mod.check(doc, floors))
    doc["storm_control_vs_admitted_p99"] = 5.0
    # disaggregation gates (ISSUE 14): a hand-off policy that stopped
    # migrating, a decode-worker stream-tail collapse, and a vanished
    # prefill/decode capacity split all fail
    doc["disagg_migrations_done"] = 0
    assert any("disagg_migrations_done" in v for v in mod.check(doc, floors))
    doc["disagg_migrations_done"] = 9
    doc["disagg_inter_token_p99_ms"] = 900.0
    assert any("disagg_inter_token_p99_ms" in v
               for v in mod.check(doc, floors))
    doc["disagg_inter_token_p99_ms"] = 23.0
    doc["prefill_tokens_per_sec"] = 0.0
    assert any("prefill_tokens_per_sec" in v for v in mod.check(doc, floors))
    doc["prefill_tokens_per_sec"] = 850.0
    # prefix-cache + tiering gates (ISSUE 18): a vanished TTFT win, a
    # token-divergent hit pass, residency collapsing back to device HBM,
    # and a restore-pause blowup all fail
    doc["chat_prefix_ttft_speedup"] = 1.0
    assert any("chat_prefix_ttft_speedup" in v for v in mod.check(doc, floors))
    doc["chat_prefix_ttft_speedup"] = 2.4
    doc["chat_token_identical"] = 0
    assert any("chat_token_identical" in v for v in mod.check(doc, floors))
    doc["chat_token_identical"] = 1
    doc["chat_resident_over_capacity"] = 1.0
    assert any("chat_resident_over_capacity" in v
               for v in mod.check(doc, floors))
    doc["chat_resident_over_capacity"] = 1.6
    doc["chat_restore_pause_p50_ms"] = 900.0
    assert any("chat_restore_pause_p50_ms" in v for v in mod.check(doc, floors))
    doc["chat_restore_pause_p50_ms"] = 1.0
    # end-to-end: main() exits nonzero on a regressed artifact
    bench_json = tmp_path / "bench.json"
    doc["value"] = 100.0
    bench_json.write_text("warmup noise\n" + json.dumps(doc) + "\n")
    assert mod.main([str(bench_json), str(REPO / "bench_floor.json")]) == 1


def test_floor_checker_flags_missing_metric():
    mod = _floor_mod()
    assert mod.check({}, {"floors": {"value": 1.0}}) != []


# ---------------------------------------------------------------------------
# bench backend-probe watchdog (satellite: regression test, not just CI grep)
# ---------------------------------------------------------------------------


def test_tpu_probe_child_skips_cleanly_on_cpu_host():
    """The PR-5 watchdog contract: on a host with no TPU the tpu bench
    child must exit 0 with a one-line {"skipped": ...} JSON — never the
    r04/r05 `child rc=1` traceback that polluted BENCH output."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # probe as bench does on a bare host
    env["BENCH_TPU_PROBE_TIMEOUT_S"] = "20"  # keep the tier-1 wall low
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--jax-child", "tpu"],
        capture_output=True, text=True, timeout=240, cwd=str(REPO), env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = (proc.stdout.strip().splitlines() or [""])[-1]
    child = json.loads(line)
    # a CPU host yields a clean skip; a real TPU host yields real metrics —
    # either way the error keys must not appear
    assert child.get("skipped") or "embeds_per_sec" in child
    assert "embed_error" not in child and "model_error" not in child


@pytest.mark.slow
def test_bench_jax_smoke_output_has_no_error_keys():
    """Full bench_jax(smoke=True) merge logic on a CPU host: the output
    dict must carry metrics, not embed_error/model_error keys."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    results = bench.bench_jax(smoke=True)
    assert "embed_error" not in results and "model_error" not in results, results
    assert results.get("embeds_per_sec", 0) > 0, results
