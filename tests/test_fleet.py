"""Fleet telemetry plane (ISSUE 9): exporter delta encoding, cross-process
aggregation correctness (merged counter == per-process sum, merged-histogram
quantiles == union-stream quantiles, counter-reset detection on restart),
SLO burn rates, the runtime profiler, Prometheus text-format conformance,
span-drop accounting, and the gateway's fleet surfaces."""
import asyncio
import gc
import random
import time

from aiohttp.test_utils import TestClient, TestServer

from cordum_tpu.controlplane.gateway.app import Gateway
from cordum_tpu.controlplane.gateway.auth import BasicAuthProvider
from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
from cordum_tpu.infra.bus import LoopbackBus
from cordum_tpu.infra.config import parse_pool_config
from cordum_tpu.infra.configschema import ConfigError
from cordum_tpu.infra.jobstore import JobStore
from cordum_tpu.infra.kv import MemoryKV
from cordum_tpu.infra.memstore import MemoryStore
from cordum_tpu.infra.metrics import Histogram, Metrics
from cordum_tpu.infra.schemareg import SchemaRegistry
from cordum_tpu.obs import (
    FleetAggregator,
    RuntimeProfiler,
    SLOTracker,
    SpanCollector,
    TelemetryExporter,
    render_fleet_table,
)
from cordum_tpu.protocol import subjects as subj
from cordum_tpu.protocol.types import BusPacket, Span
from cordum_tpu.utils.ids import now_us
from cordum_tpu.workflow.engine import Engine as WorkflowEngine
from cordum_tpu.workflow.store import WorkflowStore

POLICY = {"tenants": {"default": {"allow_topics": ["job.*", "job.>"]}}, "rules": []}


# ---------------------------------------------------------------------------
# Prometheus text-format conformance (satellite)
# ---------------------------------------------------------------------------


def _parse_exposition(text: str, exemplars: dict = None) -> dict:
    """Minimal conformance parser for the Prometheus text format: returns
    {metric_name: {frozenset(label items): value}} and raises on malformed
    lines/labels (unterminated quotes, raw newlines, bad floats).
    OpenMetrics-style exemplar suffixes (`` # {trace_id="..."} v ts``,
    ISSUE 10) are validated and collected into ``exemplars`` when a dict is
    passed: {(name, frozenset(labels)): trace_id}."""
    out: dict = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        exemplar_tid = None
        if " # " in line:  # exemplar suffix on a histogram bucket line
            line, _, ex = line.partition(" # ")
            assert ex.startswith('{trace_id="'), ex
            body, _, tail = ex[len('{trace_id="'):].partition('"}')
            exemplar_tid = body
            ex_value, ex_ts = tail.split()  # value + timestamp, both floats
            float(ex_value), float(ex_ts)
        if "{" in line:
            name, rest = line.split("{", 1)
            labels_part, value_part = rest.rsplit("}", 1)
            labels = {}
            i = 0
            while i < len(labels_part):
                eq = labels_part.index("=", i)
                key = labels_part[i:eq]
                assert labels_part[eq + 1] == '"', f"unquoted value in {line!r}"
                j = eq + 2
                buf = []
                while True:
                    ch = labels_part[j]
                    if ch == "\\":
                        esc = labels_part[j + 1]
                        buf.append({"n": "\n", '"': '"', "\\": "\\"}[esc])
                        j += 2
                    elif ch == '"':
                        break
                    else:
                        buf.append(ch)
                        j += 1
                labels[key] = "".join(buf)
                i = j + 1
                if i < len(labels_part) and labels_part[i] == ",":
                    i += 1
            value = float(value_part.strip())
        else:
            name, value_s = line.rsplit(" ", 1)
            name = name.strip()
            labels = {}
            value = float(value_s)
        out.setdefault(name, {})[frozenset(labels.items())] = value
        if exemplar_tid is not None and exemplars is not None:
            exemplars[(name, frozenset(labels.items()))] = exemplar_tid
    return out


def test_label_value_escaping_round_trips():
    m = Metrics()
    nasty = 'a"b\\c\nd'
    m.jobs_received.inc(topic=nasty)
    parsed = _parse_exposition(m.render())
    series = parsed["cordum_jobs_received_total"]
    assert series[frozenset({("topic", nasty)}.union())] == 1.0


def test_histogram_le_bounds_are_plain_floats():
    h = Histogram("h_test", buckets=(0.25, 1.0, 2.5))
    h.observe(0.3)
    text = "\n".join(h.render())
    parsed = _parse_exposition(text)
    les = sorted(
        dict(k)["le"] for k in parsed["h_test_bucket"]
    )
    assert les == ["+Inf", "0.25", "1.0", "2.5"], les
    # every le except +Inf parses as a float
    for le in les:
        if le != "+Inf":
            float(le)


def test_full_registry_renders_parseable():
    m = Metrics()
    m.jobs_dispatched.inc(topic="job.x")
    m.e2e_latency.observe(0.2, job_class="BATCH")
    m.workers_live.set(3.0)
    parsed = _parse_exposition(m.render())
    assert parsed["cordum_jobs_dispatched_total"][frozenset({("topic", "job.x")})] == 1.0
    assert parsed["cordum_workers_live"][frozenset()] == 3.0


# ---------------------------------------------------------------------------
# exporter delta encoding
# ---------------------------------------------------------------------------


def test_exporter_delta_only_ships_changed_series():
    m = Metrics()
    exp = TelemetryExporter("scheduler", None, m, instance_id="s0", full_every=100)
    m.jobs_dispatched.inc(topic="a")
    m.jobs_dispatched.inc(topic="b")
    first = exp.build_snapshot()  # seq 0 → full
    assert first.full
    assert len(first.metrics["counters"]["cordum_jobs_dispatched_total"]) == 2

    m.jobs_dispatched.inc(topic="a")  # only series "a" moves
    second = exp.build_snapshot()
    assert not second.full
    changed = second.metrics["counters"]["cordum_jobs_dispatched_total"]
    assert changed == [[{"topic": "a"}, 2.0]]

    third = exp.build_snapshot()  # nothing moved → family absent
    assert "cordum_jobs_dispatched_total" not in third.metrics["counters"]


def test_exporter_periodic_full_snapshot():
    m = Metrics()
    exp = TelemetryExporter("w", None, m, full_every=3)
    m.workers_live.set(1.0)
    assert exp.build_snapshot().full  # seq 0
    assert not exp.build_snapshot().full
    assert not exp.build_snapshot().full
    snap = exp.build_snapshot()  # seq 3
    assert snap.full
    assert snap.metrics["gauges"]["cordum_workers_live"] == [[{}, 1.0]]


# ---------------------------------------------------------------------------
# cross-process aggregation correctness (satellite)
# ---------------------------------------------------------------------------


def _drive(agg: FleetAggregator, exporters: list[TelemetryExporter]):
    for exp in exporters:
        agg.ingest(exp.build_snapshot())


def test_fleet_counter_equals_per_process_sum_randomized():
    """Randomized multi-process streams: after arbitrary interleavings of
    increments and snapshot publishes — including a process restart mid-
    stream — the fleet-merged counter equals the true sum of every
    increment ever made."""
    rng = random.Random(1234)
    agg = FleetAggregator(None)
    registries = [Metrics() for _ in range(3)]
    exporters = [
        TelemetryExporter("scheduler", None, m, instance_id=f"s{i}")
        for i, m in enumerate(registries)
    ]
    topics = ["a", "b", "c"]
    truth: dict[str, float] = {t: 0.0 for t in topics}
    for step in range(200):
        i = rng.randrange(3)
        t = rng.choice(topics)
        amt = rng.randint(1, 5)
        registries[i].jobs_dispatched.inc(amount=float(amt), topic=t)
        truth[t] += amt
        if rng.random() < 0.3:
            agg.ingest(exporters[i].build_snapshot())
        if step == 120:
            # process 1 restarts mid-stream: new registry, new exporter
            # epoch — its counters reset to zero.  The aggregator must keep
            # the dead epoch's contribution (counter-reset detection).
            registries[1] = Metrics()
            exporters[1] = TelemetryExporter(
                "scheduler", None, registries[1], instance_id="s1"
            )
            # distinct epoch even at equal wall-clock microseconds
            exporters[1].started_at_us = exporters[0].started_at_us - 1
    _drive(agg, exporters)
    merged = agg.merged_counters()["cordum_jobs_dispatched_total"]
    for t in topics:
        assert merged[(("topic", t),)] == truth[t], t
    assert agg.counter_total("cordum_jobs_dispatched_total") == sum(truth.values())


def test_fleet_histogram_quantiles_equal_union_stream():
    """Merged-histogram quantiles == quantiles of the union stream: a
    reference Histogram observing every sample from every process must
    agree with the aggregator's merged buckets at every quantile."""
    rng = random.Random(99)
    agg = FleetAggregator(None)
    registries = [Metrics() for _ in range(4)]
    exporters = [
        TelemetryExporter("scheduler", None, m, instance_id=f"p{i}")
        for i, m in enumerate(registries)
    ]
    reference = Histogram("ref")  # same default buckets as e2e_latency
    for _ in range(600):
        i = rng.randrange(4)
        v = rng.expovariate(8.0)
        registries[i].e2e_latency.observe(v, job_class="BATCH")
        reference.observe(v)
        if rng.random() < 0.1:
            agg.ingest(exporters[i].build_snapshot())
    _drive(agg, exporters)
    buckets, fams = agg.merged_histograms()["cordum_job_e2e_seconds"]
    merged = fams[(("job_class", "BATCH"),)]
    assert merged["total"] == 600
    from cordum_tpu.obs.fleet import quantile_from_buckets

    for q in (0.1, 0.5, 0.9, 0.99):
        assert quantile_from_buckets(
            buckets, merged["counts"], merged["total"], q
        ) == reference.quantile(q), q


def test_restart_folds_histograms_too():
    agg = FleetAggregator(None)
    m = Metrics()
    exp = TelemetryExporter("w", None, m, instance_id="w0")
    m.e2e_latency.observe(0.01)
    agg.ingest(exp.build_snapshot())
    # restart: fresh registry, new epoch, two more observations
    m2 = Metrics()
    exp2 = TelemetryExporter("w", None, m2, instance_id="w0")
    exp2.started_at_us = exp.started_at_us + 7
    m2.e2e_latency.observe(0.02)
    m2.e2e_latency.observe(0.03)
    agg.ingest(exp2.build_snapshot())
    _, fams = agg.merged_histograms()["cordum_job_e2e_seconds"]
    assert fams[()]["total"] == 3


def test_gauges_keep_their_instance_in_fleet_render():
    agg = FleetAggregator(None)
    for i in range(2):
        m = Metrics()
        m.workers_live.set(4.0)
        agg.ingest(TelemetryExporter(
            "scheduler", None, m, instance_id=f"s{i}").build_snapshot())
    text = agg.render()
    # NOT summed to 8: one line per instance
    assert 'cordum_workers_live{instance="s0"} 4.0' in text
    assert 'cordum_workers_live{instance="s1"} 4.0' in text
    parsed = _parse_exposition(text)
    assert parsed["cordum_fleet_instances"][frozenset({("service", "scheduler")})] == 2.0


# ---------------------------------------------------------------------------
# end-to-end over the loopback bus + SLO burn rates
# ---------------------------------------------------------------------------


async def test_exporters_to_aggregator_over_bus():
    bus = LoopbackBus()
    agg = FleetAggregator(bus, metrics=Metrics(), fine_step_s=0.02)
    await agg.start()
    m = Metrics()
    exp = TelemetryExporter(
        "worker", bus, m, instance_id="w1", interval_s=0.02,
        health_fn=lambda: {"role": "worker", "active_jobs": 2},
    )
    m.jobs_by_class.inc(job_class="BATCH", status="SUCCEEDED")
    assert await exp.publish_once()
    await bus.drain()
    agg.sample()
    doc = agg.fleet_doc()
    assert doc["healthy_services"] == 1
    svc = doc["services"][0]
    assert svc["service"] == "worker" and svc["instance"] == "w1"
    assert svc["role"] == "worker" and svc["active_jobs"] == 2
    assert svc["healthy"]
    await agg.stop()
    await bus.close()


async def test_exporter_skips_when_nobody_listens():
    bus = LoopbackBus()
    m = Metrics()
    exp = TelemetryExporter("worker", bus, m, instance_id="w1")
    assert not await exp.publish_once()  # no aggregator → no packet built
    assert m.telemetry_snapshots.total() == 0


def test_slo_burn_rates_and_states():
    agg = FleetAggregator(None)
    agg.sample()  # zero baseline
    m = Metrics()
    exp = TelemetryExporter("scheduler", None, m, instance_id="s0")
    # 10 INTERACTIVE jobs: 4 above the 100 ms objective, 1 FAILED
    for v in (0.01, 0.02, 0.03, 0.04, 0.05, 0.06, 0.3, 0.4, 0.5, 0.6):
        m.e2e_latency.observe(v, job_class="INTERACTIVE")
    for status in ["SUCCEEDED"] * 9 + ["FAILED"]:
        m.jobs_by_class.inc(job_class="INTERACTIVE", status=status)
    agg.ingest(exp.build_snapshot())
    gauge_reg = Metrics()
    tracker = SLOTracker.from_config({
        "interactive": {
            "job_class": "INTERACTIVE", "latency_ms": 100,
            "latency_target": 0.9, "availability_target": 0.99,
        },
        "quiet": {"job_class": "CRITICAL", "latency_ms": 50},
    }, metrics=gauge_reg)
    states = tracker.evaluate(agg)
    inter = next(s for s in states if s["name"] == "interactive")
    w5 = inter["windows"]["5m"]
    # latency: 4/10 over → error fraction 0.4, budget 0.1 → burn 4.0
    assert w5["latency_error_fraction"] == 0.4
    assert w5["latency_burn_rate"] == 4.0
    # availability: 1/10 failed → 0.1 error over 0.01 budget → burn 10.0
    assert w5["availability_burn_rate"] == 10.0
    assert w5["burn_rate"] == 10.0
    assert inter["state"] == "warn"
    assert gauge_reg.slo_burn_rate.value(slo="interactive", window="5m") == 10.0
    quiet = next(s for s in states if s["name"] == "quiet")
    assert quiet["state"] == "ok" and quiet["windows"]["5m"]["total"] == 0


def test_slo_page_state_needs_both_windows_hot():
    """The page state requires BOTH the 5 m and 1 h windows burning (the
    multi-window rule); a fleet burning 100% of a tight budget trips it."""
    agg = FleetAggregator(None)
    agg.sample()
    m = Metrics()
    exp = TelemetryExporter("scheduler", None, m, instance_id="s0")
    for _ in range(50):
        m.e2e_latency.observe(5.0, job_class="INTERACTIVE")  # all way over
    agg.ingest(exp.build_snapshot())
    tracker = SLOTracker.from_config({
        "i": {"job_class": "INTERACTIVE", "latency_ms": 100,
              "latency_target": 0.99},
    })
    st = tracker.evaluate(agg)[0]
    assert st["windows"]["5m"]["burn_rate"] == 100.0
    assert st["state"] == "page"


def test_pools_yaml_slo_stanza_schema():
    cfg = parse_pool_config({
        "topics": {"job.x": "p"}, "pools": {"p": {}},
        "slo": {"inter": {"job_class": "INTERACTIVE", "latency_ms": 250,
                          "latency_target": 0.99}},
    })
    assert cfg.slo["inter"]["latency_ms"] == 250
    try:
        parse_pool_config({
            "pools": {"p": {}},
            "slo": {"bad": {"latency_target": 0.99}},  # latency_ms required
        })
    except ConfigError as e:
        assert "latency_ms" in str(e)
    else:
        raise AssertionError("schema accepted an slo entry without latency_ms")


# ---------------------------------------------------------------------------
# runtime profiler
# ---------------------------------------------------------------------------


async def test_profiler_observes_lag_and_slow_ticks():
    m = Metrics()
    prof = RuntimeProfiler(m, service="test", tick_s=0.02, slow_tick_s=0.05)
    await prof.start()
    await asyncio.sleep(0.06)  # a couple of clean ticks

    async def hog():
        time.sleep(0.12)  # deliberately block the loop (the stall under test)

    await asyncio.ensure_future(hog())
    await asyncio.sleep(0.08)
    await prof.stop()
    assert m.eventloop_lag._totals, "no lag samples recorded"
    assert m.slow_ticks.total() >= 1
    assert prof.last_slow_tick is not None
    assert prof.last_slow_tick["lag_s"] >= 0.05
    assert "last_slow_tick_lag_s" in prof.health()


async def test_profiler_counts_gc_pauses():
    m = Metrics()
    prof = RuntimeProfiler(m, service="test", tick_s=5.0)
    await prof.start()
    gc.collect()
    await prof.stop()
    assert m.gc_pauses.total() >= 1
    total = sum(m.gc_pause_seconds._totals.values())
    assert total >= 1
    gc.collect()
    after = m.gc_pauses.total()
    gc.collect()
    assert m.gc_pauses.total() == after  # callback removed on stop


# ---------------------------------------------------------------------------
# span-drop accounting (satellite)
# ---------------------------------------------------------------------------


async def test_collector_counts_per_trace_cap_drops():
    kv, bus, m = MemoryKV(), LoopbackBus(), Metrics()
    col = SpanCollector(kv, bus, metrics=m, max_spans_per_trace=4)
    for i in range(6):
        await col.add(Span(span_id=f"s{i}", trace_id="t1", name="x",
                           service="w", start_us=now_us(), end_us=now_us()))
    assert m.spans_dropped.value(reason="per_trace_cap") == 2.0
    assert len(await col.spans("t1")) == 4


async def test_collector_counts_eviction_drops():
    kv, bus, m = MemoryKV(), LoopbackBus(), Metrics()
    col = SpanCollector(kv, bus, metrics=m, max_traces=2)
    for t in ("t1", "t2", "t3"):
        await col.add(Span(span_id=f"s-{t}", trace_id=t, name="x",
                           service="w", start_us=now_us(), end_us=now_us()))
    assert m.spans_dropped.value(reason="trace_evicted") == 1.0


async def test_collector_recent_lists_newest_first():
    kv, bus = MemoryKV(), LoopbackBus()
    col = SpanCollector(kv, bus)
    t0 = now_us()
    for i, tid in enumerate(("t1", "t2")):
        await col.add(Span(span_id=f"root-{tid}", trace_id=tid, name="submit",
                           service="gateway", start_us=t0 + i,
                           end_us=t0 + i + 5000))
        await col.add(Span(span_id=f"leaf-{tid}", trace_id=tid,
                           parent_span_id=f"root-{tid}", name="execute",
                           service="worker", start_us=t0 + i + 1000,
                           end_us=t0 + i + 4000))
    recent = await col.recent(10)
    assert [t["trace_id"] for t in recent] == ["t2", "t1"]
    assert recent[0]["root"] == "submit"
    assert recent[0]["span_count"] == 2
    assert recent[0]["services"] == ["gateway", "worker"]
    assert recent[0]["duration_ms"] == 5.0


# ---------------------------------------------------------------------------
# gateway surfaces
# ---------------------------------------------------------------------------


class _FleetStack:
    """Gateway with telemetry enabled + a fake scheduler exporter on the
    same loopback bus, behind a live HTTP server."""

    def __init__(self):
        self.kv = MemoryKV()
        self.bus = LoopbackBus()
        wf_store = WorkflowStore(self.kv)
        mem = MemoryStore(self.kv)
        self.gw = Gateway(
            kv=self.kv, bus=self.bus, job_store=JobStore(self.kv), mem=mem,
            kernel=SafetyKernel(policy_doc=POLICY), wf_store=wf_store,
            wf_engine=WorkflowEngine(store=wf_store, bus=self.bus, mem=mem),
            schemas=SchemaRegistry(self.kv),
            auth=BasicAuthProvider(["user-key"]),
            slo_config={"batch": {"job_class": "BATCH", "latency_ms": 1000}},
        )
        self.sched_metrics = Metrics()
        self.sched_exporter = TelemetryExporter(
            "scheduler", self.bus, self.sched_metrics, instance_id="sched-0",
            health_fn=lambda: {"role": "scheduler", "shard_index": 0,
                               "shard_count": 1, "jobs_scheduled":
                               self.sched_metrics.jobs_dispatched.total()},
        )
        self.client = None

    async def __aenter__(self):
        await self.gw.fleet.start()
        await self.gw.telemetry.start()
        await self.gw.span_collector.start()
        self.client = TestClient(TestServer(self.gw.app))
        await self.client.start_server()
        return self

    async def __aexit__(self, *exc):
        await self.client.close()
        await self.gw.span_collector.stop()
        await self.gw.telemetry.stop()
        await self.gw.fleet.stop()
        await self.bus.close()

    def h(self):
        return {"X-Api-Key": "user-key"}


async def test_gateway_fleet_endpoint_and_fleet_metrics():
    async with _FleetStack() as s:
        s.sched_metrics.jobs_dispatched.inc(amount=3, topic="job.x")
        await s.sched_exporter.publish_once()
        await s.gw.telemetry.publish_once()
        await s.bus.drain()
        s.gw.fleet.sample()

        r = await s.client.get("/api/v1/fleet", headers=s.h())
        assert r.status == 200
        doc = await r.json()
        services = {sv["service"] for sv in doc["services"]}
        assert {"scheduler", "gateway"} <= services
        assert doc["healthy_services"] >= 2
        assert doc["fleet"]["jobs_dispatched_total"] == 3.0
        # fleet counter == sum of the per-service beacon values
        beacon_sum = sum(sv.get("jobs_scheduled", 0) for sv in doc["services"])
        assert doc["fleet"]["jobs_dispatched_total"] == beacon_sum
        assert doc["slo"][0]["name"] == "batch"
        assert "burn_rate" in doc["slo"][0]["windows"]["5m"]

        r = await s.client.get("/metrics?scope=fleet", headers=s.h())
        parsed = _parse_exposition(await r.text())
        assert parsed["cordum_jobs_dispatched_total"][
            frozenset({("topic", "job.x")})] == 3.0

        # the plain scope still renders the gateway's own registry
        r = await s.client.get("/metrics", headers=s.h())
        assert "cordum_http_requests_total" in await r.text()

        # the CLI table renders from the same doc
        table = render_fleet_table(doc)
        assert "scheduler" in table and "sched-0" in table
        assert "slo batch" in table


async def test_gateway_traces_listing():
    async with _FleetStack() as s:
        t0 = now_us()
        await s.gw.span_collector.add(Span(
            span_id="r1", trace_id="tr-1", name="submit", service="gateway",
            start_us=t0, end_us=t0 + 1000,
        ))
        r = await s.client.get("/api/v1/traces?last=5", headers=s.h())
        assert r.status == 200
        doc = await r.json()
        assert doc["traces"][0]["trace_id"] == "tr-1"
        assert doc["traces"][0]["root"] == "submit"


# ---------------------------------------------------------------------------
# wire round-trip
# ---------------------------------------------------------------------------


def test_telemetry_snapshot_wire_round_trip():
    m = Metrics()
    m.jobs_dispatched.inc(topic="t")
    m.e2e_latency.observe(0.1, job_class="BATCH")
    snap = TelemetryExporter("scheduler", None, m,
                             instance_id="s0").build_snapshot()
    pkt = BusPacket.wrap(snap, sender_id="s0")
    decoded = BusPacket.from_wire(pkt.to_wire())
    assert subj.telemetry_subject("scheduler") == "sys.telemetry.scheduler"
    got = decoded.telemetry
    assert got.service == "scheduler" and got.instance == "s0"
    assert got.metrics["counters"]["cordum_jobs_dispatched_total"] == [
        [{"topic": "t"}, 1.0]
    ]
    agg = FleetAggregator(None)
    agg.ingest(got)
    assert agg.counter_total("cordum_jobs_dispatched_total") == 1.0


def test_telemetry_subject_not_durable():
    assert not subj.is_durable_subject(subj.telemetry_subject("worker"))
