"""Gang scheduling (docs/GANG.md): DeviceLedger all-or-nothing invariants,
FIFO admission, engine → rendezvous → aggregated-result flow, abort/requeue
fault semantics (member failure, crash, rendezvous timeout, preemption,
cancel), MPMD pipeline numerics, pool-requirement enforcement, the gang
observability surfaces, and the MeshSpec.resolve edge cases."""
from __future__ import annotations

import asyncio
import json
import logging
import random
import sys
import time
from pathlib import Path
from types import SimpleNamespace

import pytest

from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
from cordum_tpu.controlplane.scheduler.engine import Engine
from cordum_tpu.controlplane.scheduler.gang import (
    DeviceLedger,
    GangScheduler,
    render_gang_table,
)
from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
from cordum_tpu.controlplane.scheduler.strategy import (
    LeastLoadedStrategy,
    pool_requirement_mismatch,
)
from cordum_tpu.infra.bus import LoopbackBus
from cordum_tpu.infra.config import Pool, parse_pool_config
from cordum_tpu.infra.jobstore import JobStore
from cordum_tpu.infra.kv import MemoryKV
from cordum_tpu.infra.memstore import MemoryStore
from cordum_tpu.infra.registry import WorkerRegistry
from cordum_tpu.parallel.mesh import MeshSpec
from cordum_tpu.protocol import subjects as subj
from cordum_tpu.protocol.types import (
    BusPacket,
    GangMsg,
    Heartbeat,
    JobPreempt,
    JobRequest,
    LABEL_GANG_CHIPS,
    LABEL_GANG_WORKERS,
    gang_chips,
    gang_workers,
    payload_gang,
)
from cordum_tpu.worker.gang import GangRunner
from cordum_tpu.worker.runtime import Worker

REPO = Path(__file__).resolve().parents[1]


# ---------------------------------------------------------------------------
# MeshSpec.resolve edge cases (satellite: previously only exercised by the
# MULTICHIP dryruns)
# ---------------------------------------------------------------------------


def test_mesh_resolve_default_absorbs_all():
    assert MeshSpec().resolve(8) == {"dp": 8, "tp": 1, "sp": 1, "ep": 1, "pp": 1}


def test_mesh_resolve_fixed_exact_fit():
    assert MeshSpec(dp=2, tp=2, sp=2).resolve(8)["dp"] == 2


def test_mesh_resolve_free_axis_divides_remainder():
    sizes = MeshSpec(dp=-1, tp=2, sp=2).resolve(8)
    assert sizes["dp"] == 2 and sizes["tp"] == 2 and sizes["sp"] == 2


def test_mesh_resolve_non_divisible_raises():
    with pytest.raises(ValueError, match="not divisible"):
        MeshSpec(dp=-1, tp=3).resolve(8)


def test_mesh_resolve_axis_exceeds_devices_raises():
    with pytest.raises(ValueError):
        MeshSpec(dp=1, tp=16).resolve(8)
    # a free axis cannot rescue an oversized fixed product either
    with pytest.raises(ValueError):
        MeshSpec(dp=-1, tp=16).resolve(8)


def test_mesh_resolve_zero_axis_raises():
    # regression: dp=0 used to slip through the fixed-axes product and
    # build a zero-sized mesh dimension downstream
    with pytest.raises(ValueError, match="axes must be"):
        MeshSpec(dp=0, tp=2, sp=2, ep=2).resolve(8)
    with pytest.raises(ValueError, match="axes must be"):
        MeshSpec(tp=-2).resolve(8)


def test_mesh_resolve_two_free_axes_raises():
    with pytest.raises(ValueError, match="at most one"):
        MeshSpec(dp=-1, tp=-1).resolve(8)


def test_mesh_resolve_fixed_mismatch_raises():
    with pytest.raises(ValueError, match="needs"):
        MeshSpec(dp=2, tp=2).resolve(8)


# ---------------------------------------------------------------------------
# gang payload declaration + labels
# ---------------------------------------------------------------------------


def test_payload_gang_parsing():
    assert payload_gang({"op": "train", "gang": {"workers": 2}}) == {"workers": 2}
    assert payload_gang({"op": "train"}) is None
    assert payload_gang({"gang": {"workers": 0}}) is None
    assert payload_gang({"gang": {"workers": "x"}}) is None
    assert payload_gang("nope") is None
    assert gang_workers({LABEL_GANG_WORKERS: "3"}) == 3
    assert gang_workers({LABEL_GANG_WORKERS: "bad"}) == 0
    assert gang_workers(None) == 0
    assert gang_chips({LABEL_GANG_CHIPS: "8"}) == 8


# ---------------------------------------------------------------------------
# DeviceLedger: all-or-nothing reservation
# ---------------------------------------------------------------------------


def _hb(wid, pool="gangpool", region="", chips=8, **kw):
    return Heartbeat(worker_id=wid, pool=pool, region=region,
                     chip_count=chips, max_parallel_jobs=8, **kw)


def _pools():
    return [Pool(name="gangpool")]


def test_ledger_all_or_nothing_and_release():
    reg = WorkerRegistry()
    for i in range(3):
        reg.update(_hb(f"w{i}"))
    led = DeviceLedger(reg)
    got = led.try_reserve("g1", 2, pools=_pools(), job_requires=[])
    assert got is not None and len(got) == 2
    # only one worker left: a 2-gang must get NOTHING, not one worker
    assert led.try_reserve("g2", 2, pools=_pools(), job_requires=[]) is None
    assert len(led.reserved_workers) == 2  # untouched by the failed attempt
    assert led.verify() == 0
    # release frees the full set and the next gang fits
    assert led.release("g1") == 2
    assert led.try_reserve("g2", 2, pools=_pools(), job_requires=[]) is not None
    assert led.release("unknown") == 0  # benign double-release


def test_ledger_respects_chips_and_slice_colocation():
    reg = WorkerRegistry()
    reg.update(_hb("small", chips=2))
    reg.update(_hb("big1", chips=8))
    reg.update(_hb("big2", chips=8))
    # different region = different slice: cannot co-locate
    reg.update(_hb("far", chips=8, region="other"))
    led = DeviceLedger(reg)
    got = led.try_reserve("g", 2, pools=_pools(), job_requires=[], chips=4)
    assert got is not None and set(got) == {"big1", "big2"}
    assert led.try_reserve("g2", 2, pools=_pools(), job_requires=[], chips=4) is None


def test_ledger_excludes_draining_unhealthy_and_excluded():
    reg = WorkerRegistry()
    reg.update(_hb("ok1"))
    reg.update(_hb("ok2"))
    reg.update(_hb("drainy", draining=True))
    reg.update(_hb("sick", devices_healthy=False))
    led = DeviceLedger(reg)
    got = led.try_reserve("g", 2, pools=_pools(), job_requires=[],
                          exclude=("ok1",))
    assert got is None  # only ok2 remains eligible
    got = led.try_reserve("g", 2, pools=_pools(), job_requires=[])
    assert got is not None and set(got) == {"ok1", "ok2"}


def test_ledger_property_never_partial():
    """Randomized admit/release interleavings: after EVERY operation the
    ledger is either holding a gang's full member set or none of it — the
    acceptance-bar property test."""
    rng = random.Random(1234)
    reg = WorkerRegistry()
    n_workers = 7
    for i in range(n_workers):
        reg.update(_hb(f"w{i}"))
    led = DeviceLedger(reg)
    live: list[str] = []
    seq = 0
    for _ in range(2000):
        op = rng.random()
        if op < 0.55 or not live:
            seq += 1
            size = rng.randint(1, n_workers + 1)  # sometimes unsatisfiable
            got = led.try_reserve(f"g{seq}", size, pools=_pools(),
                                  job_requires=[])
            if got is not None:
                assert len(got) == size
                live.append(f"g{seq}")
            else:
                # failed reservation must not strand anything
                assert f"g{seq}" not in led.reserved_workers.values()
        else:
            gid = live.pop(rng.randrange(len(live)))
            freed = led.release(gid)
            assert freed > 0
        assert led.verify() == 0
        # reservation map and gang map agree in both directions
        held = led.reserved_workers
        for gid in live:
            members = led.gang_members(gid)
            assert members and all(held[w] == gid for w in members)
        assert set(held.values()) == set(live)


# ---------------------------------------------------------------------------
# pool requirement enforcement (satellite: exclusion + one-shot warning)
# ---------------------------------------------------------------------------


def test_pool_requirement_mismatch_reasons():
    pool = Pool(name="tpu", min_chips=4, topology="2x2x1",
                device_kind="TPU v5p")
    ok = Heartbeat(worker_id="w", chip_count=4, slice_topology="2x2x1",
                   device_kind="TPU v5p")
    assert pool_requirement_mismatch(ok, pool) == ""
    assert "min_chips" in pool_requirement_mismatch(
        Heartbeat(worker_id="w", chip_count=2, slice_topology="2x2x1",
                  device_kind="TPU v5p"), pool)
    assert "topology" in pool_requirement_mismatch(
        Heartbeat(worker_id="w", chip_count=4, slice_topology="2x2x2",
                  device_kind="TPU v5p"), pool)
    assert "device_kind" in pool_requirement_mismatch(
        Heartbeat(worker_id="w", chip_count=4, slice_topology="2x2x1",
                  device_kind="TPU v4"), pool)
    assert pool_requirement_mismatch(ok, None) == ""


def test_pool_requirements_exclude_worker_with_one_shot_warning(caplog):
    """A worker advertising fewer chips than its pool's min_chips is
    excluded from that pool's routing and the exclusion is logged exactly
    once per (worker, pool)."""
    reg = WorkerRegistry()
    reg.update(Heartbeat(worker_id="tiny", pool="tpu", chip_count=1,
                         capabilities=["tpu"], max_parallel_jobs=8))
    reg.update(Heartbeat(worker_id="full", pool="tpu", chip_count=8,
                         capabilities=["tpu"], max_parallel_jobs=8))
    pc = parse_pool_config({
        "topics": {"job.tpu": "tpu"},
        "pools": {"tpu": {"requires": ["tpu"], "min_chips": 4}},
    })
    strat = LeastLoadedStrategy(reg, pc, native=False)
    req = JobRequest(job_id="j", topic="job.tpu")
    with caplog.at_level(logging.WARNING, logger="cordum"):
        assert strat.pick_subject(req) == "worker.full.jobs"
        assert strat.pick_subject(req) == "worker.full.jobs"
    warnings = [r for r in caplog.records
                if "excluded from pool routing" in r.getMessage()]
    assert len(warnings) == 1  # one-shot per (worker, pool)
    assert warnings[0].kv["worker_id"] == "tiny"
    assert "min_chips" in warnings[0].kv["reason"]


# ---------------------------------------------------------------------------
# engine → gang scheduler → worker rendezvous e2e
# ---------------------------------------------------------------------------


async def make_stack(n_workers=2, *, trainer=False, rendezvous_timeout_s=2.0,
                     peer_timeout_s=5.0, registry_ttl_s=30.0,
                     watch_interval_s=0.05, hb_interval_s=0.3):
    from cordum_tpu.worker.training import TrainRunner

    kv = MemoryKV()
    bus = LoopbackBus()
    js = JobStore(kv)
    kernel = SafetyKernel(policy_doc={
        "tenants": {"default": {"allow_topics": ["job.*", "job.>"]}},
    })
    reg = WorkerRegistry(ttl_s=registry_ttl_s)
    pc = parse_pool_config({
        "topics": {"job.gang": "gangpool", "job.single": "single"},
        "pools": {"gangpool": {}, "single": {}},
    })
    eng = Engine(bus=bus, job_store=js, safety=SafetyClient(kernel.check),
                 strategy=LeastLoadedStrategy(reg, pc), registry=reg)
    gangs = GangScheduler(eng, pc, rendezvous_timeout_s=rendezvous_timeout_s,
                          watch_interval_s=watch_interval_s)
    await eng.start()
    await gangs.start()
    store = MemoryStore(kv)
    workers = []
    for i in range(n_workers):
        w = Worker(bus=bus, store=store, worker_id=f"w{i}", pool="gangpool",
                   heartbeat_interval_s=hb_interval_s)
        w.attach_gang(GangRunner(
            w, trainer=TrainRunner() if trainer else None,
            rendezvous_timeout_s=rendezvous_timeout_s,
            peer_timeout_s=peer_timeout_s, beacon_interval_s=0.05,
        ), metrics=eng.metrics)
        await w.start()
        workers.append(w)
    await asyncio.sleep(0.05)
    stack = SimpleNamespace(kv=kv, bus=bus, js=js, eng=eng, gangs=gangs,
                            store=store, workers=workers, reg=reg)
    return stack


async def teardown(stack) -> None:
    await stack.gangs.stop()
    await stack.eng.stop()
    for w in stack.workers:
        try:
            await w.stop()
        except Exception:
            pass
    await stack.bus.close()


async def submit_gang(stack, job_id, payload, *, workers=2, chips=0,
                      priority="BATCH"):
    ptr = await stack.store.put_context(job_id, payload)
    labels = {LABEL_GANG_WORKERS: str(workers)}
    if chips:
        labels[LABEL_GANG_CHIPS] = str(chips)
    req = JobRequest(job_id=job_id, topic="job.gang", tenant_id="default",
                     priority=priority, context_ptr=ptr, labels=labels)
    await stack.bus.publish(subj.SUBMIT, BusPacket.wrap(req, sender_id="test"))
    return req


async def wait_state(js, job_id, want=("SUCCEEDED",), timeout_s=30.0):
    deadline = time.monotonic() + timeout_s
    st = None
    while time.monotonic() < deadline:
        st = await js.get_state(job_id)
        if st in want or st in ("FAILED", "DENIED", "CANCELLED"):
            return st
        await asyncio.sleep(0.02)
    return st


async def test_gang_happy_path_aggregates_member_results():
    stack = await make_stack(2)
    try:
        await submit_gang(stack, "g-happy", {"op": "gang_echo"}, workers=2)
        assert await wait_state(stack.js, "g-happy") == "SUCCEEDED"
        res = await stack.store.get_result("g-happy")
        assert set(res["per_rank"]) == {"0", "1"}
        assert sorted(res["workers"]) == ["w0", "w1"]
        meta = await stack.js.get_meta("g-happy")
        assert meta["dispatch_subject"].startswith(subj.GANG_PREFIX)
        assert meta["gang_members"] in ("w0,w1", "w1,w0")
        # full release + invariant intact + metrics counted
        assert stack.gangs.ledger.reserved_workers == {}
        assert stack.gangs.ledger.verify() == 0
        m = stack.eng.metrics
        assert m.gang_admissions.value(outcome="reserved") == 1
        assert m.gang_completed.value(status="succeeded") == 1
        assert m.gang_partial_reservations.total() == 0
    finally:
        await teardown(stack)


async def test_gang_queueing_is_fifo_all_or_nothing():
    """Two 2-gangs over two workers: the second queues (never half-
    reserves) and runs after the first releases."""
    stack = await make_stack(2)
    try:
        await submit_gang(stack, "g-a", {"op": "gang_test", "spin_s": 0.5},
                          workers=2)
        # give the first gang time to reserve, then pile the second on
        await asyncio.sleep(0.15)
        assert len(stack.gangs.ledger.reserved_workers) == 2
        await submit_gang(stack, "g-b", {"op": "gang_test", "spin_s": 0.1},
                          workers=2)
        await asyncio.sleep(0.15)
        # g-b is queued, not half-reserved; g-a still holds both workers
        assert len(stack.gangs._fifo) == 1
        assert set(stack.gangs.ledger.reserved_workers.values()) == {
            stack.gangs._by_job["g-a"].gang_id}
        assert await wait_state(stack.js, "g-a") == "SUCCEEDED"
        assert await wait_state(stack.js, "g-b") == "SUCCEEDED"
        assert stack.gangs.ledger.reserved_workers == {}
        assert stack.eng.metrics.gang_admissions.value(outcome="queued") >= 1
        assert stack.eng.metrics.gang_partial_reservations.total() == 0
    finally:
        await teardown(stack)


async def test_gang_member_failure_aborts_all_and_requeues_excluding():
    """Rank failure on one worker aborts the WHOLE gang, releases every
    device, and the requeue excludes the failed worker — the job completes
    on the survivors with attempts == 2."""
    stack = await make_stack(3)
    try:
        # w0 fails its member; the requeue must land on {w1, w2}
        await submit_gang(
            stack, "g-fail",
            {"op": "gang_test", "spin_s": 0.2, "fail_workers": ["w0"]},
            workers=2,
        )
        assert await wait_state(stack.js, "g-fail") == "SUCCEEDED"
        res = await stack.store.get_result("g-fail")
        assert "w0" not in res["workers"]
        meta = await stack.js.get_meta("g-fail")
        assert meta["attempts"] == "2"
        assert stack.gangs.ledger.reserved_workers == {}
        m = stack.eng.metrics
        assert m.gang_aborts.value(reason="member_failed") == 1
        assert m.gang_partial_reservations.total() == 0
    finally:
        await teardown(stack)


async def test_gang_persistent_failure_lands_in_dlq():
    stack = await make_stack(2)
    try:
        dlq: list = []

        async def on_dlq(subject, pkt):
            dlq.append(pkt.job_result)

        await stack.bus.subscribe(subj.DLQ, on_dlq)
        await submit_gang(
            stack, "g-doom",
            {"op": "gang_test", "fail_workers": ["w0", "w1"]},
            workers=2,
        )
        assert await wait_state(stack.js, "g-doom", timeout_s=40.0) == "FAILED"
        await stack.bus.drain()
        assert any(r.job_id == "g-doom" for r in dlq)
        assert stack.gangs.ledger.reserved_workers == {}
    finally:
        await teardown(stack)


async def test_gang_rendezvous_timeout_excludes_silent_member():
    """A phantom worker (heartbeats, but never answers its member packet)
    times out the barrier; the healthy member's abort excludes the silent
    one and the retry completes on real workers."""
    stack = await make_stack(2, rendezvous_timeout_s=0.5)
    try:
        # phantom: registry entry + a subscription that swallows the packet
        stack.reg.update(_hb("ghost", pool="gangpool", chips=8))

        async def swallow(subject, pkt):
            return None

        await stack.bus.subscribe(subj.direct_subject("ghost"), swallow,
                                  queue="ghost")
        ghost_beat = asyncio.ensure_future(_beat(stack, "ghost"))
        try:
            await submit_gang(stack, "g-rdv", {"op": "gang_echo"}, workers=2)
            assert await wait_state(stack.js, "g-rdv", timeout_s=20.0) == "SUCCEEDED"
        finally:
            ghost_beat.cancel()
        res = await stack.store.get_result("g-rdv")
        assert "ghost" not in res["workers"]
        assert stack.eng.metrics.gang_aborts.value(
            reason="rendezvous_timeout") >= 1
        assert stack.gangs.ledger.verify() == 0
    finally:
        await teardown(stack)


async def _beat(stack, wid):
    while True:
        stack.reg.update(_hb(wid, pool="gangpool", chips=8))
        await asyncio.sleep(0.1)


async def test_gang_preempted_as_a_unit_attempts_exempt():
    """A JobPreempt for a BATCH gang aborts the whole gang, requeues it
    attempts-EXEMPT after the hold-off, and it completes."""
    stack = await make_stack(2)
    try:
        await submit_gang(stack, "g-pre", {"op": "gang_test", "spin_s": 1.5},
                          workers=2, priority="BATCH")
        await asyncio.sleep(0.3)
        rec = stack.gangs._by_job["g-pre"]
        assert rec.state == "RUNNING"
        await stack.bus.publish(subj.PREEMPT, BusPacket.wrap(
            JobPreempt(job_id="g-pre", reason="slo_pressure"),
            sender_id="governor"))
        assert await wait_state(stack.js, "g-pre", timeout_s=20.0) == "SUCCEEDED"
        meta = await stack.js.get_meta("g-pre")
        assert meta["attempts"] == "1"  # the preempt re-dispatch was exempt
        assert stack.eng.metrics.gang_aborts.value(reason="preempted") == 1
        assert stack.gangs.ledger.reserved_workers == {}
    finally:
        await teardown(stack)


async def test_gang_cancel_aborts_without_requeue():
    stack = await make_stack(2)
    try:
        await submit_gang(stack, "g-can", {"op": "gang_test", "spin_s": 5.0},
                          workers=2)
        await asyncio.sleep(0.3)
        from cordum_tpu.protocol.types import JobCancel

        await stack.bus.publish(subj.CANCEL, BusPacket.wrap(
            JobCancel(job_id="g-can", reason="test"), sender_id="test"))
        assert await wait_state(stack.js, "g-can", timeout_s=10.0) == "CANCELLED"
        # devices released, no requeue record lingering
        for _ in range(50):
            if not stack.gangs.ledger.reserved_workers:
                break
            await asyncio.sleep(0.05)
        assert stack.gangs.ledger.reserved_workers == {}
        assert "g-can" not in stack.gangs._by_job
        # members stopped spinning (their active sets drain)
        for _ in range(100):
            if all(not w._active for w in stack.workers):
                break
            await asyncio.sleep(0.05)
        assert all(not w._active for w in stack.workers)
    finally:
        await teardown(stack)


async def test_gang_member_crash_mid_step_recovers_on_survivors():
    """The chaos twin of the acceptance bar: one member crashes mid-step
    (worker torn down abruptly — no abort published, heartbeats stop).
    Peers abort via the scheduler watchdog, every device frees, the job
    requeues and completes on the survivors, and a concurrent single-worker
    job stream suffers zero loss."""
    stack = await make_stack(3, registry_ttl_s=0.6, rendezvous_timeout_s=2.0)
    try:
        # a separate single-job lane on its own pool/worker
        single = Worker(bus=stack.bus, store=stack.store, worker_id="solo",
                        pool="single", heartbeat_interval_s=0.2)

        async def echo(ctx):
            return {"ok": True}

        single.register_default(echo)
        await single.start()

        await submit_gang(stack, "g-crash",
                          {"op": "gang_test", "spin_s": 2.0}, workers=2)
        await asyncio.sleep(0.4)
        rec = stack.gangs._by_job["g-crash"]
        assert rec.state == "RUNNING"
        victim = next(w for w in stack.workers
                      if w.worker_id == rec.members[0])
        # concurrent single-worker stream, spanning the crash window
        singles = [f"s-{i}" for i in range(12)]

        async def stream_singles():
            for jid in singles:
                await stack.bus.publish(subj.SUBMIT, BusPacket.wrap(
                    JobRequest(job_id=jid, topic="job.single",
                               tenant_id="default"),
                    sender_id="test"))
                await asyncio.sleep(0.05)

        stream = asyncio.ensure_future(stream_singles())
        # SIGKILL-equivalent: tear the worker down abruptly — its member
        # task dies silently, its heartbeats stop
        await victim.stop()
        assert await wait_state(stack.js, "g-crash", timeout_s=30.0) == "SUCCEEDED"
        res = await stack.store.get_result("g-crash")
        assert victim.worker_id not in res["workers"]
        await stream
        for jid in singles:
            assert await wait_state(stack.js, jid, timeout_s=20.0) == "SUCCEEDED"
        assert stack.gangs.ledger.reserved_workers == {}
        assert stack.gangs.ledger.verify() == 0
        assert stack.eng.metrics.gang_partial_reservations.total() == 0
        assert stack.eng.metrics.gang_aborts.value(reason="worker_dead") >= 1
        await single.stop()
    finally:
        await teardown(stack)


async def test_gang_spans_cover_reserve_rendezvous_step_release():
    stack = await make_stack(2)
    try:
        spans: list = []

        async def collect(subject, pkt):
            spans.append(pkt.payload)

        await stack.bus.subscribe(subj.TRACE_SPAN, collect)
        req = await submit_gang(stack, "g-span", {"op": "gang_echo"}, workers=2)
        assert await wait_state(stack.js, "g-span") == "SUCCEEDED"
        for _ in range(20):
            await stack.bus.drain()
            await asyncio.sleep(0.01)
        names = {sp.name for sp in spans}
        assert {"gang-reserve", "gang-dispatch", "gang-rendezvous",
                "gang-step", "gang-release"} <= names
        # all on the job's trace (one waterfall)
        trace_ids = {sp.trace_id for sp in spans
                     if sp.name.startswith("gang-")}
        assert len(trace_ids) == 1
    finally:
        await teardown(stack)


# ---------------------------------------------------------------------------
# MPMD pipeline numerics: distributed == monolithic
# ---------------------------------------------------------------------------


def test_mpmd_stage_grads_match_monolithic_reference():
    """The stage-per-worker forward/backward chain (activations + cotangents
    as they would cross the wire) reproduces the monolithic model's loss and
    gradients exactly."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from cordum_tpu.models import llama, pipeline
    from cordum_tpu.models.llama import rms_norm
    from cordum_tpu.models.pipeline import _stage_apply
    from cordum_tpu.worker.gang import _mpmd_backward, _mpmd_build, _mpmd_forward

    payload = {"seed": 0}
    s0 = _mpmd_build(payload, 0, 2)
    s1 = _mpmd_build(payload, 1, 2)
    base = s0["base"]
    tokens = np.asarray(jax.random.randint(
        jax.random.PRNGKey(7), (2, 12), 0, base.vocab_size))

    # distributed: rank0 forward → serialize → rank1 loss/grad → cotangent
    # back through rank0 (round-trip through the wire encoding)
    y0, vjp0 = _mpmd_forward(s0, tokens, None)
    wire = np.frombuffer(y0.tobytes(), np.float32).reshape(y0.shape)
    loss, g1, gx = _mpmd_forward(s1, tokens, wire)
    gx_wire = np.frombuffer(
        np.asarray(gx, np.float32).tobytes(), np.float32).reshape(gx.shape)
    g0, g_none = _mpmd_backward(vjp0, gx_wire)
    assert g_none is None

    cfg = pipeline.PipelineConfig(base=base, n_stages=2, n_microbatches=1)
    full = pipeline.init_params(jax.random.PRNGKey(0), cfg)
    tok = jnp.asarray(tokens)
    mb, t = tok.shape
    pos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (mb, t))

    def ref_loss(full):
        x = full["embed"][tok].astype(jnp.float32)
        x = _stage_apply(jax.tree.map(lambda p: p[0], full["stages"]), x, pos, base)
        x = _stage_apply(jax.tree.map(lambda p: p[1], full["stages"]), x, pos, base)
        h = rms_norm(x, full["final_norm"], base.norm_eps)
        logits = (h @ full["lm_head"]).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        nll = -jnp.take_along_axis(logp, tok[:, 1:][..., None], axis=-1)[..., 0]
        return jnp.mean(nll)

    ref, gref = jax.value_and_grad(ref_loss)(full)
    assert loss == pytest.approx(float(ref), abs=1e-5)
    assert np.allclose(np.asarray(g0["embed"]),
                       np.asarray(gref["embed"]), atol=1e-4)
    assert np.allclose(np.asarray(g1["lm_head"]),
                       np.asarray(gref["lm_head"]), atol=1e-4)
    assert np.allclose(np.asarray(g0["stage"]["wq"]),
                       np.asarray(gref["stages"]["wq"][0]), atol=1e-4)
    assert np.allclose(np.asarray(g1["stage"]["wq"]),
                       np.asarray(gref["stages"]["wq"][1]), atol=1e-4)


async def test_gang_mpmd_pipeline_end_to_end():
    """pp=2, workers=2: stage-per-worker MPMD training runs end-to-end
    through the scheduled gang pipeline with activations forwarded over the
    bus; the last stage owns the loss."""
    stack = await make_stack(2, trainer=True, rendezvous_timeout_s=10.0,
                             peer_timeout_s=30.0)
    try:
        await submit_gang(stack, "g-mpmd", {
            "op": "train", "model": "pipeline", "steps": 1, "batch": 4,
            "seq": 12, "microbatches": 2, "mesh": {"dp": -1, "pp": 2},
            "gang": {"workers": 2},
        }, workers=2)
        assert await wait_state(stack.js, "g-mpmd", timeout_s=120.0) == "SUCCEEDED"
        res = await stack.store.get_result("g-mpmd")
        assert res["mode"] == "mpmd"
        assert res["per_rank"]["1"]["loss"] is not None
        assert res["per_rank"]["0"]["loss"] is None  # stage 0 never sees it
        assert res["steps_done"] == 1
        assert res["mesh"]["pp"] == 2
    finally:
        await teardown(stack)


@pytest.mark.slow
async def test_gang_spmd_dense_end_to_end():
    """The dense dp×tp×sp MULTICHIP flow as a scheduled 2-worker SPMD gang
    (each member runs the identical mesh program; slow tier — compiles a
    full train step)."""
    stack = await make_stack(2, trainer=True, rendezvous_timeout_s=15.0)
    try:
        await submit_gang(stack, "g-spmd", {
            "op": "train", "model": "llama-tiny", "steps": 1, "batch": 4,
            "seq": 16, "mesh": {"tp": 2, "sp": 2},
            "gang": {"workers": 2},
        }, workers=2)
        assert await wait_state(stack.js, "g-spmd", timeout_s=300.0) == "SUCCEEDED"
        res = await stack.store.get_result("g-spmd")
        assert res["mode"] == "spmd"
        assert res["loss"] is not None
        assert res["per_rank"]["0"]["mesh"]["tp"] == 2
    finally:
        await teardown(stack)


# ---------------------------------------------------------------------------
# observability: gangs doc, fleet merge, render, floor gates
# ---------------------------------------------------------------------------


async def test_gangs_doc_flows_to_fleet_and_renders():
    from cordum_tpu.infra.metrics import Metrics
    from cordum_tpu.obs import FleetAggregator, TelemetryExporter

    bus = LoopbackBus()
    agg = FleetAggregator(bus, metrics=Metrics(), fine_step_s=0.5)
    await agg.start()
    gang_rows = [{
        "gang_id": "gg-1", "job_id": "job-1", "state": "RUNNING",
        "workers": 2, "chips_per_worker": 8, "members": ["w0", "w1"],
        "ready": 2, "done": 0, "age_s": 1.5, "reason": "",
    }]
    exporter = TelemetryExporter(
        "scheduler", bus, Metrics(), instance_id="sched-0", interval_s=0.5,
        health_fn=lambda: {"role": "scheduler", "gangs": gang_rows,
                           "gang_queue_depth": 3},
    )
    await exporter.publish_once()
    await bus.drain()
    doc = agg.gangs_doc()
    assert doc["queue_depth"] == 3
    assert doc["scheduler_shards"] == 1
    assert doc["gangs"][0]["gang_id"] == "gg-1"
    assert doc["gangs"][0]["shard"] == "sched-0"
    table = render_gang_table(doc)
    assert "gg-1" in table and "w0,w1" in table and "RUNNING" in table
    assert render_gang_table({"gangs": []}).count("no gangs") == 1
    await agg.stop()
    await bus.close()


async def test_gang_metrics_reach_fleet_exposition():
    stack = await make_stack(2)
    try:
        from cordum_tpu.obs import FleetAggregator, TelemetryExporter

        agg = FleetAggregator(stack.bus, metrics=stack.eng.metrics,
                              fine_step_s=0.5)
        await agg.start()
        exporter = TelemetryExporter(
            "scheduler", stack.bus, stack.eng.metrics,
            instance_id="sched-0", interval_s=0.5,
            health_fn=lambda: {"role": "scheduler"},
        )
        await submit_gang(stack, "g-met", {"op": "gang_echo"}, workers=2)
        assert await wait_state(stack.js, "g-met") == "SUCCEEDED"
        await exporter.publish_once()
        await stack.bus.drain()
        text = agg.render()
        assert "cordum_gang_admissions_total" in text
        assert 'outcome="reserved"' in text
        assert "cordum_gang_rendezvous_seconds" in text
        await agg.stop()
    finally:
        await teardown(stack)


def test_floor_checker_gates_gang_keys(tmp_path):
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import check_bench_floor as mod
    finally:
        sys.path.pop(0)
    floors = json.loads((REPO / "bench_floor.json").read_text())
    base = {"gang_jobs_per_sec": 4.0, "gang_flows_ok": 1.0,
            "gang_partial_reservations": 0.0}
    # only gang keys present: every non-gang floor flags missing, but the
    # gang keys themselves pass/fail on their own values
    doc = dict(base)
    assert not any("gang" in v for v in mod.check(doc, floors))
    doc["gang_partial_reservations"] = 1.0
    assert any("gang_partial_reservations" in v for v in mod.check(doc, floors))
    doc["gang_partial_reservations"] = 0.0
    doc["gang_jobs_per_sec"] = 0.0
    assert any("gang_jobs_per_sec" in v for v in mod.check(doc, floors))
    doc["gang_jobs_per_sec"] = 4.0
    doc["gang_flows_ok"] = 0.0
    assert any("gang_flows_ok" in v for v in mod.check(doc, floors))


# ---------------------------------------------------------------------------
# chaos: SIGKILL a real gang member subprocess mid-step (acceptance bar)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.slow  # real statebus + three cmd.worker subprocesses
async def test_sigkill_gang_member_mid_step_gang_recovers(tmp_path):
    """SIGKILL a real ``cmd.worker`` subprocess mid-gang-step: the peer
    aborts (scheduler watchdog sees the silence), every reserved device is
    released, the job requeues and completes on the survivors, and a
    concurrent single-worker job stream suffers zero loss."""
    from cordum_tpu.infra.chaos import ServerProc, WorkerProc, free_port
    from cordum_tpu.infra.statebus import connect

    from .test_chaos import REPO_ROOT, wait_for

    port = free_port()
    sb = ServerProc(port, env={"STATEBUS_AOF": str(tmp_path / "s.aof")},
                    cwd=REPO_ROOT)
    await sb.start()
    url = f"statebus://127.0.0.1:{port}"
    kv, bus, conn = await connect(url)
    js, ms = JobStore(kv), MemoryStore(kv)
    kernel = SafetyKernel(policy_doc={
        "tenants": {"default": {"allow_topics": ["job.*", "job.>"]}}})
    reg = WorkerRegistry(ttl_s=3.0)
    pc = parse_pool_config({"topics": {"job.tpu.>": "tpu"},
                            "pools": {"tpu": {"requires": []}}})
    eng = Engine(bus=bus, job_store=js, safety=SafetyClient(kernel.check),
                 strategy=LeastLoadedStrategy(reg, pc), registry=reg)
    gangs = GangScheduler(eng, pc, rendezvous_timeout_s=8.0,
                          watch_interval_s=0.25)
    await eng.start()
    await gangs.start()
    wenv = {
        "CORDUM_STATEBUS_URL": url,
        "WORKER_POOL": "tpu",
        "WORKER_TOPICS": "job.tpu.>",
        "WORKER_CAPABILITIES": "tpu,echo",
        "WORKER_HEARTBEAT_INTERVAL": "0.5",
        "WORKER_BATCHING": "0",
        "WORKER_SERVING": "0",
        "WORKER_GANG_RENDEZVOUS_TIMEOUT": "8.0",
    }
    procs = [
        WorkerProc(f"gang-w{i}", env=wenv, cwd=REPO_ROOT,
                   log_path=str(tmp_path / f"w{i}.log"))
        for i in range(3)
    ]
    for p in procs:
        p.start()
    try:
        await wait_for(lambda: len(reg.snapshot()) >= 3, 180.0,
                       "all three workers heartbeating")
        # the gang spins long enough to span the kill + registry TTL
        ptr = await ms.put_context("g-chaos", {"op": "gang_test",
                                               "spin_s": 6.0})
        await bus.publish(subj.SUBMIT, BusPacket.wrap(
            JobRequest(job_id="g-chaos", topic="job.tpu.gang",
                       tenant_id="default", context_ptr=ptr,
                       labels={LABEL_GANG_WORKERS: "2"}),
            sender_id="t"))
        await wait_for(
            lambda: _rec_running(gangs, "g-chaos"), 60.0, "gang running")
        rec = gangs._by_job["g-chaos"]
        victim_id = rec.members[0]
        victim = next(p for p in procs if p.worker_id == victim_id)
        await asyncio.sleep(1.0)  # mid-step
        victim.kill()  # SIGKILL: no drain, no abort, heartbeats just stop
        # concurrent single-worker stream spanning the recovery window
        singles = [f"chaos-s-{i}" for i in range(8)]
        for jid in singles:
            sptr = await ms.put_context(jid, {"op": "echo", "v": jid})
            await bus.publish(subj.SUBMIT, BusPacket.wrap(
                JobRequest(job_id=jid, topic="job.tpu.echo",
                           tenant_id="default", context_ptr=sptr),
                sender_id="t"))
            await asyncio.sleep(0.1)
        await wait_for(
            lambda: _get_state_eq(js, "g-chaos", "SUCCEEDED"), 120.0,
            "gang recovered on survivors")
        res = await ms.get_result("g-chaos")
        assert victim_id not in res["workers"]
        for jid in singles:
            await wait_for(lambda jid=jid: _get_state_eq(js, jid, "SUCCEEDED"),
                           60.0, f"single {jid}")
        assert gangs.ledger.reserved_workers == {}
        assert gangs.ledger.verify() == 0
        assert eng.metrics.gang_partial_reservations.total() == 0
        assert eng.metrics.gang_aborts.value(reason="worker_dead") >= 1
    finally:
        for p in procs:
            p.kill()
        await gangs.stop()
        await eng.stop()
        await conn.close()
        sb.kill()


async def _rec_running(gangs, job_id) -> bool:
    rec = gangs._by_job.get(job_id)
    return rec is not None and rec.state == "RUNNING" and bool(rec.members)


async def _get_state_eq(js, jid, want) -> bool:
    return await js.get_state(jid) == want


async def test_gang_member_redelivery_republishes_done():
    """A redelivered member packet after completion republishes the cached
    done report instead of re-running the step program (worker-level
    idempotence, gang-shaped)."""
    stack = await make_stack(2)
    try:
        await submit_gang(stack, "g-redo", {"op": "gang_echo"}, workers=2)
        assert await wait_state(stack.js, "g-redo") == "SUCCEEDED"
        w0 = stack.workers[0]
        runner = w0.gang
        assert "g-redo" in runner._done
        done_msgs: list = []

        async def tap(subject, pkt):
            m = pkt.gang_msg
            if m is not None and m.kind == "done":
                done_msgs.append(m)

        gid = runner._done["g-redo"].gang_id
        await stack.bus.subscribe(subj.gang_subject(gid), tap)
        # re-deliver the member packet (the scheduler nudge path's shape)
        member_req = JobRequest(
            job_id="g-redo", topic="job.gang",
            labels={"cordum.gang_id": gid, "cordum.gang_rank": "0",
                    "cordum.gang_size": "2"},
        )
        await runner.handle(member_req, {"op": "gang_echo"})
        await stack.bus.drain()
        assert done_msgs and done_msgs[0].rank == 0
    finally:
        await teardown(stack)
